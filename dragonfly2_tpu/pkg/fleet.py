"""Fleet observatory: bounded scheduler-side cluster health, cross-task
host scorecards, and a scheduling decision audit log.

The flight recorder (pkg/flight) answers "where did the wall time go" for
one task on one daemon; the PodAggregator names the slowest host within
one task. Neither survives the task or sees the fleet. This module is the
scheduler's continuous view, built from the report traffic the service
layer already handles (piece reports with per-phase ``timings``, typed
``piece_failed.reason``, announces, registrations) — the same posture as
the reference's manager/scheduler cluster state (PAPER.md §0-1), but
strictly bounded:

  * ``FleetTimeSeries`` — a preallocated ring of fixed-width time buckets
    (default 5 s x 720 = 1 h) of numeric columns. O(1) per event, O(buckets
    x columns) resident bytes regardless of host count. Gauge columns
    (hosts by state, active broadcasts, quarantine population) are sampled
    from a provider callback at bucket rotation — at most once per
    ``bucket_s`` no matter the event rate. Served at ``/debug/fleet``.

  * ``HostScorecards`` — decaying per-host cross-task stats: EWMA piece
    service time as a downloader (from report ``timings``), EWMA serve
    cost as a parent (from children's reports), failure counts by typed
    reason, upload-serve load. A robust z-score (median/MAD — a single
    outlier cannot inflate the yardstick it is measured against) flags
    fleet-wide stragglers, which feeds an ADVISORY filter into
    ``scheduling._is_candidate``. Bounded: LRU-evicted past ``max_hosts``.
    Served at ``/debug/fleet/hosts``.

  * ``DecisionLog`` — a preallocated ring of scheduling decisions (parent
    handouts with top rejected alternatives, quarantine demotions,
    back-to-source demotions, stripe handouts/reshuffles, straggler
    filters), so "why did host X get parent Y" is answerable after the
    fact at ``/debug/fleet/decisions?host=|task=``.

Hot-path contract: the per-piece feed (``note_pieces``) does one clock
read, a handful of list index increments and per-host EWMA float math —
no per-event dicts, no scans. Scans (gauge sampling, straggler
recompute) run at bucket/TTL cadence or serve time only.
benchmarks/fleet_bench.py publishes the paired on/off overhead
(``config9_fleet``: per-event overhead <= 3%, resident bytes flat in
host count).
"""

from __future__ import annotations

import sys
import time

from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("fleet")

# Typed failure-reason vocabulary (pkg/quarantine weights + piece
# downloader classifier); anything else folds into "other" so the
# time-series stays fixed-width.
REASONS = ("corrupt", "truncated", "stall", "refused", "transport",
           "throttle", "not_found", "http5xx")

COUNTERS = (
    "announces",          # host announce RPCs
    "registers",          # peer registrations (announce_peer opens)
    "reconnects",         # terminal peers replaced by re-registration
    "pieces_landed",
    "bytes_intra",        # landed piece bytes, parent in the same slice
    "bytes_cross",        # ... parent in another slice (real DCN)
    "bytes_unlabeled",    # ... either end without TPU coordinates
    "back_source",        # demotions to origin
    "quarantines",        # hosts entering scheduler-side quarantine
    "stripe_handouts",    # striped-broadcast plans attached to handouts
    "stripe_reshuffles",  # membership-change stripe pushes
    "handouts",           # parent handouts (scheduling decisions)
) + tuple(f"failed_{r}" for r in REASONS) + ("failed_other",)

GAUGES = (
    "hosts_total",
    "hosts_seed",
    "hosts_quarantined",
    "peers_running",
    "tasks_active",       # active broadcasts (RUNNING tasks)
    "straggler_hosts",
)

# Hot-path column handles (ints; name lookup only at export time).
C_ANNOUNCES = COUNTERS.index("announces")
C_REGISTERS = COUNTERS.index("registers")
C_RECONNECTS = COUNTERS.index("reconnects")
C_PIECES = COUNTERS.index("pieces_landed")
C_BYTES_INTRA = COUNTERS.index("bytes_intra")
C_BYTES_CROSS = COUNTERS.index("bytes_cross")
C_BYTES_UNLABELED = COUNTERS.index("bytes_unlabeled")
C_BACK_SOURCE = COUNTERS.index("back_source")
C_QUARANTINES = COUNTERS.index("quarantines")
C_STRIPE_HANDOUTS = COUNTERS.index("stripe_handouts")
C_STRIPE_RESHUFFLES = COUNTERS.index("stripe_reshuffles")
C_HANDOUTS = COUNTERS.index("handouts")
_FAILED_COL = {r: COUNTERS.index(f"failed_{r}") for r in REASONS}
C_FAILED_OTHER = COUNTERS.index("failed_other")


def failed_col(reason: str) -> int:
    return _FAILED_COL.get(reason, C_FAILED_OTHER)


DECISION_COUNT = metrics.counter(
    "scheduler_decisions_total",
    "Scheduling decisions recorded in the fleet audit log, by kind "
    "(handout / quarantine / back_source / stripe_handout / "
    "stripe_reshuffle / straggler_filter / schedule_failed / "
    "admission / throttle)", ("kind",))

STRAGGLER_GAUGE = metrics.gauge(
    "fleet_straggler_hosts",
    "Hosts currently flagged as fleet-wide stragglers by the scorecard "
    "robust z-score (slow serve EWMA across tasks)")

# labels() does lock+lookup work on every call; decisions are frequent
# enough (one per handout) that the children are bound once here.
_DECISION_CHILDREN: dict = {}


def _decision_child(kind: str):
    child = _DECISION_CHILDREN.get(kind)
    if child is None:
        child = _DECISION_CHILDREN[kind] = DECISION_COUNT.labels(kind)
    return child


def _deep_bytes(obj, _seen=None) -> int:
    """Recursive getsizeof over the containers the observatory owns —
    the resident-bytes bound fleet_bench publishes. Cycles guarded."""
    if _seen is None:
        _seen = set()
    oid = id(obj)
    if oid in _seen:
        return 0
    _seen.add(oid)
    size = sys.getsizeof(obj)
    if isinstance(obj, dict):
        for k, v in obj.items():
            size += _deep_bytes(k, _seen) + _deep_bytes(v, _seen)
    elif isinstance(obj, (list, tuple, set, frozenset)):
        for v in obj:
            size += _deep_bytes(v, _seen)
    elif hasattr(obj, "__slots__"):
        for slot in obj.__slots__:
            if hasattr(obj, slot):
                size += _deep_bytes(getattr(obj, slot), _seen)
    elif hasattr(obj, "__dict__"):
        size += _deep_bytes(obj.__dict__, _seen)
    return size


# --------------------------------------------------------------------- #
# Cluster time-series
# --------------------------------------------------------------------- #

class FleetTimeSeries:
    """Preallocated ring of fixed-width time buckets. ``inc`` is O(1);
    rotation (bounded by ring length, amortized once per ``bucket_s``)
    zeroes reused slots and samples the gauge provider."""

    __slots__ = ("bucket_s", "n_buckets", "_counts", "_gauges", "_stamp",
                 "_gauge_stamp", "_cur", "_sampler", "_clock",
                 "_wall_anchor")

    def __init__(self, bucket_s: float = 5.0, buckets: int = 720,
                 sampler=None, clock=time.monotonic):
        self.bucket_s = float(bucket_s)
        self.n_buckets = int(buckets)
        nc, ng = len(COUNTERS), len(GAUGES)
        self._counts = [[0.0] * nc for _ in range(self.n_buckets)]
        self._gauges = [[0.0] * ng for _ in range(self.n_buckets)]
        self._stamp = [-1] * self.n_buckets      # absolute bucket number
        # Buckets where the gauge sampler actually RAN (rotation stamps
        # skipped-over gap buckets too, but only the rotation target gets
        # a sample — gauge consumers must not read the gaps as zeros).
        self._gauge_stamp = [-1] * self.n_buckets
        self._cur = -1
        self._sampler = sampler
        self._clock = clock
        # wall = monotonic + anchor, for export timestamps.
        self._wall_anchor = time.time() - clock()

    # -- hot path ----------------------------------------------------------

    def inc(self, col: int, n: float = 1.0, now: "float | None" = None) -> None:
        if now is None:
            now = self._clock()
        b = int(now / self.bucket_s)
        if b != self._cur:
            self._rotate(b)
        self._counts[b % self.n_buckets][col] += n

    def bucket(self, now: "float | None" = None) -> list:
        """Current bucket's counter row (rotated first) — lets a batch
        caller do several ``row[col] += n`` on one clock read."""
        if now is None:
            now = self._clock()
        b = int(now / self.bucket_s)
        if b != self._cur:
            self._rotate(b)
        return self._counts[b % self.n_buckets]

    def _rotate(self, b: int) -> None:
        start = self._cur + 1 if 0 <= b - self._cur <= self.n_buckets \
            else b - self.n_buckets + 1
        for a in range(max(start, b - self.n_buckets + 1), b + 1):
            slot = a % self.n_buckets
            if self._stamp[slot] != a:
                if self._stamp[slot] >= 0:
                    # Reused slot: zero it. Pristine slots (stamp -1)
                    # were zero-constructed — the first rotation after
                    # start-up must not pay a full-ring rewrite.
                    row = self._counts[slot]
                    for i in range(len(row)):
                        row[i] = 0.0
                    grow = self._gauges[slot]
                    for i in range(len(grow)):
                        grow[i] = 0.0
                self._stamp[slot] = a
        self._cur = b
        if self._sampler is not None:
            try:
                sampled = self._sampler()
            except Exception:          # a broken sampler must not drop events
                sampled = None
            if sampled:
                slot = b % self.n_buckets
                grow = self._gauges[slot]
                for i, name in enumerate(GAUGES):
                    grow[i] = float(sampled.get(name, 0.0))
                self._gauge_stamp[slot] = b

    # -- export ------------------------------------------------------------

    def window(self, seconds: float) -> dict:
        """Newest-last series for the trailing ``seconds`` (clamped to the
        ring), as {column: [v, ...]} plus per-column totals."""
        now = self._clock()
        self.bucket(now)               # rotate so stale slots read zero
        want = max(1, min(self.n_buckets, int(seconds / self.bucket_s) + 1))
        cur = int(now / self.bucket_s)
        buckets = []
        for a in range(cur - want + 1, cur + 1):
            slot = a % self.n_buckets
            if a < 0 or self._stamp[slot] != a:
                buckets.append(None)
            else:
                buckets.append(slot)
        series = {}
        for i, name in enumerate(COUNTERS):
            series[name] = [0.0 if s is None else self._counts[s][i]
                            for s in buckets]
        gauges = {}
        for i, name in enumerate(GAUGES):
            gauges[name] = [0.0 if s is None else self._gauges[s][i]
                            for s in buckets]
        return {
            "bucket_s": self.bucket_s,
            "buckets": want,
            "t_start_wall": round(
                (cur - want + 1) * self.bucket_s + self._wall_anchor, 3),
            "counters": series,
            "gauges": gauges,
            "totals": {name: sum(vals) for name, vals in series.items()},
        }

    def totals(self, seconds: float, columns: "tuple | list") -> dict:
        """Per-column sums over the trailing window WITHOUT materializing
        per-bucket series — the SLO engine's repeated-cadence accessor
        (window() builds one list per column; at a 720-bucket ring that
        is ~25k list appends per call, too hot for a burn-rate tick)."""
        now = self._clock()
        self.bucket(now)               # rotate so stale slots read zero
        want = max(1, min(self.n_buckets, int(seconds / self.bucket_s) + 1))
        cur = int(now / self.bucket_s)
        # The all-columns call (cluster frame builder, every keepalive)
        # skips the per-column index scans.
        idx = range(len(COUNTERS)) if columns is COUNTERS \
            else [COUNTERS.index(c) for c in columns]
        sums = [0.0] * len(idx)
        for a in range(cur - want + 1, cur + 1):
            slot = a % self.n_buckets
            if a < 0 or self._stamp[slot] != a:
                continue
            row = self._counts[slot]
            for j, i in enumerate(idx):
                sums[j] += row[i]
        return dict(zip(columns, sums))

    def gauge_column(self, name: str, seconds: float) -> list:
        """One gauge column's sampled values over the trailing window —
        buckets the sampler actually ran for, only. Gap buckets (rotated
        past, never sampled) are not fabricated as zeros, so a
        fraction-of-bad-buckets SLI stays honest."""
        i = GAUGES.index(name)
        now = self._clock()
        self.bucket(now)
        want = max(1, min(self.n_buckets, int(seconds / self.bucket_s) + 1))
        cur = int(now / self.bucket_s)
        out = []
        for a in range(cur - want + 1, cur + 1):
            slot = a % self.n_buckets
            if a >= 0 and self._gauge_stamp[slot] == a:
                out.append(self._gauges[slot][i])
        return out

    def gauges_last(self, seconds: float) -> dict:
        """Newest sampled gauge row within the trailing window, as
        {name: value} — {} when the sampler never ran in the window
        (never fabricates zeros). The sampler stamps every gauge into
        one bucket, so one reverse scan serves all columns; the cluster
        frame builder needs this every keepalive and per-column
        ``gauge_column()`` calls would re-walk the ring once per gauge."""
        now = self._clock()
        self.bucket(now)
        want = max(1, min(self.n_buckets, int(seconds / self.bucket_s) + 1))
        cur = int(now / self.bucket_s)
        for a in range(cur, cur - want, -1):
            slot = a % self.n_buckets
            if a >= 0 and self._gauge_stamp[slot] == a:
                grow = self._gauges[slot]
                return {name: grow[i] for i, name in enumerate(GAUGES)}
        return {}

    def resident_bytes(self) -> int:
        return (_deep_bytes(self._counts) + _deep_bytes(self._gauges)
                + _deep_bytes(self._stamp))


# --------------------------------------------------------------------- #
# Per-host scorecards
# --------------------------------------------------------------------- #

class HostScore:
    """One host's decaying cross-task stats. EWMA math only on the hot
    path; time-based decay of failure counts is applied lazily on read."""

    __slots__ = ("host_id", "serve_ewma_ms", "serve_samples",
                 "serve_stamp", "down_ewma_ms", "down_samples", "stall_ms",
                 "dcn_ms", "store_ms", "uploads", "failures", "fail_stamp",
                 "last_seen")

    def __init__(self, host_id: str):
        self.host_id = host_id
        self.serve_ewma_ms = 0.0   # as a PARENT: children's piece cost
        self.serve_samples = 0
        self.serve_stamp = -1.0    # last serve sample (probation clock)
        self.down_ewma_ms = 0.0    # as a DOWNLOADER: own piece time
        self.down_samples = 0
        self.stall_ms = 0.0        # decayed phase accumulators (timings)
        self.dcn_ms = 0.0
        self.store_ms = 0.0
        self.uploads = 0.0         # decayed upload-serve load
        self.failures: dict = {}   # reason -> decayed count
        self.fail_stamp = -1.0     # -1 = never stamped (0.0 is a real time)
        self.last_seen = 0.0


class HostScorecards:
    """Bounded per-host registry. ``is_straggler`` consults a cached flag
    set recomputed at most every ``recompute_s`` from a robust z-score
    over serve EWMAs: z = (x - median) / max(1.4826*MAD, floor). The
    median/MAD yardstick means one pathological host cannot widen the
    spread enough to hide itself (a classic mean/sigma failure at small
    populations)."""

    def __init__(self, *, max_hosts: int = 1024, ewma_alpha: float = 0.2,
                 half_life_s: float = 600.0, z_threshold: float = 3.0,
                 min_serve_samples: int = 8, min_population: int = 8,
                 recompute_s: float = 2.0, flag_ttl_s: float = 120.0,
                 clock=time.monotonic):
        self.max_hosts = max_hosts
        self.alpha = ewma_alpha
        self.half_life_s = half_life_s
        self.z_threshold = z_threshold
        self.min_serve_samples = min_serve_samples
        self.min_population = min_population
        self.recompute_s = recompute_s
        # Probation: a flagged host stops getting handouts, so it stops
        # getting serve samples and its EWMA freezes. The flag therefore
        # only holds while samples are FRESH; past flag_ttl_s the host is
        # re-trialed (if it is still slow, the next samples re-flag it).
        self.flag_ttl_s = flag_ttl_s
        self._clock = clock
        self._hosts: dict[str, HostScore] = {}
        self._stragglers: set[str] = set()
        self._recomputed_at = -1e18

    def _score(self, host_id: str, now: float) -> HostScore:
        s = self._hosts.get(host_id)
        if s is None:
            if len(self._hosts) >= self.max_hosts:
                # Batch-evict the ~3% least-recently-seen cards in one
                # scan: a churning fleet admits new hosts constantly, and
                # a scan per admission would be O(cap) per event.
                import heapq

                k = max(1, self.max_hosts // 32)
                for victim in heapq.nsmallest(
                        k, self._hosts.values(),
                        key=lambda h: h.last_seen):
                    del self._hosts[victim.host_id]
            s = self._hosts[host_id] = HostScore(host_id)
        s.last_seen = now
        return s

    # -- hot path ----------------------------------------------------------

    def note_download(self, host_id: str, cost_ms: float,
                      timings: "dict | None",
                      now: "float | None" = None) -> None:
        """The downloading host's own piece time + phase split."""
        if now is None:
            now = self._clock()
        s = self._score(host_id, now)
        a = self.alpha
        if s.down_samples == 0:
            s.down_ewma_ms = float(cost_ms)
        else:
            s.down_ewma_ms += a * (cost_ms - s.down_ewma_ms)
        s.down_samples += 1
        if timings:
            b = 1.0 - a
            s.dcn_ms = b * s.dcn_ms + a * float(timings.get("dcn_ms", 0) or 0)
            s.stall_ms = b * s.stall_ms + a * float(
                timings.get("stall_ms", 0) or 0)
            s.store_ms = b * s.store_ms + a * float(
                timings.get("store_ms", 0) or 0)

    def note_serve(self, host_id: str, cost_ms: float,
                   now: "float | None" = None, count: int = 1) -> None:
        """A child reported ``count`` pieces served BY this host at a mean
        cost of ``cost_ms``: the parent's serving speed as experienced
        fleet-wide. ``count > 1`` applies the batch-equivalent EWMA step
        (effective alpha 1-(1-a)^k) so a coalesced report moves the
        estimate as far as k single reports at the same value would."""
        if now is None:
            now = self._clock()
        s = self._score(host_id, now)
        if s.serve_samples == 0:
            s.serve_ewma_ms = float(cost_ms)
        else:
            a = self.alpha if count == 1 else \
                1.0 - (1.0 - self.alpha) ** count
            s.serve_ewma_ms += a * (cost_ms - s.serve_ewma_ms)
        s.serve_samples += count
        s.uploads += count
        s.serve_stamp = now
        self.maybe_recompute(now)

    def note_failure(self, host_id: str, reason: str,
                     now: "float | None" = None) -> None:
        if now is None:
            now = self._clock()
        s = self._score(host_id, now)
        self._decay_failures(s, now)
        s.failures[reason] = s.failures.get(reason, 0.0) + 1.0

    def _decay_failures(self, s: HostScore, now: float) -> None:
        dt = now - s.fail_stamp
        if s.fail_stamp >= 0 and dt > 0 and s.failures:
            k = 0.5 ** (dt / self.half_life_s)
            for r in list(s.failures):
                v = s.failures[r] * k
                if v < 0.01:
                    del s.failures[r]
                else:
                    s.failures[r] = v
        s.fail_stamp = now

    # -- straggler flag ----------------------------------------------------

    def recompute_stragglers(self, now: "float | None" = None) -> set:
        if now is None:
            now = self._clock()
        self._recomputed_at = now
        sampled = [s for s in self._hosts.values()
                   if s.serve_samples >= self.min_serve_samples
                   and now - s.serve_stamp <= self.flag_ttl_s]
        flags: set[str] = set()
        if len(sampled) >= self.min_population:
            values = sorted(s.serve_ewma_ms for s in sampled)
            n = len(values)
            median = values[n // 2] if n % 2 else (
                values[n // 2 - 1] + values[n // 2]) / 2.0
            devs = sorted(abs(v - median) for v in values)
            mad = devs[n // 2] if n % 2 else (
                devs[n // 2 - 1] + devs[n // 2]) / 2.0
            # Scale floor: 5% of the median or 1 ms, so a perfectly
            # uniform fleet (MAD 0) still yields finite z-scores.
            scale = max(1.4826 * mad, 0.05 * median, 1.0)
            for s in sampled:
                if (s.serve_ewma_ms - median) / scale >= self.z_threshold:
                    flags.add(s.host_id)
        # In-place update: scheduling holds a direct reference to this
        # set (one truthiness check + lookup on its inner loop), so the
        # object must never be replaced.
        self._stragglers.clear()
        self._stragglers.update(flags)
        STRAGGLER_GAUGE.set(len(flags))
        return flags

    def is_straggler(self, host_id: str) -> bool:
        """Bare set lookup — called per candidate in the scheduling inner
        loop, so the recompute cadence rides the DATA paths (note_serve /
        note_piece, where a clock value is already in hand), not here."""
        return host_id in self._stragglers

    def maybe_recompute(self, now: float) -> None:
        if now - self._recomputed_at > self.recompute_s:
            self.recompute_stragglers(now)

    def zscore(self, host_id: str) -> float:
        """Robust z of this host's serve EWMA against the sampled fleet
        (report convenience; 0.0 when unscorable)."""
        sampled = [s.serve_ewma_ms for s in self._hosts.values()
                   if s.serve_samples >= self.min_serve_samples]
        s = self._hosts.get(host_id)
        if s is None or len(sampled) < self.min_population:
            return 0.0
        values = sorted(sampled)
        n = len(values)
        median = values[n // 2] if n % 2 else (
            values[n // 2 - 1] + values[n // 2]) / 2.0
        devs = sorted(abs(v - median) for v in values)
        mad = devs[n // 2] if n % 2 else (
            devs[n // 2 - 1] + devs[n // 2]) / 2.0
        scale = max(1.4826 * mad, 0.05 * median, 1.0)
        return round((s.serve_ewma_ms - median) / scale, 2)

    # -- export ------------------------------------------------------------

    def report(self, limit: int = 256) -> dict:
        now = self._clock()
        if now - self._recomputed_at > self.recompute_s:
            self.recompute_stragglers(now)
        rows = []
        for s in self._hosts.values():
            self._decay_failures(s, now)
            rows.append({
                "host": s.host_id,
                "serve_ewma_ms": round(s.serve_ewma_ms, 2),
                "serve_samples": s.serve_samples,
                "down_ewma_ms": round(s.down_ewma_ms, 2),
                "down_samples": s.down_samples,
                "phase_ewma_ms": {"dcn": round(s.dcn_ms, 2),
                                  "stall": round(s.stall_ms, 2),
                                  "store": round(s.store_ms, 2)},
                "uploads": round(s.uploads, 1),
                "failures": {r: round(v, 2) for r, v in s.failures.items()},
                "straggler": s.host_id in self._stragglers,
                "zscore": self.zscore(s.host_id),
                "idle_s": round(max(0.0, now - s.last_seen), 1),
            })
        rows.sort(key=lambda r: (-r["straggler"], -r["serve_ewma_ms"]))
        return {
            "hosts": rows[:limit],
            "hosts_tracked": len(self._hosts),
            "hosts_truncated": len(rows) > limit,
            "stragglers": sorted(self._stragglers),
        }

    def resident_bytes(self) -> int:
        return _deep_bytes(self._hosts) + _deep_bytes(self._stragglers)


# --------------------------------------------------------------------- #
# Scheduling decision audit log
# --------------------------------------------------------------------- #

class DecisionLog:
    """Bounded ring of decision tuples (one tuple per decision, the
    flight-ring discipline). Query iterates newest-first."""

    __slots__ = ("cap", "_ring", "_n", "_kind_counts")

    def __init__(self, cap: int = 1024):
        self.cap = cap
        self._ring: list = [None] * cap
        self._n = 0
        # Lifetime per-kind counts: the cluster frame builder ships
        # deltas of these (pkg/cluster), which must not scan the ring
        # and must not read the process-global prometheus counter (it
        # aggregates every service in the process).
        self._kind_counts: dict = {}

    def record(self, kind: str, *, task: str = "", host: str = "",
               peer: str = "", reason: str = "",
               chosen: "tuple | None" = None,
               rejected: "tuple | None" = None) -> None:
        self._ring[self._n % self.cap] = (
            time.time(), kind, task, host, peer, reason, chosen, rejected)
        self._n += 1
        self._kind_counts[kind] = self._kind_counts.get(kind, 0) + 1
        _decision_child(kind).inc()

    @property
    def recorded_total(self) -> int:
        return self._n

    @property
    def kind_counts(self) -> dict:
        return self._kind_counts

    def query(self, *, host: str = "", task: str = "", kind: str = "",
              limit: int = 256, since: float = 0.0,
              before: float = 0.0) -> dict:
        """Newest-first page. ``since``/``before`` are wall-clock bounds
        (half-open [since, before)); the ring is time-ordered, so
        ``since`` also terminates the scan early. ``truncated`` marks a
        page that hit ``limit`` with more matching entries behind it —
        the hard response cap that keeps this endpoint bounded at
        16k-host scale (page back with ``before=<oldest ts>``)."""
        out = []
        truncated = False
        newest = self._n - 1
        oldest = max(0, self._n - self.cap)
        i = newest
        while i >= oldest:
            e = self._ring[i % self.cap]
            i -= 1
            if e is None:
                continue
            ts, k, t, h, p, reason, chosen, rejected = e
            if since and ts < since:
                break          # ring is newest-first: nothing older matches
            if before and ts >= before:
                continue
            if kind and k != kind:
                continue
            if task and t != task:
                continue
            # A host filter matches the subject host OR a chosen/rejected
            # alternative — "why did host X (not) get parent Y".
            if host and h != host \
                    and not (chosen and host in chosen) \
                    and not (rejected and host in rejected):
                continue
            if len(out) >= limit:
                # One matching entry past the cap proves truncation; the
                # scan stops here either way.
                truncated = True
                break
            row = {"ts": round(ts, 3), "kind": k, "task": t, "host": h,
                   "peer": p, "reason": reason}
            if chosen:
                row["chosen"] = list(chosen)
            if rejected:
                row["rejected"] = list(rejected)
            out.append(row)
        return {"decisions": out, "recorded_total": self._n,
                "dropped": max(0, self._n - self.cap),
                "truncated": truncated}

    def resident_bytes(self) -> int:
        return _deep_bytes(self._ring)


# --------------------------------------------------------------------- #
# The observatory facade the service layer feeds
# --------------------------------------------------------------------- #

class FleetObservatory:
    """One instance per scheduler. The service layer calls the ``note_*``
    hooks from its existing report paths; the metrics server serves the
    read side. ``sampler`` (optional) returns the gauge dict
    ({name: value} for GAUGES) — called at bucket rotation + snapshot."""

    def __init__(self, *, bucket_s: float = 5.0, buckets: int = 720,
                 decision_cap: int = 4096, max_hosts: int = 1024,
                 straggler_z: float = 3.0, min_serve_samples: int = 8,
                 min_population: int = 8, sampler=None,
                 config_snapshot: "dict | None" = None):
        self.series = FleetTimeSeries(bucket_s, buckets, sampler=sampler)
        self.scorecards = HostScorecards(
            max_hosts=max_hosts, z_threshold=straggler_z,
            min_serve_samples=min_serve_samples,
            min_population=min_population)
        self.decisions = DecisionLog(decision_cap)
        self._sampler = sampler
        self.started_wall = time.time()
        self.config_snapshot = dict(config_snapshot or {})

    # -- service-layer hooks ----------------------------------------------

    def note_announce(self) -> None:
        self.series.inc(C_ANNOUNCES)

    def note_register(self, reconnect: bool = False) -> None:
        self.series.inc(C_RECONNECTS if reconnect else C_REGISTERS)

    def note_piece(self, host_id: str, locality_col: int, nbytes: float,
                   cost_ms: float, parent_host: "str | None" = None,
                   timings: "dict | None" = None) -> None:
        """Single piece-report feed — the scheduler's per-event hot path
        (``piece_finished``). Deliberately INLINED (no sub-calls beyond
        one clock read and the rare rotate/evict): fleet_bench pins the
        paired overhead of exactly this path."""
        s = self.series
        now = s._clock()
        b = int(now / s.bucket_s)
        if b != s._cur:
            s._rotate(b)
        row = s._counts[b % s.n_buckets]
        row[C_PIECES] += 1.0
        row[locality_col] += nbytes
        sc = self.scorecards
        h = sc._hosts.get(host_id)
        if h is None:
            h = sc._score(host_id, now)
        h.last_seen = now
        a = sc.alpha
        if h.down_samples == 0:
            h.down_ewma_ms = cost_ms + 0.0
        else:
            h.down_ewma_ms += a * (cost_ms - h.down_ewma_ms)
        h.down_samples += 1
        if timings:
            d = 1.0 - a
            h.dcn_ms = d * h.dcn_ms + a * (timings.get("dcn_ms") or 0)
            h.stall_ms = d * h.stall_ms + a * (timings.get("stall_ms") or 0)
            h.store_ms = d * h.store_ms + a * (timings.get("store_ms") or 0)
        if parent_host is not None:
            p = sc._hosts.get(parent_host)
            if p is None:
                p = sc._score(parent_host, now)
            p.last_seen = now
            if p.serve_samples == 0:
                p.serve_ewma_ms = cost_ms + 0.0
            else:
                p.serve_ewma_ms += a * (cost_ms - p.serve_ewma_ms)
            p.serve_samples += 1
            p.uploads += 1.0
            p.serve_stamp = now
            # Straggler recompute cadence rides the serve feed only (the
            # flag is ABOUT serve EWMAs; pieces without a parent can't
            # change it and shouldn't pay the check).
            if now - sc._recomputed_at > sc.recompute_s:
                sc.recompute_stragglers(now)

    def note_pieces(self, host_id: str, n: int, cost_ms_total: float,
                    by_parent: "dict | None" = None,
                    timings: "dict | None" = None) -> None:
        """Batch feed from a coalesced ``pieces_finished`` report: ``n``
        pieces landed by ``host_id``. ``by_parent`` maps parent host id
        ('' = unattributed) -> [count, cost_ms_sum, bytes, locality_col];
        one serve-EWMA step per DISTINCT parent, not per piece."""
        s = self.series
        now = s._clock()
        b = int(now / s.bucket_s)
        if b != s._cur:
            s._rotate(b)
        row = s._counts[b % s.n_buckets]
        row[C_PIECES] += n
        sc = self.scorecards
        a = sc.alpha
        if n:
            h = sc._hosts.get(host_id)
            if h is None:
                h = sc._score(host_id, now)
            h.last_seen = now
            mean = cost_ms_total / n
            if h.down_samples == 0:
                h.down_ewma_ms = mean
            else:
                h.down_ewma_ms += a * (mean - h.down_ewma_ms)
            h.down_samples += n
            if timings:
                d = 1.0 - a
                h.dcn_ms = d * h.dcn_ms + a * (timings.get("dcn_ms") or 0)
                h.stall_ms = d * h.stall_ms + a * (
                    timings.get("stall_ms") or 0)
                h.store_ms = d * h.store_ms + a * (
                    timings.get("store_ms") or 0)
        if by_parent:
            for parent_host, agg in by_parent.items():
                k, cost_sum, nbytes, col = agg
                row[col] += nbytes
                if parent_host:
                    p = sc._hosts.get(parent_host)
                    if p is None:
                        p = sc._score(parent_host, now)
                    p.last_seen = now
                    mean = cost_sum / k
                    if p.serve_samples == 0:
                        p.serve_ewma_ms = mean
                    else:
                        # Batch-equivalent EWMA step: effective alpha
                        # 1-(1-a)^k, so k coalesced reports move the
                        # estimate as far as k singles at the same value.
                        ak = a if k == 1 else 1.0 - (1.0 - a) ** k
                        p.serve_ewma_ms += ak * (mean - p.serve_ewma_ms)
                    p.serve_samples += k
                    p.uploads += k
                    p.serve_stamp = now
            if now - sc._recomputed_at > sc.recompute_s:
                sc.recompute_stragglers(now)

    def note_piece_failed(self, parent_host: str, reason: str) -> None:
        self.series.inc(failed_col(reason))
        if parent_host:
            self.scorecards.note_failure(parent_host, reason)

    def note_quarantine(self, task: str, host: str, reason: str,
                        reporter: str = "") -> None:
        self.series.inc(C_QUARANTINES)
        self.decisions.record("quarantine", task=task, host=host,
                              peer=reporter, reason=reason)

    def note_back_source(self, task: str, peer: str, host: str,
                         reason: str) -> None:
        self.series.inc(C_BACK_SOURCE)
        self.decisions.record("back_source", task=task, host=host,
                              peer=peer, reason=reason)

    def note_handout(self, task: str, peer: str, host: str,
                     chosen: tuple, rejected: tuple) -> None:
        self.series.inc(C_HANDOUTS)
        self.decisions.record("handout", task=task, host=host, peer=peer,
                              chosen=chosen, rejected=rejected)

    def note_stripe(self, task: str, peer: str, host: str,
                    reshuffle: bool) -> None:
        if reshuffle:
            self.series.inc(C_STRIPE_RESHUFFLES)
            self.decisions.record("stripe_reshuffle", task=task, host=host,
                                  peer=peer)
        else:
            self.series.inc(C_STRIPE_HANDOUTS)
            self.decisions.record("stripe_handout", task=task, host=host,
                                  peer=peer)

    def note_straggler_filter(self, task: str, peer: str,
                              host: str) -> None:
        self.decisions.record(
            "straggler_filter", task=task, host=host, peer=peer,
            reason="fleet scorecard flags this host as a straggler "
                   "(slow serve EWMA, robust z >= threshold)")

    def note_schedule_failed(self, task: str, peer: str, host: str,
                             reason: str) -> None:
        self.decisions.record("schedule_failed", task=task, host=host,
                              peer=peer, reason=reason)

    # -- tenant QoS plane (dragonfly2_tpu/qos) ----------------------------

    def note_admission(self, tenant: str, *, decision: str,
                       burn: float = 0.0, retry_after_s: float = 0.0,
                       source: str = "") -> None:
        """QoS admission verdict with the TENANT as subject (the ``host``
        column — decision queries filter on it like any host id)."""
        self.decisions.record(
            "admission", host=tenant, peer=source,
            reason=f"{decision} (burn={burn:.2f}"
                   + (f", retry_after={retry_after_s:.1f}s" if retry_after_s
                      else "") + ")")

    def note_throttle(self, tenant: str, *, task_id: str = "",
                      host_id: str = "", reason: str = "",
                      limit: int = 0) -> None:
        """QoS handout deprioritization of a budget-burning tenant."""
        self.decisions.record(
            "throttle", task=task_id, host=tenant, peer=host_id,
            reason=reason + (f" (candidate_limit={limit})" if limit else ""))

    # -- read side ---------------------------------------------------------

    def snapshot(self, window_s: float = 600.0) -> dict:
        gauges_now = {}
        if self._sampler is not None:
            try:
                gauges_now = dict(self._sampler() or {})
            except Exception:
                gauges_now = {}
        return {
            "uptime_s": round(time.time() - self.started_wall, 1),
            "window_s": window_s,
            "now": gauges_now,
            "series": self.series.window(window_s),
            "decisions_total": self.decisions.recorded_total,
            "resident_bytes": self.resident_bytes(),
        }

    def hosts_report(self, limit: int = 256) -> dict:
        return self.scorecards.report(limit)

    def info(self) -> dict:
        from dragonfly2_tpu import __version__

        return {
            "component": "scheduler",
            "version": __version__,
            "python": sys.version.split()[0],
            "started_wall": round(self.started_wall, 3),
            "uptime_s": round(time.time() - self.started_wall, 1),
            "config": self.config_snapshot,
            "bounds": {
                "timeseries_buckets": self.series.n_buckets,
                "timeseries_bucket_s": self.series.bucket_s,
                "scorecard_max_hosts": self.scorecards.max_hosts,
                "decision_cap": self.decisions.cap,
            },
            "resident_bytes": self.resident_bytes(),
        }

    def resident_bytes(self) -> int:
        return (self.series.resident_bytes()
                + self.scorecards.resident_bytes()
                + self.decisions.resident_bytes())
