"""Distributed tracing: spans, context propagation, JSONL + OTLP export.

Reference: OpenTelemetry with a Jaeger exporter wired per binary
(cmd/dependency/dependency.go:263-271, --jaeger flag :73) and gRPC/gin
auto-instrumentation (otelgrpc stats handlers, scheduler/scheduler.go:95).
This is the dependency-free analog: W3C-traceparent-style context that
rides drpc frame metadata (daemon → scheduler → seed peer), contextvar
scoping, a JSONL exporter (DF_TRACE_FILE) any trace UI can ingest, and an
OTLP/HTTP JSON exporter (DF_TRACE_OTLP_ENDPOINT, e.g.
``http://collector:4318``) so spans land in any standard collector —
Jaeger, Tempo, the otel-collector — closing the observability interop the
reference gets from its otel SDK, without taking the dependency.
"""

from __future__ import annotations

import contextvars
import json
import os
import queue as _queue
import secrets
import threading
import time
import urllib.request
from contextlib import contextmanager
from dataclasses import dataclass, field

from dragonfly2_tpu.pkg import metrics as _metrics

# Exporter health on the standard scrape surface: silent span loss
# (queue-full, unreachable collector) must be visible in /metrics, not
# only on the exporter object.
OTLP_SPANS = _metrics.counter(
    "tracing_otlp_spans_total",
    "OTLP span export outcomes (sent = landed in the collector, "
    "dropped = queue overflow / unreachable collector / closed exporter)",
    ("result",))

_current: contextvars.ContextVar["SpanContext | None"] = contextvars.ContextVar(
    "df_trace_ctx", default=None)

TRACEPARENT = "traceparent"


@dataclass(frozen=True)
class SpanContext:
    trace_id: str     # 32 hex
    span_id: str      # 16 hex

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, value: str) -> "SpanContext | None":
        parts = value.split("-")
        if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        return cls(trace_id=parts[1], span_id=parts[2])


@dataclass
class Span:
    name: str
    context: SpanContext
    parent_id: str = ""
    start: float = field(default_factory=time.time)
    end: float = 0.0
    attrs: dict = field(default_factory=dict)
    status: str = "ok"
    # Monotonic anchor: duration derives from perf_counter, never from two
    # wall-clock reads — an NTP step mid-span must not produce negative or
    # garbage durations. ``start`` stays wall clock for export anchoring;
    # ``end`` is reconstructed as start + monotonic duration so exported
    # timestamps and duration_ms can never disagree.
    start_pc: float = field(default_factory=time.perf_counter)
    duration_s: float = 0.0

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def finish(self, status: str = "") -> None:
        self.duration_s = max(0.0, time.perf_counter() - self.start_pc)
        self.end = self.start + self.duration_s
        if status:
            self.status = status
        _EXPORTER.export(self)

    def to_json(self) -> dict:
        return {"name": self.name, "trace_id": self.context.trace_id,
                "span_id": self.context.span_id, "parent_id": self.parent_id,
                "start": self.start, "end": self.end,
                "duration_ms": round(self.duration_s * 1000, 3),
                "attrs": self.attrs, "status": self.status}


def _otlp_attr_value(value) -> dict:
    """Map a python attr to an OTLP AnyValue (proto3 JSON form)."""
    if isinstance(value, bool):
        return {"boolValue": value}
    if isinstance(value, int):
        return {"intValue": str(value)}   # int64 rides as a JSON string
    if isinstance(value, float):
        return {"doubleValue": value}
    return {"stringValue": str(value)}


def otlp_payload(spans: "list[Span]", service_name: str) -> dict:
    """OTLP/JSON ExportTraceServiceRequest for ``spans``. The OTLP JSON
    mapping special-cases trace/span ids as HEX strings (not the generic
    proto3 base64-bytes rule), status code 1=OK 2=ERROR, and int64s as
    decimal strings."""
    return {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": service_name}}]},
        "scopeSpans": [{
            "scope": {"name": "dragonfly2_tpu.pkg.tracing"},
            "spans": [{
                "traceId": s.context.trace_id,
                "spanId": s.context.span_id,
                **({"parentSpanId": s.parent_id} if s.parent_id else {}),
                "name": s.name,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(int(s.start * 1e9)),
                "endTimeUnixNano": str(int(s.end * 1e9)),
                "attributes": [{"key": k, "value": _otlp_attr_value(v)}
                               for k, v in s.attrs.items()],
                "status": {"code": 1 if s.status == "ok" else 2,
                           **({} if s.status == "ok"
                              else {"message": s.status})},
            } for s in spans],
        }],
    }]}


class OTLPExporter:
    """Background OTLP/HTTP JSON push to ``{endpoint}/v1/traces``.

    Dependency-free (urllib on a daemon thread), never blocks the traced
    code path: finished spans enqueue; the worker batches up to
    ``batch_max`` per POST and drops on the floor when the collector is
    unreachable (tracing must never become a data-plane liability).
    """

    def __init__(self, endpoint: str, *, service_name: str = "",
                 flush_interval: float = 1.0, batch_max: int = 256,
                 timeout: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = (service_name
                             or os.environ.get("DF_SERVICE_NAME", "")
                             or "dragonfly2-tpu")
        self.flush_interval = flush_interval
        self.batch_max = batch_max
        self.timeout = timeout
        self.sent_spans = 0
        self.dropped_spans = 0
        self._q: _queue.Queue = _queue.Queue(maxsize=8192)
        self._stop = threading.Event()
        # In-flight accounting: a span is "unfinished" from enqueue until
        # its POST attempt completes (task_done in _run). flush() waits on
        # this, not on queue-emptiness — the queue empties the moment the
        # worker POPS a batch, while the POST for it can run another 5 s.
        self._done_cv = threading.Condition()
        self._unfinished = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="df-otlp-export")
        self._thread.start()

    def enqueue(self, span: "Span") -> None:
        if self._stop.is_set():
            self._drop(1)   # closed: no worker will ever post it
            return
        # Count BEFORE the put: the worker may pop and task_done between a
        # put and a later increment, driving the counter negative and
        # letting a concurrent flush() return while a span it should wait
        # for is still in flight.
        with self._done_cv:
            self._unfinished += 1
        try:
            self._q.put_nowait(span)
        except _queue.Full:
            self._drop(1)
            self._task_done(1)

    def _task_done(self, n: int) -> None:
        with self._done_cv:
            self._unfinished -= n
            self._done_cv.notify_all()

    def _drop(self, n: int) -> None:
        self.dropped_spans += n
        OTLP_SPANS.labels("dropped").inc(n)

    def _drain_batch(self) -> "list[Span]":
        batch: list[Span] = []
        try:
            batch.append(self._q.get(timeout=self.flush_interval))
            while len(batch) < self.batch_max:
                batch.append(self._q.get_nowait())
        except _queue.Empty:
            pass
        return batch

    def _post(self, batch: "list[Span]") -> None:
        body = json.dumps(
            otlp_payload(batch, self.service_name)).encode()
        req = urllib.request.Request(
            f"{self.endpoint}/v1/traces", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                self.sent_spans += len(batch)
                OTLP_SPANS.labels("sent").inc(len(batch))
        except OSError:
            self._drop(len(batch))

    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self._drain_batch()
            if batch:
                try:
                    self._post(batch)
                except Exception:
                    # The contract is "drop on the floor", never die: a
                    # malformed endpoint (ValueError from urllib) must not
                    # kill the worker and silently wedge export forever.
                    self._drop(len(batch))
                finally:
                    self._task_done(len(batch))
        # Stop raced a final enqueue: whatever is still queued will never
        # post — account it as dropped so no flush() waits forever.
        tail = 0
        while True:
            try:
                self._q.get_nowait()
                tail += 1
            except _queue.Empty:
                break
        if tail:
            self._drop(tail)
            self._task_done(tail)

    def flush(self, timeout: float = 5.0) -> None:
        """Wait until every span enqueued so far has finished its POST
        attempt (sent or dropped), up to ``timeout`` — queue-empty alone is
        not done: the worker pops a batch before POSTing it, and that POST
        can hold the final batch in flight for seconds (tests, shutdown,
        set_otlp re-point)."""
        deadline = time.monotonic() + timeout
        with self._done_cv:
            while self._unfinished > 0:
                left = deadline - time.monotonic()
                if left <= 0 or not self._done_cv.wait(timeout=min(left, 0.1)):
                    if time.monotonic() >= deadline:
                        return

    def close(self) -> None:
        self.flush(timeout=2.0)
        self._stop.set()
        # Join the worker: it wakes within flush_interval (the blocking
        # get's timeout) and exits; a close() that returns while the
        # thread still runs could post after the process tears down the
        # endpoint (or interleave with a re-pointed exporter).
        self._thread.join(timeout=self.flush_interval + self.timeout + 1.0)


class Exporter:
    """Ring buffer + optional JSONL file (DF_TRACE_FILE or set_file()) +
    optional OTLP/HTTP push (DF_TRACE_OTLP_ENDPOINT or set_otlp())."""

    _OTLP_UNSET = object()   # distinct from None: None = explicitly disabled

    def __init__(self, capacity: int = 2048):
        self.capacity = capacity
        self.spans: list[Span] = []
        self._path = os.environ.get("DF_TRACE_FILE", "")
        self._otlp = Exporter._OTLP_UNSET

    def set_file(self, path: str) -> None:
        self._path = path

    def set_otlp(self, endpoint: str, **kwargs) -> "OTLPExporter | None":
        """Enable (or re-point) the OTLP push; empty endpoint disables —
        and STAYS disabled even when DF_TRACE_OTLP_ENDPOINT is set (the
        explicit call outranks the env default)."""
        if isinstance(self._otlp, OTLPExporter):
            self._otlp.close()
        self._otlp = OTLPExporter(endpoint, **kwargs) if endpoint else None
        return self._otlp

    @property
    def otlp(self) -> "OTLPExporter | None":
        if self._otlp is Exporter._OTLP_UNSET:
            endpoint = os.environ.get("DF_TRACE_OTLP_ENDPOINT", "")
            self._otlp = OTLPExporter(endpoint) if endpoint else None
        return self._otlp

    def export(self, span: Span) -> None:
        self.spans.append(span)
        if len(self.spans) > self.capacity:
            del self.spans[: len(self.spans) - self.capacity]
        path = self._path or os.environ.get("DF_TRACE_FILE", "")
        if path:
            try:
                with open(path, "a") as f:
                    f.write(json.dumps(span.to_json()) + "\n")
            except OSError:
                pass
        otlp = self.otlp
        if otlp is not None:
            otlp.enqueue(span)

    def find(self, name: str = "", trace_id: str = "") -> list[Span]:
        return [s for s in self.spans
                if (not name or s.name == name)
                and (not trace_id or s.context.trace_id == trace_id)]

    def clear(self) -> None:
        self.spans.clear()


_EXPORTER = Exporter()


def exporter() -> Exporter:
    return _EXPORTER


def current() -> SpanContext | None:
    return _current.get()


@contextmanager
def span(name: str, **attrs):
    """Start a child of the current context (or a new root), scoped to the
    block. The span exports on exit; exceptions mark status=error."""
    parent = _current.get()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8))
    sp = Span(name=name, context=ctx,
              parent_id=parent.span_id if parent else "", attrs=dict(attrs))
    token = _current.set(ctx)
    try:
        yield sp
    except BaseException:
        sp.finish("error")
        raise
    else:
        sp.finish()
    finally:
        _current.reset(token)


def inject() -> dict:
    """Outgoing metadata for the current context ({} when not tracing)."""
    ctx = _current.get()
    return {TRACEPARENT: ctx.to_traceparent()} if ctx else {}


@contextmanager
def extract(metadata: dict | None, name: str, **attrs):
    """Server side: adopt the caller's context from frame metadata and run
    the handler inside a span (otelgrpc stats-handler analog)."""
    ctx = None
    if metadata and TRACEPARENT in metadata:
        ctx = SpanContext.from_traceparent(metadata[TRACEPARENT])
    token = _current.set(ctx) if ctx is not None else None
    try:
        with span(name, **attrs) as sp:
            yield sp
    finally:
        if token is not None:
            _current.reset(token)
