"""Distributed tracing: spans, context propagation, JSONL export.

Reference: OpenTelemetry with a Jaeger exporter wired per binary
(cmd/dependency/dependency.go:263-271, --jaeger flag :73) and gRPC/gin
auto-instrumentation (otelgrpc stats handlers, scheduler/scheduler.go:95).
This is the dependency-free analog: W3C-traceparent-style context that
rides drpc frame metadata (daemon → scheduler → seed peer), contextvar
scoping, and a JSONL exporter (DF_TRACE_FILE) any trace UI can ingest.
"""

from __future__ import annotations

import contextvars
import json
import os
import secrets
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

_current: contextvars.ContextVar["SpanContext | None"] = contextvars.ContextVar(
    "df_trace_ctx", default=None)

TRACEPARENT = "traceparent"


@dataclass(frozen=True)
class SpanContext:
    trace_id: str     # 32 hex
    span_id: str      # 16 hex

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, value: str) -> "SpanContext | None":
        parts = value.split("-")
        if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        return cls(trace_id=parts[1], span_id=parts[2])


@dataclass
class Span:
    name: str
    context: SpanContext
    parent_id: str = ""
    start: float = field(default_factory=time.time)
    end: float = 0.0
    attrs: dict = field(default_factory=dict)
    status: str = "ok"

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def finish(self, status: str = "") -> None:
        self.end = time.time()
        if status:
            self.status = status
        _EXPORTER.export(self)

    def to_json(self) -> dict:
        return {"name": self.name, "trace_id": self.context.trace_id,
                "span_id": self.context.span_id, "parent_id": self.parent_id,
                "start": self.start, "end": self.end,
                "duration_ms": round((self.end - self.start) * 1000, 3),
                "attrs": self.attrs, "status": self.status}


class Exporter:
    """Ring buffer + optional JSONL file (DF_TRACE_FILE or set_file())."""

    def __init__(self, capacity: int = 2048):
        self.capacity = capacity
        self.spans: list[Span] = []
        self._path = os.environ.get("DF_TRACE_FILE", "")

    def set_file(self, path: str) -> None:
        self._path = path

    def export(self, span: Span) -> None:
        self.spans.append(span)
        if len(self.spans) > self.capacity:
            del self.spans[: len(self.spans) - self.capacity]
        path = self._path or os.environ.get("DF_TRACE_FILE", "")
        if path:
            try:
                with open(path, "a") as f:
                    f.write(json.dumps(span.to_json()) + "\n")
            except OSError:
                pass

    def find(self, name: str = "", trace_id: str = "") -> list[Span]:
        return [s for s in self.spans
                if (not name or s.name == name)
                and (not trace_id or s.context.trace_id == trace_id)]

    def clear(self) -> None:
        self.spans.clear()


_EXPORTER = Exporter()


def exporter() -> Exporter:
    return _EXPORTER


def current() -> SpanContext | None:
    return _current.get()


@contextmanager
def span(name: str, **attrs):
    """Start a child of the current context (or a new root), scoped to the
    block. The span exports on exit; exceptions mark status=error."""
    parent = _current.get()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8))
    sp = Span(name=name, context=ctx,
              parent_id=parent.span_id if parent else "", attrs=dict(attrs))
    token = _current.set(ctx)
    try:
        yield sp
    except BaseException:
        sp.finish("error")
        raise
    else:
        sp.finish()
    finally:
        _current.reset(token)


def inject() -> dict:
    """Outgoing metadata for the current context ({} when not tracing)."""
    ctx = _current.get()
    return {TRACEPARENT: ctx.to_traceparent()} if ctx else {}


@contextmanager
def extract(metadata: dict | None, name: str, **attrs):
    """Server side: adopt the caller's context from frame metadata and run
    the handler inside a span (otelgrpc stats-handler analog)."""
    ctx = None
    if metadata and TRACEPARENT in metadata:
        ctx = SpanContext.from_traceparent(metadata[TRACEPARENT])
    token = _current.set(ctx) if ctx is not None else None
    try:
        with span(name, **attrs) as sp:
            yield sp
    finally:
        if token is not None:
            _current.reset(token)
