"""Filesystem object-storage backend.

Bucket = directory, object = file, user metadata = sidecar JSON. Serves
hermetic tests and shared-filesystem deployments (NFS-mounted checkpoint
dirs on a TPU pod); its object_url is a file:// URL so P2P back-to-source
rides the file source client. The reference has no analog (its backends are
all remote SDKs) — this fills the "local" slot our CI needs.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import AsyncIterator
from urllib.parse import quote

from dragonfly2_tpu.pkg.objectstorage.base import (
    BucketMetadata,
    ObjectMetadata,
    ObjectStorage,
    ObjectStorageError,
)

_META_SUFFIX = ".dfmeta"


class FSObjectStorage(ObjectStorage):
    name = "fs"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _bucket_dir(self, bucket: str) -> str:
        if not bucket or "/" in bucket or bucket.startswith("."):
            raise ObjectStorageError(f"invalid bucket name {bucket!r}")
        return os.path.join(self.root, bucket)

    def _object_path(self, bucket: str, key: str) -> str:
        d = self._bucket_dir(bucket)
        norm = os.path.normpath(key)
        if norm.startswith("..") or os.path.isabs(norm):
            raise ObjectStorageError(f"invalid object key {key!r}")
        return os.path.join(d, norm)

    # -- buckets -----------------------------------------------------------

    async def get_bucket_metadata(self, bucket: str) -> BucketMetadata:
        d = self._bucket_dir(bucket)
        if not os.path.isdir(d):
            raise ObjectStorageError(f"bucket {bucket!r} not found")
        return BucketMetadata(name=bucket, created_at=os.path.getctime(d))

    async def create_bucket(self, bucket: str) -> None:
        os.makedirs(self._bucket_dir(bucket), exist_ok=True)

    async def delete_bucket(self, bucket: str) -> None:
        d = self._bucket_dir(bucket)
        if not os.path.isdir(d):
            raise ObjectStorageError(f"bucket {bucket!r} not found")
        shutil.rmtree(d)

    async def list_buckets(self) -> list[BucketMetadata]:
        out = []
        for name in sorted(os.listdir(self.root)):
            d = os.path.join(self.root, name)
            if os.path.isdir(d):
                out.append(BucketMetadata(name=name, created_at=os.path.getctime(d)))
        return out

    # -- objects -----------------------------------------------------------

    def _load_meta(self, path: str) -> dict:
        try:
            with open(path + _META_SUFFIX) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    async def get_object_metadata(self, bucket: str, key: str) -> ObjectMetadata:
        path = self._object_path(bucket, key)
        if not os.path.isfile(path):
            raise ObjectStorageError(f"object {bucket}/{key} not found")
        side = self._load_meta(path)
        st = os.stat(path)
        return ObjectMetadata(
            key=key, content_length=st.st_size,
            content_type=side.get("content_type", ""),
            etag=side.get("etag", ""), digest=side.get("digest", ""),
            last_modified=st.st_mtime, user_metadata=side.get("user_metadata", {}))

    async def get_object(self, bucket: str, key: str,
                         range_start: int = -1, range_end: int = -1) -> AsyncIterator[bytes]:
        path = self._object_path(bucket, key)
        if not os.path.isfile(path):
            raise ObjectStorageError(f"object {bucket}/{key} not found")

        async def chunks() -> AsyncIterator[bytes]:
            with open(path, "rb") as f:
                if range_start >= 0:
                    f.seek(range_start)
                remaining = (range_end - range_start + 1) if range_end >= 0 else -1
                while True:
                    n = 1 << 20 if remaining < 0 else min(1 << 20, remaining)
                    if n == 0:
                        break
                    data = f.read(n)
                    if not data:
                        break
                    if remaining > 0:
                        remaining -= len(data)
                    yield data

        return chunks()

    async def put_object(self, bucket: str, key: str, data,
                         *, digest: str = "", content_type: str = "") -> None:
        path = self._object_path(bucket, key)
        if not os.path.isdir(self._bucket_dir(bucket)):
            raise ObjectStorageError(f"bucket {bucket!r} not found")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            if isinstance(data, (bytes, bytearray)):
                f.write(data)
            else:
                shutil.copyfileobj(data, f, 1 << 20)
        os.replace(tmp, path)
        with open(path + _META_SUFFIX, "w") as f:
            json.dump({"digest": digest, "content_type": content_type,
                       "etag": f"{int(time.time() * 1e6):x}",
                       "user_metadata": {}}, f)

    async def delete_object(self, bucket: str, key: str) -> None:
        path = self._object_path(bucket, key)
        if os.path.isfile(path):
            os.unlink(path)
        if os.path.isfile(path + _META_SUFFIX):
            os.unlink(path + _META_SUFFIX)

    async def list_object_metadatas(self, bucket: str, prefix: str = "",
                                    marker: str = "", limit: int = 1000) -> list[ObjectMetadata]:
        d = self._bucket_dir(bucket)
        if not os.path.isdir(d):
            raise ObjectStorageError(f"bucket {bucket!r} not found")
        keys = []
        for base, _, files in os.walk(d):
            for fn in files:
                if fn.endswith(_META_SUFFIX) or fn.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(base, fn), d)
                if rel.startswith(prefix) and rel > marker:
                    keys.append(rel)
        out = []
        for key in sorted(keys)[:limit]:
            out.append(await self.get_object_metadata(bucket, key))
        return out

    def object_url(self, bucket: str, key: str) -> str:
        return "file://" + quote(self._object_path(bucket, key))
