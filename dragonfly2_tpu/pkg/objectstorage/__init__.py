"""Object-storage backend clients behind one interface.

Reference: pkg/objectstorage/objectstorage.go — the ``ObjectStorage`` iface
(:93) with S3 (s3.go), Aliyun OSS (oss.go) and Huawei OBS (obs.go)
implementations keyed by name (:179 New). The reference has **no GCS
client**; for the TPU target GCS is primary (SURVEY.md §5), and a
filesystem backend serves hermetic tests and shared-NFS pod deployments.

Backends also expose ``object_url`` — the origin URL a P2P task uses to
back-to-source the object, which is how the daemon gateway turns object
GETs into ordinary P2P stream tasks.
"""

from __future__ import annotations

from dragonfly2_tpu.pkg.objectstorage.base import (
    BucketMetadata,
    ObjectMetadata,
    ObjectStorage,
    ObjectStorageError,
)


def new_client(name: str, **kwargs) -> ObjectStorage:
    """Construct a backend by name (reference objectstorage.go:179 New):
    fs | s3 | gcs | oss | obs."""
    if name == "fs":
        from dragonfly2_tpu.pkg.objectstorage.fs import FSObjectStorage

        return FSObjectStorage(**kwargs)
    if name == "s3":
        from dragonfly2_tpu.pkg.objectstorage.s3 import S3ObjectStorage

        return S3ObjectStorage(**kwargs)
    if name == "gcs":
        from dragonfly2_tpu.pkg.objectstorage.gcs import GCSObjectStorage

        return GCSObjectStorage(**kwargs)
    if name == "oss":
        # Native vendor auth (HMAC-SHA1 headers). An OSS bucket reached
        # through its S3-COMPATIBLE endpoint should use backend "s3"
        # (SigV4) instead — the two schemes are not interchangeable.
        from dragonfly2_tpu.pkg.objectstorage.oss import OSSObjectStorage

        return OSSObjectStorage(**kwargs)
    if name == "obs":
        from dragonfly2_tpu.pkg.objectstorage.obs import OBSObjectStorage

        return OBSObjectStorage(**kwargs)
    raise ObjectStorageError(f"unknown object storage backend {name!r}")


__all__ = [
    "BucketMetadata",
    "ObjectMetadata",
    "ObjectStorage",
    "ObjectStorageError",
    "new_client",
]
