"""Aliyun OSS object-storage backend (native header auth over aiohttp).

Reference: pkg/objectstorage/oss.go (265 LoC over aliyun-oss-go-sdk). OSS
buckets configured for the vendor's native auth sign requests with the
classic HMAC-SHA1 header scheme::

    Authorization: OSS {AccessKeyId}:{base64(hmac-sha1(secret, StringToSign))}
    StringToSign  = VERB \n Content-MD5 \n Content-Type \n Date \n
                    CanonicalizedOSSHeaders CanonicalizedResource

(the S3-compatible endpoint is covered by the SigV4 client in s3.py; this
client exists for deployments whose credentials/endpoints only speak the
native scheme — the same reason the reference carries oss.go at all).
Huawei OBS uses the identical construction with its own prefixes; obs.py
subclasses this with the constants swapped.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
import xml.etree.ElementTree as ET
from email.utils import formatdate
from typing import AsyncIterator
from urllib.parse import quote

import aiohttp

from dragonfly2_tpu.pkg.objectstorage.base import (
    BucketMetadata,
    ObjectMetadata,
    ObjectStorage,
    ObjectStorageError,
)
from dragonfly2_tpu.pkg.objectstorage.s3 import _as_body


class OSSObjectStorage(ObjectStorage):
    name = "oss"
    AUTH_SCHEME = "OSS"            # Authorization header scheme tag
    HEADER_PREFIX = "x-oss-"       # canonicalized vendor-header prefix
    # Query param carrying the STS token on URL-auth presigns: Aliyun
    # expects the bare name; Huawei expects the prefixed one (obs.py).
    PRESIGN_TOKEN_PARAM = "security-token"

    def __init__(self, *, endpoint: str, access_key: str = "",
                 secret_key: str = "", security_token: str = "",
                 region: str = ""):
        # ``region`` is accepted (and unused — the native scheme does not
        # scope signatures by region) so configs written for the previous
        # oss/obs→SigV4 aliasing keep constructing; S3-COMPATIBLE vendor
        # endpoints should set backend "s3" explicitly.
        del region
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.security_token = security_token
        self._session: aiohttp.ClientSession | None = None

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    # -- signing -----------------------------------------------------------

    def _string_to_sign(self, method: str, headers: dict,
                        resource: str) -> str:
        vendor = sorted((k.lower(), v.strip()) for k, v in headers.items()
                        if k.lower().startswith(self.HEADER_PREFIX))
        return "\n".join([
            method,
            headers.get("Content-MD5", ""),
            headers.get("Content-Type", ""),
            headers.get("Date", ""),
        ]) + "\n" + "".join(f"{k}:{v}\n" for k, v in vendor) + resource

    def _signature(self, to_sign: str) -> str:
        return base64.b64encode(
            hmac.new(self.secret_key.encode(), to_sign.encode(),
                     hashlib.sha1).digest()).decode()

    async def _request(self, method: str, path: str, *, query: str = "",
                       data=b"", extra_headers: dict | None = None,
                       ok=(200, 204)) -> aiohttp.ClientResponse:
        headers = {"Date": formatdate(usegmt=True)}
        if self.security_token:
            headers[f"{self.HEADER_PREFIX}security-token"] = self.security_token
        headers.update(extra_headers or {})
        if method in ("PUT", "POST") and "Content-Type" not in headers:
            # Pin what aiohttp would otherwise inject AFTER signing: the
            # vendor verifies the on-the-wire Content-Type, so the signed
            # value must be the sent value.
            headers["Content-Type"] = "application/octet-stream"
        if self.access_key:
            sig = self._signature(
                self._string_to_sign(method, headers, path))
            headers["Authorization"] = \
                f"{self.AUTH_SCHEME} {self.access_key}:{sig}"
        url = self.endpoint + quote(path) + (f"?{query}" if query else "")
        resp = await self._http().request(method, url, data=_as_body(data),
                                          headers=headers)
        if resp.status not in ok:
            body = (await resp.text())[:300]
            resp.release()
            raise ObjectStorageError(
                f"{self.name} {method} {path}: HTTP {resp.status} {body}")
        return resp

    # -- buckets -----------------------------------------------------------

    async def get_bucket_metadata(self, bucket: str) -> BucketMetadata:
        resp = await self._request("HEAD", f"/{bucket}")
        resp.release()
        return BucketMetadata(name=bucket)

    async def create_bucket(self, bucket: str) -> None:
        (await self._request("PUT", f"/{bucket}")).release()

    async def delete_bucket(self, bucket: str) -> None:
        (await self._request("DELETE", f"/{bucket}")).release()

    async def list_buckets(self) -> list[BucketMetadata]:
        resp = await self._request("GET", "/")
        text = await resp.text()
        root = ET.fromstring(text)
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        return [BucketMetadata(name=b.findtext(f"{ns}Name", ""))
                for b in root.iter(f"{ns}Bucket")]

    # -- objects -----------------------------------------------------------

    def _meta_key(self, name: str) -> str:
        return f"{self.HEADER_PREFIX}meta-{name}"

    async def get_object_metadata(self, bucket: str, key: str) -> ObjectMetadata:
        resp = await self._request("HEAD", f"/{bucket}/{key}")
        h = resp.headers
        resp.release()
        return ObjectMetadata(
            key=key,
            content_length=int(h.get("Content-Length", -1)),
            content_type=h.get("Content-Type", ""),
            etag=h.get("ETag", "").strip('"'),
            digest=h.get(self._meta_key("digest"), ""))

    async def get_object(self, bucket: str, key: str,
                         range_start: int = -1,
                         range_end: int = -1) -> AsyncIterator[bytes]:
        extra = {}
        if range_start >= 0:
            end = str(range_end) if range_end >= 0 else ""
            extra["Range"] = f"bytes={range_start}-{end}"
        resp = await self._request("GET", f"/{bucket}/{key}",
                                   extra_headers=extra, ok=(200, 206))

        async def chunks() -> AsyncIterator[bytes]:
            try:
                async for chunk in resp.content.iter_chunked(1 << 20):
                    yield chunk
            finally:
                resp.release()

        return chunks()

    async def put_object(self, bucket: str, key: str, data,
                         *, digest: str = "", content_type: str = "") -> None:
        extra = {}
        if digest:
            extra[self._meta_key("digest")] = digest
        if content_type:
            extra["Content-Type"] = content_type
        (await self._request("PUT", f"/{bucket}/{key}", data=data,
                             extra_headers=extra)).release()

    async def delete_object(self, bucket: str, key: str) -> None:
        (await self._request("DELETE", f"/{bucket}/{key}")).release()

    async def list_object_metadatas(self, bucket: str, prefix: str = "",
                                    marker: str = "",
                                    limit: int = 1000) -> list[ObjectMetadata]:
        query = f"max-keys={limit}"
        if prefix:
            query += f"&prefix={quote(prefix, safe='')}"
        if marker:
            query += f"&marker={quote(marker, safe='')}"
        resp = await self._request("GET", f"/{bucket}", query=query)
        text = await resp.text()
        root = ET.fromstring(text)
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        return [ObjectMetadata(
            key=c.findtext(f"{ns}Key", ""),
            content_length=int(c.findtext(f"{ns}Size", "-1")),
            etag=c.findtext(f"{ns}ETag", "").strip('"'))
            for c in root.iter(f"{ns}Contents")]

    def object_url(self, bucket: str, key: str) -> str:
        return f"{self.endpoint}/{quote(bucket)}/{quote(key)}"

    def presign_url(self, bucket: str, key: str, expires: int = 3600) -> str:
        """URL-auth form (reference oss.go GetSignURL): the string-to-sign
        swaps the Date line for the absolute expiry timestamp. STS
        credentials ride the URL too — the vendor validates token'd
        presigns only when ``security-token`` is both in the signed
        canonicalized resource and on the query string."""
        if not self.access_key:
            return self.object_url(bucket, key)
        deadline = str(int(time.time()) + expires)
        resource = f"/{bucket}/{key}"
        signed_resource = resource
        token_param = ""
        if self.security_token:
            signed_resource += (f"?{self.PRESIGN_TOKEN_PARAM}="
                                f"{self.security_token}")
            token_param = (f"&{self.PRESIGN_TOKEN_PARAM}="
                           + quote(self.security_token, safe=""))
        to_sign = "\n".join(["GET", "", "", deadline]) + "\n" + signed_resource
        sig = quote(self._signature(to_sign), safe="")
        ak_param = ("OSSAccessKeyId" if self.AUTH_SCHEME == "OSS"
                    else "AccessKeyId")
        return (f"{self.endpoint}{quote(resource)}?{ak_param}="
                f"{quote(self.access_key, safe='')}&Expires={deadline}"
                f"{token_param}&Signature={sig}")

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
