"""Huawei OBS object-storage backend (native header auth).

Reference: pkg/objectstorage/obs.go (278 LoC over esdk-obs-go). OBS's
native scheme is the same HMAC-SHA1 construction as Aliyun OSS with the
vendor constants swapped — ``Authorization: OBS ak:sig`` and ``x-obs-*``
canonicalized headers — so the client is the OSS one re-tagged (the
reference carries a second 278-line wrapper only because the vendor Go
SDKs differ; the wire shape does not).
"""

from __future__ import annotations

from dragonfly2_tpu.pkg.objectstorage.oss import OSSObjectStorage


class OBSObjectStorage(OSSObjectStorage):
    name = "obs"
    AUTH_SCHEME = "OBS"
    HEADER_PREFIX = "x-obs-"
    PRESIGN_TOKEN_PARAM = "x-obs-security-token"
