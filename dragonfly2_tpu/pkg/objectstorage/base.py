"""ObjectStorage interface + metadata types.

Reference: pkg/objectstorage/objectstorage.go:40-132 — bucket CRUD, object
get/put/delete/exists, metadata listing, signed URLs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import AsyncIterator


class ObjectStorageError(Exception):
    """Backend failure. ``status`` carries the HTTP status when one was
    received (0 = connection-level / non-HTTP failure) so callers can
    separate permanent client errors (403/404: never retry) from
    retryable server/transport trouble — the source clients' ``temporary``
    classification rides on it."""

    def __init__(self, message: str = "", status: int = 0):
        super().__init__(message)
        self.status = status


@dataclass
class BucketMetadata:
    name: str
    created_at: float = 0.0


@dataclass
class ObjectMetadata:
    key: str
    content_length: int = -1
    content_type: str = ""
    etag: str = ""
    digest: str = ""          # "algo:encoded" (stored as user metadata)
    last_modified: float = 0.0
    user_metadata: dict = field(default_factory=dict)


class ObjectStorage:
    """Async backend client. All methods raise ObjectStorageError on backend
    failure; exists-style methods return False instead of raising."""

    name = "base"

    async def get_bucket_metadata(self, bucket: str) -> BucketMetadata:
        raise NotImplementedError

    async def create_bucket(self, bucket: str) -> None:
        raise NotImplementedError

    async def delete_bucket(self, bucket: str) -> None:
        raise NotImplementedError

    async def list_buckets(self) -> list[BucketMetadata]:
        raise NotImplementedError

    async def is_bucket_exist(self, bucket: str) -> bool:
        try:
            await self.get_bucket_metadata(bucket)
            return True
        except ObjectStorageError:
            return False

    async def get_object_metadata(self, bucket: str, key: str) -> ObjectMetadata:
        raise NotImplementedError

    async def get_object(self, bucket: str, key: str,
                         range_start: int = -1, range_end: int = -1) -> AsyncIterator[bytes]:
        raise NotImplementedError

    async def put_object(self, bucket: str, key: str, data,
                         *, digest: str = "", content_type: str = "") -> None:
        """``data`` is bytes or a seekable binary file object (large bodies
        stream through files; the daemon gateway spools uploads)."""
        raise NotImplementedError

    async def delete_object(self, bucket: str, key: str) -> None:
        raise NotImplementedError

    async def is_object_exist(self, bucket: str, key: str) -> bool:
        try:
            await self.get_object_metadata(bucket, key)
            return True
        except ObjectStorageError:
            return False

    async def list_object_metadatas(self, bucket: str, prefix: str = "",
                                    marker: str = "", limit: int = 1000) -> list[ObjectMetadata]:
        raise NotImplementedError

    def object_url(self, bucket: str, key: str) -> str:
        """Origin URL for P2P back-to-source of this object (the daemon
        gateway hands this to the stream-task machinery)."""
        raise NotImplementedError

    async def close(self) -> None:
        pass
