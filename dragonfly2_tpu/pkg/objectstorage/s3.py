"""S3-compatible object-storage backend (AWS SigV4 over aiohttp).

Reference: pkg/objectstorage/s3.go (304 LoC over aws-sdk-go). No boto here —
SigV4 is ~60 lines and the same client covers MinIO, Aliyun OSS and Huawei
OBS S3-compatible endpoints (reference carries oss.go/obs.go only because
the Go vendor SDKs differ). Path-style addressing so MinIO/test servers
work without wildcard DNS.
"""

from __future__ import annotations

import hashlib
import hmac
import time
import xml.etree.ElementTree as ET
from typing import AsyncIterator
from urllib.parse import quote

import aiohttp

from dragonfly2_tpu.pkg.objectstorage.base import (
    BucketMetadata,
    ObjectMetadata,
    ObjectStorage,
    ObjectStorageError,
)

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _as_body(data):
    """bytes pass through; file-likes become a chunk generator (chunked
    transfer — fine for MinIO/fake endpoints; AWS proper wants
    Content-Length, which callers with real AWS needs can add)."""
    if isinstance(data, (bytes, bytearray)):
        return data or None

    async def gen():
        while True:
            chunk = data.read(1 << 20)
            if not chunk:
                return
            yield chunk

    return gen()


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3ObjectStorage(ObjectStorage):
    name = "s3"

    def __init__(self, *, endpoint: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1"):
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self._session: aiohttp.ClientSession | None = None

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    # -- SigV4 (AWS Signature Version 4, header auth) ----------------------

    def _auth_headers(self, method: str, path: str, query: str,
                      payload_sha: str) -> dict[str, str]:
        host = self.endpoint.split("://", 1)[-1]
        now = time.gmtime()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
        datestamp = time.strftime("%Y%m%d", now)
        headers = {"host": host, "x-amz-content-sha256": payload_sha,
                   "x-amz-date": amz_date}
        if not self.access_key:
            return {k: v for k, v in headers.items() if k != "host"}
        signed = ";".join(sorted(headers))
        canonical = "\n".join([
            method, quote(path), query,
            "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
            signed, payload_sha])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])
        k = _sign(_sign(_sign(_sign(
            ("AWS4" + self.secret_key).encode(), datestamp),
            self.region), "s3"), "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}")
        return {k: v for k, v in headers.items() if k != "host"}

    async def _request(self, method: str, path: str, *, query: str = "",
                       data=b"", extra_headers: dict | None = None,
                       ok=(200, 204)) -> aiohttp.ClientResponse:
        if isinstance(data, (bytes, bytearray)):
            payload_sha = hashlib.sha256(data).hexdigest() if data else _EMPTY_SHA256
        else:
            # File-like body: hash by streaming, then rewind for the send
            # (header-auth SigV4 needs the payload sha up front).
            h = hashlib.sha256()
            while True:
                chunk = data.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
            data.seek(0)
            payload_sha = h.hexdigest()
        headers = self._auth_headers(method, path, query, payload_sha)
        headers.update(extra_headers or {})
        url = self.endpoint + quote(path) + (f"?{query}" if query else "")
        try:
            resp = await self._http().request(method, url, data=_as_body(data),
                                              headers=headers)
        except aiohttp.ClientError as e:
            # Connection-level failure (endpoint down, DNS, reset): status
            # stays 0 so callers classify it as retryable, not as an
            # authoritative backend answer.
            raise ObjectStorageError(f"s3 {method} {path}: {e}")
        if resp.status not in ok:
            body = (await resp.text())[:300]
            resp.release()
            raise ObjectStorageError(
                f"s3 {method} {path}: HTTP {resp.status} {body}",
                status=resp.status)
        return resp

    # -- buckets -----------------------------------------------------------

    async def get_bucket_metadata(self, bucket: str) -> BucketMetadata:
        resp = await self._request("HEAD", f"/{bucket}")
        resp.release()
        return BucketMetadata(name=bucket)

    async def create_bucket(self, bucket: str) -> None:
        (await self._request("PUT", f"/{bucket}")).release()

    async def delete_bucket(self, bucket: str) -> None:
        (await self._request("DELETE", f"/{bucket}")).release()

    async def list_buckets(self) -> list[BucketMetadata]:
        resp = await self._request("GET", "/")
        text = await resp.text()
        root = ET.fromstring(text)
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        return [BucketMetadata(name=b.findtext(f"{ns}Name", ""))
                for b in root.iter(f"{ns}Bucket")]

    # -- objects -----------------------------------------------------------

    async def get_object_metadata(self, bucket: str, key: str) -> ObjectMetadata:
        resp = await self._request("HEAD", f"/{bucket}/{key}")
        h = resp.headers
        resp.release()
        return ObjectMetadata(
            key=key,
            content_length=int(h.get("Content-Length", -1)),
            content_type=h.get("Content-Type", ""),
            etag=h.get("ETag", "").strip('"'),
            digest=h.get("x-amz-meta-digest", ""))

    async def get_object(self, bucket: str, key: str,
                         range_start: int = -1, range_end: int = -1) -> AsyncIterator[bytes]:
        extra = {}
        if range_start >= 0:
            end = str(range_end) if range_end >= 0 else ""
            extra["Range"] = f"bytes={range_start}-{end}"
        resp = await self._request("GET", f"/{bucket}/{key}",
                                   extra_headers=extra, ok=(200, 206))

        async def chunks() -> AsyncIterator[bytes]:
            try:
                async for chunk in resp.content.iter_chunked(1 << 20):
                    yield chunk
            finally:
                resp.release()

        return chunks()

    async def put_object(self, bucket: str, key: str, data,
                         *, digest: str = "", content_type: str = "") -> None:
        extra = {}
        if digest:
            extra["x-amz-meta-digest"] = digest
        if content_type:
            extra["Content-Type"] = content_type
        (await self._request("PUT", f"/{bucket}/{key}", data=data,
                             extra_headers=extra)).release()

    async def delete_object(self, bucket: str, key: str) -> None:
        (await self._request("DELETE", f"/{bucket}/{key}")).release()

    async def list_object_metadatas(self, bucket: str, prefix: str = "",
                                    marker: str = "", limit: int = 1000) -> list[ObjectMetadata]:
        query = f"list-type=2&max-keys={limit}"
        if prefix:
            query += f"&prefix={quote(prefix, safe='')}"
        if marker:
            query += f"&start-after={quote(marker, safe='')}"
        resp = await self._request("GET", f"/{bucket}", query=query)
        text = await resp.text()
        root = ET.fromstring(text)
        ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") else ""
        out = []
        for c in root.iter(f"{ns}Contents"):
            out.append(ObjectMetadata(
                key=c.findtext(f"{ns}Key", ""),
                content_length=int(c.findtext(f"{ns}Size", "-1")),
                etag=c.findtext(f"{ns}ETag", "").strip('"')))
        return out

    def object_url(self, bucket: str, key: str) -> str:
        # Anonymous/path-style URL; private buckets need the daemon-side
        # header injection (the stream task carries headers through
        # UrlMeta.header) or a presigned URL from presign_url().
        return f"{self.endpoint}/{quote(bucket)}/{quote(key)}"

    def presign_url(self, bucket: str, key: str, expires: int = 3600) -> str:
        """SigV4 presigned GET (reference s3.go GetSignURL)."""
        if not self.access_key:
            return self.object_url(bucket, key)
        host = self.endpoint.split("://", 1)[-1]
        now = time.gmtime()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
        datestamp = time.strftime("%Y%m%d", now)
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        path = f"/{bucket}/{key}"
        query = "&".join([
            "X-Amz-Algorithm=AWS4-HMAC-SHA256",
            "X-Amz-Credential=" + quote(f"{self.access_key}/{scope}", safe=""),
            f"X-Amz-Date={amz_date}",
            f"X-Amz-Expires={expires}",
            "X-Amz-SignedHeaders=host",
        ])
        canonical = "\n".join([
            "GET", quote(path), query, f"host:{host}\n", "host",
            "UNSIGNED-PAYLOAD"])
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])
        k = _sign(_sign(_sign(_sign(
            ("AWS4" + self.secret_key).encode(), datestamp),
            self.region), "s3"), "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        return f"{self.endpoint}{quote(path)}?{query}&X-Amz-Signature={sig}"

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
