"""GCS object-storage backend (JSON API over aiohttp).

The reference has NO GCS backend (pkg/objectstorage ships only s3/oss/obs —
SURVEY.md §2.4); GCS is the TPU target's primary store. Auth mirrors
source/clients/gcs.py: GCE metadata-server token on GCP, DF_GCS_ANONYMOUS /
DF_GCS_ENDPOINT for tests and public data. object_url returns a gs:// URL
so P2P back-to-source rides the registered gs source client and task IDs
dedupe across peers regardless of which daemon's gateway took the request.
"""

from __future__ import annotations

import json
import os
import time
from typing import AsyncIterator
from urllib.parse import quote

import aiohttp

from dragonfly2_tpu.pkg.objectstorage.base import (
    BucketMetadata,
    ObjectMetadata,
    ObjectStorage,
    ObjectStorageError,
)
from dragonfly2_tpu.source.clients.gcs import METADATA_TOKEN_URL


def _iso_to_epoch(value: str) -> float:
    try:
        return time.mktime(time.strptime(value[:19], "%Y-%m-%dT%H:%M:%S"))
    except (ValueError, TypeError):
        return 0.0


class GCSObjectStorage(ObjectStorage):
    name = "gcs"

    def __init__(self, *, endpoint: str = "https://storage.googleapis.com",
                 project: str = ""):
        self.endpoint = os.environ.get("DF_GCS_ENDPOINT", endpoint).rstrip("/")
        self.project = project
        self._session: aiohttp.ClientSession | None = None
        self._token: str | None = None
        self._token_expiry = 0.0

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def _auth(self) -> dict[str, str]:
        # A custom endpoint (fake-gcs in CI, proxy) implies anonymous, the
        # same signal the source client honors (source/clients/gcs.py:51) —
        # off-GCP there is no metadata server to ask.
        if os.environ.get("DF_GCS_ANONYMOUS") or os.environ.get("DF_GCS_ENDPOINT"):
            return {}
        now = time.monotonic()
        if self._token is None or now >= self._token_expiry:
            try:
                async with self._http().get(
                    METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"},
                    timeout=aiohttp.ClientTimeout(total=5),
                ) as resp:
                    if resp.status != 200:
                        raise ObjectStorageError("gcs: metadata token fetch failed")
                    tok = json.loads(await resp.text())
                    self._token = tok["access_token"]
                    self._token_expiry = now + max(60, tok.get("expires_in", 300) - 60)
            except aiohttp.ClientError as e:
                raise ObjectStorageError(f"gcs: no credentials: {e}")
        return {"Authorization": f"Bearer {self._token}"}

    async def _request(self, method: str, url: str, *, data=b"",
                       headers: dict | None = None,
                       ok=(200, 204)) -> aiohttp.ClientResponse:
        hdrs = await self._auth()
        hdrs.update(headers or {})
        if not isinstance(data, (bytes, bytearray)):
            body = data

            async def gen(f=body):
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        return
                    yield chunk

            data = gen()
        try:
            resp = await self._http().request(method, url, data=data or None,
                                              headers=hdrs)
        except aiohttp.ClientError as e:
            raise ObjectStorageError(f"gcs {method} {url}: {e}")
        if resp.status not in ok:
            body = (await resp.text())[:300]
            resp.release()
            raise ObjectStorageError(f"gcs {method} {url}: HTTP {resp.status} {body}")
        return resp

    # -- buckets -----------------------------------------------------------

    def _bucket_url(self, bucket: str) -> str:
        return f"{self.endpoint}/storage/v1/b/{quote(bucket, safe='')}"

    async def get_bucket_metadata(self, bucket: str) -> BucketMetadata:
        resp = await self._request("GET", self._bucket_url(bucket))
        meta = json.loads(await resp.text())
        return BucketMetadata(name=bucket,
                              created_at=_iso_to_epoch(meta.get("timeCreated", "")))

    async def create_bucket(self, bucket: str) -> None:
        url = f"{self.endpoint}/storage/v1/b"
        if self.project:
            url += f"?project={quote(self.project, safe='')}"
        (await self._request(
            "POST", url, data=json.dumps({"name": bucket}).encode(),
            headers={"Content-Type": "application/json"})).release()

    async def delete_bucket(self, bucket: str) -> None:
        (await self._request("DELETE", self._bucket_url(bucket))).release()

    async def list_buckets(self) -> list[BucketMetadata]:
        url = f"{self.endpoint}/storage/v1/b"
        if self.project:
            url += f"?project={quote(self.project, safe='')}"
        resp = await self._request("GET", url)
        data = json.loads(await resp.text())
        return [BucketMetadata(name=b["name"],
                               created_at=_iso_to_epoch(b.get("timeCreated", "")))
                for b in data.get("items", [])]

    # -- objects -----------------------------------------------------------

    def _object_base(self, bucket: str, key: str) -> str:
        return f"{self._bucket_url(bucket)}/o/{quote(key, safe='')}"

    async def get_object_metadata(self, bucket: str, key: str) -> ObjectMetadata:
        resp = await self._request("GET", self._object_base(bucket, key))
        meta = json.loads(await resp.text())
        return ObjectMetadata(
            key=key,
            content_length=int(meta.get("size", -1)),
            content_type=meta.get("contentType", ""),
            etag=meta.get("etag", ""),
            digest=(meta.get("metadata") or {}).get("digest", ""),
            last_modified=_iso_to_epoch(meta.get("updated", "")),
            user_metadata=meta.get("metadata") or {})

    async def get_object(self, bucket: str, key: str,
                         range_start: int = -1, range_end: int = -1) -> AsyncIterator[bytes]:
        headers = {}
        if range_start >= 0:
            end = str(range_end) if range_end >= 0 else ""
            headers["Range"] = f"bytes={range_start}-{end}"
        resp = await self._request("GET", self._object_base(bucket, key) + "?alt=media",
                                   headers=headers, ok=(200, 206))

        async def chunks() -> AsyncIterator[bytes]:
            try:
                async for chunk in resp.content.iter_chunked(1 << 20):
                    yield chunk
            finally:
                resp.release()

        return chunks()

    async def put_object(self, bucket: str, key: str, data,
                         *, digest: str = "", content_type: str = "") -> None:
        url = (f"{self.endpoint}/upload/storage/v1/b/{quote(bucket, safe='')}/o"
               f"?uploadType=media&name={quote(key, safe='')}")
        headers = {"Content-Type": content_type or "application/octet-stream"}
        (await self._request("POST", url, data=data, headers=headers)).release()
        if digest:
            patch = json.dumps({"metadata": {"digest": digest}}).encode()
            (await self._request("PATCH", self._object_base(bucket, key),
                                 data=patch,
                                 headers={"Content-Type": "application/json"})).release()

    async def delete_object(self, bucket: str, key: str) -> None:
        (await self._request("DELETE", self._object_base(bucket, key))).release()

    async def list_object_metadatas(self, bucket: str, prefix: str = "",
                                    marker: str = "", limit: int = 1000) -> list[ObjectMetadata]:
        url = f"{self._bucket_url(bucket)}/o?maxResults={limit}"
        if prefix:
            url += f"&prefix={quote(prefix, safe='')}"
        if marker:
            url += f"&startOffset={quote(marker, safe='')}"
        resp = await self._request("GET", url)
        data = json.loads(await resp.text())
        return [ObjectMetadata(
            key=o["name"], content_length=int(o.get("size", -1)),
            content_type=o.get("contentType", ""), etag=o.get("etag", ""),
            digest=(o.get("metadata") or {}).get("digest", ""),
            last_modified=_iso_to_epoch(o.get("updated", "")))
            for o in data.get("items", [])]

    def object_url(self, bucket: str, key: str) -> str:
        return f"gs://{bucket}/{key}"

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
