"""Bad-parent quarantine: a decaying-penalty blocklist.

A parent that serves corrupt bytes, truncates bodies, or slow-lorises is
worse than a dead one: the dead parent fails fast and gets blocked by the
failure counter, while the bad one keeps "succeeding" at the transport
layer and burning each child's verify-reject-retry loop forever. This
module gives both ends of the fabric one penalty discipline:

  * every data-plane failure adds a REASON-WEIGHTED penalty
    (corrupt >> truncated/stall >> transport; throttle adds nothing —
    429 is the parent doing its job)
  * the score decays exponentially (half-life) so an old incident does
    not haunt a recovered parent
  * crossing the threshold quarantines the key for a bounded window;
    while quarantined the parent is invisible to selection.

The daemon keys by ``ip:upload_port`` daemon-wide (one registry shared by
every conductor, so a parent that corrupted task A is not trusted for
task B). The scheduler keys by host id and consults it in candidate
filtering, so one child's typed ``piece_failed`` reports demote the
parent for every other peer too.
"""

from __future__ import annotations

import time
from typing import Callable

# Reason → penalty weight. corrupt trips the default threshold in one
# strike: a crc32c mismatch is never noise (the transport already
# checksums), it is wrong bytes served with a straight face.
REASON_WEIGHTS: dict[str, float] = {
    "corrupt": 3.0,
    "truncated": 1.5,
    "stall": 1.5,
    "refused": 1.0,
    "transport": 1.0,
    "http5xx": 1.0,
    "not_found": 0.0,   # a warming parent legitimately lacks pieces
    "throttle": 0.0,    # 429 is backpressure, not misbehavior
}

DEFAULT_THRESHOLD = 3.0
DEFAULT_HALF_LIFE_S = 30.0
DEFAULT_QUARANTINE_S = 60.0


class DecayingPenalty:
    """One key's penalty state: exponentially-decaying score + the
    quarantine window it last earned."""

    __slots__ = ("score", "updated_at", "quarantined_until")

    def __init__(self):
        self.score = 0.0
        self.updated_at = 0.0
        self.quarantined_until = 0.0

    def current(self, now: float, half_life_s: float) -> float:
        if self.score <= 0.0:
            return 0.0
        dt = max(0.0, now - self.updated_at)
        return self.score * 0.5 ** (dt / half_life_s)

    def add(self, weight: float, now: float, half_life_s: float) -> float:
        self.score = self.current(now, half_life_s) + weight
        self.updated_at = now
        return self.score


def penalize_entry(entry: DecayingPenalty, reason: str, now: float, *,
                   threshold: float = DEFAULT_THRESHOLD,
                   half_life_s: float = DEFAULT_HALF_LIFE_S,
                   quarantine_s: float = DEFAULT_QUARANTINE_S) -> bool:
    """Apply one reason-weighted strike to ``entry``; returns True when it
    just ENTERED quarantine (callers report that edge, not every hit).
    The single penalty discipline both the daemon registry and the
    scheduler's per-host record use — they must never diverge."""
    weight = REASON_WEIGHTS.get(reason, 1.0)
    if weight <= 0.0:
        return False
    was = now < entry.quarantined_until
    score = entry.add(weight, now, half_life_s)
    if score >= threshold:
        # Repeat offenders extend the window from *now*: the bound is on
        # silence-after-last-offense, not first-offense age.
        entry.quarantined_until = now + quarantine_s
    return (now < entry.quarantined_until) and not was


class ParentQuarantine:
    """Keyed penalty registry. ``penalize`` returns True when the key just
    ENTERED quarantine (callers count/report that edge, not every hit)."""

    def __init__(self, *, threshold: float = DEFAULT_THRESHOLD,
                 half_life_s: float = DEFAULT_HALF_LIFE_S,
                 quarantine_s: float = DEFAULT_QUARANTINE_S,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = threshold
        self.half_life_s = half_life_s
        self.quarantine_s = quarantine_s
        self._clock = clock
        self._entries: dict[str, DecayingPenalty] = {}

    def penalize(self, key: str, reason: str) -> bool:
        if not key:
            return False
        e = self._entries.get(key)
        if e is None:
            e = self._entries[key] = DecayingPenalty()
        return penalize_entry(e, reason, self._clock(),
                              threshold=self.threshold,
                              half_life_s=self.half_life_s,
                              quarantine_s=self.quarantine_s)

    def is_quarantined(self, key: str) -> bool:
        e = self._entries.get(key)
        return e is not None and self._clock() < e.quarantined_until

    def score(self, key: str) -> float:
        e = self._entries.get(key)
        if e is None:
            return 0.0
        return e.current(self._clock(), self.half_life_s)

    def quarantined_keys(self) -> list[str]:
        now = self._clock()
        return [k for k, e in self._entries.items()
                if now < e.quarantined_until]

    def gc(self, max_entries: int = 4096) -> None:
        """Bound the registry: fully-decayed, unquarantined entries go
        first; called opportunistically by owners."""
        if len(self._entries) <= max_entries:
            return
        now = self._clock()
        for k in [k for k, e in self._entries.items()
                  if now >= e.quarantined_until
                  and e.current(now, self.half_life_s) < 0.05]:
            del self._entries[k]
