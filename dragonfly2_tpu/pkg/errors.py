"""Coded errors that cross RPC boundaries.

Reference: internal/dferrors/error.go (coded errors carried over gRPC) and
the Code enum from d7y.io/api commonv1/v2. We keep a compact integer code
space so errors serialize through drpc frames and can be re-raised on the
far side with their semantics intact.
"""

from __future__ import annotations

import enum
from typing import Any


class Code(enum.IntEnum):
    """Error/status codes, modeled on commonv1.Code semantics."""

    # Success
    Success = 200

    # Framework errors
    ServerUnavailable = 500
    ResourceLacked = 501
    BadRequest = 400
    Unauthorized = 401
    PeerTaskNotFound = 404
    NotFound = 404              # alias: generic REST not-found
    InvalidArgument = 422
    UnknownError = 1000
    RequestTimeout = 1001

    # Scheduler errors
    SchedError = 5000
    SchedNeedBackSource = 5001  # peer must fall back to origin
    SchedPeerGone = 5002        # peer should be terminated
    SchedPeerNotFound = 5004
    SchedPeerPieceResultReportFail = 5005
    SchedTaskStatusError = 5006
    SchedReregister = 5007      # peer should re-register (scheduler restarted)

    # CDN / seed-peer errors
    CDNTaskRegistryFail = 6001
    CDNTaskNotFound = 6404

    # Client errors
    ClientError = 4000
    ClientPieceRequestFail = 4001  # piece download request failed
    ClientScheduleTimeout = 4002
    ClientContextCanceled = 4003
    ClientWaitPieceReady = 4004
    ClientPieceDownloadFail = 4005
    ClientRequestLimitFail = 4006
    ClientConnectionError = 4007
    ClientBackSourceError = 4008
    ClientPieceNotFound = 4404

    # Manager errors
    ManagerError = 7000
    InvalidResourceType = 7001

    # Storage errors
    StorageError = 8000
    StoragePieceNotFound = 8404
    StorageTaskNotFound = 8405

    # Source / origin errors
    BackToSourceAborted = 9000
    UnsupportedProtocol = 9001
    SourceNotFound = 9404
    SourceForbidden = 9403
    SourceRangeUnsupported = 9416


def describe(e: BaseException) -> str:
    """Never-empty error text: bare TimeoutError/CancelledError stringify
    to '' which makes logs and wire errors useless."""
    return str(e) or type(e).__name__


class DfError(Exception):
    """Base coded error. Serializable across drpc.

    Attributes:
        code: machine-readable code (Code enum value).
        message: human message.
        metadata: optional structured details (JSON-safe).
    """

    def __init__(self, code: Code | int, message: str = "", metadata: dict[str, Any] | None = None):
        # Unknown codes (newer peers) must not crash the decoder: degrade to
        # UnknownError and keep the raw value for diagnostics.
        try:
            parsed = Code(code)
        except ValueError:
            parsed = Code.UnknownError
            metadata = dict(metadata or {})
            metadata["raw_code"] = int(code)
        super().__init__(message or parsed.name)
        self.code = parsed
        self.message = message or parsed.name
        self.metadata = metadata or {}

    def to_wire(self) -> dict[str, Any]:
        return {"code": int(self.code), "message": self.message, "metadata": self.metadata}

    @classmethod
    def from_wire(cls, d: dict[str, Any]) -> "DfError":
        return cls(d.get("code", Code.UnknownError), d.get("message", ""), d.get("metadata") or {})

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"DfError({self.code.name}, {self.message!r})"


class NeedBackSourceError(DfError):
    """Scheduler instructs the peer to fetch from origin itself."""

    def __init__(self, message: str = ""):
        super().__init__(Code.SchedNeedBackSource, message)


class PeerGoneError(DfError):
    def __init__(self, message: str = ""):
        super().__init__(Code.SchedPeerGone, message)


class RescheduleError(DfError):
    """Raised internally when the current parents are unusable and the
    conductor should ask the scheduler for new ones."""

    def __init__(self, message: str = "", candidates_gone: list[str] | None = None):
        super().__init__(Code.SchedError, message, {"candidates_gone": candidates_gone or []})


class StorageError(DfError):
    def __init__(self, message: str = "", code: Code = Code.StorageError):
        super().__init__(code, message)


class SourceError(DfError):
    """Origin fetch failure. ``temporary`` guides retry policy."""

    def __init__(self, message: str = "", code: Code = Code.BackToSourceAborted, temporary: bool = False):
        super().__init__(code, message, {"temporary": temporary})
        self.temporary = temporary


def is_back_source_code(code: int) -> bool:
    return code == Code.SchedNeedBackSource


def error_from_wire(d: dict[str, Any]) -> DfError:
    code = d.get("code", int(Code.UnknownError))
    if code == Code.SchedNeedBackSource:
        return NeedBackSourceError(d.get("message", ""))
    if code == Code.SchedPeerGone:
        return PeerGoneError(d.get("message", ""))
    return DfError.from_wire(d)
