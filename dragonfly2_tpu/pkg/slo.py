"""Continuous SLO / burn-rate engine for the scheduler.

The fleet observatory (pkg/fleet) records what happened; nothing so far
says whether the fleet is HEALTHY. This module closes that loop with
declarative SLO specs evaluated continuously over sliding windows, the
standard SRE multi-window burn-rate formulation:

    error_rate = bad_events / total_events          (per window)
    burn_rate  = error_rate / (1 - objective)       (1.0 = budget pace)
    state      = breach when burn_rate >= the window's threshold

Four SLI kinds, all reduced to a good/bad fraction over a window so one
burn formula serves everything:

  * ``completion`` — per-task-completion values (broadcast makespan,
    per-host TTFB, stall fraction) from the flight digests daemons ship
    on task completion (pkg/podlens.completion_stats); an event is bad
    when its value exceeds the spec threshold. Bounded ring.
  * ``ratio`` — bad/total counter columns of the fleet time-series
    (e.g. back-to-source demotions per registration).
  * ``gauge`` — fraction of time-series buckets where a sampled gauge
    exceeded the threshold (e.g. flagged straggler hosts).
  * ``probe`` — a callable ``(window, threshold) -> (bad, total)``
    registered under the spec's field (``probes=`` at construction or
    ``engine.probes[...]`` later). The runtime observatory (pkg/prof)
    feeds ``loop_lag`` this way: wedged wall-seconds over observed
    wall-seconds, so a wedged event loop burns budget in proportion to
    the wall time it stole — immune to dilution by healthy heartbeat
    ticks. Both the scheduler AND the daemon evaluate it (the daemon
    runs a runtime-only engine at its own /debug/slo).

Served at ``GET /debug/slo`` and exported as
``scheduler_slo_burn_rate{slo,window}`` /
``scheduler_slo_breaches_total{slo}`` (edge-triggered: one increment per
transition into breach, not one per scrape).

Hot-path contract: ``note_completion`` is one ring append plus a
rate-limited (default 1 s) evaluation; reads evaluate at most once per
call. podlens_bench publishes the paired on/off cost together with the
digest shipping (``config10_podlens``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from dragonfly2_tpu.pkg import dflog, metrics

log = dflog.get("slo")

BURN_GAUGE = metrics.gauge(
    "scheduler_slo_burn_rate",
    "Error-budget burn rate per SLO and sliding window (1.0 = burning "
    "exactly the budget; the window's threshold marks a breach)",
    ("slo", "window"))

BREACH_COUNT = metrics.counter(
    "scheduler_slo_breaches_total",
    "Transitions of an SLO into the breached state (any window's burn "
    "rate crossing its threshold; edge-triggered, not per-scrape)",
    ("slo",))


@dataclass(frozen=True)
class SLOSpec:
    """One declarative service-level objective.

    ``windows`` and ``burn_thresholds`` align positionally: the classic
    fast/slow pair (5 m @ 14.4x, 1 h @ 6x) by default. ``objective`` is
    the good-event target (0.99 = 1% error budget); ``threshold`` is the
    per-event/per-bucket good/bad cut for completion and gauge kinds."""

    name: str
    kind: str                  # "completion" | "ratio" | "gauge" | "probe"
    description: str = ""
    field: str = ""            # completion value / gauge column
    bad_col: str = ""          # ratio: numerator counter column
    total_col: str = ""        # ratio: denominator counter column
    threshold: float = 0.0
    objective: float = 0.95
    windows: "tuple[float, ...]" = (300.0, 3600.0)
    burn_thresholds: "tuple[float, ...]" = (14.4, 6.0)
    min_events: int = 1


# The default spec set: the SLIs ROADMAP item 2 (multi-tenant QoS
# acceptance) and the 16k-host scale work need computable. Deployments
# override by constructing the engine with their own list.
DEFAULT_SLOS = (
    SLOSpec("broadcast_makespan", "completion", field="makespan_s",
            threshold=60.0, objective=0.95,
            description="task completion wall time stays under 60 s for "
                        "95% of completions (the <60 s pod-broadcast "
                        "north star, per host)"),
    SLOSpec("host_ttfb", "completion", field="ttfb_s",
            threshold=5.0, objective=0.95,
            description="a downloading host sees its first byte within "
                        "5 s for 95% of completions"),
    SLOSpec("stall_fraction", "completion", field="stall_frac",
            threshold=0.25, objective=0.99,
            description="silent-parent stall time stays under 25% of a "
                        "task's wall for 99% of completions"),
    # Lower objectives cap the achievable burn at 1/(1-objective), so
    # their thresholds must sit below that ceiling or the breach state
    # is unreachable (SLOEngine rejects such specs at construction).
    SLOSpec("back_source_rate", "ratio", bad_col="back_source",
            total_col="registers", objective=0.75,
            burn_thresholds=(3.0, 2.0),
            description="origin demotions stay under 25% of peer "
                        "registrations (origin economy: ~one fetch per "
                        "task, not one per host)"),
    SLOSpec("straggler_hosts", "gauge", field="straggler_hosts",
            threshold=0.0, objective=0.9, burn_thresholds=(8.0, 4.0),
            description="no host is flagged a fleet-wide straggler in "
                        "90% of sampled buckets"),
    SLOSpec("loop_lag", "probe", field="loop_lag", threshold=0.25,
            objective=0.99,
            description="event-loop wedged time (heartbeat lag above "
                        "250 ms) stays under 1% of observed wall time — "
                        "the runtime observatory's loop probe feeds it; "
                        "no_data until pkg/prof is armed"),
)

# The daemon-side runtime engine evaluates just this subset (the rest
# need a scheduler's fleet series / completion feed).
RUNTIME_SLOS = tuple(s for s in DEFAULT_SLOS if s.kind == "probe")

# Per-tenant admission specs (qos.TenantBurnBook): the completion SLIs
# re-cut per tenant with a fast window tuned to admission latency — a
# tenant burning its budget should be throttled within a minute, not an
# hour. Deployments override via TenantBurnBook(specs=...).
TENANT_SLOS = (
    SLOSpec("tenant_makespan", "completion", field="makespan_s",
            threshold=60.0, objective=0.95,
            windows=(60.0, 300.0), burn_thresholds=(14.4, 6.0),
            description="per-tenant task completion wall time stays "
                        "under 60 s for 95% of the tenant's completions "
                        "— the admission ladder's primary signal"),
    SLOSpec("tenant_stall", "completion", field="stall_frac",
            threshold=0.25, objective=0.90,
            windows=(60.0, 300.0), burn_thresholds=(8.0, 4.0),
            description="per-tenant stall fraction stays under 25% of "
                        "task wall for 90% of the tenant's completions "
                        "(a tenant thrashing its parents burns here "
                        "before it hurts makespan)"),
)


@dataclass
class _WindowState:
    burn: float = 0.0
    state: str = "no_data"
    events: int = 0
    bad: float = 0.0


class SLOEngine:
    """Continuous evaluator. ``series`` is the scheduler's
    ``fleet.FleetTimeSeries`` (ratio/gauge SLIs report ``no_data``
    without one); completions arrive via ``note_completion``."""

    # Continuous means "every few seconds", not "every completion": the
    # windows are 5 m / 1 h, so a 5 s tick loses nothing while keeping
    # the engine invisible on the ingest path (podlens_bench pairs it).
    def __init__(self, specs=DEFAULT_SLOS, *, series=None, probes=None,
                 max_completions: int = 4096,
                 min_eval_interval_s: float = 5.0,
                 clock=time.monotonic):
        self.specs = tuple(specs)
        for spec in self.specs:
            ceiling = 1.0 / max(1e-9, 1.0 - spec.objective)
            for bt in spec.burn_thresholds:
                if bt >= ceiling:
                    raise ValueError(
                        f"SLO {spec.name!r}: burn threshold {bt} is "
                        f"unreachable — a total outage burns at most "
                        f"{ceiling:.1f}x with objective {spec.objective}")
            if len(spec.windows) != len(spec.burn_thresholds):
                raise ValueError(
                    f"SLO {spec.name!r}: windows and burn_thresholds "
                    f"must align positionally")
        self.series = series
        # kind="probe" feeds: field -> callable(window, threshold) ->
        # (bad, total). Wired at construction or later (the scheduler
        # attaches the runtime observatory's probes when prof arms).
        self.probes: dict = dict(probes or {})
        self.max_completions = max_completions
        self.min_eval_interval_s = min_eval_interval_s
        self._clock = clock
        # Preallocated completion ring of (t, makespan, ttfb, stall_frac,
        # host) tuples — the flight-ring discipline.
        self._ring: list = [None] * max_completions
        self._n = 0
        self._evaluated_at = -1e18
        self._last: "dict | None" = None
        self._breached: dict[str, bool] = {s.name: False for s in self.specs}
        self._breaches: dict[str, int] = {s.name: 0 for s in self.specs}
        self._burn_children: dict = {}

    # -- feed --------------------------------------------------------------

    def note_completion(self, host: str, makespan_s: float,
                        ttfb_s: float = -1.0, stall_frac: float = 0.0,
                        now: "float | None" = None) -> None:
        if now is None:
            now = self._clock()
        self._ring[self._n % self.max_completions] = (
            now, makespan_s, ttfb_s, stall_frac, host)
        self._n += 1
        if now - self._evaluated_at >= self.min_eval_interval_s:
            self.evaluate(now)

    @property
    def completions_total(self) -> int:
        return self._n

    # -- evaluation --------------------------------------------------------

    _COMPLETION_FIELD = {"makespan_s": 1, "ttfb_s": 2, "stall_frac": 3}

    def _completion_counts(self, spec: SLOSpec, window: float,
                           now: float) -> "tuple[int, int]":
        idx = self._COMPLETION_FIELD.get(spec.field)
        if idx is None:
            return 0, 0
        total = bad = 0
        newest = self._n - 1
        oldest = max(0, self._n - self.max_completions)
        i = newest
        cutoff = now - window
        while i >= oldest:
            row = self._ring[i % self.max_completions]
            i -= 1
            if row is None:
                continue
            if row[0] < cutoff:
                break           # ring is time-ordered newest-first
            value = row[idx]
            if value is None or value < 0:
                continue        # unmeasurable (e.g. digest without ttfb)
            total += 1
            if value > spec.threshold:
                bad += 1
        return bad, total

    def _series_counts(self, spec: SLOSpec,
                       window: float) -> "tuple[float, float]":
        if self.series is None:
            return 0.0, 0.0
        if spec.kind == "ratio":
            totals = self.series.totals(window,
                                        (spec.bad_col, spec.total_col))
            return (float(totals.get(spec.bad_col, 0.0)),
                    float(totals.get(spec.total_col, 0.0)))
        values = self.series.gauge_column(spec.field, window)
        if not values:
            return 0.0, 0.0
        bad = sum(1.0 for v in values if v > spec.threshold)
        return bad, float(len(values))

    def _probe_counts(self, spec: SLOSpec,
                      window: float) -> "tuple[float, float]":
        fn = self.probes.get(spec.field or spec.name)
        if fn is None:
            return 0.0, 0.0
        try:
            bad, total = fn(window, spec.threshold)
        except Exception:
            log.warning("slo probe failed", slo=spec.name, exc_info=True)
            return 0.0, 0.0
        # Clamp: burn must never exceed the total-outage ceiling because
        # a probe returned bad > total.
        return min(float(bad), float(total)), float(total)

    def evaluate(self, now: "float | None" = None) -> dict:
        """Recompute every (slo, window) burn rate, update the exported
        gauges, edge-trigger breach counters, and cache the report."""
        if now is None:
            now = self._clock()
        self._evaluated_at = now
        slos = []
        for spec in self.specs:
            budget = max(1e-9, 1.0 - spec.objective)
            windows = []
            breached = False
            for window, burn_threshold in zip(spec.windows,
                                              spec.burn_thresholds):
                if spec.kind == "completion":
                    bad, total = self._completion_counts(spec, window, now)
                elif spec.kind == "probe":
                    bad, total = self._probe_counts(spec, window)
                else:
                    bad, total = self._series_counts(spec, window)
                if total < spec.min_events:
                    w = _WindowState(0.0, "no_data", int(total), bad)
                else:
                    error_rate = bad / total
                    burn = error_rate / budget
                    state = ("breach" if burn >= burn_threshold
                             else "warn" if burn >= 1.0 else "ok")
                    w = _WindowState(round(burn, 4), state, int(total),
                                     round(bad, 2))
                    breached = breached or state == "breach"
                self._burn_gauge(spec.name, window).set(w.burn)
                windows.append({
                    "window_s": window,
                    "burn_rate": w.burn,
                    "burn_threshold": burn_threshold,
                    "state": w.state,
                    "events": w.events,
                    "bad": w.bad,
                })
            if breached and not self._breached[spec.name]:
                self._breaches[spec.name] += 1
                BREACH_COUNT.labels(spec.name).inc()
                log.warning("slo breached", slo=spec.name)
            self._breached[spec.name] = breached
            slos.append({
                "name": spec.name,
                "kind": spec.kind,
                "description": spec.description,
                "objective": spec.objective,
                "threshold": spec.threshold,
                "state": "breach" if breached else (
                    "ok" if any(w["state"] != "no_data" for w in windows)
                    else "no_data"),
                "breaches_total": self._breaches[spec.name],
                "windows": windows,
            })
        self._last = {
            "slos": slos,
            "completions_total": self._n,
            "breached": sorted(n for n, b in self._breached.items() if b),
        }
        return self._last

    def _burn_gauge(self, name: str, window: float):
        # labels() does lock+lookup work; bind children once (the fleet
        # DecisionLog discipline).
        key = (name, window)
        child = self._burn_children.get(key)
        if child is None:
            child = self._burn_children[key] = BURN_GAUGE.labels(
                name, f"{int(window)}s")
        return child

    def report(self) -> dict:
        return self.evaluate()
