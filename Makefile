# Convenience targets. Nothing here is required at runtime: the native
# library auto-builds (and auto-skips) on first import, and every native
# consumer has a pure-Python/numpy fallback rung.

PYTHON ?= python

.PHONY: native test tier1 bench-ingest bench-delta clean-native

# Build (or rebuild) the native library. Degrades, never errors: on a box
# without a C++ toolchain build.py prints a one-line skip reason and
# exits 0 — the fallback ladders (digest, chunker, io ring) carry on.
native:
	$(PYTHON) -m dragonfly2_tpu.native.build

# The tier-1 suite (what CI gates on).
test tier1:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'

bench-ingest:
	JAX_PLATFORMS=cpu $(PYTHON) benchmarks/ingest_micro.py

bench-delta:
	JAX_PLATFORMS=cpu $(PYTHON) benchmarks/delta_bench.py

clean-native:
	$(PYTHON) -c "from dragonfly2_tpu.native import build; build.clean()"
