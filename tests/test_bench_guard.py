"""Guards on bench.py's CPU-fallback artifact (fast, tier-1).

The official bench has published the CPU fallback in 4 of 5 rounds; what
made that debuggable at all was the fallback output carrying WHY. This
pins the contract: every fallback artifact names its failure stage and
reason in a structured ``fallback`` object (plus the scrubbed env and the
crc32c backend actually in use), so stale device evidence is
self-diagnosing instead of an opaque 1.3 GB/s line.
"""

from __future__ import annotations

import io
import json
import sys

import bench


def test_fallback_output_is_structured():
    out = bench.fallback_output(
        1.5e9, RuntimeError("backend init failed: tunnel wedged"),
        stage="backend_init", attempts=3, probe_timeout_s=45.0)
    assert out["metric"] == "verify_and_land_throughput"
    assert out["value"] == 1.5
    fb = out["fallback"]
    assert fb["reason"] and "tunnel wedged" in fb["reason"]
    assert fb["stage"] in ("backend_init", "device_bench")
    assert fb["attempts"] == 3
    assert fb["probe_timeout_s"] == 45.0
    assert isinstance(fb["scrubbed_env"], list)
    assert fb["cpu_crc32c_backend"] in ("native", "google-crc32c", "python")
    # Human-readable note rides along for round summaries.
    assert "device path unavailable" in out["note"]
    # Runtime snapshot (pkg/prof): the fallback says what the PROCESS
    # was doing, even unarmed (gauges always; frames when armed).
    rt = out["runtime"]
    assert rt["rss_mb"] > 0
    assert rt["threads"] >= 1
    assert isinstance(rt["top_self"], list)
    assert "max_loop_lag_ms" in rt and "gc_collections" in rt


def test_fallback_output_never_empty_reason():
    fb = bench.fallback_output(1e9, "", stage="device_bench")["fallback"]
    assert fb["reason"] == "unknown"


def test_main_fallback_path_emits_structured_reason(monkeypatch):
    """Drive main() through the real fallback path (forced, no probe wait)
    and assert the printed JSON line carries the structured reason."""
    monkeypatch.setenv("BENCH_FORCE_FALLBACK", "1")
    monkeypatch.setenv("BENCH_CPU_MB", "2")
    captured = io.StringIO()
    monkeypatch.setattr(sys, "stdout", captured)
    rc = bench.main()
    sys.stdout = sys.__stdout__
    assert rc == 0
    out = json.loads(captured.getvalue().strip().splitlines()[-1])
    assert out["fallback"]["stage"] == "backend_init"
    assert "BENCH_FORCE_FALLBACK" in out["fallback"]["reason"]
    assert out["value"] > 0
    # main() armed the observatory before the probe, so the snapshot
    # carries real sampler evidence, not just gauges.
    assert out["runtime"]["samples"] >= 0
    assert out["runtime"]["rss_mb"] > 0


def test_scrubbed_device_env_drops_cpu_pins(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    env, scrubbed = bench._scrubbed_device_env()
    assert "JAX_PLATFORMS" not in env and scrubbed == ["JAX_PLATFORMS"]
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    env, scrubbed = bench._scrubbed_device_env()
    assert env["JAX_PLATFORMS"] == "tpu" and not scrubbed
