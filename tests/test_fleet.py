"""Fleet observatory: time-series bounds, scorecard straggler math,
decision audit log, service wiring, the seeded consistently-slow-host
acceptance e2e, and scrape-under-load responsiveness.

The acceptance case: a host that serves slowly across MANY tasks (seeded
deterministic costs) must be flagged fleet-wide at /debug/fleet/hosts,
dropped from later candidate handouts, and the drops must be explained
at /debug/fleet/decisions?host=<slow> — the per-task PodAggregator can
never see this; only the cross-task scorecards can.
"""

from __future__ import annotations

import asyncio
import json

import pytest

from dragonfly2_tpu.pkg import fleet
from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.service import SchedulerService


def mk_body(host: str, peer: str, task: str = "t", slice_: str = "",
            upload_port: int = 2) -> dict:
    return {
        "host": {"id": host, "hostname": host, "ip": "10.0.0.1",
                 "port": 1, "upload_port": upload_port,
                 "tpu_slice": slice_},
        "peer_id": peer, "task_id": task, "url": "http://origin/f"}


# --------------------------------------------------------------------- #
# Time-series ring
# --------------------------------------------------------------------- #

class TestTimeSeries:
    def test_counters_land_in_time_buckets(self):
        clock = [100.0]
        ts = fleet.FleetTimeSeries(bucket_s=5.0, buckets=8,
                                   clock=lambda: clock[0])
        ts.inc(fleet.C_PIECES, 3)
        clock[0] += 5.0
        ts.inc(fleet.C_PIECES, 2)
        win = ts.window(30)
        got = win["counters"]["pieces_landed"]
        assert got[-1] == 2 and got[-2] == 3
        assert win["totals"]["pieces_landed"] == 5

    def test_ring_is_bounded_and_stale_slots_zero(self):
        """A burst, a long idle gap past the ring, then one event: the
        reused slots must read zero, not ghost the old burst."""
        clock = [0.0]
        ts = fleet.FleetTimeSeries(bucket_s=1.0, buckets=4,
                                   clock=lambda: clock[0])
        for _ in range(100):
            ts.inc(fleet.C_PIECES)
        clock[0] += 1000.0          # idle far past the ring
        ts.inc(fleet.C_PIECES)
        win = ts.window(4)
        assert win["totals"]["pieces_landed"] == 1
        # Preallocated: the burst grew nothing, the idle freed nothing.
        assert len(ts._counts) == 4
        assert all(len(row) == len(fleet.COUNTERS) for row in ts._counts)
        assert all(len(row) == len(fleet.GAUGES) for row in ts._gauges)

    def test_gauges_sampled_at_rotation(self):
        clock = [0.0]
        sampled = {"hosts_total": 7, "tasks_active": 2}
        ts = fleet.FleetTimeSeries(bucket_s=1.0, buckets=8,
                                   sampler=lambda: sampled,
                                   clock=lambda: clock[0])
        ts.inc(fleet.C_PIECES)      # first rotation samples
        win = ts.window(2)
        assert win["gauges"]["hosts_total"][-1] == 7
        assert win["gauges"]["tasks_active"][-1] == 2

    def test_broken_sampler_does_not_drop_events(self):
        def boom():
            raise RuntimeError("sampler died")

        ts = fleet.FleetTimeSeries(bucket_s=1.0, buckets=4, sampler=boom)
        ts.inc(fleet.C_PIECES, 5)
        assert ts.window(4)["totals"]["pieces_landed"] == 5

    def test_window_clamps_to_ring(self):
        ts = fleet.FleetTimeSeries(bucket_s=1.0, buckets=4)
        win = ts.window(10_000)
        assert win["buckets"] == 4


# --------------------------------------------------------------------- #
# Scorecards + straggler flag
# --------------------------------------------------------------------- #

class TestScorecards:
    def test_slow_server_flagged_uniform_fleet_not(self):
        sc = fleet.HostScorecards(min_serve_samples=4, min_population=8)
        for i in range(9):
            cost = 900.0 if i == 0 else 10.0
            for _ in range(6):
                sc.note_serve(f"h{i}", cost)
        flags = sc.recompute_stragglers()
        assert flags == {"h0"}
        assert sc.is_straggler("h0") and not sc.is_straggler("h3")
        # Uniform fleet: the scale floor keeps z finite — nobody flagged.
        sc2 = fleet.HostScorecards(min_serve_samples=4, min_population=8)
        for i in range(9):
            for _ in range(6):
                sc2.note_serve(f"u{i}", 10.0 + (i % 3))
        assert sc2.recompute_stragglers() == set()

    def test_no_flag_below_population_floor(self):
        """Small pods must never lose their only parent to the advisory
        filter: under min_population scored hosts, nobody is flagged."""
        sc = fleet.HostScorecards(min_serve_samples=2, min_population=8)
        for i in range(4):
            for _ in range(4):
                sc.note_serve(f"h{i}", 900.0 if i == 0 else 10.0)
        assert sc.recompute_stragglers() == set()

    def test_batch_serve_moves_ewma_like_singles(self):
        a = fleet.HostScorecards()
        b = fleet.HostScorecards()
        for _ in range(8):
            a.note_serve("h", 100.0)
        b.note_serve("h", 100.0)
        b.note_serve("h", 100.0, count=7)
        assert a._hosts["h"].serve_samples == b._hosts["h"].serve_samples
        assert a._hosts["h"].serve_ewma_ms == pytest.approx(
            b._hosts["h"].serve_ewma_ms)

    def test_failure_counts_decay(self):
        clock = [0.0]
        sc = fleet.HostScorecards(half_life_s=10.0,
                                  clock=lambda: clock[0])
        sc.note_failure("h", "corrupt")
        sc.note_failure("h", "corrupt")
        clock[0] += 10.0
        sc.note_failure("h", "stall")
        s = sc._hosts["h"]
        sc._decay_failures(s, clock[0])
        assert s.failures["corrupt"] == pytest.approx(1.0)
        clock[0] += 200.0
        sc._decay_failures(s, clock[0])
        assert "corrupt" not in s.failures   # decayed below the floor

    def test_lru_bound_evicts_least_recently_seen(self):
        clock = [0.0]
        sc = fleet.HostScorecards(max_hosts=4, clock=lambda: clock[0])
        for i in range(6):
            clock[0] += 1.0
            sc.note_serve(f"h{i}", 10.0)
        assert len(sc._hosts) == 4
        assert "h0" not in sc._hosts and "h5" in sc._hosts

    def test_report_shape(self):
        sc = fleet.HostScorecards(min_serve_samples=1, min_population=1)
        sc.note_serve("h", 42.0)
        sc.note_download("h", 10.0, {"dcn_ms": 8, "stall_ms": 0,
                                     "store_ms": 2})
        rep = sc.report()
        row = rep["hosts"][0]
        assert row["host"] == "h" and row["serve_ewma_ms"] == 42.0
        assert row["phase_ewma_ms"]["dcn"] > 0
        assert rep["hosts_tracked"] == 1


# --------------------------------------------------------------------- #
# Decision audit log
# --------------------------------------------------------------------- #

class TestDecisionLog:
    def test_ring_bound_and_newest_first(self):
        d = fleet.DecisionLog(cap=8)
        for i in range(20):
            d.record("handout", task=f"t{i}", host="h")
        q = d.query(limit=100)
        assert len(q["decisions"]) == 8
        assert q["decisions"][0]["task"] == "t19"   # newest first
        assert q["dropped"] == 12
        assert q["recorded_total"] == 20

    def test_filters_match_subject_and_alternatives(self):
        d = fleet.DecisionLog()
        d.record("handout", task="t1", host="child-h", peer="p",
                 chosen=("par-a", "par-b"), rejected=("par-c",))
        d.record("quarantine", task="t1", host="par-c", reason="corrupt")
        d.record("handout", task="t2", host="other")
        # host filter matches chosen parents...
        assert len(d.query(host="par-a")["decisions"]) == 1
        # ...and rejected alternatives (why did X NOT get picked).
        got = d.query(host="par-c")["decisions"]
        assert {g["kind"] for g in got} == {"handout", "quarantine"}
        assert len(d.query(task="t1")["decisions"]) == 2
        assert len(d.query(kind="quarantine")["decisions"]) == 1

    def test_decision_metric_counts_kinds(self):
        from dragonfly2_tpu.pkg import metrics as metrics_mod

        d = fleet.DecisionLog()
        d.record("back_source", task="t", host="h", reason="first peer")
        text = metrics_mod.render()[0].decode()
        assert "dragonfly_tpu_scheduler_decisions_total" in text


# --------------------------------------------------------------------- #
# Service wiring: the report paths feed the observatory
# --------------------------------------------------------------------- #

class TestServiceWiring:
    def test_reports_feed_series_scorecards_and_decisions(self, run_async):
        async def body():
            svc = SchedulerService(SchedulerConfig())
            _h, task, peer_a = svc._resolve(
                mk_body("host-a", "peer-a", slice_="s1"))
            _h2, _t, peer_b = svc._resolve(
                mk_body("host-b", "peer-b", slice_="s2"))
            svc._handle_pieces_finished({"pieces": [
                {"piece_num": 0, "range_start": 0, "range_size": 4096,
                 "download_cost_ms": 25, "dst_peer_id": "peer-b",
                 "timings": {"dcn_ms": 20, "stall_ms": 0, "store_ms": 5}},
                {"piece_num": 1, "range_start": 4096, "range_size": 4096,
                 "download_cost_ms": 35, "dst_peer_id": "peer-b"},
            ]}, task, peer_a)
            svc._handle_piece_finished({"piece": {
                "piece_num": 2, "range_start": 8192, "range_size": 4096,
                "download_cost_ms": 7, "dst_peer_id": "peer-b"}},
                task, peer_a)
            svc._handle_piece_failed(
                {"piece_num": 3, "parent_id": "peer-b",
                 "temporary": False, "reason": "corrupt"}, task, peer_a)
            f = svc.fleet
            totals = f.series.window(60)["totals"]
            assert totals["pieces_landed"] == 3
            # host-a (s1) pulled from host-b (s2): cross-slice bytes.
            assert totals["bytes_cross"] == 3 * 4096
            assert totals["failed_corrupt"] == 1
            assert totals["quarantines"] == 1
            cards = {r["host"]: r for r in f.hosts_report()["hosts"]}
            assert cards["host-b"]["serve_samples"] == 3
            assert cards["host-b"]["failures"].get("corrupt") == 1.0
            # One per PIECE (2 batched + 1 single), same unit as
            # serve_samples — a batch of k weighs like k singles.
            assert cards["host-a"]["down_samples"] == 3
            q = f.decisions.query(host="host-b", kind="quarantine")
            assert q["decisions"][0]["reason"] == "corrupt"
            # Gauge sampler sees the resource registries.
            now = svc._fleet_gauges()
            assert now["hosts_total"] == 2
            assert now["hosts_quarantined"] == 1

        run_async(body(), timeout=30)

    def test_duplicate_reports_not_double_counted(self, run_async):
        async def body():
            svc = SchedulerService(SchedulerConfig())
            _h, task, peer = svc._resolve(mk_body("h", "p"))
            piece = {"piece_num": 0, "range_start": 0, "range_size": 64,
                     "download_cost_ms": 5}
            svc._handle_piece_finished({"piece": piece}, task, peer)
            svc._handle_piece_finished({"piece": piece}, task, peer)
            svc._handle_pieces_finished({"pieces": [piece]}, task, peer)
            totals = svc.fleet.series.window(60)["totals"]
            assert totals["pieces_landed"] == 1

        run_async(body(), timeout=30)

    def test_fleet_disabled_removes_hooks(self, run_async):
        async def body():
            cfg = SchedulerConfig()
            cfg.fleet.enabled = False
            svc = SchedulerService(cfg)
            assert svc.fleet is None
            assert svc.scheduling.fleet is None
            _h, task, peer = svc._resolve(mk_body("h", "p"))
            svc._handle_piece_finished({"piece": {
                "piece_num": 0, "range_start": 0, "range_size": 64,
                "download_cost_ms": 5}}, task, peer)   # must not blow up

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# Acceptance e2e: seeded consistently-slow host
# --------------------------------------------------------------------- #

class FakeStream:
    def __init__(self, open_body):
        self.open_body = open_body
        self.to_sched: asyncio.Queue = asyncio.Queue()
        self.to_peer: asyncio.Queue = asyncio.Queue()

    async def send(self, body):
        await self.to_peer.put(body)

    async def recv(self, timeout=None):
        return await self.to_sched.get()


class TestStragglerE2E:
    """One host serves slowly across MANY tasks (seeded costs: the chaos
    discipline — one constant decides, the schedule replays). The fleet
    must name it at /debug/fleet/hosts, exclude it from later handouts,
    and explain each exclusion at /debug/fleet/decisions?host=<slow>."""

    SLOW = "host-3"
    SEED_COSTS = {True: 1200, False: 12}   # is_slow -> served cost_ms

    def _build(self):
        cfg = SchedulerConfig()
        cfg.seed_peer_enabled = False
        cfg.fleet.min_serve_samples = 4
        cfg.fleet.min_population = 6
        return SchedulerService(cfg)

    def test_slow_host_flagged_filtered_and_explained(self, run_async):
        import aiohttp

        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        async def body():
            svc = self._build()
            n_hosts, n_tasks, pieces = 10, 3, 8
            # Cross-task report storm: every host downloads every task,
            # each piece attributed to a ring-neighbor parent — so every
            # host also SERVES across tasks. Pieces served by SLOW carry
            # the seeded slow cost.
            for t in range(n_tasks):
                task_id = f"task-{t}"
                peers = {}
                for i in range(n_hosts):
                    _h, task, peer = svc._resolve(
                        mk_body(f"host-{i}", f"p{t}-{i}", task_id))
                    # The storm skips the announce stream; candidates
                    # must still be in a serving state.
                    peer.fsm.event("register_normal")
                    peer.fsm.event("download")
                    svc._mark_task_running(task)
                    peers[i] = (task, peer)
                for i in range(n_hosts):
                    task, peer = peers[i]
                    reports = []
                    for n in range(pieces):
                        j = (i + 1 + n) % n_hosts     # rotating parent
                        if j == i:
                            j = (i + 1) % n_hosts
                        reports.append({
                            "piece_num": n, "range_start": n * 65536,
                            "range_size": 65536,
                            "download_cost_ms": self.SEED_COSTS[
                                f"host-{j}" == self.SLOW],
                            "dst_peer_id": f"p{t}-{j}"})
                    svc._handle_pieces_finished({"pieces": reports},
                                                task, peer)
            flags = svc.fleet.scorecards.recompute_stragglers()
            assert flags == {self.SLOW}

            # A late child registers over a REAL announce stream: the
            # handout must exclude the flagged host, and the exclusion
            # must be auditable.
            stream = FakeStream(mk_body("host-late", "p-late", "task-0"))
            server = asyncio.ensure_future(svc.announce_peer(stream, None))
            await stream.to_sched.put({"type": "register"})
            msg = await asyncio.wait_for(stream.to_peer.get(), timeout=30)
            assert msg["type"] == "normal_task"
            handed = {(p.get("host") or {}).get("id")
                      for p in msg["parents"]}
            assert handed and self.SLOW not in handed
            await stream.to_sched.put(None)
            await asyncio.wait_for(server, timeout=30)

            # The acceptance surface: the scheduler's debug endpoints.
            srv = MetricsServer(fleet=svc.fleet)
            port = await srv.serve("127.0.0.1", 0)
            base = f"http://127.0.0.1:{port}"
            try:
                async with aiohttp.ClientSession() as sess:
                    async with sess.get(f"{base}/debug/fleet/hosts") as r:
                        assert r.status == 200
                        hosts = await r.json()
                    assert hosts["stragglers"] == [self.SLOW]
                    top = hosts["hosts"][0]
                    assert top["host"] == self.SLOW and top["straggler"]
                    assert top["zscore"] >= 3.0
                    async with sess.get(
                            f"{base}/debug/fleet/decisions",
                            params={"host": self.SLOW,
                                    "kind": "straggler_filter"}) as r:
                        assert r.status == 200
                        dec = await r.json()
                    assert dec["decisions"], \
                        "slow host's demotions are not explained"
                    assert dec["decisions"][0]["host"] == self.SLOW
                    assert "straggler" in dec["decisions"][0]["reason"]
                    # The handout that excluded it is also on record.
                    async with sess.get(
                            f"{base}/debug/fleet/decisions",
                            params={"task": "task-0",
                                    "kind": "handout"}) as r:
                        hand = await r.json()
                    assert any(d["peer"] == "p-late"
                               for d in hand["decisions"])
            finally:
                await srv.close()

        run_async(body(), timeout=120)

    def test_recovered_host_unflagged_after_fast_serves(self, run_async):
        """Advisory means reversible: once the host serves fast again,
        the EWMA falls and the next recompute clears the flag."""

        async def body():
            svc = self._build()
            sc = svc.fleet.scorecards
            for i in range(8):
                for _ in range(6):
                    sc.note_serve(f"host-{i}",
                                  1200 if i == 3 else 12)
            assert sc.recompute_stragglers() == {"host-3"}
            for _ in range(40):
                sc.note_serve("host-3", 12)
            assert sc.recompute_stragglers() == set()

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# Scrape under load (satellite): endpoints answer mid-broadcast
# --------------------------------------------------------------------- #

class TestScrapeUnderLoad:
    def test_metrics_and_fleet_endpoints_respond_mid_broadcast(
            self, run_async):
        import time as time_mod

        import aiohttp

        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        from dragonfly2_tpu.pkg import prof as proflib

        async def body():
            cfg = SchedulerConfig()
            cfg.seed_peer_enabled = False
            cfg.scheduling.retry_interval = 0.05
            svc = SchedulerService(cfg)
            # Armed observatory: the /debug/prof* endpoints must answer
            # mid-broadcast with the sampler LIVE, same 1s bound.
            obs = proflib.install()
            probe = obs.arm_loop("scrape-test")
            srv = MetricsServer(pod_flight=svc.pod_flight, fleet=svc.fleet,
                                prof=obs)
            port = await srv.serve("127.0.0.1", 0)
            base = f"http://127.0.0.1:{port}"

            n_hosts, n_pieces = 24, 12
            done = asyncio.Event()

            async def peer(i: int):
                stream = FakeStream(mk_body(
                    f"bh-{i}", f"bp-{i}", "bcast",
                    slice_=f"s{i // 8}"))
                server = asyncio.ensure_future(
                    svc.announce_peer(stream, None))
                await stream.to_sched.put({"type": "register"})
                msg = await asyncio.wait_for(stream.to_peer.get(),
                                             timeout=60)
                if msg.get("type") == "normal_task":
                    await stream.to_sched.put({
                        "type": "download_started",
                        "content_length": n_pieces * 65536,
                        "piece_size": 65536,
                        "total_piece_count": n_pieces})
                for n in range(n_pieces):
                    await asyncio.sleep(0.02)
                    await stream.to_sched.put({
                        "type": "piece_finished",
                        "piece": {"piece_num": n,
                                  "range_start": n * 65536,
                                  "range_size": 65536,
                                  "download_cost_ms": 3,
                                  "dst_peer_id": ""}})
                # Hold the stream open until the scrapes finish: the
                # broadcast must be MID-FLIGHT while we probe.
                await done.wait()
                await stream.to_sched.put({
                    "type": "download_finished",
                    "content_length": n_pieces * 65536,
                    "piece_size": 65536,
                    "total_piece_count": n_pieces})
                await stream.to_sched.put(None)
                await asyncio.wait_for(server, timeout=60)

            peers = [asyncio.ensure_future(peer(i))
                     for i in range(n_hosts)]
            await asyncio.sleep(0.1)    # mid-flight: pieces streaming
            try:
                async with aiohttp.ClientSession() as sess:
                    for path, kind in (
                            ("/metrics", "prom"),
                            ("/debug/fleet?window=60", "json"),
                            ("/debug/fleet/hosts", "json"),
                            ("/debug/fleet/decisions", "json"),
                            ("/debug/fleet/info", "json"),
                            ("/debug/prof?topn=10", "json"),
                            ("/debug/prof/runtime", "json"),
                            ("/debug/prof/flame?format=folded", "text")):
                        t0 = time_mod.perf_counter()
                        async with sess.get(base + path) as r:
                            assert r.status == 200, path
                            raw = await r.read()
                        dt = time_mod.perf_counter() - t0
                        assert dt < 1.0, f"{path} took {dt:.2f}s under load"
                        if kind == "json":
                            json.loads(raw)     # valid JSON
                        elif kind == "prom":
                            assert b"dragonfly_tpu" in raw
                    # Mid-flight sanity: the observatory saw the storm.
                    async with sess.get(
                            f"{base}/debug/fleet?window=60") as r:
                        snap = await r.json()
                    assert snap["series"]["totals"]["registers"] >= n_hosts
                    assert snap["series"]["totals"]["pieces_landed"] > 0
            finally:
                done.set()
                await asyncio.wait_for(
                    asyncio.gather(*peers, return_exceptions=True),
                    timeout=120)
                await srv.close()
                probe.disarm()
                obs.probes.pop(probe.name, None)
                proflib.release(obs)

        run_async(body(), timeout=180)
