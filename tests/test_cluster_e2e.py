"""Cluster control tower E2E: real manager + 2 schedulers + 4 daemons.

The acceptance battery for the manager-side fleet rollup
(dragonfly2_tpu/pkg/cluster.py): every process is a real
``python -m dragonfly2_tpu.cli.main`` subprocess on localhost.

One scenario, staged:

1. Serve choreography gives daemon d1 fast serve samples and d2 slow
   ones (d3 runs under a DF_CHAOS ``piece.body`` stall, so every piece
   it pulls FROM d2 reports an inflated cost) — the manager's merged
   ``/debug/cluster`` must attribute the d2 straggler flag to its
   owning scheduler (sched-a), which it only learned via keepalive
   fleet frames.
2. SIGSTOP sched-b: the manager's keepalive GC marks it inactive — a
   ``lapse`` journal event plus ``manager_cluster_schedulers
   {state="inactive"}``; SIGCONT brings a ``return`` event.
3. SIGKILL the manager and respawn it on the same sqlite db and ports:
   the telemetry spool restores the shipped window
   (``restored_frames > 0``) before any fresh keepalive arrives.
4. ``dfget --explain --cluster --manager`` renders the merged text
   view from the restarted manager over the same drpc wire.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import random
import signal
import socket
import subprocess
import sys
import time

from aiohttp import ClientSession, ClientTimeout, web

from dragonfly2_tpu.pkg.metrics import parse_labeled_samples
from dragonfly2_tpu.pkg.piece import Range

# 12 MiB = 3 pieces at the 4 MiB default — enough serve samples per
# pull without making the battery heavy.
CONTENT = bytes(random.Random(99).randbytes(12 * 1024 * 1024))
SHA = hashlib.sha256(CONTENT).hexdigest()

# Every piece d3 pulls stalls this long before the first body chunk —
# INSIDE the downloader's cost timer, so the parent's serve EWMA (as
# the scheduler experiences it) inflates by ~350ms/piece.
STALL_S = 0.35
CHAOS_SPEC = json.dumps({
    "seed": 1,
    "rules": [{"site": "piece.body", "kind": "stall",
               "rate": 1.0, "stall_s": STALL_S}],
})

SCHED_YAML = """\
hostname: {hostname}
manager_keepalive_interval: 0.5
fleet:
  straggler_z: 0.3
  min_serve_samples: 1
  min_population: 2
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _start_origin():
    async def blob(request: web.Request) -> web.Response:
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(CONTENT))
            data = CONTENT[r.start:r.start + r.length]
            return web.Response(status=206, body=data, headers={
                "Accept-Ranges": "bytes",
                "Content-Range":
                    f"bytes {r.start}-{r.start + r.length - 1}"
                    f"/{len(CONTENT)}"})
        return web.Response(body=CONTENT,
                            headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/model.bin", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


def _spawn(args: list[str], log_path: str,
           extra_env: "dict | None" = None) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    if extra_env:
        env.update(extra_env)
    logf = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "dragonfly2_tpu.cli.main", *args],
        stdout=logf, stderr=subprocess.STDOUT, env=env)


def _wait_sock(path: str, timeout: float = 90.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.1)
    return False


def _tail(path, n: int = 2000) -> str:
    try:
        return open(path).read()[-n:]
    except OSError:
        return "<no log>"


async def _wait_healthy(http: ClientSession, base: str,
                        log_path: str) -> None:
    for _ in range(300):
        try:
            async with http.get(f"{base}/healthy") as r:
                if r.status == 200:
                    return
        except Exception:
            pass
        await asyncio.sleep(0.1)
    raise AssertionError("manager never healthy: " + _tail(log_path))


async def _poll_json(http: ClientSession, url: str, pred,
                     timeout: float = 30.0, what: str = ""):
    """Poll ``url`` until ``pred(json)`` is truthy; returns the last
    body either way so assertion messages show what the manager saw."""
    deadline = time.monotonic() + timeout
    body = None
    while time.monotonic() < deadline:
        try:
            async with http.get(url) as r:
                if r.status == 200:
                    body = await r.json()
                    if pred(body):
                        return body
        except Exception:
            pass
        await asyncio.sleep(0.25)
    raise AssertionError(f"timeout waiting for {what or url}: {body}")


def test_cluster_control_tower_e2e(run_async, tmp_path):
    async def run():
        runner, origin_port = await _start_origin()
        rest_port, grpc_port = _free_port(), _free_port()
        mgr_metrics = _free_port()
        mgr_args = [
            "manager", "--host", "127.0.0.1", "--port", str(rest_port),
            "--grpc-port", str(grpc_port),
            "--metrics-port", str(mgr_metrics),
            "--db", str(tmp_path / "manager.db"),
            "--keepalive-timeout", "2",
            "--keepalive-gc-interval", "0.5"]
        mbase = f"http://127.0.0.1:{mgr_metrics}"
        procs: dict[str, subprocess.Popen] = {}
        homes: dict[str, str] = {}
        try:
            procs["manager"] = _spawn(mgr_args, str(tmp_path / "manager.log"))
            async with ClientSession(
                    timeout=ClientTimeout(total=10)) as http:
                await _wait_healthy(http, f"http://127.0.0.1:{rest_port}",
                                    str(tmp_path / "manager.log"))

                # Two schedulers with distinct advertised hostnames and a
                # straggler config a 2-host population can actually trip.
                sched_ports = {}
                for name in ("sched-a", "sched-b"):
                    cfg_path = str(tmp_path / f"{name}.yaml")
                    with open(cfg_path, "w") as f:
                        f.write(SCHED_YAML.format(hostname=name))
                    port = _free_port()
                    sched_ports[name] = port
                    procs[name] = _spawn(
                        ["scheduler", "--config", cfg_path,
                         "--host", "127.0.0.1", "--port", str(port),
                         "--manager", f"127.0.0.1:{grpc_port}"],
                        str(tmp_path / f"{name}.log"))

                # d1/d2/d3 on sched-a (d3 under a piece.body stall chaos:
                # its pulls make its PARENTS look slow); d4 on sched-b.
                for name, sched, env in (
                        ("d1", "sched-a", None),
                        ("d2", "sched-a", None),
                        ("d3", "sched-a", {"DF_CHAOS": CHAOS_SPEC}),
                        ("d4", "sched-b", None)):
                    home = str(tmp_path / name)
                    homes[name] = home
                    procs[name] = _spawn(
                        ["daemon", "--work-home", home,
                         "--hostname", name,
                         "--scheduler",
                         f"127.0.0.1:{sched_ports[sched]}"],
                        str(tmp_path / f"{name}.log"), extra_env=env)
                for name in ("d1", "d2", "d3", "d4"):
                    ok = await asyncio.to_thread(
                        _wait_sock, f"{homes[name]}/run/dfdaemon.sock")
                    assert ok, _tail(tmp_path / f"{name}.log")

                def url(v: int) -> str:
                    return (f"http://127.0.0.1:{origin_port}"
                            f"/model.bin?v={v}")

                async def dfget(name: str, v: int, out: str,
                                extra: "list | None" = None) -> str:
                    p = _spawn(
                        ["dfget", url(v), "-O", out,
                         "--work-home", homes[name], "--no-daemon",
                         *(extra or [])], out + ".log")
                    rc = await asyncio.to_thread(p.wait, 120)
                    assert rc == 0, _tail(out + ".log")
                    with open(out, "rb") as f:
                        got = hashlib.sha256(f.read()).hexdigest()
                    assert got == SHA, f"{name} v{v} sha mismatch"
                    return _tail(out + ".log")

                # Stage 1 — serve choreography. t1: d1 back-sources, d2
                # pulls from it (fast serve samples for d1). t2: d2
                # back-sources, d3 pulls from it through the stall (slow
                # samples for d2). t3 after the 2s recompute cadence:
                # one more clean pull re-triggers the straggler sweep
                # with both hosts scored.
                await dfget("d1", 1, str(tmp_path / "t1a.bin"))
                await dfget("d2", 1, str(tmp_path / "t1b.bin"))
                await dfget("d2", 2, str(tmp_path / "t2a.bin"))
                await dfget("d3", 2, str(tmp_path / "t2b.bin"))
                await asyncio.sleep(2.1)
                await dfget("d1", 3, str(tmp_path / "t3a.bin"))
                await dfget("d2", 3, str(tmp_path / "t3b.bin"))

                # The merged view must attribute the d2 flag to sched-a:
                # that mapping only exists if keepalive fleet frames
                # carried the scorecard verdict into the manager.
                def straggler_attributed(rep) -> bool:
                    return any(
                        h.startswith("d2-") and s.startswith("sched-a@")
                        for h, s in (rep.get("stragglers") or {}).items())

                rep = await _poll_json(
                    http, f"{mbase}/debug/cluster?window=600",
                    straggler_attributed, timeout=40.0,
                    what="d2 straggler attributed to sched-a")
                assert rep["totals"].get("pieces_landed", 0) >= 1, rep
                assert not any(h.startswith(("d1-", "d3-"))
                               for h in rep["stragglers"]), rep

                scheds = await _poll_json(
                    http, f"{mbase}/debug/cluster/schedulers",
                    lambda r: {s["scheduler"].split("@")[0]
                               for s in r["schedulers"]
                               if s["state"] == "active"}
                    >= {"sched-a", "sched-b"},
                    what="both schedulers active with frames")
                by_name = {s["scheduler"].split("@")[0]: s
                           for s in scheds["schedulers"]}
                assert by_name["sched-a"]["frames"] >= 1, scheds
                ev = await _poll_json(
                    http, f"{mbase}/debug/cluster/events?kind=straggler",
                    lambda r: any(e["subject"].startswith("d2-")
                                  for e in r["events"]),
                    what="straggler journal event for d2")
                assert all(e["kind"] == "straggler" for e in ev["events"])

                # Stage 2 — keepalive lapse. Freeze sched-b: its
                # keepalives stop but the process (and TCP stream) stay
                # up, exactly the silence the manager GC must call.
                procs["sched-b"].send_signal(signal.SIGSTOP)
                await _poll_json(
                    http, f"{mbase}/debug/cluster/events?kind=lapse",
                    lambda r: any(
                        e["scheduler"].startswith("sched-b@")
                        for e in r["events"]),
                    timeout=20.0, what="lapse event for sched-b")
                async with http.get(f"{mbase}/metrics") as r:
                    assert r.status == 200
                    states = parse_labeled_samples(
                        await r.text(),
                        "dragonfly_tpu_manager_cluster_schedulers",
                        "state")
                assert states.get("inactive", 0) >= 1, states
                procs["sched-b"].send_signal(signal.SIGCONT)
                await _poll_json(
                    http, f"{mbase}/debug/cluster/events?kind=return",
                    lambda r: any(
                        e["scheduler"].startswith("sched-b@")
                        for e in r["events"]),
                    timeout=20.0, what="return event for sched-b")

                # Stage 3 — manager restart. SIGKILL + respawn on the
                # same db/ports: the spool must hand the restarted
                # process its shipped window before any fresh keepalive.
                procs["manager"].kill()
                await asyncio.to_thread(procs["manager"].wait, 15)
                procs["manager"] = _spawn(
                    mgr_args, str(tmp_path / "manager2.log"))
                await _wait_healthy(http, f"http://127.0.0.1:{rest_port}",
                                    str(tmp_path / "manager2.log"))
                rep = await _poll_json(
                    http, f"{mbase}/debug/cluster?window=600",
                    lambda r: r.get("restored_frames", 0) >= 1
                    and any(k.startswith("sched-a@")
                            for k in (r.get("stragglers") or {}).values()),
                    timeout=30.0,
                    what="spool-restored view after manager restart")
                assert straggler_attributed(rep), rep

                # Stage 4 — the operator wire: dfget renders the SAME
                # merged view over drpc from the restarted manager.
                log4 = await dfget(
                    "d1", 1, str(tmp_path / "t4.bin"),
                    extra=["--explain", "--cluster",
                           "--manager", f"127.0.0.1:{grpc_port}"])
                assert "cluster view" in log4, log4
                assert "sched-a" in log4, log4
                assert "restored from spool" in log4, log4
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.send_signal(signal.SIGCONT)
                    p.send_signal(signal.SIGTERM)
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
            await runner.cleanup()

    run_async(run(), timeout=300)
