"""Submission-ring backends: byte-equivalence, failure shapes, metrics.

storage/io_ring.py promises every rung of its ladder (batch native
syscall loop, io_uring, thread pool, serial) lands identical bytes with
identical failure semantics behind LocalTaskStore's unchanged API. The
benches only prove the fast rung is fast; this suite proves no rung can
drift.
"""

from __future__ import annotations

import os
import random

import pytest

from dragonfly2_tpu.pkg.errors import StorageError
from dragonfly2_tpu.storage import io_ring
from dragonfly2_tpu.storage.local_store import LocalTaskStore, TaskStoreMetadata


def _available_rings():
    """Every backend constructible on this box. serial/threads always
    exist; batch and io_uring join when the native library (and kernel)
    allow."""
    rings = [io_ring.SubmissionRing("serial"),
             io_ring.SubmissionRing("threads")]
    batch = io_ring._probe_batch()
    if batch is not None:
        rings.append(batch)
    uring = io_ring._probe_io_uring()
    if uring is not None:
        rings.append(uring)
    return rings


@pytest.fixture
def data_fd(tmp_path):
    data = random.Random(21).randbytes(2 << 20)
    path = tmp_path / "blob"
    path.write_bytes(data)
    fd = os.open(path, os.O_RDWR)
    yield fd, data
    os.close(fd)


@pytest.fixture(autouse=True)
def restore_singleton():
    prev = io_ring.swap_ring(None)
    yield
    io_ring.swap_ring(prev)


def test_read_spans_byte_identical_across_backends(data_fd):
    fd, data = data_fd
    rng = random.Random(22)
    spans = [(rng.randrange(len(data) - 9000), rng.randrange(1, 9000))
             for _ in range(40)]
    total = sum(ln for _, ln in spans)
    offsets, at = [], 0
    for _, ln in spans:
        offsets.append(at)
        at += ln
    expected = b"".join(data[o:o + ln] for o, ln in spans)
    for ring in _available_rings():
        buf = bytearray(total)
        got = ring.read_spans(fd, spans, buf, offsets)
        assert got == total, ring.backend
        assert bytes(buf) == expected, f"{ring.backend} corrupted bytes"
        ring.close()


def test_read_spans_batch_larger_than_ring_depth(data_fd):
    # io_uring submits in waves of sq_entries; batches longer than the
    # ring depth must still complete (and every other rung trivially so).
    fd, data = data_fd
    n = io_ring._DEPTH * 2 + 7
    spans = [((i * 997) % (len(data) - 512), 512) for i in range(n)]
    offsets = [i * 512 for i in range(n)]
    expected = b"".join(data[o:o + 512] for o, _ in spans)
    for ring in _available_rings():
        buf = bytearray(n * 512)
        ring.read_spans(fd, spans, buf, offsets)
        assert bytes(buf) == expected, ring.backend
        ring.close()


def test_zero_length_spans_skipped(data_fd):
    fd, data = data_fd
    spans = [(0, 100), (500, 0), (1000, 50)]
    offsets = [0, 100, 100]
    for ring in _available_rings():
        buf = bytearray(150)
        got = ring.read_spans(fd, spans, buf, offsets)
        assert got == 150
        assert bytes(buf) == data[:100] + data[1000:1050], ring.backend
        ring.close()


def test_short_read_same_error_every_backend(data_fd):
    fd, data = data_fd
    spans = [(0, 1024), (len(data) - 100, 1024)]   # second runs past EOF
    offsets = [0, 1024]
    for ring in _available_rings():
        buf = bytearray(2048)
        with pytest.raises(io_ring.ShortReadError):
            ring.read_spans(fd, spans, buf, offsets)
        ring.close()


def test_write_chunks_byte_identical_across_backends(tmp_path):
    chunks = [random.Random(23 + i).randbytes(random.Random(i).randrange(1, 5000))
              for i in range(30)]
    offsets, at = [], 0
    for c in chunks:
        offsets.append(at)
        at += len(c)
    expected = b"".join(chunks)
    for ring in _available_rings():
        path = tmp_path / f"w-{ring.backend}"
        fd = os.open(path, os.O_RDWR | os.O_CREAT)
        try:
            total = ring.write_chunks(fd, chunks, offsets)
            assert total == len(expected)
            assert path.read_bytes() == expected, ring.backend
        finally:
            os.close(fd)
            ring.close()


def test_store_read_spans_translates_short_read(tmp_path):
    store = LocalTaskStore(
        str(tmp_path / "s"),
        TaskStoreMetadata(task_id="ring-t", piece_size=1 << 16))
    with open(os.path.join(str(tmp_path / "s"), "data"), "wb") as f:
        f.write(b"x" * 4096)
    buf = bytearray(8192)
    # Multi-span batches route through the ring; a span past EOF must be
    # the same StorageError the serial path raises.
    with pytest.raises(StorageError):
        store.read_spans_into([(0, 1024), (3800, 1024)], buf)


def test_store_read_spans_matches_serial(tmp_path):
    data = random.Random(29).randbytes(1 << 20)
    store = LocalTaskStore(
        str(tmp_path / "s"),
        TaskStoreMetadata(task_id="ring-t", piece_size=1 << 18))
    with open(os.path.join(str(tmp_path / "s"), "data"), "wb") as f:
        f.write(data)
    rng = random.Random(31)
    spans = [(rng.randrange(len(data) - 8192), rng.randrange(1, 8192))
             for _ in range(25)]
    total = sum(ln for _, ln in spans)
    ref = bytearray(total)
    io_ring.swap_ring(io_ring.SubmissionRing("serial"))
    store.read_spans_into(spans, ref)
    for ring in _available_rings():
        io_ring.swap_ring(ring)
        buf = bytearray(total)
        got = store.read_spans_into(spans, buf)
        assert got == total
        assert buf == ref, f"{ring.backend} diverged from serial store path"


def test_ring_metrics_flow(data_fd):
    fd, data = data_fd
    ring = io_ring.get_ring()
    sub = io_ring.RING_SUBMISSIONS.labels(ring.backend)
    spans = io_ring.RING_SPANS.labels("read")
    sub0, spans0 = sub._value.get(), spans._value.get()
    buf = bytearray(2048)
    ring.read_spans(fd, [(0, 1024), (4096, 1024)], buf, [0, 1024])
    assert sub._value.get() == sub0 + 1
    assert spans._value.get() == spans0 + 2


def test_env_pins_rung(monkeypatch):
    monkeypatch.setenv("DF_RING_BACKEND", "serial")
    assert io_ring._select_ring().backend == "serial"
    monkeypatch.setenv("DF_RING_BACKEND", "off")
    assert io_ring._select_ring().backend == "serial"
    monkeypatch.setenv("DF_RING_BACKEND", "threads")
    assert io_ring._select_ring().backend == "threads"
    monkeypatch.delenv("DF_RING_BACKEND")
    auto = io_ring._select_ring()
    assert auto.backend in ("batch", "threads")
    auto.close()
    monkeypatch.setenv("DF_RING_BACKEND", "io_uring")
    pinned = io_ring._select_ring()
    assert pinned.backend in ("io_uring", "threads")   # threads = degrade
    pinned.close()
