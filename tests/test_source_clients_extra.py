"""s3/hdfs/oras source clients against hermetic fakes.

Reference: pkg/source/clients/{s3,hdfs,oras}protocol — tested here the way
the reference e2e suite uses minio/fixtures: in-process servers speaking
just enough of each protocol.
"""

from __future__ import annotations

import hashlib
import json
import os

import pytest
from aiohttp import web

from dragonfly2_tpu.pkg.objectstorage.s3 import S3ObjectStorage
from dragonfly2_tpu.source.client import Request, get_client
from dragonfly2_tpu.source.clients.hdfs import HDFSSourceClient
from dragonfly2_tpu.source.clients.oras import OrasSourceClient
from dragonfly2_tpu.source.clients.s3 import S3SourceClient

from tests.test_objectstorage import start_fake_s3

PAYLOAD = os.urandom(256 * 1024)


# -- s3 ----------------------------------------------------------------------

def test_s3_source_client(run_async):
    async def run():
        runner, port = await start_fake_s3()
        backend = S3ObjectStorage(endpoint=f"http://127.0.0.1:{port}",
                                  access_key="ak", secret_key="sk")
        client = S3SourceClient(backend=backend)
        try:
            await backend.create_bucket("ckpt")
            await backend.put_object("ckpt", "model/w.bin", PAYLOAD)
            url = "s3://ckpt/model/w.bin"
            assert await client.get_content_length(Request(url)) == len(PAYLOAD)
            assert await client.is_support_range(Request(url))
            resp = await client.download(Request(url))
            assert await resp.read_all() == PAYLOAD
            ranged = await client.download(
                Request(url).with_range("bytes=100-299"))
            assert await ranged.read_all() == PAYLOAD[100:300]
            listing = await client.list_metadata(Request("s3://ckpt/model"))
            assert [e.name for e in listing] == ["model/w.bin"]
        finally:
            await client.close()
            await runner.cleanup()

    run_async(run())


# -- hdfs (webhdfs fake) -----------------------------------------------------

async def start_fake_webhdfs():
    files = {"/data/shard.bin": PAYLOAD}

    async def handler(request: web.Request) -> web.Response:
        path = request.path[len("/webhdfs/v1"):]
        op = request.query.get("op", "")
        if op == "GETFILESTATUS":
            data = files.get(path)
            if data is None:
                return web.Response(status=404)
            return web.json_response({"FileStatus": {
                "length": len(data), "type": "FILE", "pathSuffix": ""}})
        if op == "OPEN":
            data = files.get(path)
            if data is None:
                return web.Response(status=404)
            offset = int(request.query.get("offset", 0))
            length = int(request.query.get("length", len(data) - offset))
            return web.Response(body=data[offset:offset + length])
        if op == "LISTSTATUS":
            entries = []
            for p, data in files.items():
                if p.startswith(path.rstrip("/") + "/") or p == path:
                    entries.append({"pathSuffix": p.rsplit("/", 1)[-1]
                                    if p != path else "",
                                    "type": "FILE", "length": len(data)})
            return web.json_response({"FileStatuses": {"FileStatus": entries}})
        return web.Response(status=400)

    app = web.Application()
    app.router.add_get("/webhdfs/v1/{tail:.*}", handler)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


def test_hdfs_source_client(run_async):
    async def run():
        runner, port = await start_fake_webhdfs()
        client = HDFSSourceClient()
        try:
            url = f"hdfs://127.0.0.1:{port}/data/shard.bin"
            length, support = await client.probe(Request(url))
            assert length == len(PAYLOAD) and support
            resp = await client.download(Request(url))
            assert await resp.read_all() == PAYLOAD
            ranged = await client.download(Request(url).with_range("bytes=0-99"))
            assert await ranged.read_all() == PAYLOAD[:100]
            listing = await client.list_metadata(
                Request(f"hdfs://127.0.0.1:{port}/data"))
            assert [e.name for e in listing] == ["shard.bin"]
            with pytest.raises(Exception):
                await client.download(
                    Request(f"hdfs://127.0.0.1:{port}/nope"))
        finally:
            await client.close()
            await runner.cleanup()

    run_async(run())


# -- oras (OCI registry fake with bearer auth) -------------------------------

async def start_fake_oci():
    digest = "sha256:" + hashlib.sha256(PAYLOAD).hexdigest()
    manifest = {"schemaVersion": 2,
                "layers": [{"digest": digest, "size": len(PAYLOAD)}]}
    state = {"token_fetches": 0}

    async def token(request: web.Request) -> web.Response:
        state["token_fetches"] += 1
        assert "repository:models/llama:pull" in request.query.get("scope", "")
        return web.json_response({"token": "tok-123"})

    def _authed(request: web.Request) -> bool:
        return request.headers.get("Authorization") == "Bearer tok-123"

    async def manifests(request: web.Request) -> web.Response:
        if not _authed(request):
            return web.Response(status=401, headers={
                "WWW-Authenticate":
                    f'Bearer realm="http://127.0.0.1:{state["port"]}/token",'
                    f'service="fake-oci"'})
        return web.json_response(manifest)

    async def blobs(request: web.Request) -> web.Response:
        if not _authed(request):
            return web.Response(status=401, headers={
                "WWW-Authenticate":
                    f'Bearer realm="http://127.0.0.1:{state["port"]}/token"'})
        assert request.match_info["digest"] == digest
        rng = request.headers.get("Range")
        if rng:
            spec = rng.split("=", 1)[1]
            s, _, e = spec.partition("-")
            start = int(s)
            end = int(e) if e else len(PAYLOAD) - 1
            return web.Response(status=206, body=PAYLOAD[start:end + 1])
        return web.Response(body=PAYLOAD)

    app = web.Application()
    app.router.add_get("/token", token)
    app.router.add_get("/v2/models/llama/manifests/{tag}", manifests)
    app.router.add_get("/v2/models/llama/blobs/{digest}", blobs)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    state["port"] = site._server.sockets[0].getsockname()[1]
    return runner, state


def test_oras_source_client(run_async):
    async def run():
        runner, state = await start_fake_oci()
        client = OrasSourceClient(plain_http=True)
        try:
            url = f"oras://127.0.0.1:{state['port']}/models/llama:v1"
            length, support = await client.probe(Request(url))
            assert length == len(PAYLOAD) and support
            resp = await client.download(Request(url))
            assert await resp.read_all() == PAYLOAD
            ranged = await client.download(
                Request(url).with_range("bytes=10-19"))
            assert await ranged.read_all() == PAYLOAD[10:20]
            # Token fetched once, then reused.
            assert state["token_fetches"] == 1
        finally:
            await client.close()
            await runner.cleanup()

    run_async(run())


def test_registry_has_new_schemes():
    assert get_client("hdfs://nn:9870/x") is not None
    assert get_client("oras://reg/x:latest") is not None


def test_oss_and_obs_source_clients(run_async):
    """oss:// and obs:// ride the SigV4 client against S3-compatible
    vendor endpoints (reference ossprotocol/oss.go behavioral parity)."""
    from dragonfly2_tpu.source.clients.oss import OBSSourceClient, OSSSourceClient

    async def run():
        runner, port = await start_fake_s3()
        backend = S3ObjectStorage(endpoint=f"http://127.0.0.1:{port}",
                                  access_key="ak", secret_key="sk")
        oss = OSSSourceClient(backend=backend)
        try:
            await backend.create_bucket("b")
            await backend.put_object("b", "shard.tar", PAYLOAD)
            resp = await oss.download(Request("oss://b/shard.tar"))
            assert await resp.read_all() == PAYLOAD
            ranged = await oss.download(
                Request("oss://b/shard.tar").with_range("bytes=10-19"))
            assert await ranged.read_all() == PAYLOAD[10:20]
            # Wrong scheme rejected per client.
            import pytest

            from dragonfly2_tpu.pkg.errors import SourceError

            with pytest.raises(SourceError):
                await oss.download(Request("obs://b/shard.tar"))
            obs = OBSSourceClient(backend=backend)
            assert (await obs.download(Request("obs://b/shard.tar"))
                    ).status in (200, 206)
        finally:
            await oss.close()
            await runner.cleanup()

    run_async(run())
