"""Chaos fabric + graceful-degradation tests.

Fast tier-1: schedule determinism, disabled-by-default/zero-overhead
guards, per-fault injection units (corruption → crc reject → quarantine,
truncation, stall → watchdog, refusal), announce-stream recovery with
report flush, rpc reconnect backoff, source-client temporary
classification, scheduler-side typed demotion.

Slow (@chaos): a seeded 4-host pod e2e completing byte-identical under
25% parent death + corruption bursts, and converging to back-to-source
when every parent is refused.
"""

from __future__ import annotations

import asyncio
import hashlib
import math
import subprocess
import sys

import pytest

from dragonfly2_tpu.pkg import chaos as chaos_mod
from dragonfly2_tpu.pkg import digest as pkgdigest
from dragonfly2_tpu.pkg import retry as retrylib
from dragonfly2_tpu.pkg.errors import Code, DfError, SourceError, StorageError
from dragonfly2_tpu.pkg.quarantine import ParentQuarantine
from dragonfly2_tpu.storage import StorageManager, StorageOption, TaskStoreMetadata


@pytest.fixture(autouse=True)
def _chaos_disabled():
    """Every test starts and ends with the fabric disarmed."""
    chaos_mod.disable()
    yield
    chaos_mod.disable()


def make_store(tmp_path, task_id="chaos-t", piece_size=4, content_length=8):
    sm = StorageManager(StorageOption(data_dir=str(tmp_path / "data")))
    return sm.register_task(TaskStoreMetadata(
        task_id=task_id, peer_id="p1", url="http://x/f",
        piece_size=piece_size, content_length=content_length,
        total_piece_count=math.ceil(content_length / piece_size)
        if content_length >= 0 else -1))


# --------------------------------------------------------------------- #
# Schedule determinism
# --------------------------------------------------------------------- #

class TestSchedule:
    SPEC = {"seed": 42, "rules": [
        {"site": "piece.body", "kind": "corrupt", "rate": 0.3},
        {"site": "piece.request", "kind": "refuse", "rate": 0.2,
         "key_substr": "10.0.0.9"},
        {"site": "rpc.recv", "kind": "drop", "at": [3]},
    ]}

    @staticmethod
    def _drive(fabric):
        out = []
        # Interleave keys deliberately: determinism must hold per
        # (site, key) stream, independent of global call order.
        for n in range(40):
            for key in ("10.0.0.1:80|t|%d" % (n % 5), "10.0.0.9:80|t|0"):
                f = fabric.decide("piece.body" if n % 2 else "piece.request",
                                  key)
                out.append(f.kind if f else None)
            out.append((lambda f: f.kind if f else None)(
                fabric.decide("rpc.recv", "sched")))
        return out

    def test_same_seed_identical_schedule(self):
        a = chaos_mod.parse_spec(dict(self.SPEC))
        b = chaos_mod.parse_spec(dict(self.SPEC))
        assert self._drive(a) == self._drive(b)
        assert a.injected == b.injected

    def test_interleaving_independent(self):
        a = chaos_mod.parse_spec(dict(self.SPEC))
        b = chaos_mod.parse_spec(dict(self.SPEC))
        # Drive b's (site,key) streams in a shuffled global order: each
        # stream's n-th decision must still match a's.
        decisions_a = {}
        for n in range(12):
            f = a.decide("piece.body", "K1")
            decisions_a.setdefault("K1", []).append(f.kind if f else None)
            f = a.decide("piece.body", "K2")
            decisions_a.setdefault("K2", []).append(f.kind if f else None)
        decisions_b = {"K1": [], "K2": []}
        for n in range(12):   # all of K2 first, then K1
            f = b.decide("piece.body", "K2")
            decisions_b["K2"].append(f.kind if f else None)
        for n in range(12):
            f = b.decide("piece.body", "K1")
            decisions_b["K1"].append(f.kind if f else None)
        assert decisions_a == decisions_b

    def test_different_seed_differs(self):
        a = chaos_mod.parse_spec(dict(self.SPEC))
        other = dict(self.SPEC, seed=43)
        b = chaos_mod.parse_spec(other)
        assert self._drive(a) != self._drive(b)

    def test_at_and_max_fires(self):
        fabric = chaos_mod.parse_spec({"seed": 1, "rules": [
            {"site": "s", "kind": "drop", "at": [2, 4], "max_fires": 1}]})
        kinds = [fabric.decide("s", "k") for _ in range(5)]
        assert [k.kind if k else None for k in kinds] == \
            [None, "drop", None, None, None]   # max_fires caps the 2nd at


# --------------------------------------------------------------------- #
# Disabled by default: inert, unimported, hook-free
# --------------------------------------------------------------------- #

class TestDisabledByDefault:
    def test_hooks_are_none_by_default(self):
        from dragonfly2_tpu.daemon.peer import piece_downloader
        from dragonfly2_tpu.rpc import client as rpc_client
        from dragonfly2_tpu.rpc import framing as rpc_framing
        from dragonfly2_tpu.source import client as source_client

        for mod in (piece_downloader, rpc_client, rpc_framing,
                    source_client):
            assert mod._chaos is None, mod.__name__

    def test_enable_disable_roundtrip(self):
        from dragonfly2_tpu.daemon.peer import piece_downloader

        fabric = chaos_mod.parse_spec({"seed": 0, "rules": []})
        chaos_mod.enable(fabric)
        assert piece_downloader._chaos is fabric
        assert chaos_mod.enabled() is fabric
        chaos_mod.disable()
        assert piece_downloader._chaos is None
        assert chaos_mod.enabled() is None

    def test_piece_write_path_never_imports_chaos(self):
        """The zero-overhead guard: importing the entire piece write path
        (downloader, store, rpc, source registry, conductor) must not pull
        in pkg.chaos — with the fabric off, no chaos symbol is reachable
        from the hot path."""
        code = (
            "import sys\n"
            "import dragonfly2_tpu.daemon.peer.conductor\n"
            "import dragonfly2_tpu.daemon.peer.piece_downloader\n"
            "import dragonfly2_tpu.daemon.peer.piece_manager\n"
            "import dragonfly2_tpu.storage.local_store\n"
            "import dragonfly2_tpu.rpc.client\n"
            "import dragonfly2_tpu.source.client\n"
            "assert 'dragonfly2_tpu.pkg.chaos' not in sys.modules, "
            "'chaos leaked into the piece write path'\n"
            "print('CLEAN')\n"
        )
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "CLEAN" in out.stdout

    def test_env_arming_requires_env(self, monkeypatch):
        monkeypatch.delenv(chaos_mod.ENV_VAR, raising=False)
        assert chaos_mod.maybe_enable_from_env() is None
        monkeypatch.setenv(chaos_mod.ENV_VAR,
                           '{"seed": 5, "rules": []}')
        fabric = chaos_mod.maybe_enable_from_env()
        assert fabric is not None and fabric.seed == 5


# --------------------------------------------------------------------- #
# Per-fault injection through the real piece download path
# --------------------------------------------------------------------- #

async def _serve_piece(content: bytes):
    """Minimal parent upload server: GET /download/... -> content."""
    from aiohttp import web

    async def handler(request):
        return web.Response(body=content)

    app = web.Application()
    app.router.add_get("/download/{pre}/{tid}", handler)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


class TestPieceFaults:
    CONTENT = b"abcd"
    DIGEST = "crc32c:" + pkgdigest.hash_bytes("crc32c", b"abcd").encoded

    def test_corrupt_trips_crc_and_quarantine(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_dispatcher import PieceDispatcher
        from dragonfly2_tpu.daemon.peer.piece_downloader import (
            PieceDownloader,
            failure_reason,
        )

        async def body():
            runner, port = await _serve_piece(self.CONTENT)
            store = make_store(tmp_path, content_length=4)
            chaos_mod.enable(chaos_mod.parse_spec({"seed": 7, "rules": [
                {"site": "piece.body", "kind": "corrupt", "rate": 1.0}]}))
            dl = PieceDownloader()
            try:
                chunks, size, _cost, received = await dl.download_piece(
                    "127.0.0.1", port, "chaos-t", 0, expected_size=4,
                    expected_digest=self.DIGEST)
                assert size == 4
                got = b"".join(bytes(c) for c in chunks)
                assert got != self.CONTENT     # the bit flip happened
                with pytest.raises(StorageError) as ei:
                    store.write_piece_chunks(
                        0, chunks, received, expected_digest=self.DIGEST)
                e = ei.value
                assert e.code == Code.ClientPieceDownloadFail
                assert failure_reason(e) == "corrupt"
                # One corrupt strike quarantines the parent daemon-wide...
                q = ParentQuarantine()
                assert q.penalize(f"127.0.0.1:{port}", failure_reason(e))
                # ...and the dispatcher stops selecting it.
                d = PieceDispatcher(quarantine=q)
                p = d.upsert_parent("bad", "127.0.0.1", port)
                p.pieces.add(0)
                d.total_piece_count = 1
                assert d.active_parents() == []
                assert not d.has_assignable()
                assert "bad" in d.unusable_parent_ids()
                # A clean write still lands after chaos is disarmed.
                chaos_mod.disable()
                chunks2, _s, _c, rec2 = await dl.download_piece(
                    "127.0.0.1", port, "chaos-t", 0, expected_size=4,
                    expected_digest=self.DIGEST)
                rec = store.write_piece_chunks(
                    0, chunks2, rec2, expected_digest=self.DIGEST)
                assert rec.size == 4
            finally:
                await dl.close()
                await runner.cleanup()

        run_async(body(), timeout=60)

    def test_truncate_rejected_as_truncated(self, run_async):
        from dragonfly2_tpu.daemon.peer.piece_downloader import (
            PieceDownloader,
            failure_reason,
        )

        async def body():
            runner, port = await _serve_piece(self.CONTENT)
            chaos_mod.enable(chaos_mod.parse_spec({"seed": 3, "rules": [
                {"site": "piece.body", "kind": "truncate", "rate": 1.0}]}))
            dl = PieceDownloader()
            try:
                with pytest.raises(DfError) as ei:
                    await dl.download_piece("127.0.0.1", port, "chaos-t", 0,
                                            expected_size=4)
                assert ei.value.code == Code.ClientPieceDownloadFail
                assert failure_reason(ei.value) == "truncated"
            finally:
                await dl.close()
                await runner.cleanup()

        run_async(body(), timeout=60)

    def test_stall_trips_watchdog_and_reschedules(self, run_async):
        from dragonfly2_tpu.daemon.peer.piece_dispatcher import PieceDispatcher
        from dragonfly2_tpu.daemon.peer.piece_downloader import (
            PieceDownloader,
            failure_reason,
            is_parent_gone,
        )

        async def body():
            runner, port = await _serve_piece(self.CONTENT)
            chaos_mod.enable(chaos_mod.parse_spec({"seed": 9, "rules": [
                {"site": "piece.body", "kind": "stall", "rate": 1.0,
                 "stall_s": 5.0}]}))
            dl = PieceDownloader(idle_timeout=0.2)
            try:
                with pytest.raises(DfError) as ei:
                    await dl.download_piece("127.0.0.1", port, "chaos-t", 0,
                                            expected_size=4)
                e = ei.value
                assert failure_reason(e) == "stall"
                assert is_parent_gone(e)   # watchdog evicts, not retries
                # The dispatcher reassigns the piece to the healthy holder.
                d = PieceDispatcher()
                stalled = d.upsert_parent("stalled", "127.0.0.1", port)
                healthy = d.upsert_parent("healthy", "127.0.0.1", port + 1)
                stalled.pieces.add(0)
                healthy.pieces.add(0)
                d.total_piece_count = 1
                a = d.try_get()
                d.report_failure(a, parent_gone=True)
                b = d.try_get()
                assert b is not None and b.parent is healthy
            finally:
                await dl.close()
                await runner.cleanup()

        run_async(body(), timeout=60)

    def test_refuse_is_parent_gone(self, run_async):
        from dragonfly2_tpu.daemon.peer.piece_downloader import (
            PieceDownloader,
            failure_reason,
            is_parent_gone,
        )

        async def body():
            chaos_mod.enable(chaos_mod.parse_spec({"seed": 2, "rules": [
                {"site": "piece.request", "kind": "refuse", "rate": 1.0}]}))
            dl = PieceDownloader()
            try:
                with pytest.raises(DfError) as ei:
                    await dl.download_piece("127.0.0.1", 1, "chaos-t", 0,
                                            expected_size=4)
                assert failure_reason(ei.value) == "refused"
                assert is_parent_gone(ei.value)
            finally:
                await dl.close()

        run_async(body(), timeout=60)


# --------------------------------------------------------------------- #
# Announce-stream death mid-download: recovery + report flush
# --------------------------------------------------------------------- #

class FakeAnnounceStream:
    def __init__(self, script=()):
        self.sent: list[dict] = []
        self._q: asyncio.Queue = asyncio.Queue()
        for m in script:
            self._q.put_nowait(m)
        self.closed = False

    async def send(self, body):
        if self.closed:
            raise DfError(Code.ClientConnectionError, "stream closed")
        self.sent.append(body)

    async def recv(self, timeout=None):
        if self.closed:
            return None
        try:
            return await asyncio.wait_for(self._q.get(), timeout or 5.0)
        except asyncio.TimeoutError:
            raise DfError(Code.RequestTimeout, "recv timeout")

    async def close(self):
        self.closed = True


class FakeSchedulerClient:
    """open_announce_stream pops scripted outcomes: an Exception instance
    is raised, anything else is returned."""

    def __init__(self, outcomes):
        self.outcomes = list(outcomes)
        self.opens = 0

    async def open_announce_stream(self, open_body):
        self.opens += 1
        if not self.outcomes:
            raise DfError(Code.ClientConnectionError, "no scheduler")
        o = self.outcomes.pop(0)
        if isinstance(o, Exception):
            raise o
        return o


def _make_conductor(tmp_path, sched, quarantine=None):
    from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor
    from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager

    store = make_store(tmp_path, content_length=8)
    store.write_piece(0, b"aaaa")
    store.write_piece(1, b"bbbb")
    c = PeerTaskConductor(
        task_id="chaos-t", peer_id="peer-1", url="http://x/f", store=store,
        scheduler_client=sched, piece_manager=PieceManager(),
        host_info={"id": "h1"}, quarantine=quarantine)
    c._open_body = {"host": {"id": "h1"}, "peer_id": "peer-1",
                    "task_id": "chaos-t", "url": "http://x/f"}
    return c


class TestAnnounceRecovery:
    def test_reports_survive_dead_stream_and_flush_on_reconnect(
            self, run_async, tmp_path, monkeypatch):
        from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor

        monkeypatch.setattr(PeerTaskConductor, "RECONNECT_BUDGET", 3)
        monkeypatch.setattr(retrylib, "ANNOUNCE",
                            retrylib.BackoffPolicy(base=0.01, cap=0.02))

        async def body():
            fresh = FakeAnnounceStream([{  # register answer
                "type": "normal_task",
                "task": {"content_length": 8, "piece_size": 4,
                         "total_piece_count": 2},
                "parents": []}])
            sched = FakeSchedulerClient(
                [DfError(Code.ClientConnectionError, "down"), fresh])
            c = _make_conductor(tmp_path, sched)
            dead = FakeAnnounceStream()
            dead.closed = True
            c._stream = dead

            # A report lands while the stream is dead: buffered, NOT lost.
            rec = c.store.get_pieces()[0]
            await c._report_piece(rec, parent_id="parent-x")
            assert await c._flush_reports() is False
            assert len(c._pending_reports) == 1

            ok = await c._recover_announce_stream()
            assert ok and c._stream is fresh
            assert sched.opens == 2          # first open failed, second ok
            # The recovery register carries FULL resume state (ISSUE 9):
            # a failover member rebuilds Task/Peer from it instead of
            # treating us as fresh.
            assert fresh.sent[0]["type"] == "register"
            resume = fresh.sent[0]["resume"]
            assert resume["piece_nums"] == [0, 1]
            assert resume["content_length"] == 8
            assert resume["piece_size"] == 4
            # The flush carried BOTH the buffered report and the full
            # completed-piece re-report (idempotent at the scheduler).
            reported = []
            for m in fresh.sent[1:]:
                if m["type"] == "piece_finished":
                    reported.append(m["piece"]["piece_num"])
                elif m["type"] == "pieces_finished":
                    reported += [p["piece_num"] for p in m["pieces"]]
            assert set(reported) == {0, 1}
            assert not c._pending_reports
            # The register answer was applied.
            assert c.dispatcher.total_piece_count == 2

        run_async(body(), timeout=30)

    def test_budget_exhausted_degrades_to_back_source(
            self, run_async, tmp_path, monkeypatch):
        from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor

        monkeypatch.setattr(PeerTaskConductor, "RECONNECT_BUDGET", 2)
        monkeypatch.setattr(retrylib, "ANNOUNCE",
                            retrylib.BackoffPolicy(base=0.01, cap=0.02))

        async def body():
            sched = FakeSchedulerClient([])   # every open refused
            c = _make_conductor(tmp_path, sched)
            dead = FakeAnnounceStream()
            dead.closed = True
            c._stream = dead
            c.dispatcher.upsert_parent("p2", "10.0.0.2", 80)
            assert not await c._recover_announce_stream()
            assert sched.opens == 2           # the budget, exactly
            c._degrade_after_scheduler_loss()
            assert c._need_back_source
            assert c.dispatcher.parents["p2"].blocked

        run_async(body(), timeout=30)

    def test_schedule_failed_answer_stops_recovery(
            self, run_async, tmp_path, monkeypatch):
        monkeypatch.setattr(retrylib, "ANNOUNCE",
                            retrylib.BackoffPolicy(base=0.01, cap=0.02))

        async def body():
            answer = FakeAnnounceStream([{"type": "schedule_failed",
                                          "reason": "nope"}])
            sched = FakeSchedulerClient([answer])
            c = _make_conductor(tmp_path, sched)
            dead = FakeAnnounceStream()
            dead.closed = True
            c._stream = dead
            assert not await c._recover_announce_stream()
            assert sched.opens == 1   # an ANSWER ends the loop, no retry

        run_async(body(), timeout=30)

    def test_teardown_blocks_recovery(self, run_async, tmp_path):
        async def body():
            sched = FakeSchedulerClient([FakeAnnounceStream()])
            c = _make_conductor(tmp_path, sched)
            c._announce_done = True
            assert not await c._recover_announce_stream()
            assert sched.opens == 0

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# rpc client: reconnect backoff + chaos at the transport
# --------------------------------------------------------------------- #

class TestRpcBackoffAndChaos:
    def test_connect_failure_arms_backoff(self, run_async):
        from dragonfly2_tpu.pkg.types import NetAddr
        from dragonfly2_tpu.rpc import Client

        async def body():
            cli = Client(NetAddr.tcp("127.0.0.1", 1), connect_timeout=0.2)
            with pytest.raises(DfError):
                await cli.call("X.Y", {}, timeout=1.0)
            assert cli._connect_failures == 1
            assert cli._next_connect_at > 0
            with pytest.raises(DfError):
                await cli.call("X.Y", {}, timeout=1.0)
            assert cli._connect_failures == 2
            await cli.close()

        run_async(body(), timeout=30)

    def test_backoff_delays_grow_and_cap(self):
        p = retrylib.BackoffPolicy(base=0.05, cap=2.0, jitter=False)
        delays = [p.raw_delay(i) for i in range(10)]
        assert delays == sorted(delays)
        assert delays[0] == pytest.approx(0.05)
        assert delays[-1] == 2.0
        # full jitter stays within [0, raw]
        pj = retrylib.BackoffPolicy(base=0.05, cap=2.0)
        for i in range(10):
            d = pj.delay(i, rng=lambda: 0.5)
            assert 0 <= d <= pj.raw_delay(i)
            assert d == pytest.approx(pj.raw_delay(i) * 0.5)

    def test_chaos_drop_kills_connection_then_recovers(self, run_async):
        from dragonfly2_tpu.pkg.types import NetAddr
        from dragonfly2_tpu.rpc import Client, Server

        async def body():
            server = Server("t")

            async def ping(body, ctx):
                return {"pong": True}

            server.register_unary("T.Ping", ping)
            await server.serve(NetAddr.tcp("127.0.0.1", 0))
            port = server.port()
            cli = Client(NetAddr.tcp("127.0.0.1", port))
            # Drop the FIRST frame read on this connection: the call fails
            # with a connection error (scheduler-crash simulation)...
            chaos_mod.enable(chaos_mod.parse_spec({"seed": 1, "rules": [
                {"site": "rpc.recv", "kind": "drop", "at": [1],
                 "key_substr": f"127.0.0.1:{port}"}]}))
            with pytest.raises(DfError) as ei:
                await cli.call("T.Ping", {}, timeout=2.0)
            assert ei.value.code == Code.ClientConnectionError
            # ...and the next use reconnects (paced by backoff) and works.
            resp = await cli.call("T.Ping", {}, timeout=5.0)
            assert resp == {"pong": True}
            await cli.close()
            await server.close()

        run_async(body(), timeout=30)

    def test_chaos_connect_refusal(self, run_async):
        from dragonfly2_tpu.pkg.types import NetAddr
        from dragonfly2_tpu.rpc import Client, Server

        async def body():
            server = Server("t")
            server.register_unary("T.Ping", lambda b, c: asyncio.sleep(0))
            await server.serve(NetAddr.tcp("127.0.0.1", 0))
            cli = Client(NetAddr.tcp("127.0.0.1", server.port()))
            chaos_mod.enable(chaos_mod.parse_spec({"seed": 1, "rules": [
                {"site": "rpc.connect", "kind": "refuse", "max_fires": 1,
                 "rate": 1.0}]}))
            with pytest.raises(DfError) as ei:
                await cli.call("T.Ping", {}, timeout=2.0)
            assert ei.value.code == Code.ClientConnectionError
            assert cli._connect_failures == 1   # chaos refusal arms backoff
            await cli.close()
            await server.close()

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# Source clients: temporary classification + chaos source sites
# --------------------------------------------------------------------- #

class TestSourceClassification:
    def test_permanent_4xx_not_temporary(self):
        from dragonfly2_tpu.source.clients.http import _status_error

        for status, code in ((403, Code.SourceForbidden),
                             (404, Code.SourceNotFound),
                             (416, Code.SourceRangeUnsupported)):
            e = _status_error(status, "http://o/f")
            assert e.code == code and not e.temporary, status
        for status in (408, 429, 500, 502, 503, 504, 599):
            assert _status_error(status, "http://o/f").temporary, status
        assert not _status_error(400, "http://o/f").temporary

    def test_client_response_error_maps_status(self):
        import aiohttp

        from dragonfly2_tpu.source.clients.http import _client_error

        e404 = aiohttp.ClientResponseError(request_info=None, history=(),
                                           status=404)
        mapped = _client_error(e404, "http://o/f", "connect")
        assert mapped.code == Code.SourceNotFound and not mapped.temporary
        e503 = aiohttp.ClientResponseError(request_info=None, history=(),
                                           status=503)
        assert _client_error(e503, "http://o/f", "connect").temporary
        conn = aiohttp.ClientConnectionError("refused")
        assert _client_error(conn, "http://o/f", "connect").temporary

    def test_s3_permanent_errors_not_temporary(self):
        from dragonfly2_tpu.pkg.objectstorage.base import ObjectStorageError
        from dragonfly2_tpu.source.clients.s3 import S3SourceClient

        cli = S3SourceClient.__new__(S3SourceClient)
        stat = cli._stat_error(ObjectStorageError("HTTP 403", status=403),
                               "s3://b/k")
        assert stat.code == Code.SourceForbidden and not stat.temporary
        assert cli._stat_error(ObjectStorageError("HTTP 404", status=404),
                               "s3://b/k").code == Code.SourceNotFound
        assert cli._stat_error(ObjectStorageError("reset"),
                               "s3://b/k").temporary
        assert cli._stat_error(ObjectStorageError("HTTP 503", status=503),
                               "s3://b/k").temporary

    def test_origin_5xx_burst_retried_then_succeeds(self, run_async,
                                                    tmp_path, monkeypatch):
        """source.request http5xx burst (2 fires) + the policy-driven
        origin retry: the third attempt lands the content."""
        from aiohttp import web

        from dragonfly2_tpu.daemon.peer.piece_manager import (
            PieceManager,
            PieceManagerOption,
        )

        monkeypatch.setattr(retrylib, "SOURCE",
                            retrylib.BackoffPolicy(base=0.01, cap=0.02))
        content = b"x" * 64

        async def body():
            async def blob(request):
                return web.Response(body=content)

            app = web.Application()
            app.router.add_get("/blob", blob)
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            url = f"http://127.0.0.1:{port}/blob"

            chaos_mod.enable(chaos_mod.parse_spec({"seed": 11, "rules": [
                {"site": "source.request", "kind": "http5xx", "rate": 1.0,
                 "max_fires": 2}]}))
            store = make_store(tmp_path, task_id="src-t", piece_size=32,
                               content_length=-1)
            pm = PieceManager(PieceManagerOption(origin_attempts=3))
            try:
                await pm.download_source(store, url)
                assert store.is_complete()
                fabric = chaos_mod.enabled()
                assert fabric.injected_by_kind().get("http5xx") == 2
            finally:
                await runner.cleanup()

        run_async(body(), timeout=60)

    def test_permanent_origin_error_fails_without_retry(self, run_async,
                                                        tmp_path,
                                                        monkeypatch):
        """A 404 origin must fail the back-source on the FIRST attempt —
        the retry budget is for temporary trouble only."""
        from aiohttp import web

        from dragonfly2_tpu.daemon.peer.piece_manager import (
            PieceManager,
            PieceManagerOption,
        )

        monkeypatch.setattr(retrylib, "SOURCE",
                            retrylib.BackoffPolicy(base=0.01, cap=0.02))

        async def body():
            hits = {"n": 0}

            async def blob(request):
                hits["n"] += 1
                return web.Response(status=404)

            app = web.Application()
            app.router.add_get("/blob", blob)
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            store = make_store(tmp_path, task_id="src-404",
                               content_length=-1, piece_size=32)
            pm = PieceManager(PieceManagerOption(origin_attempts=3))
            try:
                with pytest.raises(SourceError) as ei:
                    await pm.download_source(
                        store, f"http://127.0.0.1:{port}/blob")
                assert ei.value.code == Code.SourceNotFound
                # probe + the one download attempt — NOT 3 retries
                assert hits["n"] <= 2
            finally:
                await runner.cleanup()

        run_async(body(), timeout=60)


# --------------------------------------------------------------------- #
# Quarantine semantics
# --------------------------------------------------------------------- #

class TestQuarantine:
    def test_corrupt_tips_in_one_strike_and_decays(self):
        t = {"now": 0.0}
        q = ParentQuarantine(clock=lambda: t["now"])
        assert q.penalize("1.2.3.4:80", "corrupt")
        assert q.is_quarantined("1.2.3.4:80")
        t["now"] += q.quarantine_s + q.half_life_s * 8
        assert not q.is_quarantined("1.2.3.4:80")
        assert q.score("1.2.3.4:80") < 0.05

    def test_transport_needs_repeats_throttle_never(self):
        t = {"now": 0.0}
        q = ParentQuarantine(clock=lambda: t["now"])
        assert not q.penalize("k", "transport")
        assert not q.penalize("k", "transport")
        assert q.penalize("k", "transport")       # 3rd strike tips
        for _ in range(50):
            assert not q.penalize("throttled", "throttle")
        assert not q.is_quarantined("throttled")

    def test_decay_between_strikes_forgives(self):
        t = {"now": 0.0}
        q = ParentQuarantine(clock=lambda: t["now"])
        for _ in range(10):
            assert not q.penalize("slowburn", "transport")
            t["now"] += q.half_life_s * 6   # fully decayed between strikes

    def test_reenter_reports_edge_once(self):
        t = {"now": 0.0}
        q = ParentQuarantine(clock=lambda: t["now"])
        assert q.penalize("k", "corrupt") is True    # entered
        assert q.penalize("k", "corrupt") is False   # already in
        t["now"] += q.quarantine_s + q.half_life_s * 10
        assert q.penalize("k", "corrupt") is True    # entered again


# --------------------------------------------------------------------- #
# Scheduler-side typed demotion
# --------------------------------------------------------------------- #

class TestSchedulerDemotion:
    def _svc(self):
        from dragonfly2_tpu.scheduler.config import SchedulerConfig
        from dragonfly2_tpu.scheduler.service import SchedulerService

        cfg = SchedulerConfig()
        cfg.scheduling.retry_interval = 0.02
        cfg.seed_peer_enabled = False
        return SchedulerService(cfg)

    def test_corrupt_report_quarantines_host_for_everyone(self, run_async):
        from tests.test_stripe import FakeStream, _serve

        async def body():
            svc = self._svc()
            # A parent that "completed" the task.
            parent = FakeStream({
                "host": {"id": "host-p", "hostname": "host-p",
                         "ip": "10.0.0.1", "port": 8001,
                         "upload_port": 9001},
                "peer_id": "peer-parent", "task_id": "q-task",
                "url": "http://o/f"})
            asyncio.ensure_future(_serve(svc, parent))
            await parent.to_sched.put({"type": "register"})
            msg = await asyncio.wait_for(parent.to_peer.get(), 10)
            assert msg["type"] == "need_back_source"
            await parent.to_sched.put({
                "type": "download_started", "content_length": 8,
                "piece_size": 4, "total_piece_count": 2})
            for n in range(2):
                await parent.to_sched.put({
                    "type": "piece_finished",
                    "piece": {"piece_num": n, "range_start": n * 4,
                              "range_size": 4, "digest": "",
                              "download_cost_ms": 1, "dst_peer_id": ""}})
            await parent.to_sched.put({
                "type": "download_finished", "content_length": 8,
                "piece_size": 4, "total_piece_count": 2})
            await asyncio.sleep(0.05)

            # A child registers, is handed the parent, reports corruption.
            child = FakeStream({
                "host": {"id": "host-c", "hostname": "host-c",
                         "ip": "10.0.0.2", "port": 8002,
                         "upload_port": 9002},
                "peer_id": "peer-child", "task_id": "q-task",
                "url": "http://o/f"})
            asyncio.ensure_future(_serve(svc, child))
            await child.to_sched.put({"type": "register"})
            handed = await asyncio.wait_for(child.to_peer.get(), 10)
            assert handed["type"] in ("normal_task", "small_task"), handed
            await child.to_sched.put({
                "type": "piece_failed", "piece_num": 0,
                "parent_id": "peer-parent", "temporary": False,
                "reason": "corrupt"})
            await asyncio.sleep(0.05)

            parent_peer = svc.peers.load("peer-parent")
            assert parent_peer.host.quarantined()
            # Demoted for EVERY peer, not just the reporter: the child's
            # candidate search no longer returns it.
            child_peer = svc.peers.load("peer-child")
            assert all(
                p.id != "peer-parent"
                for p in svc.scheduling.find_candidate_parents(child_peer))
            await parent.to_sched.put(None)
            await child.to_sched.put(None)

        run_async(body(), timeout=30)

    def test_throttle_report_does_not_quarantine(self, run_async):
        from dragonfly2_tpu.scheduler.resource.host import Host

        async def body():
            h = Host("h1")
            for _ in range(20):
                assert not h.note_served_bad("throttle")
            assert not h.quarantined()
            assert h.note_served_bad("corrupt")
            assert h.quarantined()

        run_async(body(), timeout=10)

    def test_failed_peer_reregisters_fresh(self, run_async):
        """Announce-stream recovery: the SAME peer id re-registering after
        its stream dropped (peer FAILED) gets a fresh record instead of a
        TransitionError."""
        from tests.test_stripe import FakeStream, _serve

        async def body():
            svc = self._svc()
            body1 = {
                "host": {"id": "host-r", "hostname": "host-r",
                         "ip": "10.0.0.3", "port": 8003,
                         "upload_port": 9003},
                "peer_id": "peer-re", "task_id": "re-task",
                "url": "http://o/f"}
            s1 = FakeStream(body1)
            t1 = asyncio.ensure_future(_serve(svc, s1))
            await s1.to_sched.put({"type": "register"})
            await asyncio.wait_for(s1.to_peer.get(), 10)
            await s1.to_sched.put(None)     # stream dies mid-task
            await asyncio.wait_for(t1, 10)
            from dragonfly2_tpu.scheduler.resource import PeerState

            assert svc.peers.load("peer-re").fsm.current == PeerState.FAILED

            s2 = FakeStream(dict(body1))
            asyncio.ensure_future(_serve(svc, s2))
            await s2.to_sched.put({"type": "register"})
            msg = await asyncio.wait_for(s2.to_peer.get(), 10)
            assert msg["type"] in ("normal_task", "need_back_source",
                                   "schedule_failed")
            fresh = svc.peers.load("peer-re")
            assert fresh.fsm.current != PeerState.FAILED
            await s2.to_sched.put(None)

        run_async(body(), timeout=30)


class TestWireSchema:
    def test_piece_failed_reason_field(self):
        from dragonfly2_tpu.proto import wire

        wire.validate_stream_msg("Scheduler.AnnouncePeer", {
            "type": "piece_failed", "piece_num": 1, "parent_id": "p",
            "temporary": False, "reason": "corrupt"})
        with pytest.raises(wire.SchemaError, match="reason"):
            wire.validate_stream_msg("Scheduler.AnnouncePeer", {
                "type": "piece_failed", "piece_num": 1, "reason": 7})


# --------------------------------------------------------------------- #
# Seeded pod e2e: 25% parent death + corruption; all-parents-die →
# back-to-source convergence
# --------------------------------------------------------------------- #

@pytest.mark.slow
@pytest.mark.chaos
class TestChaosPodE2E:
    def test_pod_survives_parent_death_and_corruption(self, run_async,
                                                      tmp_path):
        import random

        from tests.test_p2p_e2e import daemon_config, start_scheduler
        from aiohttp import web

        from dragonfly2_tpu.client import dfget as dfget_lib
        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.proto.common import UrlMeta

        content = bytes(random.Random(1234).randbytes(12 * 1024 * 1024))
        sha = "sha256:" + hashlib.sha256(content).hexdigest()

        async def body():
            from dragonfly2_tpu.pkg.piece import Range

            async def blob(request):
                rng = request.headers.get("Range")
                if rng:
                    r = Range.parse_http(rng, len(content))
                    return web.Response(
                        status=206,
                        body=content[r.start:r.start + r.length],
                        headers={"Content-Range":
                                 f"bytes {r.start}-{r.start + r.length - 1}"
                                 f"/{len(content)}",
                                 "Accept-Ranges": "bytes"})
                return web.Response(body=content,
                                    headers={"Accept-Ranges": "bytes"})

            app = web.Application()
            app.router.add_get("/blob", blob)
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            oport = site._server.sockets[0].getsockname()[1]
            sched = await start_scheduler()
            url = f"http://127.0.0.1:{oport}/blob"
            daemons = []
            try:
                seed = Daemon(daemon_config(tmp_path, "seed", sched.port(),
                                            seed=True))
                await seed.start()
                daemons.append(seed)
                peers = []
                for i in range(4):
                    d = Daemon(daemon_config(tmp_path, f"peer{i}",
                                             sched.port()))
                    await d.start()
                    daemons.append(d)
                    peers.append(d)

                # Seeded schedule: peer0's upload endpoint dies (25% of
                # the 4-host pod's parents) + two corrupt piece bodies
                # anywhere in the swarm.
                victim = f"127.0.0.1:{peers[0].upload.port}"
                fabric = chaos_mod.enable(chaos_mod.parse_spec({
                    "seed": 77, "rules": [
                        {"site": "piece.request", "kind": "refuse",
                         "rate": 1.0, "key_substr": victim},
                        {"site": "piece.body", "kind": "corrupt",
                         "at": [1], "max_fires": 2},
                    ]}))

                async def pull(i):
                    return await dfget_lib.download(dfget_lib.DfgetConfig(
                        url=url, output=str(tmp_path / f"out{i}.bin"),
                        daemon_sock=peers[i].config.unix_sock,
                        meta=UrlMeta(digest=sha),
                        allow_source_fallback=False, timeout=180.0))

                results = await asyncio.gather(*[pull(i) for i in range(4)])
                for i, r in enumerate(results):
                    assert r["state"] == "done", (i, r)
                    data = (tmp_path / f"out{i}.bin").read_bytes()
                    # Byte-identical completion despite the faults.
                    assert hashlib.sha256(data).hexdigest() == sha[7:], i

                # The schedule actually injected, and the typed reason
                # metrics saw the recoveries.
                by_kind = fabric.injected_by_kind()
                assert by_kind.get("corrupt", 0) == 2, by_kind
                from dragonfly2_tpu.pkg import metrics as metrics_mod

                text = metrics_mod.render()[0].decode()
                reasons = metrics_mod.parse_labeled_samples(
                    text, "dragonfly_tpu_peer_piece_failures_total",
                    "reason")
                assert reasons.get("corrupt", 0) >= 2, reasons
            finally:
                chaos_mod.disable()
                for d in daemons:
                    await d.stop()
                await sched.stop()
                await runner.cleanup()

        run_async(body(), timeout=300)

    def test_all_parents_dead_converges_to_back_source(self, run_async,
                                                       tmp_path):
        import random

        from tests.test_p2p_e2e import daemon_config, start_scheduler
        from aiohttp import web

        from dragonfly2_tpu.client import dfget as dfget_lib
        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.proto.common import UrlMeta

        content = bytes(random.Random(99).randbytes(4 * 1024 * 1024))
        sha = "sha256:" + hashlib.sha256(content).hexdigest()

        async def body():
            streams = {"n": 0}

            async def blob(request):
                streams["n"] += 1
                return web.Response(body=content,
                                    headers={"Accept-Ranges": "bytes"})

            app = web.Application()
            app.router.add_get("/blob", blob)
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            oport = site._server.sockets[0].getsockname()[1]
            sched = await start_scheduler()
            url = f"http://127.0.0.1:{oport}/blob"
            daemons = []
            try:
                seed = Daemon(daemon_config(tmp_path, "seed", sched.port(),
                                            seed=True))
                await seed.start()
                daemons.append(seed)
                peers = []
                for i in range(2):
                    d = Daemon(daemon_config(tmp_path, f"bpeer{i}",
                                             sched.port()))
                    await d.start()
                    daemons.append(d)
                    peers.append(d)

                # EVERY parent upload endpoint refuses: P2P is dead; the
                # pod must converge to per-peer back-to-source.
                chaos_mod.enable(chaos_mod.parse_spec({
                    "seed": 5, "rules": [
                        {"site": "piece.request", "kind": "refuse",
                         "rate": 1.0}]}))

                async def pull(i):
                    return await dfget_lib.download(dfget_lib.DfgetConfig(
                        url=url, output=str(tmp_path / f"bout{i}.bin"),
                        daemon_sock=peers[i].config.unix_sock,
                        meta=UrlMeta(digest=sha),
                        allow_source_fallback=False, timeout=180.0))

                results = await asyncio.gather(pull(0), pull(1))
                for i, r in enumerate(results):
                    assert r["state"] == "done", (i, r)
                    data = (tmp_path / f"bout{i}.bin").read_bytes()
                    assert hashlib.sha256(data).hexdigest() == sha[7:], i
                # Origin served the peers directly (seed's fetch + the two
                # demoted peers): more than one full-content stream.
                assert streams["n"] >= 3, streams
            finally:
                chaos_mod.disable()
                for d in daemons:
                    await d.stop()
                await sched.stop()
                await runner.cleanup()

        run_async(body(), timeout=300)
