"""Tenant QoS plane (dragonfly2_tpu/qos): tenant normalization, DWRR
weighted-fair dispatch, per-tenant upload buckets, the burn-rate
admission ladder, and the scheduler/manager integration points
(Task tenant attribution, handout deprioritization, fleet decision
kinds, keepalive-piggybacked burn ingest, REST 429).
"""

from __future__ import annotations

import asyncio

import pytest

from dragonfly2_tpu import qos
from dragonfly2_tpu.pkg import metrics
from dragonfly2_tpu.pkg.ratelimit import INF
from dragonfly2_tpu.pkg.slo import SLOSpec, TENANT_SLOS
from dragonfly2_tpu.qos import (
    AdmissionController,
    TenantBuckets,
    TenantBurnBook,
    WFQGate,
)


# -- identity --------------------------------------------------------------

class TestNormalizeTenant:
    def test_valid_passthrough(self):
        for t in ("team-a", "a", "Research.ckpt_pulls", "0rg-1"):
            assert qos.normalize_tenant(t) == t

    def test_empty_and_none_default(self):
        assert qos.normalize_tenant("") == qos.DEFAULT_TENANT
        assert qos.normalize_tenant(None) == qos.DEFAULT_TENANT

    def test_invalid_chars_stripped_not_dropped(self):
        # Attribution degrades, bytes still flow: a weird tag becomes a
        # usable (splice-safe) one instead of being rejected.
        assert qos.normalize_tenant("team a/b") == "teamab"
        assert qos.normalize_tenant("a&b=c") == "abc"

    def test_never_emits_splice_unsafe_output(self):
        # The normalized form is interpolated into piece-GET query
        # strings (including the native server's raw head): no output
        # may contain separators that would break the request line.
        for raw in ("a&x=1", "q?y", "h#frag", "sp ace", "%2e%2e",
                    "新しい", "..hidden", "-lead", '"quote"'):
            norm = qos.normalize_tenant(raw)
            assert norm
            assert not set(norm) - set(
                "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
                "0123456789._-"), (raw, norm)
            assert norm[0].isalnum(), (raw, norm)

    def test_too_long_truncated(self):
        assert len(qos.normalize_tenant("x" * 200)) <= 64

    def test_all_invalid_becomes_default(self):
        assert qos.normalize_tenant("///") == qos.DEFAULT_TENANT


class TestClasses:
    def test_priority_mapping(self):
        assert qos.class_of(6) == "interactive"
        assert qos.class_of(5) == "interactive"
        assert qos.class_of(4) == "normal"
        assert qos.class_of(3) == "normal"
        assert qos.class_of(2) == "background"
        assert qos.class_of(0) == "background"

    def test_garbage_priority_is_normal(self):
        assert qos.class_of("bogus") == "normal"
        assert qos.class_of(None) == "normal"

    def test_weights_ordered(self):
        assert (qos.weight_of(6) > qos.weight_of(3)
                > qos.weight_of(0) >= 1)


# -- WFQ gate --------------------------------------------------------------

class TestWFQGate:
    def test_uncontended_fast_path(self, run_async):
        async def body():
            g = WFQGate(4)
            for _ in range(4):
                await asyncio.wait_for(g.acquire(3), 1.0)
            assert g.active == 4
            for _ in range(4):
                g.release()
            assert g.active == 0

        run_async(body())

    def test_dwrr_prefers_interactive_without_starving(self, run_async):
        async def body():
            # One slot, 16 interactive + 16 background queued: the grant
            # ORDER must be weight-proportional (16:1 per sweep), and
            # every waiter must eventually run (no starvation).
            g = WFQGate(1)
            await g.acquire(3)  # occupy the slot
            order: list[str] = []

            async def worker(tag: str, prio: int) -> None:
                await g.acquire(prio)
                order.append(tag)
                g.release()

            tasks = [asyncio.create_task(worker(f"bg{i}", 1))
                     for i in range(8)]
            await asyncio.sleep(0)  # background enqueues first
            tasks += [asyncio.create_task(worker(f"hi{i}", 6))
                      for i in range(8)]
            await asyncio.sleep(0)
            g.release()  # start the DWRR handout chain
            await asyncio.wait_for(asyncio.gather(*tasks), 5.0)
            assert len(order) == 16
            # Weight 16 vs 1: all 8 interactive grants land before the
            # 2nd background grant despite arriving later.
            second_bg = [i for i, t in enumerate(order)
                         if t.startswith("bg")][1]
            hi_done = [i for i, t in enumerate(order)
                       if t.startswith("hi")][-1]
            assert hi_done < second_bg, order

        run_async(body())

    def test_cancelled_waiter_leaves_queue(self, run_async):
        async def body():
            g = WFQGate(1)
            await g.acquire(3)
            t = asyncio.create_task(g.acquire(3))
            await asyncio.sleep(0)
            assert g.queued()["normal"] == 1
            t.cancel()
            with pytest.raises(asyncio.CancelledError):
                await t
            assert g.queued()["normal"] == 0
            # The slot still hands out cleanly afterwards.
            g.release()
            await asyncio.wait_for(g.acquire(3), 1.0)

        run_async(body())

    def test_capacity_never_exceeded_under_churn(self, run_async):
        async def body():
            g = WFQGate(3)
            peak = 0
            running = 0

            async def worker(prio: int) -> None:
                nonlocal peak, running
                await g.acquire(prio)
                running += 1
                peak = max(peak, running)
                await asyncio.sleep(0.001)
                running -= 1
                g.release()

            await asyncio.wait_for(
                asyncio.gather(*(worker(i % 7) for i in range(40))), 10.0)
            assert peak <= 3
            assert g.active == 0

        run_async(body())


# -- tenant buckets --------------------------------------------------------

class TestTenantBuckets:
    def test_even_resplit(self):
        tb = TenantBuckets(100.0)
        tb.bucket("a")
        assert tb.shares() == {"a": 100.0}
        tb.bucket("b")
        assert tb.shares() == {"a": 50.0, "b": 50.0}

    def test_min_share_floor(self):
        tb = TenantBuckets(100.0, min_share_fraction=0.25)
        for t in ("a", "b", "c", "d", "e", "f"):
            tb.bucket(t)
        assert all(v == 25.0 for v in tb.shares().values())

    def test_unlimited_is_pure_accounting(self, run_async):
        async def body():
            tb = TenantBuckets()  # no cap
            assert await tb.wait("a", 1 << 30) == 0.0
            assert tb.shares()["a"] == INF

        run_async(body())

    def test_overflow_tenant_folds_to_default(self):
        tb = TenantBuckets(100.0, max_tenants=2)
        tb.bucket(qos.DEFAULT_TENANT)
        tb.bucket("a")
        b = tb.bucket("overflow-tenant")
        assert b is tb.bucket(qos.DEFAULT_TENANT)
        assert set(tb.shares()) == {qos.DEFAULT_TENANT, "a"}

    def test_byte_accounting_exact(self, run_async):
        async def body():
            tb = TenantBuckets()
            sent = {"bulk": 0, "pull": 0}
            for i in range(5):
                await tb.wait("bulk", 1000 + i)
                sent["bulk"] += 1000 + i
            await tb.wait("pull", 77)
            sent["pull"] += 77
            text = metrics.render()[0].decode()
            by_tenant = metrics.parse_labeled_samples(
                text, "dragonfly_tpu_peer_upload_bytes_total", "tenant")
            # Counters are process-global: assert deltas are AT LEAST the
            # bytes this test pushed (exact equality belongs to the
            # bench's fresh-process run).
            assert by_tenant["bulk"] >= sent["bulk"]
            assert by_tenant["pull"] >= sent["pull"]

        run_async(body())


# -- burn book -------------------------------------------------------------

def _clock_at(t: list[float]):
    return lambda: t[0]


class TestTenantBurnBook:
    def test_no_data_is_ok(self):
        book = TenantBurnBook()
        assert book.snapshot() == {}
        assert book.throttled() == set()

    def test_rejects_non_completion_specs(self):
        bad = SLOSpec("x", "series", field="y", threshold=1.0,
                      objective=0.9, windows=(60.0,),
                      burn_thresholds=(5.0,))
        with pytest.raises(ValueError):
            TenantBurnBook(specs=(bad,))

    def test_hot_tenant_breaches_cool_stays_ok(self):
        now = [1000.0]
        book = TenantBurnBook(clock=_clock_at(now))
        for _ in range(20):
            # makespan 120s > 60s threshold: every completion is "bad"
            # -> burn = 1.0/(1-0.95) = 20 >= 14.4 (breach)
            book.note_completion("hot", 120.0)
            book.note_completion("cool", 5.0)
        snap = book.snapshot()
        assert snap["hot"]["state"] == "breach"
        assert snap["hot"]["burn"] >= 14.4
        assert snap["cool"]["state"] == "ok"
        assert book.throttled() == {"hot"}

    def test_stall_spec_also_burns(self):
        now = [1000.0]
        book = TenantBurnBook(clock=_clock_at(now))
        for _ in range(20):
            # fast makespan but stalled 60% of the time: the stall spec
            # (threshold 0.25, obj 0.90) burns at 10 >= 8.0.
            book.note_completion("stally", 5.0, stall_frac=0.6)
        snap = book.snapshot()
        assert snap["stally"]["state"] == "breach"

    def test_burn_decays_out_of_window(self):
        now = [1000.0]
        book = TenantBurnBook(clock=_clock_at(now))
        for _ in range(10):
            book.note_completion("t", 120.0)
        assert book.snapshot()["t"]["state"] == "breach"
        # All completions age out of both windows (60s and 300s).
        now[0] += 400.0
        assert book.snapshot()["t"]["state"] == "no_data"
        assert book.throttled() == set()

    def test_lru_eviction_bounded(self):
        now = [1000.0]
        book = TenantBurnBook(max_tenants=3, clock=_clock_at(now))
        for i in range(6):
            now[0] += 1.0
            book.note_completion(f"t{i}", 5.0)
        snap = book.snapshot()
        assert len(snap) == 3
        assert "t5" in snap and "t0" not in snap

    def test_tenant_normalized(self):
        book = TenantBurnBook()
        book.note_completion("", 5.0)
        assert qos.DEFAULT_TENANT in book.snapshot()


# -- admission controller --------------------------------------------------

class TestAdmissionController:
    def _ctl(self, now):
        return AdmissionController(clock=_clock_at(now))

    def test_no_data_fails_open(self):
        now = [0.0]
        ok, retry, detail = self._ctl(now).check("anyone")
        assert ok and retry == 0.0 and detail["state"] == "no_data"

    def test_breach_denied_with_scaled_retry_after(self):
        now = [100.0]
        ctl = self._ctl(now)
        ctl.ingest({"hot": {"burn": 3.0, "state": "breach"}})
        ok, retry, detail = ctl.check("hot")
        assert not ok
        assert retry == pytest.approx(6.0)  # base 2.0 * burn 3.0
        assert detail["state"] == "breach"

    def test_retry_after_capped(self):
        now = [100.0]
        ctl = self._ctl(now)
        ctl.ingest({"hot": {"burn": 1000.0, "state": "breach"}})
        _, retry, _ = ctl.check("hot")
        assert retry == 30.0

    def test_warn_admits(self):
        now = [100.0]
        ctl = self._ctl(now)
        ctl.ingest({"w": {"burn": 2.0, "state": "warn"}})
        ok, _, detail = ctl.check("w")
        assert ok and detail["state"] == "warn"

    def test_stale_fails_open(self):
        now = [100.0]
        ctl = self._ctl(now)
        ctl.ingest({"hot": {"burn": 9.0, "state": "breach"}})
        assert not ctl.check("hot")[0]
        now[0] += 120.0  # > stale_after_s=60
        ok, _, detail = ctl.check("hot")
        assert ok and detail["state"] == "no_data"
        assert ctl.report()["hot"]["stale"]

    def test_same_instant_keeps_hotter_view(self):
        # Two schedulers report the same tenant in one clock instant:
        # the colder view must not mask the hotter one.
        now = [100.0]
        ctl = self._ctl(now)
        ctl.ingest({"t": {"burn": 9.0, "state": "breach"}})
        ctl.ingest({"t": {"burn": 0.1, "state": "ok"}})
        assert not ctl.check("t")[0]

    def test_malformed_ingest_ignored(self):
        now = [100.0]
        ctl = self._ctl(now)
        assert ctl.ingest("garbage") == 0
        assert ctl.ingest({"t": "not-a-dict", "u": {"burn": "NaNsense",
                                                    "state": "wat"}}) == 1
        ok, _, detail = ctl.check("u")
        assert ok and detail["state"] == "no_data"


# -- wire & resource attribution -------------------------------------------

class TestWireAttribution:
    def test_urlmeta_tenant_roundtrip(self):
        from dragonfly2_tpu.proto.common import UrlMeta

        m = UrlMeta(tenant="team-a", priority=6)
        w = m.to_wire()
        assert w["tenant"] == "team-a" and w["priority"] == 6
        back = UrlMeta.from_wire(w)
        assert back.tenant == "team-a" and back.priority == 6

    def test_trigger_download_schema_accepts_tenant(self):
        from dragonfly2_tpu.proto.wire import validate_unary

        validate_unary("Peer.TriggerDownloadTask",
                       {"task_id": "t", "url": "http://x", "tenant": "a",
                        "priority": 6})

    def test_announce_open_schema_accepts_tenant(self):
        from dragonfly2_tpu.proto.wire import validate_stream_open

        validate_stream_open(
            "Scheduler.AnnouncePeer",
            {"task_id": "t", "peer_id": "p", "tenant": "a",
             "host": {"id": "h1", "hostname": "h1"}})

    def test_task_carries_tenant_not_identity(self):
        from dragonfly2_tpu.pkg import idgen
        from dragonfly2_tpu.scheduler.resource import Task

        t = Task("tid", "http://x", tenant="team-a")
        assert t.to_wire()["tenant"] == "team-a"
        # Task id hash must NOT see the tenant: two tenants pulling the
        # same content share one task (dedup beats isolation).
        a = idgen.task_id_v1("http://x", digest="", tag="", application="")
        b = idgen.task_id_v1("http://x", digest="", tag="", application="")
        assert a == b


# -- scheduler integration -------------------------------------------------

class TestSchedulerIntegration:
    def test_resolve_sets_and_backfills_tenant(self):
        from dragonfly2_tpu.scheduler.service import SchedulerService

        svc = SchedulerService()
        body = {"task_id": "task-1", "peer_id": "peer-1",
                "url": "http://x", "tenant": "team-a",
                "host": {"id": "h1", "hostname": "h1"}}
        _, task, _ = svc._resolve(body)
        assert task.tenant == "team-a"
        # A later registrant without a tenant does not clear it...
        _, task2, _ = svc._resolve({**body, "peer_id": "peer-2",
                                    "tenant": ""})
        assert task2 is task and task.tenant == "team-a"
        # ...and a later registrant CAN backfill an empty one.
        body3 = {"task_id": "task-2", "peer_id": "peer-3",
                 "url": "http://y",
                 "host": {"id": "h1", "hostname": "h1"}}
        _, t2, _ = svc._resolve(body3)
        assert t2.tenant == ""
        svc._resolve({**body3, "peer_id": "peer-4", "tenant": "late"})
        assert t2.tenant == "late"

    def test_completion_feeds_burn_book(self):
        from dragonfly2_tpu.scheduler.service import SchedulerService

        svc = SchedulerService()
        body = {"task_id": "task-b", "peer_id": "peer-b",
                "url": "http://x", "tenant": "bulk",
                "host": {"id": "h1", "hostname": "h1"}}
        _, task, peer = svc._resolve(body)
        # completion_stats reads makespan from the digest's wall_s.
        flight = {"state": "done", "wall_s": 120.0,
                  "phases": {"stall": 0.0}}
        svc._note_shipped_flight({"flight": flight}, task, peer)
        assert "bulk" in svc.tenant_burn.snapshot()

    def test_burn_payload_records_admission_transitions(self):
        from dragonfly2_tpu.scheduler.service import SchedulerService

        svc = SchedulerService()
        assert svc.fleet is not None
        for _ in range(10):
            svc.tenant_burn.note_completion("hot", 120.0)
        payload = svc.tenant_burn_payload()
        assert payload["tenant_burn"]["hot"]["state"] == "breach"
        kinds = [d["kind"] for d in
                 svc.fleet.decisions.query(kind="admission")["decisions"]]
        assert kinds == ["admission"]
        # Repeat snapshot: no transition -> no duplicate decision row.
        svc.tenant_burn_payload()
        assert len(svc.fleet.decisions.query(
            kind="admission")["decisions"]) == 1

    def test_throttled_tenant_handouts_halved(self):
        from dragonfly2_tpu.pkg.types import HostType
        from dragonfly2_tpu.scheduler.config import SchedulingConfig
        from dragonfly2_tpu.scheduler.resource import (
            Host, Peer, PeerState, Task,
        )
        from dragonfly2_tpu.scheduler.scheduling import Scheduling

        def build(tenant: str):
            s = Scheduling(SchedulingConfig(candidate_parent_limit=4))
            t = Task("t1", "http://x", tenant=tenant)
            t.total_piece_count = 10
            child_host = Host("hc", ip="10.0.0.1", port=8000,
                              upload_port=9000, host_type=HostType.NORMAL)
            child = Peer("child", t, child_host)
            t.add_peer(child)
            for i in range(8):
                h = Host(f"h{i}", ip="10.0.0.2", port=8000,
                         upload_port=9000, host_type=HostType.NORMAL)
                p = Peer(f"p{i}", t, h)
                t.add_peer(p)
                h.peer_ids.add(p.id)
                p.fsm.event("register_normal")
                p.fsm.event("download")
                p.fsm.event("download_succeeded")
                for n in range(10):
                    p.add_finished_piece(n, cost_ms=50)
            return s, child

        s, child = build("bulk")
        assert len(s.find_candidate_parents(child)) == 4
        s.wire_qos(lambda: {"bulk"})
        assert len(s.find_candidate_parents(child)) == 2  # halved
        # A non-throttled tenant keeps the full fan-out.
        s2, child2 = build("pull")
        s2.wire_qos(lambda: {"bulk"})
        assert len(s2.find_candidate_parents(child2)) == 4


# -- fleet decision kinds --------------------------------------------------

class TestFleetDecisions:
    def _fleet(self):
        from dragonfly2_tpu.pkg.fleet import FleetObservatory

        return FleetObservatory()

    def test_throttle_and_admission_recorded_with_tenant_subject(self):
        fleet = self._fleet()
        fleet.note_throttle("bulk", task_id="t1", host_id="h1",
                            reason="burn_rate_handout", limit=2)
        fleet.note_admission("bulk", decision="deny", burn=15.0,
                             retry_after_s=30.0)
        th = fleet.decisions.query(kind="throttle")["decisions"]
        ad = fleet.decisions.query(kind="admission")["decisions"]
        assert len(th) == 1 and th[0]["host"] == "bulk"
        assert th[0]["task"] == "t1" and "candidate_limit=2" in th[0]["reason"]
        assert len(ad) == 1 and ad[0]["host"] == "bulk"
        assert "deny" in ad[0]["reason"] and "burn=15.00" in ad[0]["reason"]
        # Tenant-as-subject means ?host=<tenant> queries work unchanged.
        assert len(fleet.decisions.query(host="bulk")["decisions"]) == 2


# -- upload serve admission ------------------------------------------------

class TestUploadQoS:
    def test_qos_buckets_disable_native_path(self, tmp_path):
        from dragonfly2_tpu.daemon.upload import UploadManager
        from dragonfly2_tpu.storage import StorageManager
        from dragonfly2_tpu.storage.manager import StorageOption

        store = StorageManager(StorageOption(data_dir=str(tmp_path)))
        um = UploadManager(store, qos_buckets=TenantBuckets())
        assert um._native_eligible("127.0.0.1") is None

    def test_serve_debits_tenant_then_flat_cap(self, run_async, tmp_path):
        # Unit-level: the double-wait discipline — per-tenant share then
        # daemon-wide ceiling — expressed through TenantBuckets + Limiter
        # exactly as upload._download_traced composes them.
        from dragonfly2_tpu.pkg.ratelimit import Limiter

        async def body():
            buckets = TenantBuckets(200.0, min_share_fraction=0.5)
            flat = Limiter(200.0, burst=200)
            buckets.bucket("a")
            buckets.bucket("b")
            # Each tenant's share is 100/s; the flat cap is 200/s. Tenant
            # a pushing 200 units must wait on its SHARE (~1s), not just
            # the flat cap (~0s after burst).
            await buckets.wait("a", 100)   # consumes a's burst
            start = asyncio.get_event_loop().time()
            await buckets.wait("a", 50)
            await flat.wait(50)
            waited = asyncio.get_event_loop().time() - start
            assert waited >= 0.2, waited

        run_async(body())


# -- manager integration ---------------------------------------------------

class TestManagerAdmission:
    def test_service_ingest_and_check(self):
        from dragonfly2_tpu.manager.service import ManagerService

        svc = ManagerService()
        assert svc.check_admission("t")[0]  # fail open
        assert svc.ingest_tenant_burn(
            {"t": {"burn": 16.0, "state": "breach"}}) == 1
        admitted, retry, detail = svc.check_admission("t")
        assert not admitted and retry == 30.0
        assert svc.ingest_tenant_burn("junk") == 0
        assert svc.ingest_tenant_burn(None) == 0

    def test_rest_create_job_429_for_burning_tenant(self, run_async):
        import aiohttp

        from dragonfly2_tpu.manager.config import ManagerConfig
        from dragonfly2_tpu.manager.server import ManagerServer

        async def body():
            server = ManagerServer(ManagerConfig())
            await server.start()
            base = f"http://127.0.0.1:{server.rest_port}"
            try:
                server.service.ingest_tenant_burn(
                    {"hot": {"burn": 4.0, "state": "breach"}})
                async with aiohttp.ClientSession() as http:
                    resp = await http.post(
                        f"{base}/api/v1/users/signin",
                        json={"name": "root", "password": "dragonfly"})
                    hdr = {"Authorization":
                           f"Bearer {(await resp.json())['token']}"}
                    job = {"type": "preheat", "tenant": "hot",
                           "args": {"type": "file", "url": "http://o/x"}}
                    resp = await http.post(f"{base}/api/v1/jobs",
                                           headers=hdr, json=job)
                    assert resp.status == 429
                    assert "Retry-After" in resp.headers
                    body_json = await resp.json()
                    assert body_json["retry_after_s"] == pytest.approx(8.0)
                    assert body_json["tenant"] == "hot"
                    # A cool tenant's submission is untouched.
                    resp = await http.post(
                        f"{base}/api/v1/jobs", headers=hdr,
                        json={**job, "tenant": "cool"})
                    assert resp.status == 200
            finally:
                await server.stop()

        run_async(body())

    def test_keepalive_piggyback_reaches_admission(self, run_async):
        from dragonfly2_tpu.manager.client import ManagerClient
        from dragonfly2_tpu.manager.config import ManagerConfig
        from dragonfly2_tpu.manager.server import ManagerServer
        from dragonfly2_tpu.pkg.types import NetAddr

        async def body():
            server = ManagerServer(ManagerConfig())
            await server.start()
            cli = ManagerClient(
                NetAddr.tcp("127.0.0.1", server.grpc_port()))
            try:
                cluster_id = server.db.find(
                    "scheduler_clusters", name="default")["id"]
                await cli.update_scheduler(
                    hostname="s1", ip="127.0.0.1", port=1234,
                    scheduler_cluster_id=cluster_id)
                cli.start_keepalive(
                    source_type="scheduler", hostname="s1",
                    ip="127.0.0.1", cluster_id=cluster_id,
                    interval=0.05,
                    payload=lambda: {"tenant_burn": {
                        "hot": {"burn": 15.0, "state": "breach"}}})
                for _ in range(100):
                    await asyncio.sleep(0.05)
                    if not server.service.check_admission("hot")[0]:
                        break
                else:
                    pytest.fail("burn snapshot never reached admission")
            finally:
                await cli.close()
                await server.stop()

        run_async(body())
