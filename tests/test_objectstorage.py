"""Object-storage stack: backends, daemon gateway, dfstore SDK, dfcache.

Reference test model: the e2e suite drives dfstore/dfcache against a live
daemon + minio (test/e2e/v2, hack/install-e2e-test.sh:42-60 installs
minio); here the backends get hermetic fakes (fs is real, s3/gcs against
in-process aiohttp servers) and the gateway runs on a real TaskManager so
GETs genuinely ride the P2P stream-task machinery.
"""

from __future__ import annotations

import asyncio
import hashlib
import os

import pytest
from aiohttp import web

from dragonfly2_tpu.client.dfstore import Dfstore, DfstoreError
from dragonfly2_tpu.daemon.objectstorage import ObjectStorageService
from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager, PieceManagerOption
from dragonfly2_tpu.daemon.peer.task_manager import TaskManager
from dragonfly2_tpu.daemon.transport import P2PTransport
from dragonfly2_tpu.pkg.objectstorage import new_client
from dragonfly2_tpu.pkg.objectstorage.fs import FSObjectStorage
from dragonfly2_tpu.pkg.objectstorage.gcs import GCSObjectStorage
from dragonfly2_tpu.pkg.objectstorage.s3 import S3ObjectStorage
from dragonfly2_tpu.storage import StorageManager, StorageOption


# -- fs backend -------------------------------------------------------------

def test_fs_backend_roundtrip(run_async, tmp_path):
    async def run():
        be = FSObjectStorage(root=str(tmp_path / "buckets"))
        await be.create_bucket("ckpt")
        assert await be.is_bucket_exist("ckpt")
        assert not await be.is_bucket_exist("nope")
        await be.put_object("ckpt", "model/shard-0.safetensors", b"hello world",
                            digest="sha256:x" * 0 or "", content_type="application/octet-stream")
        meta = await be.get_object_metadata("ckpt", "model/shard-0.safetensors")
        assert meta.content_length == 11
        chunks = b"".join([c async for c in await be.get_object(
            "ckpt", "model/shard-0.safetensors")])
        assert chunks == b"hello world"
        ranged = b"".join([c async for c in await be.get_object(
            "ckpt", "model/shard-0.safetensors", 6, 10)])
        assert ranged == b"world"
        listing = await be.list_object_metadatas("ckpt", prefix="model/")
        assert [m.key for m in listing] == ["model/shard-0.safetensors"]
        assert (await be.object_url("ckpt", "model/shard-0.safetensors") if False
                else be.object_url("ckpt", "model/shard-0.safetensors")).startswith("file://")
        await be.delete_object("ckpt", "model/shard-0.safetensors")
        assert not await be.is_object_exist("ckpt", "model/shard-0.safetensors")
        names = [b.name for b in await be.list_buckets()]
        assert names == ["ckpt"]
        await be.delete_bucket("ckpt")
        assert not await be.is_bucket_exist("ckpt")

    run_async(run())


def test_fs_backend_rejects_traversal(run_async, tmp_path):
    async def run():
        be = FSObjectStorage(root=str(tmp_path / "buckets"))
        await be.create_bucket("b")
        with pytest.raises(Exception):
            await be.put_object("b", "../escape", b"x")
        with pytest.raises(Exception):
            be._bucket_dir("../b")

    run_async(run())


# -- fake S3 ---------------------------------------------------------------

async def start_fake_s3():
    objects: dict[tuple[str, str], bytes] = {}
    buckets: set[str] = set()

    async def handler(request: web.Request) -> web.Response:
        parts = request.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        if request.method == "PUT" and not key:
            buckets.add(bucket)
            return web.Response()
        if request.method == "HEAD" and not key:
            return web.Response(status=200 if bucket in buckets else 404)
        if request.method == "PUT":
            objects[(bucket, key)] = await request.read()
            return web.Response()
        if request.method == "HEAD":
            data = objects.get((bucket, key))
            if data is None:
                return web.Response(status=404)
            return web.Response(headers={"Content-Length": str(len(data)),
                                         "ETag": '"abc"'})
        if request.method == "GET" and not key:
            contents = "".join(
                f"<Contents><Key>{k}</Key><Size>{len(v)}</Size></Contents>"
                for (b, k), v in sorted(objects.items()) if b == bucket)
            return web.Response(
                text=f"<ListBucketResult>{contents}</ListBucketResult>",
                content_type="application/xml")
        if request.method == "GET":
            data = objects.get((bucket, key))
            if data is None:
                return web.Response(status=404)
            rng = request.headers.get("Range")
            if rng:
                spec = rng.split("=", 1)[1]
                start_s, _, end_s = spec.partition("-")
                start = int(start_s)
                end = int(end_s) if end_s else len(data) - 1
                return web.Response(status=206, body=data[start:end + 1])
            return web.Response(body=data)
        if request.method == "DELETE":
            if key:
                objects.pop((bucket, key), None)
            else:
                buckets.discard(bucket)
            return web.Response(status=204)
        return web.Response(status=400)

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handler)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


def test_s3_backend_against_fake(run_async):
    async def run():
        runner, port = await start_fake_s3()
        be = S3ObjectStorage(endpoint=f"http://127.0.0.1:{port}",
                             access_key="ak", secret_key="sk")
        try:
            await be.create_bucket("b")
            assert await be.is_bucket_exist("b")
            await be.put_object("b", "k/obj", b"payload", digest="crc32c:1234abcd")
            meta = await be.get_object_metadata("b", "k/obj")
            assert meta.content_length == 7
            got = b"".join([c async for c in await be.get_object("b", "k/obj")])
            assert got == b"payload"
            part = b"".join([c async for c in await be.get_object("b", "k/obj", 2, 4)])
            assert part == b"ylo"
            listing = await be.list_object_metadatas("b")
            assert [m.key for m in listing] == ["k/obj"]
            presigned = be.presign_url("b", "k/obj")
            assert "X-Amz-Signature=" in presigned
            await be.delete_object("b", "k/obj")
            assert not await be.is_object_exist("b", "k/obj")
        finally:
            await be.close()
            await runner.cleanup()

    run_async(run())


# -- fake OSS / OBS ---------------------------------------------------------

async def start_fake_osslike(scheme: str, header_prefix: str,
                             secret: str = "vendor-secret"):
    """Hermetic vendor endpoint that independently re-derives the
    HMAC-SHA1 header signature from the raw request (its own
    canonicalization, written from the vendor spec, not shared with the
    client) and 403s any mismatch — so canonicalization drift in the
    client is a test failure, not a silent pass."""
    import base64
    import hmac as _hmac
    import hashlib as _hashlib

    objects: dict[tuple[str, str], tuple[bytes, dict]] = {}
    buckets: set[str] = set()

    def expected_sig(request: web.Request) -> str:
        vendor = sorted(
            (k.lower(), v.strip()) for k, v in request.headers.items()
            if k.lower().startswith(header_prefix))
        to_sign = "\n".join([
            request.method,
            request.headers.get("Content-MD5", ""),
            request.headers.get("Content-Type", ""),
            request.headers.get("Date", ""),
        ]) + "\n" + "".join(f"{k}:{v}\n" for k, v in vendor) + request.path
        return base64.b64encode(_hmac.new(
            secret.encode(), to_sign.encode(), _hashlib.sha1).digest()).decode()

    async def handler(request: web.Request) -> web.Response:
        auth = request.headers.get("Authorization", "")
        if not auth.startswith(f"{scheme} ak:"):
            return web.Response(status=403, text="bad scheme")
        if auth.split(":", 1)[1] != expected_sig(request):
            return web.Response(status=403, text="signature mismatch")
        parts = request.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        if request.method == "PUT" and not key:
            buckets.add(bucket)
            return web.Response()
        if request.method == "HEAD" and not key:
            return web.Response(status=200 if bucket in buckets else 404)
        if request.method == "PUT":
            meta = {k: v for k, v in request.headers.items()
                    if k.lower().startswith(f"{header_prefix}meta-")}
            objects[(bucket, key)] = (await request.read(), meta)
            return web.Response()
        if request.method == "HEAD":
            entry = objects.get((bucket, key))
            if entry is None:
                return web.Response(status=404)
            data, meta = entry
            return web.Response(headers={"Content-Length": str(len(data)),
                                         "ETag": '"v1"', **meta})
        if request.method == "GET" and not key:
            contents = "".join(
                f"<Contents><Key>{k}</Key><Size>{len(v[0])}</Size></Contents>"
                for (b, k), v in sorted(objects.items()) if b == bucket)
            return web.Response(
                text=f"<ListBucketResult>{contents}</ListBucketResult>",
                content_type="application/xml")
        if request.method == "GET":
            entry = objects.get((bucket, key))
            if entry is None:
                return web.Response(status=404)
            data = entry[0]
            rng = request.headers.get("Range")
            if rng:
                spec = rng.split("=", 1)[1]
                start_s, _, end_s = spec.partition("-")
                start = int(start_s)
                end = int(end_s) if end_s else len(data) - 1
                return web.Response(status=206, body=data[start:end + 1])
            return web.Response(body=data)
        if request.method == "DELETE":
            if key:
                objects.pop((bucket, key), None)
            else:
                buckets.discard(bucket)
            return web.Response(status=204)
        return web.Response(status=400)

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handler)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


@pytest.mark.parametrize("backend,scheme,prefix", [
    ("oss", "OSS", "x-oss-"),
    ("obs", "OBS", "x-obs-"),
])
def test_osslike_backend_native_auth(run_async, backend, scheme, prefix):
    """OSS/OBS native header auth end-to-end against a fake that
    re-derives the signature independently (reference
    pkg/objectstorage/{oss,obs}.go — vendor scheme, not SigV4)."""

    async def run():
        runner, port = await start_fake_osslike(scheme, prefix)
        be = new_client(backend, endpoint=f"http://127.0.0.1:{port}",
                        access_key="ak", secret_key="vendor-secret")
        try:
            await be.create_bucket("b")
            assert await be.is_bucket_exist("b")
            await be.put_object("b", "k/obj", b"payload",
                                digest="crc32c:1234abcd",
                                content_type="application/octet-stream")
            meta = await be.get_object_metadata("b", "k/obj")
            assert meta.content_length == 7
            assert meta.digest == "crc32c:1234abcd"
            got = b"".join([c async for c in await be.get_object("b", "k/obj")])
            assert got == b"payload"
            part = b"".join(
                [c async for c in await be.get_object("b", "k/obj", 2, 4)])
            assert part == b"ylo"
            listing = await be.list_object_metadatas("b")
            assert [m.key for m in listing] == ["k/obj"]
            presigned = be.presign_url("b", "k/obj")
            assert "Signature=" in presigned and "Expires=" in presigned
            if backend == "oss":
                assert "OSSAccessKeyId=ak" in presigned
            await be.delete_object("b", "k/obj")
            assert not await be.is_object_exist("b", "k/obj")

            # A wrong secret must be rejected by the endpoint.
            bad = new_client(backend, endpoint=f"http://127.0.0.1:{port}",
                             access_key="ak", secret_key="wrong")
            try:
                with pytest.raises(Exception) as ei:
                    await bad.create_bucket("b2")
                assert "403" in str(ei.value)
            finally:
                await bad.close()
        finally:
            await be.close()
            await runner.cleanup()

    run_async(run())


# -- fake GCS ---------------------------------------------------------------

async def start_fake_gcs():
    objects: dict[tuple[str, str], bytes] = {}
    buckets: set[str] = set()

    async def route(request: web.Request) -> web.Response:
        import json as _json
        from urllib.parse import unquote

        path = request.path
        if path == "/storage/v1/b" and request.method == "POST":
            body = await request.json()
            buckets.add(body["name"])
            return web.json_response({"name": body["name"]})
        if path == "/storage/v1/b" and request.method == "GET":
            return web.json_response({"items": [{"name": b} for b in sorted(buckets)]})
        if path.startswith("/upload/storage/v1/b/"):
            bucket = path.split("/")[5]
            name = unquote(request.query["name"])
            objects[(bucket, name)] = await request.read()
            return web.json_response({"name": name})
        if path.startswith("/storage/v1/b/"):
            parts = path.split("/")
            bucket = unquote(parts[4])
            if len(parts) == 5:   # bucket ops
                if request.method == "GET":
                    return (web.json_response({"name": bucket, "timeCreated": ""})
                            if bucket in buckets else web.Response(status=404))
                if request.method == "DELETE":
                    buckets.discard(bucket)
                    return web.Response(status=204)
            if len(parts) == 6 and parts[5] == "o":  # list objects
                items = [{"name": k, "size": str(len(v))}
                         for (b, k), v in sorted(objects.items()) if b == bucket]
                return web.json_response({"items": items})
            if len(parts) >= 6 and parts[5] == "o":
                key = unquote("/".join(parts[6:]))
                data = objects.get((bucket, key))
                if data is None:
                    return web.Response(status=404)
                if request.query.get("alt") == "media":
                    rng = request.headers.get("Range")
                    if rng:
                        spec = rng.split("=", 1)[1]
                        s, _, e = spec.partition("-")
                        start = int(s)
                        end = int(e) if e else len(data) - 1
                        return web.Response(status=206, body=data[start:end + 1])
                    return web.Response(body=data)
                if request.method == "DELETE":
                    objects.pop((bucket, key), None)
                    return web.Response(status=204)
                if request.method == "PATCH":
                    return web.json_response({"name": key})
                return web.json_response({"name": key, "size": str(len(data)),
                                          "etag": "e1", "metadata": {}})
        return web.Response(status=400)

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", route)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


def test_gcs_backend_against_fake(run_async, monkeypatch):
    async def run():
        runner, port = await start_fake_gcs()
        be = GCSObjectStorage(endpoint=f"http://127.0.0.1:{port}")
        try:
            await be.create_bucket("tpu-ckpts")
            assert await be.is_bucket_exist("tpu-ckpts")
            await be.put_object("tpu-ckpts", "llama/shard-00.safetensors",
                                b"weights", digest="sha256:aa")
            meta = await be.get_object_metadata("tpu-ckpts", "llama/shard-00.safetensors")
            assert meta.content_length == 7
            got = b"".join([c async for c in await be.get_object(
                "tpu-ckpts", "llama/shard-00.safetensors")])
            assert got == b"weights"
            part = b"".join([c async for c in await be.get_object(
                "tpu-ckpts", "llama/shard-00.safetensors", 0, 2)])
            assert part == b"wei"
            listing = await be.list_object_metadatas("tpu-ckpts")
            assert [m.key for m in listing] == ["llama/shard-00.safetensors"]
            assert be.object_url("tpu-ckpts", "x") == "gs://tpu-ckpts/x"
            await be.delete_object("tpu-ckpts", "llama/shard-00.safetensors")
            assert not await be.is_object_exist("tpu-ckpts", "llama/shard-00.safetensors")
        finally:
            await be.close()
            await runner.cleanup()

    monkeypatch.setenv("DF_GCS_ANONYMOUS", "1")
    run_async(run())


def test_new_client_dispatch(tmp_path):
    assert new_client("fs", root=str(tmp_path)).name == "fs"
    assert new_client("s3", endpoint="http://x").name == "s3"
    assert new_client("oss", endpoint="http://x").name == "oss"
    assert new_client("obs", endpoint="http://x").name == "obs"
    with pytest.raises(Exception):
        new_client("bogus")


# -- daemon gateway + dfstore ------------------------------------------------

def make_task_manager(tmp_path) -> TaskManager:
    storage = StorageManager(StorageOption(data_dir=str(tmp_path / "p2p")))
    pm = PieceManager(PieceManagerOption(concurrency=2))
    return TaskManager(storage, pm)


async def start_gateway(tmp_path, **kwargs):
    backend = FSObjectStorage(root=str(tmp_path / "buckets"))
    tm = make_task_manager(tmp_path)
    svc = ObjectStorageService(backend, P2PTransport(tm), **kwargs)
    port = await svc.serve("127.0.0.1", 0)
    return svc, port, tm


def test_gateway_put_get_via_p2p(run_async, tmp_path):
    async def run():
        svc, port, tm = await start_gateway(tmp_path)
        store = Dfstore(f"http://127.0.0.1:{port}")
        try:
            await store.create_bucket("data")
            payload = os.urandom(3 * 1024 * 1024)
            digest = await store.put_object("data", "webds/shard-000.tar", payload,
                                            mode="write_back")
            assert digest == "sha256:" + hashlib.sha256(payload).hexdigest()
            # GET rides a stream task over the file:// origin.
            got = await store.get_object("data", "webds/shard-000.tar")
            assert got == payload
            # The bytes landed in the P2P piece store (cache hit next time).
            assert any(s.metadata.done for s in tm.storage.tasks())
            # Ranged GET.
            part = await store.get_object("data", "webds/shard-000.tar",
                                          range_header="bytes=100-199")
            assert part == payload[100:200]
            # Range at EOF -> 416.
            with pytest.raises(DfstoreError) as exc:
                await store.get_object("data", "webds/shard-000.tar",
                                       range_header=f"bytes={len(payload)}-")
            assert exc.value.status == 416
            # Stat + list + delete.
            info = await store.stat_object("data", "webds/shard-000.tar")
            assert info.content_length == len(payload)
            assert info.digest == digest
            objs = await store.list_objects("data", prefix="webds/")
            assert [o.key for o in objs] == ["webds/shard-000.tar"]
            await store.delete_object("data", "webds/shard-000.tar")
            assert not await store.is_object_exist("data", "webds/shard-000.tar")
        finally:
            await store.close()
            await svc.close()

    run_async(run())


def test_gateway_replicates_to_seeds(run_async, tmp_path):
    async def run():
        triggered: list[tuple[dict, dict]] = []

        async def trigger(seed, spec):
            triggered.append((seed, spec))
            return True

        svc, port, _ = await start_gateway(
            tmp_path,
            get_seed_peers=lambda: [{"ip": "10.0.0.1", "port": 1},
                                    {"ip": "10.0.0.2", "port": 2}],
            trigger_seed=trigger)
        store = Dfstore(f"http://127.0.0.1:{port}")
        try:
            await store.create_bucket("b")
            await store.put_object("b", "obj", b"x" * 100, mode="write_back")
            assert len(triggered) == 2
            assert all(s["url"].startswith("file://") for _, s in triggered)
            assert all(s["tag"] == "b" for _, s in triggered)
        finally:
            await store.close()
            await svc.close()

    run_async(run())


def test_gateway_streaming_get(run_async, tmp_path):
    async def run():
        svc, port, _ = await start_gateway(tmp_path)
        store = Dfstore(f"http://127.0.0.1:{port}")
        try:
            await store.create_bucket("w")
            payload = os.urandom(1024 * 1024)
            await store.put_object("w", "t.tar", payload)
            got = b""
            async for chunk in await store.stream_object("w", "t.tar"):
                got += chunk
            assert got == payload
        finally:
            await store.close()
            await svc.close()

    run_async(run())


def test_stream_object_ranged_and_no_total_timeout(run_async, tmp_path):
    """stream_object accepts a range like get_object, and long streams
    ride a per-read timeout, not the session-wide total (a 60 s budget
    must not kill a large cold shard mid-stream)."""

    async def run():
        svc, port, _ = await start_gateway(tmp_path)
        # Pathologically small total timeout: streaming must not use it.
        store = Dfstore(f"http://127.0.0.1:{port}", timeout=0.001,
                        read_timeout=30.0)
        assert store.stream_timeout.total is None
        assert store.stream_timeout.sock_read == 30.0
        try:
            await asyncio.sleep(0.01)  # put via a fresh, sane-timeout store
            setup = Dfstore(f"http://127.0.0.1:{port}")
            payload = os.urandom(2 * 1024 * 1024 + 13)
            await setup.create_bucket("w")
            await setup.put_object("w", "t.tar", payload, mode="write_back")
            await setup.close()
            got = b""
            async for chunk in await store.stream_object(
                    "w", "t.tar", range_header="1000-99999"):
                got += chunk
            assert got == payload[1000:100000]
            # bytes= prefix form too
            got2 = b""
            async for chunk in await store.stream_object(
                    "w", "t.tar", range_header="bytes=0-9"):
                got2 += chunk
            assert got2 == payload[:10]
            # Whole-object stream with the absurd total timeout still runs.
            whole = b""
            async for chunk in await store.stream_object("w", "t.tar"):
                whole += chunk
            assert whole == payload
        finally:
            await store.close()
            await svc.close()

    run_async(run())


def test_copy_object_streams_without_buffering(run_async, tmp_path):
    """copy_object must stream chunk-by-chunk (never a whole-object
    get_object), return the digest, and produce a byte-exact copy."""

    async def run():
        svc, port, _ = await start_gateway(tmp_path)
        store = Dfstore(f"http://127.0.0.1:{port}")

        async def poisoned_get(*a, **k):
            raise AssertionError("copy_object buffered via get_object")

        store.get_object = poisoned_get
        try:
            setup = Dfstore(f"http://127.0.0.1:{port}")
            payload = os.urandom(3 * 1024 * 1024 + 7)
            await setup.create_bucket("c")
            digest = await setup.put_object("c", "src.bin", payload,
                                            mode="write_back")
            copied_digest = await store.copy_object("c", "src.bin", "dst.bin",
                                                    mode="write_back")
            assert copied_digest == digest
            assert await setup.get_object("c", "dst.bin") == payload
            with pytest.raises(DfstoreError):
                await store.copy_object("c", "ghost.bin", "dst2.bin")
            await setup.close()
        finally:
            await store.close()
            await svc.close()

    run_async(run())


def test_replication_task_id_matches_gateway_get(run_async, tmp_path):
    """Regression: replicated copies must live under the SAME task ID a
    gateway GET produces, or seeds prefetch into a task no GET ever hits."""
    from dragonfly2_tpu.daemon.peer.task_manager import StreamTaskRequest
    from dragonfly2_tpu.proto.common import UrlMeta

    async def run():
        specs = []

        async def trigger(seed, spec):
            specs.append(spec)
            return True

        svc, port, _ = await start_gateway(
            tmp_path, get_seed_peers=lambda: [{"ip": "h", "port": 1}],
            trigger_seed=trigger)
        store = Dfstore(f"http://127.0.0.1:{port}")
        try:
            await store.create_bucket("b")
            await store.put_object("b", "obj", b"data", mode="write_back")
            assert len(specs) == 1
            get_task_id = StreamTaskRequest(
                url=specs[0]["url"], meta=UrlMeta(tag="b")).task_id()
            assert specs[0]["task_id"] == get_task_id
        finally:
            await store.close()
            await svc.close()

    run_async(run())


def test_s3_backend_file_like_put(run_async, tmp_path):
    async def run():
        import io

        runner, port = await start_fake_s3()
        be = S3ObjectStorage(endpoint=f"http://127.0.0.1:{port}",
                             access_key="ak", secret_key="sk")
        try:
            await be.create_bucket("b")
            payload = os.urandom(256 * 1024)
            await be.put_object("b", "big", io.BytesIO(payload))
            got = b"".join([c async for c in await be.get_object("b", "big")])
            assert got == payload
        finally:
            await be.close()
            await runner.cleanup()

    run_async(run())


def test_gateway_ranged_get_unknown_length_origin(run_async, tmp_path):
    """Ranged GET whose origin never reported a total length (chunked
    source): the resolved slice must stream as 206 with an unknown-total
    Content-Range, not a spurious 416 (ADVICE round 1)."""
    import aiohttp

    from dragonfly2_tpu.pkg.piece import Range

    payload = os.urandom(1024)

    class ChunkedTransport:
        async def fetch(self, url, headers):
            rng = Range.parse_http(headers["Range"], -1)

            async def body():
                yield payload[rng.start:rng.start + rng.length]

            return {"range": rng, "content_length": -1}, body()

    async def run():
        backend = FSObjectStorage(root=str(tmp_path / "buckets"))
        await backend.create_bucket("data")
        await backend.put_object("data", "blob", payload)
        svc = ObjectStorageService(backend, ChunkedTransport())
        port = await svc.serve("127.0.0.1", 0)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{port}/buckets/data/objects/blob",
                        headers={"Range": "bytes=100-199"}) as resp:
                    assert resp.status == 206
                    assert resp.headers["Content-Range"] == "bytes 100-199/*"
                    assert await resp.read() == payload[100:200]
        finally:
            await svc.close()

    run_async(run())


def test_gateway_warm_get_rides_sendfile(run_async, tmp_path, monkeypatch):
    """Once a task is completed in the piece store, gateway GETs must take
    the sendfile fast path (zero Python byte handling) with bytes-exact
    whole and ranged responses — and partial/cold fetches must not."""

    async def run():
        hits = {"n": 0}
        orig = ObjectStorageService._try_sendfile

        def probe(attrs, rng, total):
            r = orig(attrs, rng, total)
            if r is not None:
                hits["n"] += 1
            return r

        monkeypatch.setattr(ObjectStorageService, "_try_sendfile",
                            staticmethod(probe))
        svc, port, tm = await start_gateway(tmp_path)
        store = Dfstore(f"http://127.0.0.1:{port}")
        try:
            await store.create_bucket("warm")
            payload = os.urandom(2 * 1024 * 1024 + 77)
            await store.put_object("warm", "blob.bin", payload,
                                   mode="write_back")
            got_cold = await store.get_object("warm", "blob.bin")
            assert got_cold == payload
            cold_hits = hits["n"]  # cold GET streams through the task
            got_warm = await store.get_object("warm", "blob.bin")
            assert got_warm == payload
            assert hits["n"] == cold_hits + 1, "warm GET missed sendfile"
            part = await store.get_object("warm", "blob.bin",
                                          range_header="bytes=1000-4999")
            assert part == payload[1000:5000]
            assert hits["n"] == cold_hits + 2, "warm ranged GET missed sendfile"
            # open-ended suffix range stays correct through the fast path
            tail = await store.get_object("warm", "blob.bin",
                                          range_header=f"bytes={len(payload)-500}-")
            assert tail == payload[-500:]
        finally:
            await store.close()
            await svc.close()

    run_async(run())


def test_gateway_prefetch_and_device_sink(run_async, tmp_path):
    """dfstore prefetch warms the daemon's piece store without streaming
    bytes to the client, and `device=tpu` additionally lands the object in
    the HBM sink with on-device verification (north-star dfstore
    --device=tpu; CPU jax backend in tests)."""
    from dragonfly2_tpu.daemon.peer.device_sink import DeviceSinkManager
    from dragonfly2_tpu.daemon.peer.task_manager import TaskManager

    async def run():
        backend = FSObjectStorage(root=str(tmp_path / "buckets"))
        storage = StorageManager(StorageOption(data_dir=str(tmp_path / "p2p")))
        sinks = DeviceSinkManager()
        tm = TaskManager(storage, PieceManager(PieceManagerOption(concurrency=2)),
                         device_sinks=sinks)
        svc = ObjectStorageService(backend, P2PTransport(tm))
        port = await svc.serve("127.0.0.1", 0)
        store = Dfstore(f"http://127.0.0.1:{port}")
        try:
            await store.create_bucket("warmup")
            payload = os.urandom((1 << 20) + 33)
            await store.put_object("warmup", "shard.tar", payload,
                                   mode="write_back")
            result = await store.prefetch_object("warmup", "shard.tar",
                                                 device="tpu")
            assert result["state"] == "done", result
            assert result["device_verified"] is True, result
            assert result["content_length"] == len(payload)
            # The piece store is warm: a GET must not touch the backend's
            # object_url again... it rides reuse (from_reuse on 2nd prefetch).
            again = await store.prefetch_object("warmup", "shard.tar")
            assert again["from_reuse"] is True
            got = await store.get_object("warmup", "shard.tar")
            assert got == payload
            # Unknown object → 502 with a coded message, not a hang.
            with pytest.raises(DfstoreError) as exc:
                await store.prefetch_object("warmup", "ghost.tar")
            assert exc.value.status == 502
        finally:
            await store.close()
            await svc.close()
            sinks.close()
            storage.close()

    run_async(run())


def test_gateway_ranged_prefetch(run_async, tmp_path):
    """dfstore prefetch --range warms ONE span as its own ranged task
    (sharded warm-up through the object gateway), with device=tpu
    landing the slice in the HBM sink; malformed spans are 400s."""
    from dragonfly2_tpu.daemon.peer.device_sink import DeviceSinkManager
    from dragonfly2_tpu.daemon.peer.task_manager import TaskManager

    async def run():
        backend = FSObjectStorage(root=str(tmp_path / "buckets"))
        storage = StorageManager(StorageOption(data_dir=str(tmp_path / "p2p")))
        sinks = DeviceSinkManager()
        tm = TaskManager(storage, PieceManager(PieceManagerOption(concurrency=2)),
                         device_sinks=sinks)
        svc = ObjectStorageService(backend, P2PTransport(tm))
        port = await svc.serve("127.0.0.1", 0)
        store = Dfstore(f"http://127.0.0.1:{port}")
        try:
            await store.create_bucket("sharded")
            payload = os.urandom((2 << 20) + 7)
            await store.put_object("sharded", "ckpt.bin", payload,
                                   mode="write_back")
            result = await store.prefetch_object(
                "sharded", "ckpt.bin", device="tpu",
                range_header="4096-1052671")
            assert result["state"] == "done", result
            assert result["device_verified"] is True, result
            assert result["content_length"] == 1052672 - 4096
            # The ranged task's slice is resident in the sink.
            sink = sinks.get(result["task_id"])
            assert sink is not None and sink.verified
            import numpy as np

            assert (bytes(np.asarray(sink.as_bytes_array()))
                    == payload[4096:1052672])
            # Non-device ranged prefetch with a WARM whole-object
            # parent: must serve from the local store (fresh ranged task
            # + local import), never crash on the file-only export path.
            whole = await store.prefetch_object("sharded", "ckpt.bin")
            assert whole["state"] == "done"
            ranged2 = await store.prefetch_object(
                "sharded", "ckpt.bin", range_header="0-65535")
            assert ranged2["state"] == "done", ranged2
            assert ranged2["content_length"] == 65536

            with pytest.raises(DfstoreError) as exc:
                await store.prefetch_object("sharded", "ckpt.bin",
                                            range_header="9-5")
            assert exc.value.status == 400
        finally:
            await store.close()
            await svc.close()
            sinks.close()
            storage.close()

    run_async(run())
