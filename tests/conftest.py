"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths
compile and execute on a single machine (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The axon sandbox pins jax to its TPU tunnel via sitecustomize; the env var
# alone does not win, so force the platform through jax.config before any
# backend initialization.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def run_async():
    """Run a coroutine to completion on a fresh event loop."""

    def _run(coro, timeout=60):
        async def _with_timeout():
            return await asyncio.wait_for(coro, timeout)

        return asyncio.run(_with_timeout())

    return _run
