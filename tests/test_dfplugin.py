"""Plugin mechanism: source clients / evaluators / searchers from outside
the package.

Reference: internal/dfplugin/dfplugin.go:53-55 — plugin .so files loaded
from the dfpath plugin dir by name. Here: df_plugin_*.py files from
DRAGONFLY_PLUGIN_DIR (or entry points), registered via a ``register(reg)``
hook or PLUGIN_TYPE/PLUGIN_NAME/create attributes.
"""

from __future__ import annotations

import textwrap

from dragonfly2_tpu.pkg.dfplugin import (
    TYPE_EVALUATOR,
    TYPE_SOURCE,
    PluginRegistry,
)


def _write_plugin(tmp_path, name: str, body: str) -> str:
    p = tmp_path / f"df_plugin_{name}.py"
    p.write_text(textwrap.dedent(body))
    return str(tmp_path)


def test_plugin_dir_register_hook(tmp_path):
    d = _write_plugin(tmp_path, "myproto", """
        from dragonfly2_tpu.pkg.dfplugin import TYPE_SOURCE

        class FakeClient:
            scheme = "myproto"

        def register(reg):
            reg.add(TYPE_SOURCE, "myproto", FakeClient)
    """)
    reg = PluginRegistry()
    reg.load(d)
    client = reg.create(TYPE_SOURCE, "myproto")
    assert type(client).__name__ == "FakeClient"


def test_plugin_attrs_form_and_names(tmp_path):
    d = _write_plugin(tmp_path, "scorer", """
        PLUGIN_TYPE = "evaluator"
        PLUGIN_NAME = "random-scorer"

        def create(**kwargs):
            return ("evaluator-instance", kwargs)
    """)
    reg = PluginRegistry()
    reg.load(d)
    inst, kwargs = reg.create(TYPE_EVALUATOR, "random-scorer", config=None)
    assert inst == "evaluator-instance" and kwargs == {"config": None}
    assert reg.names(TYPE_EVALUATOR) == ["random-scorer"]


def test_source_registry_resolves_plugin_scheme(tmp_path, monkeypatch):
    """An unknown URL scheme is resolved through the plugin registry —
    the end-to-end 'registered from outside the package' check."""
    plugin_dir = _write_plugin(tmp_path, "dfs", """
        from dragonfly2_tpu.pkg.dfplugin import TYPE_SOURCE

        class DfsClient:
            async def download(self, request):
                raise NotImplementedError

        def register(reg):
            reg.add(TYPE_SOURCE, "dfs", DfsClient)
    """)
    monkeypatch.setenv("DRAGONFLY_PLUGIN_DIR", plugin_dir)
    # Reset the process-global plugin registry state for the test.
    import dragonfly2_tpu.pkg.dfplugin as dfplugin_mod

    monkeypatch.setattr(dfplugin_mod, "_default",
                        dfplugin_mod.PluginRegistry())

    from dragonfly2_tpu.source.client import Registry

    reg = Registry()
    client = reg.get("dfs://cluster/path/to/shard")
    assert type(client).__name__ == "DfsClient"
    # Cached: second lookup returns the same instance.
    assert reg.get("dfs://other") is client


def test_scheduling_uses_evaluator_plugin(tmp_path, monkeypatch):
    plugin_dir = _write_plugin(tmp_path, "tpueval", """
        PLUGIN_TYPE = "evaluator"
        PLUGIN_NAME = "always-first"

        class AlwaysFirst:
            def __init__(self, config=None):
                self.config = config

            def evaluate_parents(self, parents, child, total_piece_count=-1):
                return list(parents)

            def is_bad_node(self, peer):
                return False

        def create(config=None):
            return AlwaysFirst(config)
    """)
    monkeypatch.setenv("DRAGONFLY_PLUGIN_DIR", plugin_dir)
    import dragonfly2_tpu.pkg.dfplugin as dfplugin_mod

    monkeypatch.setattr(dfplugin_mod, "_default",
                        dfplugin_mod.PluginRegistry())

    from dragonfly2_tpu.scheduler.config import SchedulingConfig
    from dragonfly2_tpu.scheduler.scheduling import Scheduling

    cfg = SchedulingConfig()
    cfg.algorithm = "always-first"
    s = Scheduling(cfg)
    assert type(s.evaluator).__name__ == "AlwaysFirst"
