"""Traffic shaper: plain shared bucket vs sampling reallocation.

Reference: client/daemon/peer/traffic_shaper.go (:65-110 plain, :125+
sampling reallocation by observed need).
"""

from __future__ import annotations

import asyncio

import pytest

from dragonfly2_tpu.daemon.peer.traffic_shaper import (
    MIN_SHARE_FRACTION,
    TrafficShaper,
    TYPE_PLAIN,
    TYPE_SAMPLING,
)
from dragonfly2_tpu.pkg.ratelimit import INF


def test_plain_returns_shared_bucket():
    shaper = TrafficShaper(1000, algorithm=TYPE_PLAIN)
    a = shaper.start_task("a")
    b = shaper.start_task("b")
    assert a is b is shaper._shared


def test_unlimited_total_short_circuits():
    shaper = TrafficShaper(INF, algorithm=TYPE_SAMPLING)
    assert shaper.start_task("a") is shaper._shared


def test_sampling_even_split_on_start_and_finish():
    shaper = TrafficShaper(1000, algorithm=TYPE_SAMPLING)
    a = shaper.start_task("a")
    assert a.limit == 1000
    b = shaper.start_task("b")
    assert a.limit == 500 and b.limit == 500
    shaper.finish_task("a")
    assert b.limit == 1000


def test_sampling_reallocates_toward_need(run_async):
    async def run():
        shaper = TrafficShaper(1000, algorithm=TYPE_SAMPLING)
        hot = shaper.start_task("hot")
        cold = shaper.start_task("cold")
        # Simulate a window: the hot task moved 9x the bytes.
        await hot.wait(0)
        hot.window_bytes = 9000
        cold.window_bytes = 1000
        shaper.reallocate()
        floor = 1000 * MIN_SHARE_FRACTION / 2
        assert hot.limit == pytest.approx(floor + (1000 - 2 * floor) * 0.9)
        assert cold.limit == pytest.approx(floor + (1000 - 2 * floor) * 0.1)
        # Idle window: falls back to an even split.
        shaper.reallocate()
        assert hot.limit == pytest.approx(500)
        assert cold.limit == pytest.approx(500)

    run_async(run())


def test_sampling_floor_keeps_starved_task_alive(run_async):
    async def run():
        shaper = TrafficShaper(1000, algorithm=TYPE_SAMPLING)
        busy = shaper.start_task("busy")
        starved = shaper.start_task("starved")
        busy.window_bytes = 10_000
        starved.window_bytes = 0
        shaper.reallocate()
        assert starved.limit >= 1000 * MIN_SHARE_FRACTION / 2
        assert busy.limit < 1000  # the floor is carved out of the total

    run_async(run())


def test_task_limiter_tracks_window(run_async):
    async def run():
        shaper = TrafficShaper(1_000_000, algorithm=TYPE_SAMPLING)
        lim = shaper.start_task("t")
        await lim.wait(100)
        await lim.wait(50)
        assert lim.take_window() == 150
        assert lim.take_window() == 0

    run_async(run())


def test_bad_algorithm_falls_back_to_plain():
    # A typo'd algorithm warns and degrades to plain instead of failing
    # daemon startup (reference traffic_shaper.go:59).
    assert TrafficShaper(100, algorithm="bogus").algorithm == "plain"


def test_window_not_double_counted_for_oversize_requests(run_async):
    """Regression: requests larger than the bucket burst chunk internally;
    the window counter must see the request once, not request + chunks."""
    async def run():
        shaper = TrafficShaper(1000, algorithm=TYPE_SAMPLING)
        lim = shaper.start_task("t")
        lim.set_limit(1000, burst=100)
        await lim.wait(250)   # 3 internal chunks
        assert lim.take_window() == 250

    run_async(run())
