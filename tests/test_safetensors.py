"""safetensors checkpoints straight out of the device sink.

The north-star payload: a safetensors file lands in HBM via the P2P
fabric and becomes named (optionally mesh-sharded) tensors without a
host round trip of the data. The test builds the format by hand
(8-byte LE header length + JSON + raw tensors — the public stable
layout) and round-trips through an HBMSink and the full P2P path.
"""

from __future__ import annotations

import hashlib
import json
import struct

import numpy as np
import pytest

from dragonfly2_tpu.ops.hbm_sink import HBMSink
from dragonfly2_tpu.ops import safetensors as st


def make_safetensors(tensors: dict[str, np.ndarray],
                     dtype_names: dict[str, str]) -> bytes:
    header = {}
    blobs = []
    off = 0
    for name, arr in tensors.items():
        raw = arr.tobytes()
        header[name] = {"dtype": dtype_names[name],
                        "shape": list(arr.shape),
                        "data_offsets": [off, off + len(raw)]}
        blobs.append(raw)
        off += len(raw)
    hjson = json.dumps(header).encode()
    return struct.pack("<Q", len(hjson)) + hjson + b"".join(blobs)


@pytest.fixture
def checkpoint():
    rng = np.random.RandomState(3)
    tensors = {
        "model.embed": rng.randn(64, 32).astype(np.float32),
        "model.w1": (rng.randn(32, 128) * 0.1).astype(np.float32),
        "model.bias": rng.randn(128).astype(np.float32),
        "model.step": np.array([1234], dtype=np.int64),
    }
    dtypes = {"model.embed": "F32", "model.w1": "F32",
              "model.bias": "F32", "model.step": "I64"}
    return tensors, make_safetensors(tensors, dtypes)


def _land(content: bytes, piece: int = 4096) -> HBMSink:
    sink = HBMSink(len(content), piece, batch_pieces=4)
    for n in range((len(content) + piece - 1) // piece):
        sink.land_piece(n, content[n * piece:(n + 1) * piece])
    assert sink.complete() and sink.verify()
    return sink


def test_tensors_from_sink_exact(checkpoint):
    tensors, content = checkpoint
    sink = _land(content)
    loaded = st.load_from_sink(sink)
    assert set(loaded) == set(tensors)
    for name, want in tensors.items():
        got = np.asarray(loaded[name])
        if want.dtype.itemsize == 8:
            # jax x64 disabled: 64-bit tensors canonicalize to 32-bit
            # (low word — exact for values fitting 32 bits).
            assert got.dtype.itemsize == 4, name
            want = want.astype(got.dtype)
        else:
            assert got.dtype == want.dtype, name
        np.testing.assert_array_equal(got, want, err_msg=name)


def test_names_filter_and_shardings(checkpoint):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dragonfly2_tpu.parallel.ici import make_mesh

    tensors, content = checkpoint
    sink = _land(content)
    mesh = make_mesh(8)
    loaded = st.load_from_sink(
        sink, names=["model.w1"],
        shardings={"model.w1": NamedSharding(mesh, P(None, "d"))})
    assert list(loaded) == ["model.w1"]
    sharded = loaded["model.w1"]
    assert len(sharded.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(sharded), tensors["model.w1"])


def test_corrupt_header_rejected():
    content = struct.pack("<Q", 1 << 40) + b"{}"
    sink = _land(content + b"\x00" * 100)
    with pytest.raises(st.SafetensorsError, match="header length"):
        st.load_from_sink(sink)


def test_span_mismatch_rejected():
    header = {"t": {"dtype": "F32", "shape": [4], "data_offsets": [0, 12]}}
    hj = json.dumps(header).encode()
    content = struct.pack("<Q", len(hj)) + hj + b"\x00" * 16
    sink = _land(content)
    with pytest.raises(st.SafetensorsError, match="data span"):
        st.load_from_sink(sink)


def test_p2p_checkpoint_to_named_tensors(run_async, tmp_path, checkpoint):
    """End to end: safetensors served by an origin, pulled through the
    P2P fabric with --device landing, consumed as named tensors."""
    from aiohttp import web

    from dragonfly2_tpu.client import device as device_lib
    from dragonfly2_tpu.pkg.piece import Range
    from tests.test_device_sink import _start_sink_daemon
    from tests.test_p2p_e2e import start_scheduler

    tensors, content = checkpoint
    sha = "sha256:" + hashlib.sha256(content).hexdigest()

    async def body():
        async def blob(request):
            rng = request.headers.get("Range")
            if rng:
                r = Range.parse_http(rng, len(content))
                return web.Response(
                    status=206, body=content[r.start:r.start + r.length],
                    headers={"Accept-Ranges": "bytes",
                             "Content-Range": f"bytes {r.start}-"
                             f"{r.start + r.length - 1}/{len(content)}"})
            return web.Response(body=content,
                                headers={"Accept-Ranges": "bytes"})

        app = web.Application()
        app.router.add_get("/ckpt.safetensors", blob)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        oport = site._server.sockets[0].getsockname()[1]

        sched = await start_scheduler()
        peer = await _start_sink_daemon(tmp_path, "ckpt", sched.port())
        try:
            result = await device_lib.download_to_device(
                peer, f"http://127.0.0.1:{oport}/ckpt.safetensors",
                digest=sha)
            loaded = result.load_safetensors()
            for name, want in tensors.items():
                np.testing.assert_array_equal(
                    np.asarray(loaded[name]), want, err_msg=name)
        finally:
            await peer.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=120)


class TestReviewRegressions:
    def test_bool_tensor_loads(self):
        arr = np.array([1, 0, 1, 1, 0, 0, 1, 0], dtype=np.uint8)
        content = make_safetensors({"mask": arr}, {"mask": "BOOL"})
        sink = _land(content, piece=256)
        loaded = st.load_from_sink(sink)
        np.testing.assert_array_equal(
            np.asarray(loaded["mask"]), arr.astype(bool))

    def test_f64_refused_without_x64(self):
        arr = np.ones(4, dtype=np.float64)
        content = make_safetensors({"w": arr}, {"w": "F64"})
        sink = _land(content, piece=256)
        with pytest.raises(st.SafetensorsError, match="x64"):
            st.load_from_sink(sink)

    def test_out_of_range_offsets_rejected(self):
        header = {"t": {"dtype": "F32", "shape": [64],
                        "data_offsets": [0, 256]}}
        hj = json.dumps(header).encode()
        content = struct.pack("<Q", len(hj)) + hj + b"\x00" * 16  # short
        sink = _land(content, piece=256)
        with pytest.raises(st.SafetensorsError, match="outside content"):
            st.load_from_sink(sink)

    def test_negative_offsets_rejected(self):
        header = {"t": {"dtype": "F32", "shape": [2],
                        "data_offsets": [-8, 0]}}
        hj = json.dumps(header).encode()
        content = struct.pack("<Q", len(hj)) + hj + b"\x00" * 16
        sink = _land(content, piece=256)
        with pytest.raises(st.SafetensorsError, match="outside content"):
            st.load_from_sink(sink)

    def test_missing_requested_name_rejected(self):
        arr = np.ones(4, dtype=np.float32)
        content = make_safetensors({"w": arr}, {"w": "F32"})
        sink = _land(content, piece=256)
        with pytest.raises(st.SafetensorsError, match="not in checkpoint"):
            st.load_from_sink(sink, names=["w_typo"])

    def test_unknown_sharding_name_rejected(self):
        arr = np.ones(4, dtype=np.float32)
        content = make_safetensors({"w": arr}, {"w": "F32"})
        sink = _land(content, piece=256)
        with pytest.raises(st.SafetensorsError, match="not loaded"):
            st.load_from_sink(sink, shardings={"w_typo": None})

    def test_structurally_malformed_headers_raise_schema_error(self):
        cases = [
            b"[1, 2]",                                       # header not object
            b'{"t": "not-an-object"}',                       # entry not object
            b'{"t": {"dtype": "F32", "data_offsets": [0, 4]}}',   # no shape
            b'{"t": {"dtype": "F32", "shape": "x", "data_offsets": [0, 4]}}',
            b'{"t": {"dtype": "F32", "shape": [1], "data_offsets": [0.0, 4]}}',
            b'{"t": {"dtype": "F32", "shape": [-1], "data_offsets": [0, 4]}}',
        ]
        for hj in cases:
            content = struct.pack("<Q", len(hj)) + hj + b"\x00" * 64
            sink = _land(content, piece=256)
            with pytest.raises(st.SafetensorsError):
                st.load_from_sink(sink)

    def test_i64_beyond_32_bits_refused(self):
        arr = np.array([(1 << 40) + 7], dtype=np.int64)
        content = make_safetensors({"big": arr}, {"big": "I64"})
        sink = _land(content, piece=256)
        with pytest.raises(st.SafetensorsError, match="exceed 32 bits"):
            st.load_from_sink(sink)

    def test_i64_negative_within_32_bits_exact(self):
        arr = np.array([-5, 7, -1], dtype=np.int64)
        content = make_safetensors({"ids": arr}, {"ids": "I64"})
        sink = _land(content, piece=256)
        loaded = st.load_from_sink(sink)
        np.testing.assert_array_equal(
            np.asarray(loaded["ids"]), arr.astype(np.int32))


class TestZeroLengthAndMetadata:
    """ISSUE 10 satellites: zero-length tensors load without raising,
    and the ``__metadata__`` entry has a public accessor instead of
    being silently dropped."""

    def _content(self, header: dict, data: bytes = b"") -> bytes:
        hj = json.dumps(header).encode()
        return struct.pack("<Q", len(hj)) + hj + data

    def test_zero_length_tensors_all_dtypes(self):
        header = {
            "f32": {"dtype": "F32", "shape": [0], "data_offsets": [0, 0]},
            "f64": {"dtype": "F64", "shape": [0], "data_offsets": [0, 0]},
            "i64": {"dtype": "I64", "shape": [0, 4], "data_offsets": [0, 0]},
            "bool": {"dtype": "BOOL", "shape": [0], "data_offsets": [0, 0]},
            "mid": {"dtype": "F32", "shape": [2], "data_offsets": [0, 8]},
            "empty_at_end": {"dtype": "F16", "shape": [4, 0],
                             "data_offsets": [8, 8]},
        }
        sink = _land(self._content(header, b"\x11" * 8), piece=256)
        loaded = st.load_from_sink(sink)
        assert loaded["f32"].shape == (0,)
        assert loaded["f64"].shape == (0,)     # no x64 refusal for 0 elems
        assert loaded["i64"].shape == (0, 4)
        assert loaded["bool"].shape == (0,)
        assert bool(loaded["bool"].dtype == np.bool_)
        assert loaded["empty_at_end"].shape == (4, 0)
        assert loaded["mid"].shape == (2,)

    def test_zero_length_span_mismatch_still_rejected(self):
        # A 0-element shape with a NON-empty span is malformed.
        header = {"t": {"dtype": "F32", "shape": [0],
                        "data_offsets": [0, 4]}}
        sink = _land(self._content(header, b"\0" * 4), piece=256)
        with pytest.raises(st.SafetensorsError, match="data span"):
            st.load_from_sink(sink)

    def test_header_metadata_accessor(self):
        header = {"__metadata__": {"format": "pt", "step": "1234"},
                  "w": {"dtype": "F32", "shape": [1],
                        "data_offsets": [0, 4]}}
        content = self._content(header, b"\0" * 4)
        parsed, _ = st.parse_header(content)
        assert st.header_metadata(parsed) == {"format": "pt",
                                              "step": "1234"}
        # tensor_views still skips it.
        sink = _land(content, piece=256)
        assert set(st.load_from_sink(sink)) == {"w"}

    def test_header_metadata_absent_is_empty(self):
        parsed, _ = st.parse_header(self._content(
            {"w": {"dtype": "F32", "shape": [1], "data_offsets": [0, 4]}},
            b"\0" * 4))
        assert st.header_metadata(parsed) == {}

    def test_header_metadata_malformed_rejected(self):
        for bad in ([1, 2], "x", {"k": 3}, {"k": None}, {"k": ["v"]}):
            with pytest.raises(st.SafetensorsError, match="__metadata__"):
                st.header_metadata({"__metadata__": bad})
        with pytest.raises(st.SafetensorsError, match="JSON object"):
            st.header_metadata([])


def test_pod_global_shardings_from_preheated_sink(checkpoint):
    """The north-star consumption chain: a preheat-landed checkpoint loads
    straight into tensors placed on a pod-global factored mesh —
    load_from_sink's shardings hook composes with parallel.multihost
    (single process here; the same NamedSharding spans processes on a
    pod where every host preheated the same content)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dragonfly2_tpu.ops.safetensors import load_from_sink
    from dragonfly2_tpu.parallel import multihost

    arrays, content = checkpoint
    sink = _land(content)
    mesh = multihost.global_mesh({"dp": 2, "tp": 4})
    name, ref = next((n, a) for n, a in arrays.items() if a.ndim >= 2)
    axis = "tp" if ref.shape[-1] % 4 == 0 else "dp"
    spec = P(*([None] * (ref.ndim - 1) + [axis]))
    tensors = load_from_sink(
        sink, names=[name],
        shardings={name: NamedSharding(mesh, spec)})
    arr = tensors[name]
    assert arr.sharding.mesh.shape == {"dp": 2, "tp": 4}
    np.testing.assert_array_equal(np.asarray(arr), ref)
    # a consumer jit under the same mesh uses it directly
    out = jax.jit(lambda x: x.sum())(arr)
    np.testing.assert_allclose(float(out), float(ref.sum()), rtol=1e-4)
