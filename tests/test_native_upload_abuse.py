"""Adversarial coverage for the native upload server (dfupload.cc).

The serving side of the piece hop faces other daemons' pulls — and
anything else that can reach the port. These drive the abuse paths the
happy-path contract tests (test_native_upload.py) skip: slow-loris heads,
oversized heads, pathological Range headers, clients that stop reading
mid-sendfile, and task deregistration racing an in-flight send. Spirit of
the dfhttp head fuzz (test_native_http.py), aimed at the server.

The server's abuse timeouts are env-tuned down (DF_UPLOAD_HEAD_DEADLINE_S,
DF_UPLOAD_SEND_TIMEOUT_S are read per-connection in conn_loop) so expiry
is observable in test time.
"""

from __future__ import annotations

import asyncio
import random
import socket
import time

import aiohttp
import pytest

from dragonfly2_tpu.daemon.upload import UploadManager
from dragonfly2_tpu.storage.local_store import TaskStoreMetadata, _native
from dragonfly2_tpu.storage.manager import StorageManager, StorageOption

nb = _native()
pytestmark = pytest.mark.skipif(nb is None, reason="native library unavailable")

# 8 MiB: must exceed server sndbuf + client rcvbuf so a stalled reader
# genuinely blocks the server's sendfile (loopback auto-tunes buffers to
# multiple MB; 1 MiB vanished into them without ever blocking).
PIECE = 8 << 20


async def _boot(tmp_path, monkeypatch, *, head_deadline_s=2, send_timeout_s=2):
    monkeypatch.setenv("DF_UPLOAD_HEAD_DEADLINE_S", str(head_deadline_s))
    monkeypatch.setenv("DF_UPLOAD_SEND_TIMEOUT_S", str(send_timeout_s))
    storage = StorageManager(StorageOption(data_dir=str(tmp_path / "d")))
    content = random.Random(5).randbytes(4 * PIECE)
    store = storage.register_task(TaskStoreMetadata(
        task_id="abuse-task", content_length=len(content), piece_size=PIECE,
        total_piece_count=4))
    for n in range(4):
        store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
    upload = UploadManager(storage)
    port = await upload.serve("127.0.0.1", 0)
    assert upload._native_srv is not None
    return storage, store, content, upload, port


async def _get_piece(port: int, n: int) -> bytes:
    async with aiohttp.ClientSession() as http:
        async with http.get(
                f"http://127.0.0.1:{port}/download/abu/abuse-task",
                params={"peerId": "p", "pieceNum": str(n)},
                timeout=aiohttp.ClientTimeout(total=30)) as r:
            assert r.status == 200, r.status
            return await r.read()


def _raw_conn(port: int, rcvbuf: int = 0) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    if rcvbuf:
        # Before connect: the receive window is negotiated at SYN time —
        # setting it later leaves the kernel's multi-MB autotuned window.
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
    s.settimeout(10)
    s.connect(("127.0.0.1", port))
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def test_slow_loris_heads_reaped_and_serving_continues(run_async, tmp_path,
                                                       monkeypatch):
    """Heads that dribble a byte at a time defeat the per-recv timeout;
    the whole-head deadline must reap them, and normal piece serving must
    continue while they dribble."""

    async def body():
        storage, store, content, upload, port = await _boot(
            tmp_path, monkeypatch, head_deadline_s=2)
        conns = [_raw_conn(port) for _ in range(6)]
        stop = time.monotonic() + 5.5

        async def dribble(s: socket.socket):
            payload = b"GET /download/abu/abuse-task?pieceNum=0 HTTP/1.1\r\n"
            i = 0
            try:
                while time.monotonic() < stop:
                    s.send(payload[i % len(payload):i % len(payload) + 1])
                    i += 1
                    await asyncio.sleep(0.3)
            except OSError:
                return "closed"
            return "alive"

        try:
            dribblers = [asyncio.ensure_future(dribble(s)) for s in conns]
            # Serving continues while the loris connections dribble.
            for n in range(4):
                assert await _get_piece(port, n) == \
                    content[n * PIECE:(n + 1) * PIECE]
            results = await asyncio.gather(*dribblers)
            # The deadline (2s) reaped the dribblers mid-run: sends start
            # failing once the server closes its end.
            assert results.count("closed") >= 4, results
            # And the pool is healthy afterwards.
            assert await _get_piece(port, 0) == content[:PIECE]
        finally:
            for s in conns:
                s.close()
            await upload.close()

    run_async(body(), timeout=60)


def test_oversized_head_closes_connection(run_async, tmp_path, monkeypatch):
    async def body():
        storage, store, content, upload, port = await _boot(
            tmp_path, monkeypatch)
        try:
            s = _raw_conn(port)
            junk = b"GET /x HTTP/1.1\r\nX-Filler: " + b"a" * (20 << 10)
            with pytest.raises(OSError):
                # No terminator: the server must close at HEAD_MAX; the
                # send eventually fails rather than buffering forever.
                for _ in range(64):
                    s.sendall(junk)
                    time.sleep(0.02)
            s.close()
            assert await _get_piece(port, 1) == content[PIECE:2 * PIECE]
        finally:
            await upload.close()

    run_async(body(), timeout=60)


def test_pathological_range_headers(run_async, tmp_path, monkeypatch):
    """Oversized range lists and malformed ranges are 400/416, never a
    crash, and never a served body."""

    async def body():
        storage, store, content, upload, port = await _boot(
            tmp_path, monkeypatch)
        bad = [
            "bytes=" + ",".join(f"{i}-{i + 1}" for i in range(2000)),
            "bytes=9999999999999999999999999-999999999999999999999999999",
            "bytes=5-4",
            "bytes=--10",
            "bytes=",
            "bites=0-10",
            "bytes=0-10,",
        ]
        try:
            async with aiohttp.ClientSession() as http:
                for hdr in bad:
                    async with http.get(
                            f"http://127.0.0.1:{port}/download/abu/abuse-task",
                            headers={"Range": hdr},
                            timeout=aiohttp.ClientTimeout(total=15)) as r:
                        assert r.status in (400, 416), (hdr, r.status)
                # A range far past EOF: not satisfiable, not a crash.
                async with http.get(
                        f"http://127.0.0.1:{port}/download/abu/abuse-task",
                        headers={"Range": f"bytes={10 * PIECE}-{11 * PIECE}"},
                        timeout=aiohttp.ClientTimeout(total=15)) as r:
                    assert r.status in (400, 416), r.status
            assert await _get_piece(port, 2) == content[2 * PIECE:3 * PIECE]
        finally:
            await upload.close()

    run_async(body(), timeout=60)


def test_stalled_reader_does_not_park_worker_forever(run_async, tmp_path,
                                                     monkeypatch):
    """A live-but-not-reading client must hit the send timeout (EAGAIN on
    the blocking socket) and free its worker — the round-3 advisor finding
    (EAGAIN-forever retry) regression-tested end to end."""

    async def body():
        storage, store, content, upload, port = await _boot(
            tmp_path, monkeypatch, send_timeout_s=2)
        s = _raw_conn(port, rcvbuf=4096)
        s.sendall(b"GET /download/abu/abuse-task?pieceNum=0 HTTP/1.1\r\n"
                  b"Host: x\r\n\r\n")
        try:
            # Never read. Within ~send_timeout the server must abort the
            # send; its FIN shows up as EOF once we finally drain.
            await asyncio.sleep(4.0)
            s.settimeout(10)
            total = 0
            while True:
                b = s.recv(1 << 16)
                if not b:
                    break
                total += len(b)
            # Far less than the full piece arrived: the send was cut off.
            assert total < PIECE, total
            # The worker is free again: serving proceeds normally.
            assert await _get_piece(port, 1) == content[PIECE:2 * PIECE]
        finally:
            s.close()
            await upload.close()

    run_async(body(), timeout=60)


def test_deregister_task_during_inflight_send(run_async, tmp_path,
                                              monkeypatch):
    """Unregistering a task while one of its pieces is being sent must
    neither crash nor corrupt the in-flight response (the server resolved
    the path/offsets before the send; the open fd outlives the registry
    entry), and later requests 404."""

    async def body():
        storage, store, content, upload, port = await _boot(
            tmp_path, monkeypatch, send_timeout_s=5)
        s = _raw_conn(port, rcvbuf=8192)
        s.sendall(b"GET /download/abu/abuse-task?pieceNum=3 HTTP/1.1\r\n"
                  b"Host: x\r\n\r\n")
        try:
            await asyncio.sleep(0.1)   # send in flight, reader slow
            nb.upload_unregister_task(upload._native_srv, "abuse-task")
            # Drain slowly AFTER the dereg: bytes must still be the piece.
            s.settimeout(10)
            got = b""
            while b"\r\n\r\n" not in got:
                got += s.recv(4096)
            head, _, rest = got.partition(b"\r\n\r\n")
            assert b"200 OK" in head.splitlines()[0]
            body_bytes = rest
            while len(body_bytes) < PIECE:
                b = s.recv(1 << 16)
                if not b:
                    break
                body_bytes += b
            assert body_bytes == content[3 * PIECE:]
            # Registry entry is gone for new requests.
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{port}/download/abu/abuse-task",
                        params={"pieceNum": "0"},
                        timeout=aiohttp.ClientTimeout(total=15)) as r:
                    assert r.status == 404
        finally:
            s.close()
            await upload.close()

    run_async(body(), timeout=60)
