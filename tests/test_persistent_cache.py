"""Persistent cache tasks: durable records, replica management, RPC family.

Reference: scheduler/resource/persistentcache (Redis-backed durability) +
service_v2.go:1580-1895 (UploadPersistentCacheTask* family). Durability here
is sqlite: records survive a scheduler restart, replicas are re-established
when hosts leave, TTL-expired tasks are deleted everywhere.
"""

from __future__ import annotations

import asyncio
import os

import pytest

from dragonfly2_tpu.client import dfcache
from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.resource.persistentcache import (
    PersistentCacheResource,
    STATE_SUCCEEDED,
)
from dragonfly2_tpu.scheduler.server import SchedulerServer

from tests.test_p2p_e2e import start_daemon


async def _wait(predicate, timeout: float = 40.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(0.05)
    return False


def _sched_config(tmp_path) -> SchedulerConfig:
    cfg = SchedulerConfig()
    cfg.server.port = 0
    cfg.scheduling.retry_interval = 0.05
    cfg.scheduling.no_source_patience = 0.5
    cfg.gc.interval = 3600
    cfg.persistent_cache_db = str(tmp_path / "pc.sqlite")
    return cfg


# -- resource unit ----------------------------------------------------------

def test_resource_survives_reopen(tmp_path):
    path = str(tmp_path / "pc.sqlite")
    r = PersistentCacheResource(path)
    r.upsert_task("t1", url="dfcache://x", replica_count=3, state="succeeded")
    r.upsert_peer("p1", "t1", "h1", state=STATE_SUCCEEDED)
    r.upsert_host("h1", hostname="a", ip="1.2.3.4", port=9)
    r.close()

    r2 = PersistentCacheResource(path)
    task = r2.get_task("t1")
    assert task["replica_count"] == 3 and task["url"] == "dfcache://x"
    assert r2.replica_count("t1") == 1
    assert r2.get_host("h1")["ip"] == "1.2.3.4"
    r2.close()


def test_resource_host_departure_and_ttl(tmp_path):
    r = PersistentCacheResource(":memory:")
    r.upsert_task("t1", replica_count=2, ttl=0.001)
    r.upsert_peer("p1", "t1", "h1", state=STATE_SUCCEEDED)
    r.upsert_peer("p2", "t1", "h2", state=STATE_SUCCEEDED)
    assert r.replica_count("t1") == 2
    assert r.delete_peers_of_host("h1") == ["t1"]
    assert r.replica_count("t1") == 1
    import time

    time.sleep(0.01)
    assert [t["task_id"] for t in r.expired_tasks()] == ["t1"]
    r.close()


# -- end-to-end: import → auto-replication → restart → delete ---------------

def test_persistent_import_replicates_and_survives_restart(run_async, tmp_path):
    async def run():
        cfg = _sched_config(tmp_path)
        sched = SchedulerServer(cfg)
        await sched.start()
        d_a = await start_daemon(tmp_path, "pc-a", sched.port())
        d_b = await start_daemon(tmp_path, "pc-b", sched.port())
        sched2 = None
        try:
            payload = os.urandom(1024 * 1024)
            src = tmp_path / "data.bin"
            src.write_bytes(payload)
            # Both daemons must be announced before replication fans out.
            assert await _wait(lambda: len(sched.service.hosts.all()) >= 2)

            cfg_a = dfcache.DfcacheConfig(
                daemon_sock=d_a.config.unix_sock, cache_id="pc-entry")
            result = await dfcache.import_file(
                cfg_a, str(src), persistent=True, replica_count=2)
            task_id = result["task_id"]

            # The scheduler recorded the task and fired replication at B.
            wire = sched.service.persistent.task_wire(task_id)
            assert wire is not None and wire["replica_count"] == 2
            assert await _wait(
                lambda: sched.service.persistent.replica_count(task_id) >= 2)
            # B actually holds the bytes now.
            store_b = d_b.task_manager.storage.try_get(task_id)
            assert store_b is not None and store_b.metadata.done

            # Restart the scheduler with the same sqlite: state survives.
            await sched.stop()
            sched2 = SchedulerServer(cfg)
            await sched2.start()
            wire2 = sched2.service.persistent.task_wire(task_id)
            assert wire2 is not None
            assert wire2["current_replicas"] == 2

            # Delete fans Peer.DeleteTask to the recorded holders.
            resp = await sched2.service.delete_persistent_cache_task(
                {"task_id": task_id}, None)
            assert resp["ok"], resp
            assert await _wait(
                lambda: d_b.task_manager.storage.try_get(task_id) is None)
            assert d_a.task_manager.storage.try_get(task_id) is None
            assert sched2.service.persistent.get_task(task_id) is None
        finally:
            await d_a.stop()
            await d_b.stop()
            if sched2 is not None:
                await sched2.stop()
            else:
                await sched.stop()

    run_async(run())


def test_replicas_restored_when_host_leaves(run_async, tmp_path):
    async def run():
        cfg = _sched_config(tmp_path)
        sched = SchedulerServer(cfg)
        await sched.start()
        d_a = await start_daemon(tmp_path, "rep-a", sched.port())
        d_b = await start_daemon(tmp_path, "rep-b", sched.port())
        d_c = await start_daemon(tmp_path, "rep-c", sched.port())
        try:
            payload = os.urandom(512 * 1024)
            src = tmp_path / "d.bin"
            src.write_bytes(payload)
            assert await _wait(lambda: len(sched.service.hosts.all()) >= 3)

            cfg_a = dfcache.DfcacheConfig(
                daemon_sock=d_a.config.unix_sock, cache_id="rep-entry")
            result = await dfcache.import_file(
                cfg_a, str(src), persistent=True, replica_count=2)
            task_id = result["task_id"]
            assert await _wait(
                lambda: sched.service.persistent.replica_count(task_id) >= 2)
            holders = {p["host_id"] for p in
                       sched.service.persistent.peers_of(task_id)}
            # Kill a replica host (not the uploader): leave_host must
            # re-replicate onto the remaining free host.
            victim = next(h for h in holders
                          if h != sched.service.persistent.peers_of(
                              task_id)[0]["host_id"])
            replica_daemon = {d.config.host.hostname: d
                             for d in (d_a, d_b, d_c)}
            await sched.service.leave_host({"id": victim}, None)
            assert await _wait(
                lambda: sched.service.persistent.replica_count(task_id) >= 2)
            new_holders = {p["host_id"] for p in
                           sched.service.persistent.peers_of(task_id)}
            assert victim not in new_holders
        finally:
            await d_a.stop()
            await d_b.stop()
            await d_c.stop()
            await sched.stop()

    run_async(run())


def test_task_retrievable_after_replica_host_killed(run_async, tmp_path):
    """The VERDICT r04 item-6 'done' bar: import with --replica-count 2,
    HARD-KILL one replica's daemon (no goodbye — its announcer is torn
    off before stop so no LeaveHost is ever sent, the failure-detection
    analog of a SIGKILL), and a THIRD host must still export the exact
    bytes over P2P from the surviving replica — replication repair is
    stubbed out until after the export so the survivor cannot be
    pre-warmed by the repair racing the pull. Then the repair path is
    restored and the GC top-up re-establishes the count without the dead
    host. Reference capability: service_v2.go:1726-1895 +
    persistentcache host GC."""

    async def run():
        cfg = _sched_config(tmp_path)
        sched = SchedulerServer(cfg)
        await sched.start()
        d_a = await start_daemon(tmp_path, "kill-a", sched.port())
        d_b = await start_daemon(tmp_path, "kill-b", sched.port())
        d_c = await start_daemon(tmp_path, "kill-c", sched.port())
        alive = [d_a, d_b, d_c]
        try:
            payload = os.urandom(768 * 1024)
            src = tmp_path / "k.bin"
            src.write_bytes(payload)
            assert await _wait(lambda: len(sched.service.hosts.all()) >= 3)

            cfg_a = dfcache.DfcacheConfig(
                daemon_sock=d_a.config.unix_sock, cache_id="kill-entry")
            result = await dfcache.import_file(
                cfg_a, str(src), persistent=True, replica_count=2)
            task_id = result["task_id"]
            assert await _wait(
                lambda: sched.service.persistent.replica_count(task_id) >= 2)

            # The uploader is d_a by construction; the victim is the
            # OTHER holder (replication placed it on b or c).
            uploader_host = d_a._host_wire()["id"]
            holders = {p["host_id"] for p in
                       sched.service.persistent.peers_of(task_id)}
            assert uploader_host in holders
            victim_host = next(h for h in holders if h != uploader_host)
            by_host = {d._host_wire()["id"]: d for d in (d_a, d_b, d_c)}
            victim = by_host[victim_host]
            alive.remove(victim)
            # Hard kill: no announcer → no LeaveHost goodbye; the
            # scheduler still lists the host until failure detection
            # (modeled by the explicit leave below) reaps it.
            victim.announcer = None
            await victim.stop()
            assert any(h.id == victim_host
                       for h in sched.service.hosts.all())

            # Stub replication repair so the upcoming leave cannot
            # pre-warm the survivor before the export exercises P2P.
            real_trigger = sched.service.seed_clients.trigger_download_task

            async def no_repair(host, spec):
                return False

            sched.service.seed_clients.trigger_download_task = no_repair
            resp = await sched.service.leave_host({"id": victim_host}, None)
            assert resp.get("ok"), resp

            # The survivor that never held the entry exports it: bytes
            # must arrive exactly, pulled over P2P from the live replica.
            survivor = next(d for d in alive
                            if d._host_wire()["id"] not in holders)
            assert survivor.task_manager.storage.try_get(task_id) is None
            out = tmp_path / "exported.bin"
            cfg_s = dfcache.DfcacheConfig(
                daemon_sock=survivor.config.unix_sock, cache_id="kill-entry")
            await dfcache.export_file(cfg_s, str(out))
            assert out.read_bytes() == payload

            # Restore repair; the GC top-up re-establishes the count
            # without ever handing out the dead host.
            sched.service.seed_clients.trigger_download_task = real_trigger
            sched.service.gc()
            assert await _wait(
                lambda: sched.service.persistent.replica_count(task_id) >= 2)
            assert victim_host not in {
                p["host_id"]
                for p in sched.service.persistent.peers_of(task_id)}
        finally:
            for d in alive:
                await d.stop()
            await sched.stop()

    run_async(run(), timeout=120)


def test_gc_repairs_under_replication(run_async):
    """A replication trigger whose download failed leaves the task under-
    replicated with no retry scheduled; the GC pass must re-check succeeded
    tasks and top them up (ADVICE round 1, service.py _ensure_replicas)."""
    from dragonfly2_tpu.scheduler.service import SchedulerService
    from dragonfly2_tpu.scheduler.resource import Host

    async def run():
        svc = SchedulerService()
        svc.persistent.upsert_task(
            "t-under", url="dfcache://x", replica_count=2, state="succeeded",
            tag="", application="", digest="")
        svc.persistent.upsert_peer("p1", "t-under", "h1", state="succeeded")
        svc.hosts.store(Host("h2", ip="10.0.0.2", port=8000, upload_port=9000))

        fired = []

        async def fake_trigger(host, spec):
            fired.append((host.id, spec["task_id"]))
            return True

        svc.seed_clients.trigger_download_task = fake_trigger
        svc.gc()
        await asyncio.sleep(0.1)  # let the spawned repair run
        assert fired == [("h2", "t-under")]

        # At quota: no repair fires.
        svc.persistent.upsert_peer("p2", "t-under", "h2", state="succeeded")
        fired.clear()
        svc.gc()
        await asyncio.sleep(0.1)
        assert fired == []

    run_async(run())
