"""TLS/mTLS on drpc: encrypted transport, client-cert enforcement.

Reference: pkg/rpc/credential.go (mTLS transport credentials). Test certs
are minted with the openssl CLI — one fabric CA signing a server and a
client cert, like the reference's certify flow.
"""

from __future__ import annotations

import subprocess

import pytest

from dragonfly2_tpu.pkg import security
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.rpc import Client, Server
from dragonfly2_tpu.rpc.client import RpcError


def _openssl(*args) -> None:
    subprocess.run(["openssl", *args], check=True,
                   stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    try:
        # CA
        _openssl("req", "-x509", "-newkey", "rsa:2048", "-nodes",
                 "-keyout", str(d / "ca.key"), "-out", str(d / "ca.crt"),
                 "-days", "1", "-subj", "/CN=df-test-ca")
        for name in ("server", "client"):
            _openssl("req", "-newkey", "rsa:2048", "-nodes",
                     "-keyout", str(d / f"{name}.key"),
                     "-out", str(d / f"{name}.csr"),
                     "-subj", f"/CN=df-{name}")
            _openssl("x509", "-req", "-in", str(d / f"{name}.csr"),
                     "-CA", str(d / "ca.crt"), "-CAkey", str(d / "ca.key"),
                     "-CAcreateserial", "-days", "1",
                     "-out", str(d / f"{name}.crt"))
    except (FileNotFoundError, subprocess.CalledProcessError):
        pytest.skip("openssl CLI unavailable")
    return d


def test_tls_roundtrip(run_async, certs):
    async def run():
        server = Server("tls")

        async def echo(body, ctx):
            return {"echo": body}

        server.register_unary("T.Echo", echo)
        await server.serve(
            NetAddr.tcp("127.0.0.1", 0),
            ssl_context=security.server_ssl_context(
                str(certs / "server.crt"), str(certs / "server.key")))
        cli = Client(
            NetAddr.tcp("127.0.0.1", server.port()),
            ssl_context=security.client_ssl_context(
                ca_file=str(certs / "ca.crt")))
        try:
            assert (await cli.call("T.Echo", {"x": 1}))["echo"] == {"x": 1}
        finally:
            await cli.close()
            await server.close()

    run_async(run())


def test_mtls_rejects_certless_client(run_async, certs):
    async def run():
        server = Server("mtls")

        async def echo(body, ctx):
            return {"ok": True}

        server.register_unary("T.Echo", echo)
        await server.serve(
            NetAddr.tcp("127.0.0.1", 0),
            ssl_context=security.server_ssl_context(
                str(certs / "server.crt"), str(certs / "server.key"),
                ca_file=str(certs / "ca.crt"), require_client_cert=True))
        port = server.port()
        # Without a client cert: handshake fails.
        bad = Client(NetAddr.tcp("127.0.0.1", port),
                     ssl_context=security.client_ssl_context(
                         ca_file=str(certs / "ca.crt")))
        try:
            with pytest.raises(RpcError):
                await bad.call("T.Echo", {}, timeout=5.0)
        finally:
            await bad.close()
        # With the CA-signed client cert: accepted.
        good = Client(NetAddr.tcp("127.0.0.1", port),
                      ssl_context=security.client_ssl_context(
                          cert_file=str(certs / "client.crt"),
                          key_file=str(certs / "client.key"),
                          ca_file=str(certs / "ca.crt")))
        try:
            assert (await good.call("T.Echo", {}))["ok"]
        finally:
            await good.close()
            await server.close()

    run_async(run())
