"""Dataset plane: tar-shard indexing against pathological archives.

The indexer is a from-scratch streaming header walk, so Python's tarfile
serves as the independent oracle: member names, sizes and data offsets
must agree for every dialect tarfile can write (ustar, GNU long names,
pax), and failure modes (truncation, corruption) must surface as TYPED
errors — a silently partial index would drop training samples.
"""

from __future__ import annotations

import io
import json
import tarfile

import pytest

from dragonfly2_tpu.dataset import tar_index
from dragonfly2_tpu.dataset.tar_index import (
    ShardIndex,
    TarIndexer,
    TarIndexError,
    TruncatedShardError,
    group_samples,
    index_tar_bytes,
)


def make_tar(entries, fmt=tarfile.USTAR_FORMAT) -> bytes:
    """entries: (name, payload) for files, (name, None, linktype, target)
    for links."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w", format=fmt) as tar:
        for entry in entries:
            if len(entry) == 2:
                name, payload = entry
                info = tarfile.TarInfo(name=name)
                info.size = len(payload)
                tar.addfile(info, io.BytesIO(payload))
            else:
                name, _, linktype, target = entry
                info = tarfile.TarInfo(name=name)
                info.type = linktype
                info.linkname = target
                tar.addfile(info)
    return buf.getvalue()


def oracle(data: bytes) -> list[tuple[str, int, int]]:
    """tarfile's view: (name, data_offset, size) of regular members."""
    out = []
    with tarfile.open(fileobj=io.BytesIO(data)) as tar:
        for ti in tar:
            if ti.isreg():
                out.append((ti.name, ti.offset_data, ti.size))
    return out


def webdataset_entries(n_samples: int, payload=lambda i: b"x" * (100 + i)):
    entries = []
    for i in range(n_samples):
        entries.append((f"{i:06d}.jpg", payload(i)))
        entries.append((f"{i:06d}.cls", str(i % 10).encode()))
    return entries


@pytest.mark.parametrize("fmt", [tarfile.USTAR_FORMAT, tarfile.GNU_FORMAT,
                                 tarfile.PAX_FORMAT])
def test_index_matches_tarfile_oracle(fmt):
    data = make_tar(webdataset_entries(5), fmt=fmt)
    idx = index_tar_bytes(data, "train-0.tar")
    assert idx.size == len(data)
    got = [(m.name, m.data_offset, m.size) for m in idx.members]
    assert got == oracle(data)
    assert [s.key for s in idx.samples] == [f"{i:06d}" for i in range(5)]
    for i, s in enumerate(idx.samples):
        parts = dict(s.parts)
        assert set(parts) == {"jpg", "cls"}
        jpg = idx.members[parts["jpg"]]
        assert data[jpg.data_offset:jpg.data_offset + jpg.size] \
            == b"x" * (100 + i)


@pytest.mark.parametrize("fmt", [tarfile.GNU_FORMAT, tarfile.PAX_FORMAT])
def test_long_names(fmt):
    """>100-char member names ride GNU 'L' or pax 'x' extensions; the
    extension blocks must not shift data offsets."""
    deep = "a/" * 70
    entries = [(f"{deep}{i:04d}.bin", b"payload-%d" % i) for i in range(3)]
    data = make_tar(entries, fmt=fmt)
    idx = index_tar_bytes(data)
    assert [(m.name, m.data_offset, m.size) for m in idx.members] \
        == oracle(data)
    assert all(m.name.startswith(deep) for m in idx.members)


def test_pax_non_ascii_and_long_linkname():
    entries = [("émoji/" + "x" * 120 + ".jpg", b"d" * 7)]
    data = make_tar(entries, fmt=tarfile.PAX_FORMAT)
    idx = index_tar_bytes(data)
    assert [(m.name, m.data_offset, m.size) for m in idx.members] \
        == oracle(data)


def test_links_recorded_not_sampled():
    entries = [
        ("0001.jpg", b"a" * 64),
        ("0001.cls", b"3"),
        ("alias.jpg", None, tarfile.SYMTYPE, "0001.jpg"),
        ("hard.jpg", None, tarfile.LNKTYPE, "0001.jpg"),
        ("0002.jpg", b"b" * 64),
    ]
    data = make_tar(entries)
    idx = index_tar_bytes(data)
    assert [(m.name, m.data_offset, m.size) for m in idx.members] \
        == oracle(data)
    assert [(m.name, m.typeflag, m.linkname) for m in idx.links] == \
        [("alias.jpg", "2", "0001.jpg"), ("hard.jpg", "1", "0001.jpg")]
    assert [s.key for s in idx.samples] == ["0001", "0002"]


def test_non_512_aligned_final_block_tolerated():
    """EOF right after the last data byte (no final padding, no
    end-of-archive blocks) — seen in the wild; must index fully."""
    data = make_tar(webdataset_entries(3))
    last = oracle(data)[-1]
    cut = data[: last[1] + last[2]]
    assert len(cut) % 512 != 0
    idx = index_tar_bytes(cut)
    assert [(m.name, m.data_offset, m.size) for m in idx.members] \
        == oracle(data)
    assert len(idx.samples) == 3


def test_missing_end_blocks_tolerated():
    """EOF at a clean member boundary without the two zero blocks."""
    data = make_tar(webdataset_entries(2))
    last = oracle(data)[-1]
    end = last[1] + last[2]
    end += (-end) % 512   # keep the final padding, drop the zero blocks
    idx = index_tar_bytes(data[:end])
    assert len(idx.members) == 4


def test_truncated_mid_data_raises_typed():
    data = make_tar(webdataset_entries(3))
    last = oracle(data)[-1]
    cut = data[: last[1] + last[2] // 2]
    with pytest.raises(TruncatedShardError):
        index_tar_bytes(cut)


def test_truncated_mid_header_raises_typed():
    data = make_tar(webdataset_entries(2))
    with pytest.raises(TruncatedShardError):
        index_tar_bytes(data[:100])
    # ...and mid-extension: cut inside a GNU longname header's payload.
    long = make_tar([("n" * 150 + ".jpg", b"x")], fmt=tarfile.GNU_FORMAT)
    with pytest.raises(TruncatedShardError):
        index_tar_bytes(long[:512 + 64])


def test_corrupt_checksum_raises():
    data = bytearray(make_tar(webdataset_entries(1)))
    data[0] ^= 0xFF   # clobber the first header's name byte
    with pytest.raises(TarIndexError):
        index_tar_bytes(bytes(data))


def test_lone_zero_block_raises():
    data = make_tar(webdataset_entries(1))
    first_member_end = 512 + 512   # header + padded 100-byte payload
    bad = (data[:first_member_end] + b"\0" * 512
           + data[first_member_end:])
    with pytest.raises(TarIndexError):
        index_tar_bytes(bad)


@pytest.mark.parametrize("chunk", [1, 7, 511, 512, 513, 1 << 16])
def test_incremental_feed_any_chunking(chunk):
    """The streaming indexer must be split-point independent."""
    data = make_tar(webdataset_entries(4), fmt=tarfile.GNU_FORMAT)
    ix = TarIndexer()
    for i in range(0, len(data), chunk):
        ix.feed(data[i:i + chunk])
    idx = ix.finish("s")
    assert [(m.name, m.data_offset, m.size) for m in idx.members] \
        == oracle(data)
    assert idx.size == len(data)


def test_sample_grouping_rules():
    members = [
        tar_index.TarMember("a/b/0001.seg.png", 0, 512, 10),
        tar_index.TarMember("a/b/0001.jpg", 1024, 1536, 10),
        tar_index.TarMember("a/b/0002.jpg", 2048, 2560, 10),
        tar_index.TarMember("a/c/0001.jpg", 3072, 3584, 10),  # distinct dir
        tar_index.TarMember("a/b/.hidden", 4096, 4608, 10),   # no stem
        tar_index.TarMember("a/b/0001.jpg", 5120, 5632, 10),  # dup ext
    ]
    samples = group_samples(members)
    assert [s.key for s in samples] == ["a/b/0001", "a/b/0002", "a/c/0001"]
    first = dict(samples[0].parts)
    assert first == {"seg.png": 0, "jpg": 1}   # dup kept first


def test_index_json_roundtrip():
    data = make_tar(webdataset_entries(3) + [
        ("alias.jpg", None, tarfile.SYMTYPE, "000000.jpg")])
    idx = index_tar_bytes(data, "train-7.tar")
    raw = idx.to_json_bytes()
    back = ShardIndex.from_json_bytes(raw)
    assert back.shard == "train-7.tar"
    assert back.size == idx.size
    assert back.members == idx.members
    assert back.samples == idx.samples
    assert [(m.name, m.typeflag, m.linkname) for m in back.links] \
        == [(m.name, m.typeflag, m.linkname) for m in idx.links]


def test_index_json_rejects_garbage():
    with pytest.raises(TarIndexError):
        ShardIndex.from_json_bytes(b"not json")
    with pytest.raises(TarIndexError):
        ShardIndex.from_json_bytes(json.dumps(
            {"v": 99, "shard": "s", "size": 0, "members": [],
             "samples": []}).encode())
    # Sample referencing a member index out of range.
    with pytest.raises(TarIndexError):
        ShardIndex.from_json_bytes(json.dumps(
            {"v": 1, "shard": "s", "size": 0, "members": [],
             "samples": [["k", [["jpg", 0]]]]}).encode())


def test_empty_archive():
    data = make_tar([])
    idx = index_tar_bytes(data)
    assert idx.members == [] and idx.samples == []
    assert idx.size == len(data)
