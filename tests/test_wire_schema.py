"""Wire-contract enforcement at the drpc server boundary.

proto/wire.py plays the role of the reference's d7y.io/api/v2 protobuf
module: one typed schema per method, validated in rpc/server.py before
any handler runs. Malformed bodies must fail with Code.BadRequest naming
the field — not as deep KeyErrors — and unknown fields must pass
(forward compatibility).
"""

from __future__ import annotations

import pytest

from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.proto import wire
from dragonfly2_tpu.rpc import Client, Server


class TestSchemas:
    def test_missing_required_field(self):
        with pytest.raises(wire.SchemaError, match="task_id"):
            wire.validate_unary("Scheduler.StatTask", {})

    def test_type_mismatch(self):
        with pytest.raises(wire.SchemaError, match="task_id"):
            wire.validate_unary("Scheduler.StatTask", {"task_id": 7})

    def test_bool_does_not_satisfy_int(self):
        with pytest.raises(wire.SchemaError, match="priority"):
            wire.validate_stream_open("Scheduler.AnnouncePeer", {
                "host": {"id": "h"}, "peer_id": "p", "task_id": "t",
                "priority": True})

    def test_int_satisfies_float(self):
        wire.validate_unary("Manager.PollJob", {"queue": "q", "timeout": 5})

    def test_nested_message(self):
        with pytest.raises(wire.SchemaError, match="host.*port|port"):
            wire.validate_stream_open("Scheduler.AnnouncePeer", {
                "host": {"id": "h", "port": "not-a-port"},
                "peer_id": "p", "task_id": "t"})

    def test_unknown_fields_pass(self):
        wire.validate_unary("Scheduler.StatTask",
                            {"task_id": "t", "future_field": {"x": 1}})

    def test_unknown_method_passes(self):
        wire.validate_unary("Plugin.CustomMethod", {"anything": object()})

    def test_list_item_type(self):
        with pytest.raises(wire.SchemaError, match="blocklist"):
            wire.validate_stream_msg("Scheduler.AnnouncePeer", {
                "type": "reschedule", "blocklist": ["ok", 42]})

    def test_pieces_finished_batch(self):
        wire.validate_stream_msg("Scheduler.AnnouncePeer", {
            "type": "pieces_finished",
            "pieces": [{"piece_num": 0, "range_start": 0, "range_size": 4,
                        "digest": "d", "download_cost_ms": 1,
                        "dst_peer_id": "p"},
                       {"piece_num": 1}]})
        with pytest.raises(wire.SchemaError, match="pieces"):
            wire.validate_stream_msg("Scheduler.AnnouncePeer", {
                "type": "pieces_finished",
                "pieces": [{"piece_num": "not-an-int"}]})
        # Either wire form is schema-legal: the legacy dict list above,
        # or the negotiated packed batch (envelope types only — the
        # structural decode lives in proto/reportcodec). A bare message
        # carries neither and validates as an empty batch.
        wire.validate_stream_msg("Scheduler.AnnouncePeer", {
            "type": "pieces_finished",
            "packed": {"v": 1, "n": 1, "peers": ["p"],
                       "nums": b"\x00", "cols": b"\x00" * 36}})
        with pytest.raises(wire.SchemaError, match="packed"):
            wire.validate_stream_msg("Scheduler.AnnouncePeer", {
                "type": "pieces_finished",
                "packed": {"v": 1, "n": 1, "peers": [7],
                           "nums": b"", "cols": b""}})
        wire.validate_stream_msg("Scheduler.AnnouncePeer", {
            "type": "pieces_finished"})

    def test_every_registered_schema_accepts_empty_optional(self):
        # Optional-only messages validate {} (no accidental requireds).
        for method, msg in wire.UNARY.items():
            required = [n for n, f in msg.fields.items() if f.required]
            body = {}
            for n in required:
                f = msg.fields[n]
                body[n] = ({} if f.type is dict else
                           [] if f.type is list else
                           0 if f.type in (int, float) else "x")
            wire.validate_unary(method, body)


class TestServerBoundary:
    def test_unary_bad_body_rejected(self, run_async):
        async def body():
            server = Server("test")

            async def handler(b, ctx):  # must never run
                raise AssertionError("handler ran on invalid body")

            server.register_unary("Scheduler.StatTask", handler)
            await server.serve(NetAddr.tcp("127.0.0.1", 0))
            cli = Client(NetAddr.tcp("127.0.0.1", server.port()))
            try:
                with pytest.raises(DfError) as ei:
                    await cli.call("Scheduler.StatTask", {"task_id": 123})
                assert ei.value.code == Code.BadRequest
                assert "task_id" in str(ei.value)
            finally:
                await cli.close()
                await server.close()

        run_async(body())

    def test_stream_bad_open_rejected(self, run_async):
        async def body():
            server = Server("test")

            async def handler(stream, ctx):
                raise AssertionError("handler ran on invalid open")

            server.register_stream("Scheduler.AnnouncePeer", handler)
            await server.serve(NetAddr.tcp("127.0.0.1", 0))
            cli = Client(NetAddr.tcp("127.0.0.1", server.port()))
            try:
                stream = await cli.open_stream(
                    "Scheduler.AnnouncePeer", {"peer_id": "p"})  # no task_id
                with pytest.raises(DfError) as ei:
                    await stream.recv(timeout=10)
                assert ei.value.code == Code.BadRequest
            finally:
                await cli.close()
                await server.close()

        run_async(body())

    def test_stream_bad_msg_fails_stream(self, run_async):
        """A contract breach mid-stream fails the stream BOTH ways: the
        handler's recv raises BadRequest (a later benign close must not
        clobber it) and the client receives an ERR frame — a handler must
        never record success off a stream that dropped messages."""
        async def body():
            import asyncio

            server = Server("test")
            got: list = []
            handler_error: list = []

            async def handler(stream, ctx):
                try:
                    while True:
                        msg = await stream.recv()
                        if msg is None:
                            return
                        got.append(msg)
                except DfError as e:
                    handler_error.append(e)

            server.register_stream("Scheduler.AnnouncePeer", handler)
            await server.serve(NetAddr.tcp("127.0.0.1", 0))
            cli = Client(NetAddr.tcp("127.0.0.1", server.port()))
            try:
                stream = await cli.open_stream(
                    "Scheduler.AnnouncePeer",
                    {"host": {"id": "h"}, "peer_id": "p", "task_id": "t"})
                await stream.send({"type": "register"})
                # piece_finished without the required piece map.
                await stream.send({"type": "piece_finished"})
                # A later valid message + close must not mask the breach.
                await stream.send({"type": "download_finished"})
                with pytest.raises(DfError) as ei:
                    while True:
                        if await stream.recv(timeout=10) is None:
                            break
                assert ei.value.code == Code.BadRequest
                await asyncio.sleep(0.2)
                assert got == [{"type": "register"}]
                assert handler_error and handler_error[0].code == Code.BadRequest
            finally:
                await cli.close()
                await server.close()

        run_async(body())


class TestReviewRegressions:
    def test_bool_does_not_satisfy_float(self):
        with pytest.raises(wire.SchemaError, match="timeout"):
            wire.validate_unary("Manager.PollJob",
                                {"queue": "q", "timeout": True})

    def test_non_map_stream_msg_rejected(self):
        with pytest.raises(wire.SchemaError, match="must be a map"):
            wire.validate_stream_msg("Scheduler.AnnouncePeer", "x")

    def test_non_map_on_unschemad_method_passes(self):
        wire.validate_stream_msg("Plugin.CustomStream", "anything")
