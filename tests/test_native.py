"""Native C++ data-plane library: build, equivalence with pure Python, and
the fused storage paths that use it.

Model: the reference validates its hot piece path with in-package unit tests
(client/daemon/storage/*_test.go); here we additionally pin the native/Python
implementations to each other so the fallback can never drift.
"""

from __future__ import annotations

import os

import pytest

from dragonfly2_tpu.pkg import digest as pkgdigest
from dragonfly2_tpu.pkg.digest import _crc32c_py
from dragonfly2_tpu.storage.local_store import LocalTaskStore, TaskStoreMetadata

binding = pytest.importorskip("dragonfly2_tpu.native.binding")


def test_crc32c_matches_python_reference():
    for payload in (b"", b"a", b"123456789", os.urandom(5), os.urandom(8192)):
        assert binding.crc32c(payload) == _crc32c_py(payload)


def test_crc32c_known_vector():
    # RFC 3720 §B.4 test vector: crc32c("123456789") == 0xE3069283.
    assert binding.crc32c(b"123456789") == 0xE3069283


def test_crc32c_incremental():
    data = os.urandom(100_000)
    whole = binding.crc32c(data)
    part = binding.crc32c(data[40_000:], binding.crc32c(data[:40_000]))
    assert whole == part
    # and the public pkg/digest entry point routes to the same value
    assert pkgdigest.crc32c(data) == whole


def test_fused_write_and_read(tmp_path):
    fd = os.open(tmp_path / "f", os.O_RDWR | os.O_CREAT)
    try:
        data = os.urandom(1 << 20)
        crc = binding.write_piece_crc(fd, 4096, data)
        assert crc == binding.crc32c(data)
        got, crc2 = binding.read_piece_crc(fd, 4096, len(data))
        assert got == data and crc2 == crc
    finally:
        os.close(fd)


def test_hash_pieces_parallel(tmp_path):
    fd = os.open(tmp_path / "f", os.O_RDWR | os.O_CREAT)
    try:
        pieces = [os.urandom(64 * 1024) for _ in range(16)]
        offsets, sizes = [], []
        off = 0
        for p in pieces:
            os.pwrite(fd, p, off)
            offsets.append(off)
            sizes.append(len(p))
            off += len(p)
        crcs = binding.hash_pieces_crc(fd, offsets, sizes, threads=4)
        assert crcs == [binding.crc32c(p) for p in pieces]
    finally:
        os.close(fd)


def test_copy_range(tmp_path):
    src, dst = tmp_path / "src", tmp_path / "dst"
    data = os.urandom(3 * 1024 * 1024 + 17)
    src.write_bytes(data)
    in_fd = os.open(src, os.O_RDONLY)
    out_fd = os.open(dst, os.O_WRONLY | os.O_CREAT)
    try:
        binding.copy_range(in_fd, out_fd, len(data))
    finally:
        os.close(in_fd)
        os.close(out_fd)
    assert dst.read_bytes() == data


def _make_store(tmp_path, piece_size=4096):
    meta = TaskStoreMetadata(task_id="t1", piece_size=piece_size)
    return LocalTaskStore.create(str(tmp_path / "t1"), meta)


def test_store_fused_crc32c_write_path(tmp_path):
    store = _make_store(tmp_path)
    data = os.urandom(4096)
    d = pkgdigest.hash_bytes(pkgdigest.ALGORITHM_CRC32C, data)
    rec = store.write_piece(1, data, expected_digest=str(d))
    assert rec.digest == str(d)
    assert store.read_piece(1) == data
    assert store.reverify_pieces() == []


def test_store_fused_crc32c_rejects_corrupt(tmp_path):
    store = _make_store(tmp_path)
    data = os.urandom(4096)
    wrong = pkgdigest.Digest(pkgdigest.ALGORITHM_CRC32C, "deadbeef")
    with pytest.raises(Exception):
        store.write_piece(0, data, expected_digest=str(wrong))
    assert 0 not in store.metadata.pieces


def test_store_reverify_detects_bitrot(tmp_path):
    store = _make_store(tmp_path)
    blobs = [os.urandom(4096) for _ in range(4)]
    for i, b in enumerate(blobs):
        d = pkgdigest.hash_bytes(pkgdigest.ALGORITHM_CRC32C, b)
        store.write_piece(i, b, expected_digest=str(d))
    assert store.reverify_pieces(threads=2) == []
    # flip a byte inside piece 2 on disk
    path = os.path.join(store.dir, "data")
    with open(path, "r+b") as f:
        f.seek(2 * 4096 + 7)
        c = f.read(1)
        f.seek(2 * 4096 + 7)
        f.write(bytes([c[0] ^ 0xFF]))
    assert store.reverify_pieces(threads=2) == [2]


def test_store_recorded_piece_never_corrupted_by_bad_rewrite(tmp_path):
    """A re-download of an already-recorded piece with corrupt bytes must
    not overwrite the valid on-disk data (the fused write-then-verify path
    is only safe for unrecorded pieces)."""
    store = _make_store(tmp_path)
    good = os.urandom(4096)
    d = pkgdigest.hash_bytes(pkgdigest.ALGORITHM_CRC32C, good)
    store.write_piece(0, good, expected_digest=str(d))
    corrupt = os.urandom(4096)
    with pytest.raises(Exception):
        store.write_piece(0, corrupt, expected_digest=str(d))
    assert store.read_piece(0) == good
    assert store.reverify_pieces() == []


def test_store_reverify_survives_truncated_file(tmp_path):
    """A truncated data file must be reported as bad pieces, not crash the
    sweep with the native batch hasher's -EIO (ADVICE round 1)."""
    store = _make_store(tmp_path)
    blobs = [os.urandom(4096) for _ in range(4)]
    for i, b in enumerate(blobs):
        d = pkgdigest.hash_bytes(pkgdigest.ALGORITHM_CRC32C, b)
        store.write_piece(i, b, expected_digest=str(d))
    path = os.path.join(store.dir, "data")
    with open(path, "r+b") as f:
        f.truncate(2 * 4096 + 100)  # piece 2 short, piece 3 gone
    assert store.reverify_pieces(threads=2) == [2, 3]
