"""Runtime observatory (pkg/prof): sampler attribution, loop-lag probe,
GC observatory, the loop_lag SLO probe, /debug/prof* endpoints, the
thread-naming hygiene guard — and the acceptance e2e: seeded CPU burn +
forced GC churn + a wedged loop in a real daemon mid-broadcast must be
attributed BY NAME at /debug/prof, recorded in the lag histogram,
breached at /debug/slo, and stamped into the task's flight autopsy as
typed events.
"""

from __future__ import annotations

import ast
import asyncio
import gc
import glob
import gzip
import json
import math
import os
import threading
import time

import pytest

from dragonfly2_tpu.pkg import flight
from dragonfly2_tpu.pkg import prof as proflib
from dragonfly2_tpu.pkg.prof import (
    GCObservatory,
    LoopLagProbe,
    ProfConfig,
    RuntimeObservatory,
    StackSampler,
    proc_stats,
)

PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "dragonfly2_tpu")


# --------------------------------------------------------------------- #
# Stack sampler: attribution, bounds, folded rendering
# --------------------------------------------------------------------- #

class TestStackSampler:
    def test_attributes_samples_to_thread_names(self):
        """A named CPU-burn thread shows up under ITS name with its hot
        frame carrying the self-time."""
        # Self-exclusion is only observable when OURS is the sole
        # sampler: another process-wide observatory's thread shares the
        # name and would legitimately be sampled by this one.
        assert proflib.observatory() is None, \
            "another test leaked an installed observatory"
        smp = StackSampler(hz=200)
        stop = threading.Event()

        def burn():
            while not stop.is_set():
                math.sqrt(12345.6789)

        t = threading.Thread(target=burn, daemon=True, name="df-ut-burn")
        t.start()
        smp.start()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                rep = smp.report()
                if rep["threads"].get("df-ut-burn", {}).get("samples", 0) \
                        >= 5:
                    break
                time.sleep(0.02)
        finally:
            smp.stop()
            stop.set()
            t.join(timeout=5)
        rep = smp.report(topn=5)
        assert rep["samples"] > 0
        burn_t = rep["threads"]["df-ut-burn"]
        assert burn_t["samples"] >= 5
        frames = [f["frame"] for f in burn_t["top_self"]]
        assert any("burn" in f for f in frames), frames
        # Self-time fractions are normalized per thread.
        assert all(0 <= f["frac"] <= 1 for f in burn_t["top_self"])
        # The sampler never samples itself.
        assert "df-prof-sampler" not in rep["threads"]

    @staticmethod
    def _park_deep(depth: int):
        """A df- named thread parked ``depth`` frames deep on an Event —
        a stable stack the main thread can sample deterministically
        (``_sample_once`` skips the CALLING thread, so sampling from the
        test itself sees only other threads)."""
        ready, release = threading.Event(), threading.Event()

        def recurse(n):
            if n == 0:
                ready.set()
                release.wait(timeout=30)
                return
            recurse(n - 1)

        t = threading.Thread(target=recurse, args=(depth,), daemon=True,
                             name="df-ut-parked")
        t.start()
        assert ready.wait(timeout=10)
        return t, release

    def test_trie_node_cap_degrades_to_truncation_counter(self):
        """Past max_nodes the trie stops growing and counts truncations
        instead — the flight-ring discipline (bounded memory, visible
        degradation)."""
        smp = StackSampler(hz=1, max_nodes=4, max_depth=48)
        t, release = self._park_deep(30)
        try:
            with smp._lock:
                smp._sample_once()
        finally:
            release.set()
            t.join(timeout=10)
        assert smp.nodes <= 4
        assert smp.truncated >= 1
        rep = smp.report()
        assert rep["max_nodes"] == 4
        assert rep["truncated"] == smp.truncated

    def test_folded_output_is_collapse_format(self):
        smp = StackSampler(hz=1)
        t, release = self._park_deep(3)
        try:
            with smp._lock:
                smp._sample_once()
        finally:
            release.set()
            t.join(timeout=10)
        folded = smp.folded()
        assert folded
        for line in folded.strip().splitlines():
            stack, count = line.rsplit(" ", 1)
            assert int(count) > 0
            assert stack.split(";")[0]          # leading thread name
        assert any(line.startswith("df-ut-parked;")
                   for line in folded.splitlines())
        # Bounded rendering: max_lines caps the emission.
        assert len(smp.folded(max_lines=1).strip().splitlines()) <= 1

    def test_steady_state_sample_interns_repeated_stacks(self):
        """Two passes over the same parked stack: the second pass must
        intern the whole path (the parked thread adds zero new nodes)."""
        smp = StackSampler(hz=1)
        t, release = self._park_deep(5)
        try:
            with smp._lock:
                smp._sample_once()
            before = smp.nodes
            assert before > 0
            with smp._lock:
                smp._sample_once()
            # The parked thread's stack is frame-for-frame identical;
            # other live threads may have moved, so allow tiny growth.
            assert smp.nodes <= before + 4
        finally:
            release.set()
            t.join(timeout=10)


# --------------------------------------------------------------------- #
# Loop-lag probe: ring, histogram, wedged-seconds SLO feed
# --------------------------------------------------------------------- #

class TestLoopLagProbe:
    def _probe(self, **kw) -> LoopLagProbe:
        obs = RuntimeObservatory(ProfConfig(enabled=False))
        kw.setdefault("interval_s", 0.05)
        kw.setdefault("slow_s", 0.25)
        return LoopLagProbe(obs, "ut", **kw)

    def test_note_lag_feeds_ring_histogram_and_max(self):
        p = self._probe()
        for lag in (0.001, 0.02, 0.3):
            p.note_lag(lag)
        s = p.summary()
        assert s["ticks"] == 3
        assert s["max_lag_s"] == pytest.approx(0.3)
        assert s["slow_ticks"] == 1
        assert sum(s["histogram"]["counts"]) == 3
        assert len(s["histogram"]["counts"]) == \
            len(s["histogram"]["edges_s"]) + 1

    def test_wedged_seconds_counts_wall_time_not_ticks(self):
        """A single 1.5 s wedge among hundreds of healthy ticks must
        dominate the probe output — wedged TIME over observed TIME, so
        healthy ticks cannot dilute a stall (the reason this SLI is a
        probe, not a completion ratio)."""
        p = self._probe()
        p.started_mono = time.monotonic() - 5.0     # ran ~5 s already
        for _ in range(500):
            p.note_lag(0.001)
        p.note_lag(1.5)
        bad, total = p.wedged_seconds(window=3600.0, threshold=0.25)
        assert bad == pytest.approx(1.5, abs=0.01)
        assert total == pytest.approx(5.0, abs=0.5)
        # With a 0.99 objective the burn is bad/total/0.01 — a 1.5 s
        # wedge breaches any observation window under 25 s.
        assert bad / max(total, 1e-9) / 0.01 > 14.4

    def test_wedged_seconds_respects_window_cutoff(self):
        p = self._probe()
        now = time.monotonic()
        p.started_mono = now - 100.0
        p._ring[0] = (now - 50.0, 2.0)      # outside the 10 s window
        p._ring[1] = (now - 2.0, 1.0)       # inside
        p._n = 2
        bad, total = p.wedged_seconds(window=10.0, threshold=0.25, now=now)
        assert bad == pytest.approx(1.0)
        assert total == pytest.approx(10.0)

    def test_armed_probe_measures_a_real_wedge(self, run_async):
        async def body():
            obs = RuntimeObservatory(ProfConfig(
                enabled=False, lag_interval_s=0.02, lag_slow_s=0.15))
            p = obs.arm_loop("ut-wedge")
            try:
                await asyncio.sleep(0.08)
                time.sleep(0.3)             # wedge the loop
                await asyncio.sleep(0.08)   # let the heartbeat observe it
            finally:
                p.disarm()
            s = p.summary()
            assert s["max_lag_s"] >= 0.2, s
            assert s["slow_ticks"] >= 1, s

        run_async(body(), timeout=30)

    def test_slow_tick_stamps_running_flights(self):
        rec = flight.FlightRecorder(max_tasks=8)
        rec.task("t-run")
        rec.task("t-done")
        rec.finish_task("t-done", "done")
        obs = RuntimeObservatory(ProfConfig(enabled=False), recorder=rec)
        p = LoopLagProbe(obs, "ut", interval_s=0.05, slow_s=0.25)
        p.note_lag(0.8)
        running = rec.get("t-run")
        evs = [e for e in running.events() if e[1] == flight.EV_LOOP_LAG]
        assert len(evs) == 1
        assert evs[0][3] == pytest.approx(0.8)
        done = rec.get("t-done")
        assert not [e for e in done.events()
                    if e[1] == flight.EV_LOOP_LAG]


# --------------------------------------------------------------------- #
# GC observatory
# --------------------------------------------------------------------- #

class TestGCObservatory:
    def test_counts_collections_per_generation(self):
        obs = RuntimeObservatory(ProfConfig(enabled=False))
        g = obs.gc
        g.arm()
        try:
            gc.collect(0)
            gc.collect(2)
        finally:
            g.disarm()
        s = g.summary()
        assert s["collections"][0] >= 1
        assert s["collections"][2] >= 1
        assert s["max_pause_s"] >= 0
        assert len(s["tracked"]) == 3

    def test_slow_pause_stamps_running_flights(self):
        rec = flight.FlightRecorder(max_tasks=8)
        rec.task("t-gc")
        obs = RuntimeObservatory(ProfConfig(enabled=False, gc_slow_s=0.0),
                                 recorder=rec)
        g = obs.gc
        g.arm()
        try:
            gc.collect()        # any pause >= 0.0 counts as slow
        finally:
            g.disarm()
        assert g.slow_pauses >= 1
        evs = [e for e in rec.get("t-gc").events()
               if e[1] == flight.EV_GC_PAUSE]
        assert evs, "slow GC pause not stamped into the running flight"

    def test_disarm_removes_callback(self):
        g = GCObservatory(RuntimeObservatory(ProfConfig(enabled=False)))
        g.arm()
        assert g._cb in gc.callbacks
        g.disarm()
        assert g._cb not in gc.callbacks
        g.disarm()                          # idempotent


# --------------------------------------------------------------------- #
# proc gauges
# --------------------------------------------------------------------- #

def test_proc_stats_reads_linux_gauges():
    s = proc_stats()
    assert s["threads"] >= 1
    if os.path.exists("/proc/self/statm"):
        assert s["rss_bytes"] > 0
        assert s["open_fds"] > 0
        assert s["voluntary_ctx_switches"] > 0


# --------------------------------------------------------------------- #
# install()/release(): the refcounted process singleton
# --------------------------------------------------------------------- #

class TestInstallRelease:
    def test_refcounted_singleton(self):
        assert proflib.observatory() is None, \
            "another test leaked an installed observatory"
        a = proflib.install(ProfConfig(hz=50))
        b = proflib.install(ProfConfig(hz=7))   # second cfg ignored
        try:
            assert a is b
            assert proflib.observatory() is a
            assert a.cfg.hz == 50
            # One sampler thread, not two.
            names = [t.name for t in threading.enumerate()]
            assert names.count("df-prof-sampler") == 1
        finally:
            proflib.release(b)
            assert proflib.observatory() is a   # still one ref held
            proflib.release(a)
        assert proflib.observatory() is None
        names = [t.name for t in threading.enumerate()]
        assert "df-prof-sampler" not in names

    def test_release_of_private_observatory_stops_it(self):
        obs = RuntimeObservatory(ProfConfig())
        obs.start()
        proflib.release(obs)                    # not the singleton
        assert obs.sampler._thread is None


# --------------------------------------------------------------------- #
# loop_lag SLO: the probe kind end to end
# --------------------------------------------------------------------- #

class TestLoopLagSLO:
    def test_probe_kind_breaches_on_wedged_time(self):
        from dragonfly2_tpu.pkg import slo as slolib

        obs = RuntimeObservatory(ProfConfig(enabled=False))
        p = LoopLagProbe(obs, "ut", interval_s=0.05, slow_s=0.25)
        obs.probes["ut"] = p
        p.started_mono = time.monotonic() - 5.0
        p.note_lag(1.5)                     # 1.5 s wedge in ~5 s observed
        eng = slolib.SLOEngine(specs=slolib.RUNTIME_SLOS,
                               probes=obs.slo_probes())
        rep = eng.evaluate()
        ll = [s for s in rep["slos"] if s["name"] == "loop_lag"][0]
        assert ll["kind"] == "probe"
        assert ll["state"] == "breach", ll
        assert "loop_lag" in rep["breached"]
        fast = ll["windows"][0]
        assert fast["burn_rate"] > fast["burn_threshold"]

    def test_unfed_probe_reports_no_data(self):
        from dragonfly2_tpu.pkg import slo as slolib

        eng = slolib.SLOEngine(specs=slolib.RUNTIME_SLOS)
        rep = eng.evaluate()
        ll = [s for s in rep["slos"] if s["name"] == "loop_lag"][0]
        assert ll["state"] == "no_data"
        assert all(w["state"] == "no_data" for w in ll["windows"])

    def test_failing_probe_degrades_to_no_data(self):
        from dragonfly2_tpu.pkg import slo as slolib

        def boom(window, threshold):
            raise RuntimeError("probe exploded")

        eng = slolib.SLOEngine(specs=slolib.RUNTIME_SLOS,
                               probes={"loop_lag": boom})
        rep = eng.evaluate()
        ll = [s for s in rep["slos"] if s["name"] == "loop_lag"][0]
        assert ll["state"] == "no_data"

    def test_default_slos_include_loop_lag(self):
        from dragonfly2_tpu.pkg import slo as slolib

        names = [s.name for s in slolib.DEFAULT_SLOS]
        assert "loop_lag" in names
        assert all(s.kind == "probe" for s in slolib.RUNTIME_SLOS)


# --------------------------------------------------------------------- #
# /debug/prof* endpoints
# --------------------------------------------------------------------- #

class TestProfEndpoints:
    def test_endpoints_serve_armed_observatory(self, run_async):
        import aiohttp

        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        async def body():
            obs = RuntimeObservatory(ProfConfig(hz=100))
            obs.start()
            probe = obs.arm_loop("ut-endpoint")
            srv = MetricsServer(prof=obs)
            port = await srv.serve("127.0.0.1", 0)
            base = f"http://127.0.0.1:{port}"
            try:
                await asyncio.sleep(0.1)    # a few sampler passes
                async with aiohttp.ClientSession() as sess:
                    async with sess.get(base + "/debug/prof?topn=3") as r:
                        assert r.status == 200
                        rep = await r.json()
                    assert rep["samples"] >= 1
                    assert rep["hz"] == 100
                    for t in rep["threads"].values():
                        assert len(t["top_self"]) <= 3
                    async with sess.get(
                            base + "/debug/prof/runtime") as r:
                        assert r.status == 200
                        rt = await r.json()
                    assert rt["proc"]["threads"] >= 2
                    assert rt["loops"][0]["name"] == "ut-endpoint"
                    async with sess.get(
                            base + "/debug/prof/flame?format=folded") as r:
                        assert r.status == 200
                        assert "json" not in r.headers["Content-Type"]
                        text = await r.text()
                    assert text.strip(), "no folded stacks"
                    # Only the folded collapse format exists.
                    async with sess.get(
                            base + "/debug/prof/flame?format=svg") as r:
                        assert r.status == 400
                    # The runtime_* gauges refreshed on the scrape above.
                    async with sess.get(base + "/metrics") as r:
                        metrics_text = await r.text()
                    assert "dragonfly_tpu_runtime_rss_bytes" in metrics_text
                    assert ("dragonfly_tpu_runtime_profiler_samples_total"
                            in metrics_text)
            finally:
                probe.disarm()
                await srv.close()
                obs.stop()

        run_async(body(), timeout=60)

    def test_endpoints_404_without_observatory(self, run_async):
        import aiohttp

        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        async def body():
            srv = MetricsServer()
            port = await srv.serve("127.0.0.1", 0)
            try:
                async with aiohttp.ClientSession() as sess:
                    for path in ("/debug/prof", "/debug/prof/flame",
                                 "/debug/prof/runtime"):
                        async with sess.get(
                                f"http://127.0.0.1:{port}{path}") as r:
                            assert r.status == 404, path
            finally:
                await srv.close()

        run_async(body(), timeout=60)


# --------------------------------------------------------------------- #
# Flight integration: advisory line + post-mortem bundle snapshot
# --------------------------------------------------------------------- #

class TestFlightRuntimeIntegration:
    def _report_with_runtime(self):
        tf = flight.TaskFlight("rt-task")
        tf.record(flight.EV_REGISTER)
        tf.record(flight.EV_LOOP_LAG, -1, 0.7, "loop_lag")
        tf.record(flight.EV_LOOP_LAG, -1, 0.3, "loop_lag")
        tf.record(flight.EV_GC_PAUSE, -1, 0.12, "gc_pause")
        tf.finish("done", "")
        return flight.analyze(tf)

    def test_analyze_summarizes_runtime_events(self):
        rep = self._report_with_runtime()
        rt = rep["runtime"]
        assert rt["loop_lag"]["count"] == 2
        assert rt["loop_lag"]["max_s"] == pytest.approx(0.7)
        assert rt["loop_lag"]["total_s"] == pytest.approx(1.0)
        assert rt["gc_pause"]["count"] == 1

    def test_advisory_renders_in_waterfall(self):
        rep = self._report_with_runtime()
        advisory = flight.runtime_advisory(rep)
        assert "event loop wedged 2x" in advisory
        assert "gc paused 1x" in advisory
        assert "/debug/prof" in advisory
        text = flight.render_waterfall(rep)
        assert advisory in text

    def test_quiet_runtime_prints_no_advisory(self):
        tf = flight.TaskFlight("quiet")
        tf.record(flight.EV_REGISTER)
        tf.finish("done", "")
        rep = flight.analyze(tf)
        assert flight.runtime_advisory(rep) == ""
        assert "runtime interference" not in flight.render_waterfall(rep)

    def test_postmortem_bundle_embeds_runtime_snapshot(self, tmp_path):
        rec = flight.FlightRecorder(dump_dir=str(tmp_path), max_tasks=8)
        obs = RuntimeObservatory(ProfConfig(enabled=False), recorder=rec)
        rec.runtime = obs
        obs.probes["ut"] = p = LoopLagProbe(obs, "ut")
        rec.task("doomed")
        p.note_lag(0.9)                     # stamped while running
        rec.finish_task("doomed", "failed", "chaos")
        bundles = glob.glob(str(tmp_path / "flight-*.json.gz"))
        assert len(bundles) == 1
        with gzip.open(bundles[0], "rt") as f:
            bundle = json.load(f)
        rt = bundle["runtime"]
        assert "prof" in rt and "loops" in rt and "gc" in rt
        assert rt["loops"][0]["slow_ticks"] == 1
        assert rt["proc"]["threads"] >= 1
        assert bundle["report"]["runtime"]["loop_lag"]["count"] == 1


# --------------------------------------------------------------------- #
# Thread-naming hygiene: every long-lived thread carries a df- prefix
# --------------------------------------------------------------------- #

# Spawn sites allowed to skip the prefix (none today — additions need a
# reason the profiler can live with).
THREAD_NAME_EXEMPT: set = set()


def _literal_prefix(node) -> "str | None":
    """Best-effort leading text of a name expression: plain constants
    and f-strings with a literal head resolve; anything dynamic is
    None (flagged — an unnamed or unprefixed thread is unattributable
    in /debug/prof)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    return None


def test_every_long_lived_thread_has_df_prefix():
    """AST walk over the whole package: every ``threading.Thread(...)``
    must pass ``name="df-..."`` and every ``ThreadPoolExecutor(...)``
    must pass ``thread_name_prefix="df-..."``. Attribution in the
    sampling profiler is BY THREAD NAME — an anonymous Thread-7 burning
    a core is a mystery; ``df-ioring`` is a diagnosis."""
    violations = []
    for path in glob.glob(os.path.join(PKG_ROOT, "**", "*.py"),
                          recursive=True):
        rel = os.path.relpath(path, PKG_ROOT)
        tree = ast.parse(open(path).read(), filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            callee = func.attr if isinstance(func, ast.Attribute) \
                else getattr(func, "id", "")
            if callee == "Thread":
                kw = {k.arg: k.value for k in node.keywords}
                name = _literal_prefix(kw.get("name"))
                if name is None or not name.startswith("df-"):
                    violations.append(
                        (rel, node.lineno,
                         f"Thread name {name!r} lacks the df- prefix"))
            elif callee == "ThreadPoolExecutor":
                kw = {k.arg: k.value for k in node.keywords}
                prefix = _literal_prefix(kw.get("thread_name_prefix"))
                if prefix is None or not prefix.startswith("df-"):
                    violations.append(
                        (rel, node.lineno,
                         f"ThreadPoolExecutor prefix {prefix!r} lacks "
                         f"the df- prefix"))
    violations = [v for v in violations
                  if (v[0], v[1]) not in THREAD_NAME_EXEMPT]
    assert not violations, (
        "long-lived threads without a df- name prefix (profiler "
        f"attribution is by thread name): {violations}")


# --------------------------------------------------------------------- #
# Acceptance e2e: runtime interference in a real daemon mid-broadcast
# --------------------------------------------------------------------- #

class TestRuntimeObservatoryE2E:
    def test_interference_attributed_named_and_breached(self, run_async,
                                                        tmp_path):
        """The ISSUE's acceptance drill: during a REAL broadcast (two
        parent daemons serving a conductor download over loopback), a
        seeded CPU-burn thread, forced GC churn, and a wedged event loop
        must surface in every layer at once:

          * /debug/prof names the burn thread (by its df- name) with
            self-time samples;
          * the loop-lag histogram records the wedge and /debug/slo
            breaches ``loop_lag``;
          * the task's flight autopsy carries the typed slow-tick
            events and --explain's waterfall prints the advisory.
        """
        import random

        import aiohttp

        from dataclasses import replace as dc_replace

        from tests.test_flight import _start_parent
        from tests.test_chaos import FakeAnnounceStream, FakeSchedulerClient
        from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor
        from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager
        from dragonfly2_tpu.pkg import slo as slolib
        from dragonfly2_tpu.pkg.metrics_server import MetricsServer
        from dragonfly2_tpu.storage import StorageManager, StorageOption
        from dragonfly2_tpu.storage import TaskStoreMetadata

        piece_size = 8192
        n_pieces = 48
        content = bytes(random.Random(99).randbytes(n_pieces * piece_size))
        task_id = "prof-e2e-task"
        rec = flight.recorder()

        async def body():
            cfg = ProfConfig(hz=100, lag_interval_s=0.02, lag_slow_s=0.2,
                             gc_slow_s=0.0)
            # The install below must create the singleton (first cfg
            # wins): a leaked observatory from another test would run
            # this drill with the wrong thresholds.
            assert proflib.observatory() is None, \
                "another test leaked an installed observatory"
            obs = proflib.install(cfg, recorder=rec)
            rec.runtime = obs
            probe = obs.arm_loop("daemon")
            engine = slolib.SLOEngine(
                specs=tuple(dc_replace(s, threshold=cfg.lag_slow_s)
                            for s in slolib.RUNTIME_SLOS),
                probes=obs.slo_probes())

            burn_stop = threading.Event()

            def burn():
                while not burn_stop.is_set():
                    math.sqrt(98765.4321)

            burner = threading.Thread(target=burn, daemon=True,
                                      name="df-e2e-burn")

            parent_a = await _start_parent(tmp_path, "parent-a", task_id,
                                           content, piece_size)
            parent_b = await _start_parent(tmp_path, "parent-b", task_id,
                                           content, piece_size)
            child_storage = StorageManager(
                StorageOption(data_dir=str(tmp_path / "child-data")))
            store = child_storage.register_task(TaskStoreMetadata(
                task_id=task_id, peer_id="child-peer",
                url="http://origin/blob"))
            announce = FakeAnnounceStream([{
                "type": "normal_task",
                "task": {"content_length": len(content),
                         "piece_size": piece_size,
                         "total_piece_count": n_pieces},
                "parents": [parent_a.wire, parent_b.wire],
            }])
            conductor = PeerTaskConductor(
                task_id=task_id, peer_id="child-peer",
                url="http://origin/blob", store=store,
                scheduler_client=FakeSchedulerClient([announce]),
                piece_manager=PieceManager(),
                host_info={"id": "child-host"}, disable_back_source=True)
            try:
                burner.start()
                run = asyncio.ensure_future(conductor.run())
                # Mid-broadcast interference, injected while pieces are
                # in flight on THIS loop: GC churn, then a hard wedge.
                await asyncio.sleep(0.02)
                junk = []
                for _ in range(5):
                    cycle = [junk]
                    cycle.append(cycle)
                    junk.append(cycle)
                    gc.collect(0)
                time.sleep(0.45)            # wedge: blocks loop + pieces
                await asyncio.sleep(0.1)    # heartbeat observes the wedge
                await asyncio.wait_for(run, timeout=60)
                assert store.is_complete()
                rec.finish_task(task_id, "done")

                # A second wedge post-download pushes total wedged wall
                # time to ~1.5 s, so the loop_lag burn rate breaches the
                # slow window regardless of how long this box took to
                # finish the broadcast (burn = 100 * wedged/observed;
                # observed stays well under the 25 s break-even).
                time.sleep(1.0)
                await asyncio.sleep(0.1)    # heartbeat observes it

                # Give the 100 Hz sampler a beat to catch the burner.
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if obs.profile_report()["threads"].get(
                            "df-e2e-burn", {}).get("samples", 0) >= 3:
                        break
                    await asyncio.sleep(0.05)

                srv = MetricsServer(flight=rec, prof=obs, slo=engine)
                port = await srv.serve("127.0.0.1", 0)
                base = f"http://127.0.0.1:{port}"
                try:
                    async with aiohttp.ClientSession() as sess:
                        async with sess.get(base + "/debug/prof") as r:
                            assert r.status == 200
                            prof_rep = await r.json()
                        async with sess.get(base + "/debug/slo") as r:
                            assert r.status == 200
                            slo_rep = await r.json()
                        async with sess.get(
                                base + f"/debug/flight/{task_id}") as r:
                            assert r.status == 200
                            autopsy = await r.json()
                        async with sess.get(
                                base + f"/debug/flight/{task_id}"
                                "?format=text") as r:
                            text = await r.text()
                        async with sess.get(
                                base + "/debug/prof/runtime") as r:
                            runtime_rep = await r.json()
                finally:
                    await srv.close()

                # (1) The burn thread is attributed BY NAME.
                burn_prof = prof_rep["threads"].get("df-e2e-burn")
                assert burn_prof and burn_prof["samples"] >= 3, \
                    sorted(prof_rep["threads"])
                assert any("burn" in f["frame"]
                           for f in burn_prof["top_self"]), burn_prof

                # (2) The lag histogram recorded the wedge...
                loop_sum = [l for l in runtime_rep["loops"]
                            if l["name"] == "daemon"][0]
                assert loop_sum["max_lag_s"] >= 0.3, loop_sum
                assert loop_sum["slow_ticks"] >= 1, loop_sum
                # ...and the GC observatory saw the forced churn.
                assert sum(runtime_rep["gc"]["collections"]) >= 5

                # (3) The loop_lag SLO breached.
                ll = [s for s in slo_rep["slos"]
                      if s["name"] == "loop_lag"][0]
                assert ll["state"] == "breach", ll
                assert "loop_lag" in slo_rep["breached"]

                # (4) The task's autopsy carries the typed events and
                # --explain's waterfall prints the advisory.
                rt = autopsy["runtime"]
                assert rt.get("loop_lag", {}).get("count", 0) >= 1, rt
                assert rt["loop_lag"]["max_s"] >= 0.3, rt
                assert rt.get("gc_pause", {}).get("count", 0) >= 1, rt
                assert "runtime interference" in text
                assert "event loop wedged" in text
                assert "/debug/prof" in text
            finally:
                burn_stop.set()
                burner.join(timeout=5)
                probe.disarm()
                obs.probes.pop(probe.name, None)
                rec.runtime = None
                proflib.release(obs)
                await parent_a.close()
                await parent_b.close()
                child_storage.close()

        run_async(body(), timeout=120)
