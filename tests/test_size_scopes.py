"""Tiny/small size-scope shortcuts, end-to-end.

Reference: scheduler/service/service_v1.go:885-996 — once a task has
succeeded somewhere, ≤128 B content is inlined in the register response
(registerTinyTask; DirectPiece filled scheduler-side per :1196-1210) and
single-piece tasks get one direct SUCCEEDED parent (registerSmallTask), so
neither pays the announce-stream scheduling machinery.
"""

from __future__ import annotations

import asyncio
import hashlib

from aiohttp import web

from dragonfly2_tpu.pkg.piece import Range, SizeScope
from dragonfly2_tpu.scheduler.service import REGISTER_SCOPE_COUNT

from tests.test_p2p_e2e import (
    daemon_config,
    start_daemon,
    start_scheduler,
)
import tests.test_p2p_e2e as e2e


def _scope_count(scope: str) -> float:
    return REGISTER_SCOPE_COUNT.labels(scope)._value.get()


async def _start_origin(content: bytes):
    stats = {"gets": 0}

    async def blob(request: web.Request) -> web.Response:
        stats["gets"] += 1
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(content))
            return web.Response(
                status=206, body=content[r.start:r.start + r.length],
                headers={"Content-Range":
                         f"bytes {r.start}-{r.start + r.length - 1}/{len(content)}",
                         "Accept-Ranges": "bytes"})
        return web.Response(body=content, headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/blob", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1], stats


async def _dfget(daemon, url, out, digest):
    from dragonfly2_tpu.client import dfget as dfget_lib
    from dragonfly2_tpu.proto.common import UrlMeta

    return await dfget_lib.download(
        dfget_lib.DfgetConfig(
            url=url, output=out, daemon_sock=daemon.config.unix_sock,
            meta=UrlMeta(digest=digest), allow_source_fallback=False,
            timeout=60.0))


async def _wait(predicate, timeout=10.0):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(0.05)
    return False


def test_tiny_task_inlined_in_register(run_async, tmp_path):
    """100 B file: after the first download the scheduler caches the
    content (DirectPiece) off the finisher's upload server; the next
    registrant receives it inline — zero piece traffic, zero origin."""
    content = b"x" * 37 + b"tiny-checkpoint-metadata" + b"y" * 39  # 100 B
    digest = "sha256:" + hashlib.sha256(content).hexdigest()

    async def body():
        origin, oport, stats = await _start_origin(content)
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            a = await start_daemon(tmp_path, "a", sched.port())
            b = await start_daemon(tmp_path, "b", sched.port())
            daemons += [a, b]

            r1 = await _dfget(a, url, str(tmp_path / "o1"), digest)
            assert r1["state"] == "done"
            origin_after_first = stats["gets"]

            # The scheduler pulls the tiny content off peer A's upload
            # server (async after download_finished).
            task = next(iter(sched.service.tasks.all()))
            assert task.size_scope() == SizeScope.TINY
            assert await _wait(lambda: task.direct_piece == content), \
                "scheduler never cached the direct piece"

            before_tiny = _scope_count("tiny")
            r2 = await _dfget(b, url, str(tmp_path / "o2"), digest)
            assert r2["state"] == "done"
            assert (tmp_path / "o2").read_bytes() == content
            assert _scope_count("tiny") == before_tiny + 1
            assert stats["gets"] == origin_after_first  # no origin traffic
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=60)


def test_small_task_direct_parent(run_async, tmp_path):
    """1 MiB file (single piece, > tiny): a later registrant gets one
    SUCCEEDED parent + piece 0 info in the register response and completes
    with a single upload-server GET."""
    content = bytes(hashlib.sha256(b"seed").digest()) * (1 << 15)  # 1 MiB
    digest = "sha256:" + hashlib.sha256(content).hexdigest()

    async def body():
        origin, oport, stats = await _start_origin(content)
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            a = await start_daemon(tmp_path, "a", sched.port())
            b = await start_daemon(tmp_path, "b", sched.port())
            daemons += [a, b]

            r1 = await _dfget(a, url, str(tmp_path / "o1"), digest)
            assert r1["state"] == "done"
            origin_after_first = stats["gets"]

            task = next(iter(sched.service.tasks.all()))
            assert task.size_scope() == SizeScope.SMALL
            assert await _wait(lambda: 0 in task.pieces)

            before_small = _scope_count("small")
            r2 = await _dfget(b, url, str(tmp_path / "o2"), digest)
            assert r2["state"] == "done"
            assert (tmp_path / "o2").read_bytes() == content
            assert r2["from_p2p"]
            assert _scope_count("small") == before_small + 1
            assert stats["gets"] == origin_after_first
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=60)


def test_small_task_falls_back_when_parent_gone(run_async, tmp_path):
    """If the direct parent dies between scheduling and the piece GET, the
    registrant reschedules instead of failing the download."""
    content = bytes(hashlib.sha256(b"fall").digest()) * (1 << 15)  # 1 MiB
    digest = "sha256:" + hashlib.sha256(content).hexdigest()

    async def body():
        origin, oport, stats = await _start_origin(content)
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            a = await start_daemon(tmp_path, "a", sched.port())
            daemons.append(a)
            r1 = await _dfget(a, url, str(tmp_path / "o1"), digest)
            assert r1["state"] == "done"

            # Sabotage the recorded upload port so the direct pull fails;
            # the host row still looks alive to the scheduler.
            task = next(iter(sched.service.tasks.all()))
            assert task.size_scope() == SizeScope.SMALL
            host_a = next(iter(sched.service.hosts.all()))
            real_port = host_a.upload_port
            host_a.upload_port = 1  # closed port

            b = await start_daemon(tmp_path, "b", sched.port())
            daemons.append(b)
            before_small = _scope_count("small")

            async def heal():
                # Let the small attempt fail once, then restore the port so
                # the rescheduled normal path can use parent A again.
                await asyncio.sleep(0.5)
                host_a.upload_port = real_port

            healer = asyncio.ensure_future(heal())
            r2 = await _dfget(b, url, str(tmp_path / "o2"), digest)
            await healer
            assert r2["state"] == "done"
            assert (tmp_path / "o2").read_bytes() == content
            assert _scope_count("small") == before_small + 1  # tried small
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=60)
