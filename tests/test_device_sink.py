"""--device=tpu end-to-end: P2P download terminates in a device buffer.

VERDICT r2 item 1: dfget/daemon constructs an HBMSink, the conductor's
on_piece lands pieces as they verify, completion runs on-device
verification, and the result is consumable as a tensor or a mesh-sharded
array. Runs on the virtual 8-device CPU mesh (conftest) — the same code
path the real chip takes.

Terminal-store seam mirrored from the reference:
client/daemon/storage/storage_manager.go:54-131 (TaskStorageDriver), with
HBM as a second, per-task-selectable terminal.
"""

from __future__ import annotations

import hashlib
import random

import numpy as np

from dragonfly2_tpu.client import dfget as dfget_lib
from dragonfly2_tpu.client import device as device_lib
from dragonfly2_tpu.daemon.daemon import Daemon
from dragonfly2_tpu.proto.common import UrlMeta

from tests.test_p2p_e2e import daemon_config, start_origin, start_scheduler
import tests.test_p2p_e2e as e2e

CONTENT = e2e.CONTENT          # 10 MiB, 3 pieces at 4 MiB
SHA = e2e.SHA


async def _start_sink_daemon(tmp_path, name, scheduler_port, *, seed=False,
                             mesh_shape=None) -> Daemon:
    cfg = daemon_config(tmp_path, name, scheduler_port, seed=seed)
    cfg.tpu_sink.enabled = True
    if mesh_shape:
        cfg.tpu_sink.mesh_shape = mesh_shape
    d = Daemon(cfg)
    await d.start()
    return d

from dragonfly2_tpu.pkg.testing import start_range_origin as start_content_origin  # noqa: E501 - one shared ranged origin



def test_p2p_download_lands_in_device_buffer(run_async, tmp_path):
    """Seed + peer: the peer's P2P download lands in HBM piece-by-piece,
    verifies on device, and the bytes match the origin exactly."""

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            seed = await e2e.start_daemon(tmp_path, "seed", sched.port(),
                                          seed=True)
            peer = await _start_sink_daemon(tmp_path, "peer", sched.port())
            daemons += [seed, peer]

            result = await device_lib.download_to_device(
                peer, url, digest=SHA)
            assert result.from_p2p
            assert result.content_length == len(CONTENT)
            assert result.sink.verified

            landed = bytes(np.asarray(result.as_bytes_array()))
            assert landed == CONTENT

            # Streaming landing actually happened: pieces were landed by
            # the on_piece hook, not only the completion backfill.
            assert len(result.sink.landed) == 3

            # Origin served ~one copy (the seed's fetch); the device
            # landing added no origin traffic.
            assert stats["blob_bytes"] <= len(CONTENT) * 1.25
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_device_result_as_tensor_and_mesh(run_async, tmp_path):
    """Consumption paths: bitcast to a typed tensor and shard over the
    8-device CPU mesh with one contiguous shard per device."""

    async def body():
        import jax

        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "solo", sched.port())
            daemons.append(peer)

            result = await device_lib.download_to_device(
                peer, url, digest=SHA, claim=False)

            # Typed view: float32 words of the first piece region.
            n = (len(CONTENT) // 4) // 8 * 8
            t = result.as_tensor("float32", [n])
            want = np.frombuffer(CONTENT[: n * 4], dtype="<f4")
            got = np.asarray(t)
            assert got.shape == (n,)
            np.testing.assert_array_equal(
                got.view(np.uint32), want.view(np.uint32))

            # Mesh sharding: every device holds a contiguous uint32 shard.
            mesh = peer.task_manager.device_sinks.default_mesh()
            sharded = result.shard_to_mesh(mesh)
            assert len(sharded.devices()) == len(jax.devices())
            whole = np.asarray(sharded)
            padded = np.frombuffer(
                CONTENT + b"\x00" * ((-len(CONTENT)) % 4), dtype="<u4")
            np.testing.assert_array_equal(whole[: padded.size], padded)

            # claim=False leaves the sink resident for other consumers.
            assert peer.task_manager.device_sinks.get(result.task_id) is not None
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_dfget_device_flag_and_reuse(run_async, tmp_path):
    """The wire path: dfget with device="tpu" reports device_verified on
    both the fresh download and the warm (reuse) path, where the sink is
    backfilled from the completed store."""

    async def body():
        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "wire", sched.port())
            daemons.append(peer)

            r1 = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=str(tmp_path / "o1"),
                daemon_sock=peer.config.unix_sock,
                meta=UrlMeta(digest=SHA), device="tpu",
                allow_source_fallback=False, timeout=60.0))
            assert r1["state"] == "done"
            assert r1["device_verified"]
            assert (tmp_path / "o1").read_bytes() == CONTENT

            # Claim the sink (drops it from the manager), then re-download:
            # the reuse path must rebuild and re-verify from the store.
            assert peer.task_manager.device_sinks.take(r1["task_id"]) is not None
            r2 = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output="", daemon_sock=peer.config.unix_sock,
                meta=UrlMeta(digest=SHA), device="tpu",
                allow_source_fallback=False, timeout=60.0))
            assert r2["state"] == "done"
            assert r2["from_reuse"]
            assert r2["device_verified"]
            sink = peer.task_manager.device_sinks.get(r2["task_id"])
            assert sink is not None and sink.verified
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_corrupt_device_copy_fails_verification(run_async, tmp_path):
    """verify() must name a corrupted piece instead of handing back a bad
    buffer (checksum mismatch between host-recorded and on-device)."""
    import pytest

    from dragonfly2_tpu.daemon.peer.device_sink import (
        DeviceSinkError,
        TaskDeviceSink,
    )

    piece = 256 * 1024
    data0 = bytes(random.Random(1).randbytes(piece))
    data1 = bytes(random.Random(2).randbytes(piece))
    sink = TaskDeviceSink("t-corrupt", piece * 2, piece)
    sink.land(0, data0)
    # Record piece 1's checksum for DIFFERENT bytes than we land.
    sink.sink.host_checksums[1] = (0x12345678, 0x9ABCDEF0)
    sink.sink.landed.add(1)
    sink.sink._pending.append(
        (1, np.frombuffer(data1, dtype="<u4")))
    with pytest.raises(DeviceSinkError, match="piece 1"):
        sink.verify()


def test_sink_unavailable_degrades_to_disk(run_async, tmp_path):
    """Sink cap reached: the request still completes (disk verified) with
    device_verified=False rather than failing."""

    async def body():
        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            cfg = daemon_config(tmp_path, "capped", sched.port())
            cfg.tpu_sink.enabled = True
            cfg.tpu_sink.max_tasks = 0          # nothing fits
            peer = Daemon(cfg)
            await peer.start()
            daemons.append(peer)

            r = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=str(tmp_path / "o"),
                daemon_sock=peer.config.unix_sock,
                meta=UrlMeta(digest=SHA), device="tpu",
                allow_source_fallback=False, timeout=60.0))
            assert r["state"] == "done"
            assert not r["device_verified"]
            assert (tmp_path / "o").read_bytes() == CONTENT
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_device_corruption_fails_request_but_not_store(run_async, tmp_path):
    """Code-review regression: a corrupt DEVICE copy fails the requesting
    stream only — the digest-verified disk store must stay valid and
    reusable (no mark_invalid, dedup/future requests serve from disk)."""

    async def body():
        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "corrupt", sched.port())
            daemons.append(peer)
            mgr = peer.task_manager.device_sinks

            # Sabotage: make every finalize report corruption.
            async def bad_finalize(task_id, store):
                from dragonfly2_tpu.daemon.peer.device_sink import (
                    DeviceSinkError,
                )
                raise DeviceSinkError("piece 0 corrupt in HBM: injected")

            mgr.finalize = bad_finalize

            import pytest

            from dragonfly2_tpu.pkg.errors import DfError

            with pytest.raises(DfError, match="device sink verification"):
                await device_lib.download_to_device(peer, url, digest=SHA)

            # The disk store survived and serves the next (non-device)
            # request instantly from reuse.
            r = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=str(tmp_path / "o"),
                daemon_sock=peer.config.unix_sock,
                meta=UrlMeta(digest=SHA),
                allow_source_fallback=False, timeout=60.0))
            assert r["state"] == "done"
            assert r["from_reuse"]
            assert (tmp_path / "o").read_bytes() == CONTENT
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_stale_sink_rebuilt_when_store_content_changed(run_async, tmp_path):
    """Code-review regression: a resident sink whose recorded piece
    digests no longer match the store (content changed under the same
    task id) is rebuilt, never verified as a mixed buffer."""

    async def body():
        from dragonfly2_tpu.daemon.peer.device_sink import DeviceSinkManager
        from dragonfly2_tpu.storage.local_store import (
            LocalTaskStore,
            TaskStoreMetadata,
        )

        piece = 256 * 1024
        old = bytes(random.Random(3).randbytes(piece * 2))
        new = bytes(random.Random(4).randbytes(piece * 2))

        store = LocalTaskStore(
            str(tmp_path / "t1"),
            TaskStoreMetadata(task_id="t-stale", content_length=piece * 2,
                              piece_size=piece, total_piece_count=2))
        store.write_piece(0, new[:piece])
        store.write_piece(1, new[piece:])

        mgr = DeviceSinkManager()
        try:
            # A sink left over from the OLD content.
            sink = mgr._create("t-stale", piece * 2, piece)
            sink.land(0, old[:piece], "md5:stale-digest-0")
            sink.land(1, old[piece:], "md5:stale-digest-1")

            result = await mgr.finalize("t-stale", store)
            assert result is not None and result.verified
            landed = bytes(np.asarray(result.as_bytes_array()))
            assert landed == new          # rebuilt, not mixed
        finally:
            mgr.close()

    run_async(body(), timeout=60)


def test_preheat_trigger_lands_in_device_sink(run_async, tmp_path):
    """Pod-wide preheat-to-HBM (north star): a TriggerDownloadTask spec
    with device="tpu" — what the scheduler's preheat job sends when the
    manager job carries device — makes the triggered daemon back-to-source
    the content AND land it verified in its HBM sink. Daemons without a
    sink degrade to disk-only warm-up."""

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            sink_peer = await _start_sink_daemon(tmp_path, "sink-peer",
                                                 sched.port(), seed=True)
            plain_peer = await e2e.start_daemon(tmp_path, "plain-peer",
                                                sched.port())
            daemons += [sink_peer, plain_peer]
            spec = {"url": url, "device": "tpu"}
            # Trigger both directly (the scheduler preheat job fans this
            # exact spec to every target daemon).
            await sink_peer.task_manager.start_seed_task(dict(spec))
            await plain_peer.task_manager.start_seed_task(dict(spec))

            from dragonfly2_tpu.pkg import idgen
            task_id = idgen.task_id_v1(url)
            # Sink daemon: content is on disk AND verified in HBM.
            store = sink_peer.storage.find_completed_task(task_id)
            assert store is not None and store.metadata.done
            sink = sink_peer.task_manager.device_sinks._sinks.get(task_id)
            assert sink is not None and sink.verified
            landed = bytes(np.asarray(sink.as_bytes_array()))
            assert landed == CONTENT
            # Plain daemon: disk-only warm-up, no failure.
            store2 = plain_peer.storage.find_completed_task(task_id)
            assert store2 is not None and store2.metadata.done
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_device_trigger_dedups_onto_running_plain_seed(run_async, tmp_path):
    """A device=tpu trigger arriving while a PLAIN seed of the same task is
    in flight must wait for it and still land the content in HBM (device
    is not part of the task identity, so the dedup path must not swallow
    the device request)."""
    import asyncio

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            d = await _start_sink_daemon(tmp_path, "dedup-sink", sched.port(),
                                         seed=True)
            daemons.append(d)
            plain = asyncio.ensure_future(
                d.task_manager.start_seed_task({"url": url}))
            await asyncio.sleep(0)  # let the plain seed claim _running
            # Through the WIRE handler (not task_manager directly): the
            # RPC-level is_task_running shortcut must not swallow a
            # device trigger while the plain seed is in flight.
            resp = await d.rpc._trigger_download(
                {"url": url, "device": "tpu"}, None)
            assert resp["ok"]
            await plain
            # the spawned device trigger finalizes after the plain seed
            for _ in range(100):
                from dragonfly2_tpu.pkg import idgen as _idgen
                sk = d.task_manager.device_sinks._sinks.get(
                    _idgen.task_id_v1(url))
                if sk is not None and sk.verified:
                    break
                await asyncio.sleep(0.05)

            from dragonfly2_tpu.pkg import idgen
            task_id = idgen.task_id_v1(url)
            sink = d.task_manager.device_sinks._sinks.get(task_id)
            assert sink is not None and sink.verified
            assert bytes(np.asarray(sink.as_bytes_array())) == CONTENT
        finally:
            for dd in daemons:
                await dd.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_ranged_download_lands_slice_in_device_buffer(run_async, tmp_path):
    """A ranged device pull lands exactly the byte slice in HBM, and a
    second peer pulling the SAME range rides P2P off the first (the
    shard-group dedup download_sharded is built on)."""

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        start, length = 4096, 2 * 1024 * 1024 + 123
        rng = f"{start}-{start + length - 1}"
        daemons = []
        try:
            p1 = await _start_sink_daemon(tmp_path, "p1", sched.port())
            p2 = await _start_sink_daemon(tmp_path, "p2", sched.port())
            daemons += [p1, p2]

            r1 = await device_lib.download_to_device(
                p1, url, range_header=rng)
            assert r1.content_length == length
            assert r1.sink.verified
            assert (bytes(np.asarray(r1.as_bytes_array()))
                    == CONTENT[start:start + length])
            served_after_first = stats["blob_bytes"]

            r2 = await device_lib.download_to_device(
                p2, url, range_header=rng)
            assert (bytes(np.asarray(r2.as_bytes_array()))
                    == CONTENT[start:start + length])
            assert r2.from_p2p, "same-range peer must dedup via P2P"
            # The second pull must not have re-touched the origin.
            assert stats["blob_bytes"] == served_after_first
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_download_sharded_fetches_only_selected_tensors(run_async, tmp_path):
    """download_sharded: the host lands only its tensors' byte ranges
    (origin traffic ~= header + selected spans, far below the file size)
    and every returned tensor is bit-exact."""

    async def body():
        from aiohttp import web

        from tests.test_safetensors import make_safetensors

        rng_np = np.random.RandomState(11)
        tensors = {
            # Two big far-apart tensors + two small ones; select a subset
            # whose spans are well under half the file.
            "layer0.w": rng_np.randn(256, 256).astype(np.float32),   # 256 KiB
            "layer1.w": rng_np.randn(512, 512).astype(np.float32),   # 1 MiB
            "layer2.w": rng_np.randn(512, 512).astype(np.float32),   # 1 MiB
            "layer3.b": rng_np.randn(4096).astype(np.float32),       # 16 KiB
        }
        dtypes = {k: "F32" for k in tensors}
        ckpt = make_safetensors(tensors, dtypes)
        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "shards", sched.port())
            daemons.append(peer)

            got = await device_lib.download_sharded(
                peer, url, names=["layer0.w", "layer3.b"],
                coalesce_gap=4096)
            assert set(got) == {"layer0.w", "layer3.b"}
            np.testing.assert_array_equal(
                np.asarray(got["layer0.w"]), tensors["layer0.w"])
            np.testing.assert_array_equal(
                np.asarray(got["layer3.b"]), tensors["layer3.b"])
            # Origin economy: the 256K header-guess range + the two
            # selected spans (+ probe bytes), NOT the ~2 MiB of
            # unselected middle tensors.
            selected = (tensors["layer0.w"].nbytes
                        + tensors["layer3.b"].nbytes)
            assert stats["bytes"] < selected + (256 << 10) + 4096, (
                stats["bytes"], selected)

            # selector variant: every F32 tensor whose name ends in .b
            got_b = await device_lib.download_sharded(
                peer, url, selector=lambda n, m: n.endswith(".b"))
            assert set(got_b) == {"layer3.b"}
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=180)


def test_download_sharded_zero_element_and_bad_shardings(run_async, tmp_path):
    """Edge cases: a zero-element tensor synthesizes without a range pull,
    and a shardings dict referencing unselected tensors fails loudly even
    when the selector matches nothing."""

    async def body():
        import pytest
        from aiohttp import web

        from dragonfly2_tpu.ops.safetensors import SafetensorsError
        from tests.test_safetensors import make_safetensors

        tensors = {
            "empty.t": np.zeros((0, 8), dtype=np.float32),
            "real.t": np.arange(64, dtype=np.float32),
        }
        ckpt = make_safetensors(tensors, {k: "F32" for k in tensors})

        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "zedge", sched.port())
            daemons.append(peer)

            got = await device_lib.download_sharded(
                peer, url, names=["empty.t", "real.t"])
            assert np.asarray(got["empty.t"]).shape == (0, 8)
            np.testing.assert_array_equal(
                np.asarray(got["real.t"]), tensors["real.t"])

            with pytest.raises(SafetensorsError, match="shardings reference"):
                await device_lib.download_sharded(
                    peer, url, selector=lambda n, m: n.startswith("nope"),
                    shardings={"real.t": None})
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=120)


def test_dfget_ranged_device_over_the_wire(run_async, tmp_path):
    """Entry-point parity for sharded pulls: dfget with range= AND
    device="tpu" over the daemon's RPC socket reports device_verified,
    writes the slice-exact file, and leaves the ranged sink resident."""

    async def body():
        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        start, end = 8192, 8192 + 1024 * 1024 - 1
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "rwire", sched.port())
            daemons.append(peer)

            r = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=str(tmp_path / "slice"),
                daemon_sock=peer.config.unix_sock,
                meta=UrlMeta(range=f"bytes={start}-{end}"), device="tpu",
                allow_source_fallback=False, timeout=60.0))
            assert r["state"] == "done", r
            assert r["device_verified"], r
            assert ((tmp_path / "slice").read_bytes()
                    == CONTENT[start:end + 1])
            sink = peer.task_manager.device_sinks.get(r["task_id"])
            assert sink is not None and sink.verified
            assert (bytes(np.asarray(sink.as_bytes_array()))
                    == CONTENT[start:end + 1])
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_download_sharded_more_spans_than_sink_cap(run_async, tmp_path):
    """A sharded pull with more spans than the daemon's HBM-resident sink
    cap must succeed: in-flight spans are bounded below the cap instead
    of tripping the cap's disk-only degradation."""

    async def body():
        from aiohttp import web

        from tests.test_safetensors import make_safetensors

        rng_np = np.random.RandomState(21)
        # 8 tensors with forced gaps so no two spans coalesce; the
        # daemon's default sink cap is 4.
        tensors = {}
        for i in range(8):
            tensors[f"t{i}.w"] = rng_np.randn(4096).astype(np.float32)
            tensors[f"gap{i}"] = rng_np.randn(65536).astype(np.float32)
        ckpt = make_safetensors(tensors, {k: "F32" for k in tensors})

        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "cap8", sched.port())
            daemons.append(peer)
            assert peer.task_manager.device_sinks.max_tasks == 4

            wanted = [f"t{i}.w" for i in range(8)]
            got = await device_lib.download_sharded(
                peer, url, names=wanted, coalesce_gap=0)
            assert set(got) == set(wanted)
            for name in wanted:
                np.testing.assert_array_equal(
                    np.asarray(got[name]), tensors[name])
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=180)


def test_concurrent_sharded_pulls_share_admission(run_async, tmp_path):
    """Two concurrent download_sharded calls on ONE daemon must both
    succeed: admission is a per-daemon bound (DeviceSinkManager.admit),
    not a per-call semaphore that composes into cap overruns."""

    async def body():
        import asyncio

        from aiohttp import web

        from tests.test_safetensors import make_safetensors

        rng_np = np.random.RandomState(31)
        tensors = {}
        for i in range(4):
            tensors[f"a{i}"] = rng_np.randn(4096).astype(np.float32)
            tensors[f"pad{i}"] = rng_np.randn(65536).astype(np.float32)
        ckpt = make_safetensors(tensors, {k: "F32" for k in tensors})

        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "adm", sched.port())
            daemons.append(peer)
            g1, g2 = await asyncio.gather(
                device_lib.download_sharded(
                    peer, url, names=["a0", "a1"], coalesce_gap=0),
                device_lib.download_sharded(
                    peer, url, names=["a2", "a3"], coalesce_gap=0))
            for name, got in list(g1.items()) + list(g2.items()):
                np.testing.assert_array_equal(
                    np.asarray(got), tensors[name])
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=180)


def test_resident_sinks_evict_for_new_landing(run_async, tmp_path):
    """Verified, unclaimed resident sinks yield their HBM to NEW device
    landings (oldest first) instead of tripping the cap's disk-only
    degradation — residents are caches; the disk store is authoritative."""

    async def body():
        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "evict", sched.port())
            daemons.append(peer)
            peer.task_manager.device_sinks.max_tasks = 2
            # Disable the claim grace: this test's residents are seconds
            # old, and eviction under pressure is what's being proven.
            peer.task_manager.device_sinks.claim_grace_s = 0.0

            # Two unclaimed ranged pulls fill the cap with residents.
            r1 = await device_lib.download_to_device(
                peer, url, range_header="0-65535", claim=False)
            r2 = await device_lib.download_to_device(
                peer, url, range_header="65536-131071", claim=False)
            sinks = peer.task_manager.device_sinks
            assert sinks.get(r1.task_id) is not None
            assert sinks.get(r2.task_id) is not None

            # A third pull must succeed by evicting the OLDEST resident.
            r3 = await device_lib.download_to_device(
                peer, url, range_header="131072-196607", claim=False)
            assert (bytes(np.asarray(r3.as_bytes_array()))
                    == CONTENT[131072:196608])
            assert sinks.get(r1.task_id) is None, "oldest must be evicted"
            assert sinks.get(r2.task_id) is not None
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_download_global_sharded_arrays(run_async, tmp_path):
    """download_global: per-device leading-axis shards pull as their own
    byte ranges, non-leading shardings fall back to one whole-tensor
    pull, replication dedups to one range — and every returned value is
    a true global jax.Array matching the reference tensor."""

    async def body():
        import jax
        from aiohttp import web
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from tests.test_safetensors import make_safetensors

        rng_np = np.random.RandomState(41)
        tensors = {
            "rows.w": rng_np.randn(64, 32).astype(np.float32),
            "cols.w": rng_np.randn(16, 64).astype(np.float32),
            "rep.b": rng_np.randn(128).astype(np.float32),
        }
        ckpt = make_safetensors(tensors, {k: "F32" for k in tensors})
        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "glob", sched.port())
            daemons.append(peer)

            mesh = Mesh(np.array(jax.devices()), ("d",))
            shardings = {
                "rows.w": NamedSharding(mesh, P("d", None)),
                "cols.w": NamedSharding(mesh, P(None, "d")),
                "rep.b": NamedSharding(mesh, P()),
            }
            got = await device_lib.download_global(peer, url, shardings)
            assert set(got) == set(shardings)
            for name, arr in got.items():
                assert arr.shape == tensors[name].shape
                assert arr.sharding.is_equivalent_to(
                    shardings[name], len(arr.shape))
                np.testing.assert_array_equal(
                    np.asarray(arr), tensors[name])
            # rows.w landed as 8 per-device ranges that coalesce into one
            # task; cols.w + rep.b each pulled whole once. Total origin
            # data ~= the header-guess range (clamped to this tiny file)
            # + one copy of each tensor ≈ 2 file copies; big checkpoints
            # amortize the guess to ~1 copy + 256K.
            budget = 2 * len(ckpt) + 4096
            assert stats["bytes"] <= budget, (stats["bytes"], budget)
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=180)


def test_header_fetch_single_pull_and_overflow(run_async, tmp_path):
    """Header fetch is ONE guessed-range task in the common case; a
    header longer than the guess splices an exact second pull."""

    async def body():
        from tests.test_safetensors import make_safetensors

        tensors = {"a": np.arange(16, dtype=np.float32),
                   "b": np.arange(8, dtype=np.float32)}
        ckpt = make_safetensors(tensors, {k: "F32" for k in tensors})
        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "hdr", sched.port())
            daemons.append(peer)

            hd, ds, pfx = await device_lib.fetch_safetensors_header(peer, url)
            assert set(hd) == {"a", "b"}
            served_once = stats["bytes"]
            # Clamped guess = whole file (+ a range-support probe byte).
            assert served_once <= len(ckpt) + 16

            # Force the overflow path: a 16-byte guess cannot hold the
            # header, so an exact second pull splices the rest.
            hd2, ds2, pfx2 = await device_lib.fetch_safetensors_header(
                peer, url, prefix_guess=16)
            assert (hd2, ds2) == (hd, ds)
            # The guess surplus is the start of the tensor data.
            assert int(pfx.shape[0]) == len(ckpt)
            assert int(pfx2.shape[0]) == 16
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=120)


def test_download_global_2d_mesh(run_async, tmp_path):
    """download_global on a dp×tp mesh: tp-row shards replicate across
    dp (one range per distinct shard, not per device) and the assembled
    global Array is bit-exact."""

    async def body():
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from tests.test_safetensors import make_safetensors

        rng_np = np.random.RandomState(51)
        tensors = {"w": rng_np.randn(64, 16).astype(np.float32)}
        ckpt = make_safetensors(tensors, {"w": "F32"})
        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "mesh2d", sched.port())
            daemons.append(peer)

            mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))
            sharding = NamedSharding(mesh, P("tp", None))
            # Tiny prefix guess: forces the REAL ranged-pull/coalesce/
            # super_range path (a 256K guess would swallow this file and
            # leave download_global's pull machinery untested).
            got = await device_lib.download_global(peer, url, {"w": sharding},
                                                   prefix_guess=1024)
            arr = got["w"]
            assert arr.shape == (64, 16)
            np.testing.assert_array_equal(np.asarray(arr), tensors["w"])
            # 4 distinct tp row-blocks -> coalesced ranges cover the
            # tensor ~once despite 8 devices needing shards.
            assert stats["bytes"] <= len(ckpt) + (256 << 10), stats
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=120)


def test_warm_seed_serves_ranged_tasks_without_origin(run_async, tmp_path):
    """THE production composition: a plain whole-file preheat on the seed,
    then a peer's ranged device pull — the scheduler-triggered ranged
    seed imports the slice from its LOCAL warm store, so origin traffic
    does not grow at all after the preheat."""

    async def body():
        from tests.test_safetensors import make_safetensors

        rng_np = np.random.RandomState(61)
        tensors = {"stage0.w": rng_np.randn(512, 512).astype(np.float32),
                   "stage1.w": rng_np.randn(512, 512).astype(np.float32)}
        ckpt = make_safetensors(tensors, {k: "F32" for k in tensors})
        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            seed = await e2e.start_daemon(tmp_path, "wseed", sched.port(),
                                          seed=True)
            peer = await _start_sink_daemon(tmp_path, "wpeer", sched.port())
            daemons += [seed, peer]

            # Preheat: the seed holds the WHOLE checkpoint warm.
            await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=str(tmp_path / "warm.bin"),
                daemon_sock=seed.config.unix_sock,
                allow_source_fallback=False, timeout=60.0))
            after_preheat = stats["bytes"]
            assert after_preheat >= len(ckpt) - 8

            # Sharded pull from the peer: every ranged task the scheduler
            # seeds must import from the warm store, NOT origin.
            got = await device_lib.download_sharded(
                peer, url, names=["stage1.w"], prefix_guess=1024)
            np.testing.assert_array_equal(
                np.asarray(got["stage1.w"]), tensors["stage1.w"])
            assert stats["bytes"] == after_preheat, (
                "warm seed must serve ranged tasks without origin; "
                f"origin grew by {stats['bytes'] - after_preheat} bytes")
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin_cleanup(runner)

    async def origin_cleanup(runner):
        await runner.cleanup()

    run_async(body(), timeout=180)


def test_ranged_import_from_local_parent_schedulerless(run_async, tmp_path):
    """Schedulerless daemon with a warm whole-file task: a ranged request
    imports from the local parent even with back-source disabled (a
    local import is not a back-source)."""

    async def body():
        from dragonfly2_tpu.client import dfget as dfget_local
        from dragonfly2_tpu.daemon.daemon import Daemon

        content = bytes(random.Random(71).randbytes(3 * 1024 * 1024 + 77))
        runner, url, stats = await start_content_origin(content)
        cfg = daemon_config(tmp_path, "lonely", 0)
        cfg.scheduler.addrs = []        # schedulerless
        d = Daemon(cfg)
        await d.start()
        try:
            await dfget_local.download(dfget_local.DfgetConfig(
                url=url, output=str(tmp_path / "full.bin"),
                daemon_sock=d.config.unix_sock,
                allow_source_fallback=False, timeout=60.0))
            warm = stats["bytes"]

            r = await dfget_local.download(dfget_local.DfgetConfig(
                url=url, output=str(tmp_path / "slice.bin"),
                daemon_sock=d.config.unix_sock,
                meta=UrlMeta(range="bytes=4096-1052671"),
                disable_back_source=True,
                allow_source_fallback=False, timeout=60.0))
            assert r["state"] == "done"
            assert ((tmp_path / "slice.bin").read_bytes()
                    == content[4096:1052672])
            assert stats["bytes"] == warm, "local import must not hit origin"
        finally:
            await d.stop()
            await runner.cleanup()

    run_async(body(), timeout=120)


def test_warm_seed_serves_overshooting_ranges(run_async, tmp_path):
    """A checkpoint SMALLER than the header guess: the guess range
    overshoots EOF, origin clamps it — and so must the warm local
    parent, or the preheat buys nothing exactly for small files
    (the import gate must clamp like download_source does)."""

    async def body():
        from tests.test_safetensors import make_safetensors

        rng_np = np.random.RandomState(81)
        # ~40 KiB checkpoint — far under the 256 KiB header guess.
        tensors = {"small.w": rng_np.randn(100, 100).astype(np.float32)}
        ckpt = make_safetensors(tensors, {"small.w": "F32"})
        assert len(ckpt) < (256 << 10)
        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            seed = await e2e.start_daemon(tmp_path, "sseed", sched.port(),
                                          seed=True)
            peer = await _start_sink_daemon(tmp_path, "speer", sched.port())
            daemons += [seed, peer]

            await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=str(tmp_path / "w.bin"),
                daemon_sock=seed.config.unix_sock,
                allow_source_fallback=False, timeout=60.0))
            warm = stats["bytes"]

            got = await device_lib.download_sharded(
                peer, url, names=["small.w"])   # default 256K guess
            np.testing.assert_array_equal(
                np.asarray(got["small.w"]), tensors["small.w"])
            assert stats["bytes"] == warm, (
                f"overshooting guess re-touched origin by "
                f"{stats['bytes'] - warm} bytes")
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=120)


def test_download_global_composes_with_ici_all_gather(run_async, tmp_path):
    """The full TPU chain: fabric-loaded tp-sharded weight → ICI
    all_gather plan → every device holds the replicated tensor, bit
    exact. This is the load-then-redistribute step a training job runs
    right after download_global."""

    async def body():
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from dragonfly2_tpu.parallel.ici import all_gather_shards
        from tests.test_safetensors import make_safetensors

        rng_np = np.random.RandomState(91)
        tensors = {"w": rng_np.randn(64, 16).astype(np.float32)}
        ckpt = make_safetensors(tensors, {"w": "F32"})
        runner, url, stats = await start_content_origin(ckpt)
        sched = await start_scheduler()
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "ici", sched.port())
            daemons.append(peer)

            mesh = Mesh(np.array(jax.devices()), ("d",))
            got = await device_lib.download_global(
                peer, url, {"w": NamedSharding(mesh, P("d", None))},
                prefix_guess=1024)
            gathered = all_gather_shards(mesh, got["w"])
            assert gathered.shape == (64, 16)
            # Replicated: every device holds the whole tensor.
            assert len(gathered.sharding.device_set) == len(jax.devices())
            np.testing.assert_array_equal(np.asarray(gathered),
                                          tensors["w"])
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await runner.cleanup()

    run_async(body(), timeout=120)
