"""--device=tpu end-to-end: P2P download terminates in a device buffer.

VERDICT r2 item 1: dfget/daemon constructs an HBMSink, the conductor's
on_piece lands pieces as they verify, completion runs on-device
verification, and the result is consumable as a tensor or a mesh-sharded
array. Runs on the virtual 8-device CPU mesh (conftest) — the same code
path the real chip takes.

Terminal-store seam mirrored from the reference:
client/daemon/storage/storage_manager.go:54-131 (TaskStorageDriver), with
HBM as a second, per-task-selectable terminal.
"""

from __future__ import annotations

import hashlib
import random

import numpy as np

from dragonfly2_tpu.client import dfget as dfget_lib
from dragonfly2_tpu.client import device as device_lib
from dragonfly2_tpu.daemon.daemon import Daemon
from dragonfly2_tpu.proto.common import UrlMeta

from tests.test_p2p_e2e import daemon_config, start_origin, start_scheduler
import tests.test_p2p_e2e as e2e

CONTENT = e2e.CONTENT          # 10 MiB, 3 pieces at 4 MiB
SHA = e2e.SHA


async def _start_sink_daemon(tmp_path, name, scheduler_port, *, seed=False,
                             mesh_shape=None) -> Daemon:
    cfg = daemon_config(tmp_path, name, scheduler_port, seed=seed)
    cfg.tpu_sink.enabled = True
    if mesh_shape:
        cfg.tpu_sink.mesh_shape = mesh_shape
    d = Daemon(cfg)
    await d.start()
    return d


def test_p2p_download_lands_in_device_buffer(run_async, tmp_path):
    """Seed + peer: the peer's P2P download lands in HBM piece-by-piece,
    verifies on device, and the bytes match the origin exactly."""

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            seed = await e2e.start_daemon(tmp_path, "seed", sched.port(),
                                          seed=True)
            peer = await _start_sink_daemon(tmp_path, "peer", sched.port())
            daemons += [seed, peer]

            result = await device_lib.download_to_device(
                peer, url, digest=SHA)
            assert result.from_p2p
            assert result.content_length == len(CONTENT)
            assert result.sink.verified

            landed = bytes(np.asarray(result.as_bytes_array()))
            assert landed == CONTENT

            # Streaming landing actually happened: pieces were landed by
            # the on_piece hook, not only the completion backfill.
            assert len(result.sink.landed) == 3

            # Origin served ~one copy (the seed's fetch); the device
            # landing added no origin traffic.
            assert stats["blob_bytes"] <= len(CONTENT) * 1.25
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_device_result_as_tensor_and_mesh(run_async, tmp_path):
    """Consumption paths: bitcast to a typed tensor and shard over the
    8-device CPU mesh with one contiguous shard per device."""

    async def body():
        import jax

        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "solo", sched.port())
            daemons.append(peer)

            result = await device_lib.download_to_device(
                peer, url, digest=SHA, claim=False)

            # Typed view: float32 words of the first piece region.
            n = (len(CONTENT) // 4) // 8 * 8
            t = result.as_tensor("float32", [n])
            want = np.frombuffer(CONTENT[: n * 4], dtype="<f4")
            got = np.asarray(t)
            assert got.shape == (n,)
            np.testing.assert_array_equal(
                got.view(np.uint32), want.view(np.uint32))

            # Mesh sharding: every device holds a contiguous uint32 shard.
            mesh = peer.task_manager.device_sinks.default_mesh()
            sharded = result.shard_to_mesh(mesh)
            assert len(sharded.devices()) == len(jax.devices())
            whole = np.asarray(sharded)
            padded = np.frombuffer(
                CONTENT + b"\x00" * ((-len(CONTENT)) % 4), dtype="<u4")
            np.testing.assert_array_equal(whole[: padded.size], padded)

            # claim=False leaves the sink resident for other consumers.
            assert peer.task_manager.device_sinks.get(result.task_id) is not None
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_dfget_device_flag_and_reuse(run_async, tmp_path):
    """The wire path: dfget with device="tpu" reports device_verified on
    both the fresh download and the warm (reuse) path, where the sink is
    backfilled from the completed store."""

    async def body():
        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "wire", sched.port())
            daemons.append(peer)

            r1 = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=str(tmp_path / "o1"),
                daemon_sock=peer.config.unix_sock,
                meta=UrlMeta(digest=SHA), device="tpu",
                allow_source_fallback=False, timeout=60.0))
            assert r1["state"] == "done"
            assert r1["device_verified"]
            assert (tmp_path / "o1").read_bytes() == CONTENT

            # Claim the sink (drops it from the manager), then re-download:
            # the reuse path must rebuild and re-verify from the store.
            assert peer.task_manager.device_sinks.take(r1["task_id"]) is not None
            r2 = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output="", daemon_sock=peer.config.unix_sock,
                meta=UrlMeta(digest=SHA), device="tpu",
                allow_source_fallback=False, timeout=60.0))
            assert r2["state"] == "done"
            assert r2["from_reuse"]
            assert r2["device_verified"]
            sink = peer.task_manager.device_sinks.get(r2["task_id"])
            assert sink is not None and sink.verified
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_corrupt_device_copy_fails_verification(run_async, tmp_path):
    """verify() must name a corrupted piece instead of handing back a bad
    buffer (checksum mismatch between host-recorded and on-device)."""
    import pytest

    from dragonfly2_tpu.daemon.peer.device_sink import (
        DeviceSinkError,
        TaskDeviceSink,
    )

    piece = 256 * 1024
    data0 = bytes(random.Random(1).randbytes(piece))
    data1 = bytes(random.Random(2).randbytes(piece))
    sink = TaskDeviceSink("t-corrupt", piece * 2, piece)
    sink.land(0, data0)
    # Record piece 1's checksum for DIFFERENT bytes than we land.
    sink.sink.host_checksums[1] = (0x12345678, 0x9ABCDEF0)
    sink.sink.landed.add(1)
    sink.sink._pending.append(
        (1, np.frombuffer(data1, dtype="<u4")))
    with pytest.raises(DeviceSinkError, match="piece 1"):
        sink.verify()


def test_sink_unavailable_degrades_to_disk(run_async, tmp_path):
    """Sink cap reached: the request still completes (disk verified) with
    device_verified=False rather than failing."""

    async def body():
        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            cfg = daemon_config(tmp_path, "capped", sched.port())
            cfg.tpu_sink.enabled = True
            cfg.tpu_sink.max_tasks = 0          # nothing fits
            peer = Daemon(cfg)
            await peer.start()
            daemons.append(peer)

            r = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=str(tmp_path / "o"),
                daemon_sock=peer.config.unix_sock,
                meta=UrlMeta(digest=SHA), device="tpu",
                allow_source_fallback=False, timeout=60.0))
            assert r["state"] == "done"
            assert not r["device_verified"]
            assert (tmp_path / "o").read_bytes() == CONTENT
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_device_corruption_fails_request_but_not_store(run_async, tmp_path):
    """Code-review regression: a corrupt DEVICE copy fails the requesting
    stream only — the digest-verified disk store must stay valid and
    reusable (no mark_invalid, dedup/future requests serve from disk)."""

    async def body():
        origin, oport, _ = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            peer = await _start_sink_daemon(tmp_path, "corrupt", sched.port())
            daemons.append(peer)
            mgr = peer.task_manager.device_sinks

            # Sabotage: make every finalize report corruption.
            async def bad_finalize(task_id, store):
                from dragonfly2_tpu.daemon.peer.device_sink import (
                    DeviceSinkError,
                )
                raise DeviceSinkError("piece 0 corrupt in HBM: injected")

            mgr.finalize = bad_finalize

            import pytest

            from dragonfly2_tpu.pkg.errors import DfError

            with pytest.raises(DfError, match="device sink verification"):
                await device_lib.download_to_device(peer, url, digest=SHA)

            # The disk store survived and serves the next (non-device)
            # request instantly from reuse.
            r = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=str(tmp_path / "o"),
                daemon_sock=peer.config.unix_sock,
                meta=UrlMeta(digest=SHA),
                allow_source_fallback=False, timeout=60.0))
            assert r["state"] == "done"
            assert r["from_reuse"]
            assert (tmp_path / "o").read_bytes() == CONTENT
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_stale_sink_rebuilt_when_store_content_changed(run_async, tmp_path):
    """Code-review regression: a resident sink whose recorded piece
    digests no longer match the store (content changed under the same
    task id) is rebuilt, never verified as a mixed buffer."""

    async def body():
        from dragonfly2_tpu.daemon.peer.device_sink import DeviceSinkManager
        from dragonfly2_tpu.storage.local_store import (
            LocalTaskStore,
            TaskStoreMetadata,
        )

        piece = 256 * 1024
        old = bytes(random.Random(3).randbytes(piece * 2))
        new = bytes(random.Random(4).randbytes(piece * 2))

        store = LocalTaskStore(
            str(tmp_path / "t1"),
            TaskStoreMetadata(task_id="t-stale", content_length=piece * 2,
                              piece_size=piece, total_piece_count=2))
        store.write_piece(0, new[:piece])
        store.write_piece(1, new[piece:])

        mgr = DeviceSinkManager()
        try:
            # A sink left over from the OLD content.
            sink = mgr._create("t-stale", piece * 2, piece)
            sink.land(0, old[:piece], "md5:stale-digest-0")
            sink.land(1, old[piece:], "md5:stale-digest-1")

            result = await mgr.finalize("t-stale", store)
            assert result is not None and result.verified
            landed = bytes(np.asarray(result.as_bytes_array()))
            assert landed == new          # rebuilt, not mixed
        finally:
            mgr.close()

    run_async(body(), timeout=60)


def test_preheat_trigger_lands_in_device_sink(run_async, tmp_path):
    """Pod-wide preheat-to-HBM (north star): a TriggerDownloadTask spec
    with device="tpu" — what the scheduler's preheat job sends when the
    manager job carries device — makes the triggered daemon back-to-source
    the content AND land it verified in its HBM sink. Daemons without a
    sink degrade to disk-only warm-up."""

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            sink_peer = await _start_sink_daemon(tmp_path, "sink-peer",
                                                 sched.port(), seed=True)
            plain_peer = await e2e.start_daemon(tmp_path, "plain-peer",
                                                sched.port())
            daemons += [sink_peer, plain_peer]
            spec = {"url": url, "device": "tpu"}
            # Trigger both directly (the scheduler preheat job fans this
            # exact spec to every target daemon).
            await sink_peer.task_manager.start_seed_task(dict(spec))
            await plain_peer.task_manager.start_seed_task(dict(spec))

            from dragonfly2_tpu.pkg import idgen
            task_id = idgen.task_id_v1(url)
            # Sink daemon: content is on disk AND verified in HBM.
            store = sink_peer.storage.find_completed_task(task_id)
            assert store is not None and store.metadata.done
            sink = sink_peer.task_manager.device_sinks._sinks.get(task_id)
            assert sink is not None and sink.verified
            landed = bytes(np.asarray(sink.as_bytes_array()))
            assert landed == CONTENT
            # Plain daemon: disk-only warm-up, no failure.
            store2 = plain_peer.storage.find_completed_task(task_id)
            assert store2 is not None and store2.metadata.done
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_device_trigger_dedups_onto_running_plain_seed(run_async, tmp_path):
    """A device=tpu trigger arriving while a PLAIN seed of the same task is
    in flight must wait for it and still land the content in HBM (device
    is not part of the task identity, so the dedup path must not swallow
    the device request)."""
    import asyncio

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            d = await _start_sink_daemon(tmp_path, "dedup-sink", sched.port(),
                                         seed=True)
            daemons.append(d)
            plain = asyncio.ensure_future(
                d.task_manager.start_seed_task({"url": url}))
            await asyncio.sleep(0)  # let the plain seed claim _running
            # Through the WIRE handler (not task_manager directly): the
            # RPC-level is_task_running shortcut must not swallow a
            # device trigger while the plain seed is in flight.
            resp = await d.rpc._trigger_download(
                {"url": url, "device": "tpu"}, None)
            assert resp["ok"]
            await plain
            # the spawned device trigger finalizes after the plain seed
            for _ in range(100):
                from dragonfly2_tpu.pkg import idgen as _idgen
                sk = d.task_manager.device_sinks._sinks.get(
                    _idgen.task_id_v1(url))
                if sk is not None and sk.verified:
                    break
                await asyncio.sleep(0.05)

            from dragonfly2_tpu.pkg import idgen
            task_id = idgen.task_id_v1(url)
            sink = d.task_manager.device_sinks._sinks.get(task_id)
            assert sink is not None and sink.verified
            assert bytes(np.asarray(sink.as_bytes_array())) == CONTENT
        finally:
            for dd in daemons:
                await dd.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)
