"""BASELINE.json structural validation (fast, tier-1).

Benches publish directly into BASELINE.json["published"]; nothing else
ever re-reads it programmatically, so a half-written entry (NaN from a
zero-division, a missing config after a refactor, a truncated write)
would rot silently. This pins the contract: required configs present,
every numeric leaf finite, and the striped pair keeps its paired shape.
"""

from __future__ import annotations

import json
import math
import os

import pytest

BASELINE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BASELINE.json")

# Entries the README / ROADMAP cite; removing one is a deliberate act
# that should have to touch this list.
REQUIRED_CONFIGS = (
    "config1_single",
    "config2_fanout",
    "config5_pod_sim",
    "config5_pod_sim_churn_4k",
    "config2_fanout_striped",
    "config6_stripe_sim",
    "config7_chaos",
    "config8_flight",
    "config9_fleet",
    "config10_podlens",
    "config11_delta",
    "config12_prof",
    "config13_qos",
    "config14_wire",
    "config5_pod_sim_churn_16k",
    "config15_cluster",
    "ingest_micro",
)


def _walk_numbers(node, path=""):
    if isinstance(node, dict):
        for k, v in node.items():
            yield from _walk_numbers(v, f"{path}.{k}")
    elif isinstance(node, (list, tuple)):
        for i, v in enumerate(node):
            yield from _walk_numbers(v, f"{path}[{i}]")
    elif isinstance(node, bool):
        return
    elif isinstance(node, (int, float)):
        yield path, node


def _load():
    with open(BASELINE) as f:
        return json.load(f)


def test_baseline_top_level_shape():
    doc = _load()
    assert isinstance(doc.get("metric"), str) and doc["metric"]
    assert isinstance(doc.get("configs"), list) and doc["configs"]
    assert isinstance(doc.get("published"), dict) and doc["published"]


def test_required_configs_present():
    published = _load()["published"]
    missing = [c for c in REQUIRED_CONFIGS if c not in published]
    assert not missing, f"BASELINE.json lost published configs: {missing}"


def test_all_numeric_fields_finite():
    bad = [(p, v) for p, v in _walk_numbers(_load())
           if not math.isfinite(v)]
    assert not bad, f"non-finite numbers in BASELINE.json: {bad[:10]}"


def test_striped_entries_paired_shape():
    """The striped publications are PAIRED runs: both modes present, from
    the same topology, with the headline ratios derived from them."""
    published = _load()["published"]
    for key in ("config2_fanout_striped", "config6_stripe_sim"):
        entry = published[key]
        assert "striped" in entry and "unstriped" in entry, key
        assert entry["speedup"] > 0, key
        s, u = entry["striped"], entry["unstriped"]
        for r in (s, u):
            assert r["aggregate_gbps"] > 0, key
            assert r["p50_ttfp_s"] >= 0, key
            assert "per_host_dcn_mb" in r, key
        # The point of the feature: striping must not DCN-pull more.
        assert s["max_host_dcn_mb"] <= u["max_host_dcn_mb"], key


def test_chaos_entry_paired_shape():
    """config7_chaos is a PAIRED degradation run: clean + degraded walls
    from the same pod, degraded completes byte-identical, the schedule
    actually injected (a zero-fault 'degraded' run measures nothing),
    and the ratio derives from the pair."""
    entry = _load()["published"]["config7_chaos"]
    assert entry["byte_identical"] is True
    clean, degraded = entry["clean"], entry["degraded"]
    for run in (clean, degraded):
        assert run["wall_s"] > 0 and run["ok"] is True
        assert run["byte_identical"] is True
    assert degraded["faults"], "degraded run injected no faults"
    assert 0 < entry["dead_parent_fraction"] < 1
    assert entry["ratio"] == pytest.approx(
        degraded["wall_s"] / clean["wall_s"], rel=1e-2)


def test_flight_entry_paired_shape():
    """config8_flight is a PAIRED overhead run: recorder-on and
    recorder-off ingest from the same geometry, and the recorded overhead
    stays inside the always-on budget (<3%)."""
    entry = _load()["published"]["config8_flight"]
    on, off = entry["recorder_on"], entry["recorder_off"]
    for run in (on, off):
        assert run["mb_s"] > 0
        assert run["pieces"] > 0 and run["piece_kb"] > 0
    assert on["pieces"] == off["pieces"]
    assert on["piece_kb"] == off["piece_kb"]
    assert entry["overhead_frac"] < 0.03, entry["overhead_frac"]
    assert entry["overhead_frac"] == pytest.approx(
        1.0 - on["mb_s"] / off["mb_s"], abs=1e-3)


def test_fleet_entry_paired_shape():
    """config9_fleet is a PAIRED overhead run: the fleet observatory on
    vs off over the same DES churn sim geometry, CPU-time medians, with
    the acceptance budget (observatory overhead <= 3% in the DES sim)
    and the resident-bytes bound flat in host count."""
    entry = _load()["published"]["config9_fleet"]
    churn = entry["churn_sim"]
    on, off = churn["on"], churn["off"]
    for run in (on, off):
        assert run["cpu_s"] > 0 and run["wall_s"] > 0
    assert churn["hosts"] >= 1024
    # The estimator is the median of adjacent paired on/off ratios
    # (order-alternating rounds — see fleet_bench.run_churn_paired);
    # recompute it from the published per-pair ratios.
    ratios = sorted(churn["pair_ratios"])
    assert len(ratios) == churn["rounds"] and len(ratios) % 2 == 0
    median = (ratios[len(ratios) // 2 - 1] + ratios[len(ratios) // 2]) / 2
    assert churn["cpu_overhead_frac"] == pytest.approx(
        median - 1.0, abs=1e-3)
    assert churn["cpu_overhead_frac"] <= 0.03, churn["cpu_overhead_frac"]
    ingest = entry["ingest"]
    assert ingest["events"] > 0
    assert ingest["on_ns_per_event"] > 0 and ingest["off_ns_per_event"] > 0
    resident = entry["resident"]
    assert resident["hosts_large"] == 4 * resident["hosts_small"]
    assert resident["bytes_small"] > 0 and resident["bytes_large"] > 0
    # The bound: 4x the hosts must not mean 4x the memory — preallocated
    # rings + LRU-capped scorecards keep it flat.
    assert resident["ratio"] <= 1.5, resident


def test_podlens_entry_paired_shape():
    """config10_podlens is a PAIRED overhead run: the 1024-host DES
    churn sim with flight digests shipped in BOTH modes and the
    scheduler-side pod lens + SLO engine toggled; overhead = median of
    adjacent order-alternating pair ratios (the config9 estimator),
    within the <=3% budget. The digest round pins the per-task byte
    bound: every shape under the hard cap."""
    entry = _load()["published"]["config10_podlens"]
    churn = entry["churn_sim"]
    on, off = churn["on"], churn["off"]
    for run in (on, off):
        assert run["cpu_s"] > 0 and run["wall_s"] > 0
    assert churn["hosts"] >= 1024
    ratios = sorted(churn["pair_ratios"])
    assert len(ratios) == churn["rounds"] and len(ratios) % 2 == 0
    median = (ratios[len(ratios) // 2 - 1] + ratios[len(ratios) // 2]) / 2
    assert churn["cpu_overhead_frac"] == pytest.approx(
        median - 1.0, abs=1e-3)
    assert churn["cpu_overhead_frac"] <= 0.03, churn["cpu_overhead_frac"]
    # Flight-digest bytes per task: bounded and asserted, per shape and
    # as observed in the sim itself.
    digest = entry["digest"]
    assert digest["cap_bytes"] > 0
    assert 0 < digest["max_bytes"] <= digest["cap_bytes"], digest
    for name, shape in digest["shapes"].items():
        assert 0 < shape["bytes"] <= digest["cap_bytes"], name
        assert shape["build_us"] > 0, name
    assert 0 < churn["sim_digest_max_bytes"] <= digest["cap_bytes"], churn
    # The sim actually shipped digests (a zero-digest pair measures
    # nothing).
    assert churn["sim_digests"] >= churn["hosts"], churn
    ingest = entry["ingest"]
    assert ingest["on_us_per_task"] > 0
    # The scheduler-side ingest price stays sane: well under a
    # millisecond per completed task.
    assert ingest["on_us_per_task"] < 200, ingest


def test_prof_entry_paired_shape():
    """config12_prof is a PAIRED overhead run for the always-on runtime
    observatory: the shipped-digest ingest storm (sampler + GC callbacks
    installed vs not) AND the 1024-host DES churn sim (full observatory
    armed inside the measured window vs off). Both rounds are
    order-alternating with the config9 estimator — recompute the median
    from the published per-pair ratios — and both hold the <=3% budget
    independently."""
    entry = _load()["published"]["config12_prof"]
    for name, bound_hosts in (("ingest", None), ("churn_sim", 1024)):
        block = entry[name]
        ratios = sorted(block["pair_ratios"])
        assert len(ratios) == block["rounds"], name
        assert len(ratios) % 2 == 0, f"{name}: odd round count"
        median = (ratios[len(ratios) // 2 - 1]
                  + ratios[len(ratios) // 2]) / 2
        assert block["cpu_overhead_frac"] == pytest.approx(
            median - 1.0, abs=1e-3), name
        assert block["cpu_overhead_frac"] <= 0.03, (
            name, block["cpu_overhead_frac"])
        runs = block["runs_cpu_s"]
        assert len(runs["on"]) == len(runs["off"]) == block["rounds"], name
        assert all(v > 0 for v in runs["on"] + runs["off"]), name
        if bound_hosts:
            assert block["hosts"] >= bound_hosts, name
    churn = entry["churn_sim"]
    on, off = churn["on"], churn["off"]
    for run in (on, off):
        assert run["cpu_s"] > 0 and run["wall_s"] > 0
    # The treated arm actually sampled (a zero-sample pair measures
    # nothing) inside a bounded trie.
    assert churn["sampler_samples"] > 0, churn
    assert churn["sampler_nodes"] > 0, churn
    ingest = entry["ingest"]
    assert ingest["on_us_per_task"] > 0 and ingest["off_us_per_task"] > 0


def test_ingest_micro_serve_round_paired_shape():
    """The serve-side round is a PAIRED run on the same landed store:
    the old per-piece bytes path and the unified zero-copy paths, with
    the headline gain derived from the pair and holding the >=15%
    acceptance bound (pooled preadv + sendfile vs read_piece bytes)."""
    entry = _load()["published"]["ingest_micro"]
    serve = entry["serve"]
    for key in ("bytes_mbps", "pooled_mbps", "sendfile_mbps"):
        assert serve[key] > 0, key
    runs = serve["runs_mbps"]
    assert set(runs) == {"bytes", "pooled", "sendfile"}
    lens = {len(v) for v in runs.values()}
    assert len(lens) == 1 and lens.pop() >= 2, "unpaired serve runs"
    assert serve["gain_frac"] == pytest.approx(
        serve["sendfile_mbps"] / serve["bytes_mbps"] - 1.0, abs=1e-2)
    assert serve["gain_frac"] >= 0.15, serve


def test_ingest_micro_serve_spans_paired_shape():
    """The multi-span serve round is a PAIRED run through the same store
    API: submission ring on vs ring off (serial), order-alternating
    rounds, headline = median of per-round on/off ratios (the config9
    estimator). Acceptance: the ring is >=10% faster on the many-small-
    spans shape, and both arms landed byte-identical data."""
    entry = _load()["published"]["ingest_micro"]
    spans = entry["serve_spans"]
    assert spans["bytes_identical"] is True
    assert spans["ring_backend"] in ("batch", "io_uring", "threads")
    assert spans["spans_per_batch"] >= 16 and spans["span_kib"] > 0
    assert spans["on_mbps"] > 0 and spans["off_mbps"] > 0
    ratios = spans["pair_ratios"]
    assert len(ratios) == spans["rounds"] >= 4
    assert len(spans["on_runs_mbps"]) == len(spans["off_runs_mbps"]) == \
        len(ratios), "unpaired span-serve runs"
    ordered = sorted(ratios)
    mid = len(ordered) // 2
    median = (ordered[mid - 1] + ordered[mid]) / 2 \
        if len(ordered) % 2 == 0 else ordered[mid]
    assert spans["ratio_median"] == pytest.approx(median, abs=1e-3)
    assert spans["ratio_median"] >= 1.10, spans


def test_ingest_micro_chunker_round():
    """The CDC scan round: the native dfchunk.cc kernel against the numpy
    scanner over the same bytes. Acceptance on the publishing box: native
    scan >=1 GB/s and >=10x numpy, with byte-identical cut points (both
    the emitted chunk sequence and the raw scan candidates). End-to-end
    chunking (sha256-bound) is recorded alongside so the scan number
    can't masquerade as the pipeline number."""
    entry = _load()["published"]["ingest_micro"]
    ch = entry["chunker"]
    assert ch["cut_points_equal"] is True
    assert ch["scan"]["numpy_mbps"] > 0
    assert ch["chunk"]["numpy_mbps"] > 0
    if ch["backend"] == "native":
        assert ch["scan"]["native_mbps"] >= 1000.0, ch["scan"]
        assert ch["scan"]["speedup"] >= 10.0, ch["scan"]
        assert ch["chunk"]["native_mbps"] > ch["chunk"]["numpy_mbps"], ch
    else:
        # The published baseline comes from a box with the toolchain.
        pytest.fail(f"published chunker round lacks native backend: {ch}")


def test_ingest_micro_hash_fallback_round():
    """The CPU crc32c fallback is itself competitive: the selected
    non-native backend must beat the old pure-Python table composition by
    >=3x (acceptance bound; measured ~800x with google-crc32c)."""
    entry = _load()["published"]["ingest_micro"]
    hf = entry["hash_fallback"]
    assert hf["backend"] in ("google-crc32c", "python")
    assert hf["python_mbps"] > 0 and hf["fallback_mbps"] > 0
    if hf["backend"] != "python":
        assert hf["speedup"] >= 3.0, hf


def test_pod_sim_churn_4k_shape():
    """config5_pod_sim_churn_4k is the scheduler-HA acceptance sim: 4096
    hosts under sustained join/leave with one mid-sim scheduler
    crash/restore. Shape guard: completion despite the restart, every
    resume re-registration answered normal_task (zero re-downloaded
    landed bytes, no origin storm), the snapshot actually restored
    state, and rebuild time is reported."""
    entry = _load()["published"]["config5_pod_sim_churn_4k"]
    assert entry["hosts"] >= 4096
    assert entry["churn_waves"] >= 1
    assert entry["restart_enabled"] is True
    assert entry["completion_rate"] == 1.0
    assert entry["finished"] == entry["expected_finishers"]
    assert entry["origin_fetches"] <= 3
    r = entry["restart"]
    assert r["reregistered"] > 0
    assert set(r["resume_answers"]) == {"normal_task"}, r
    assert r["rebuilt_piece_mismatch"] == 0
    assert r["restored_peers"] > 0
    assert r["rebuild_s"] >= 0
    # The churn invariants promoted from the 1024-host variant.
    assert entry["straggler_dead_parent_picks"] == 0
    assert entry["peers_after_gc"] == 0
    assert entry["tasks_after_gc"] == 0
    assert entry["hosts_after_gc"] == 0


def test_delta_entry_paired_shape():
    """config11_delta is a PAIRED run: cold broadcast and delta update
    of the same 1%-scattered-mutation checkpoint over the same pod
    shape, order-alternating rounds. The acceptance bound: the delta
    moves <5% of the bytes of the cold broadcast, and the byte
    accounting (reused + fetched) sums EXACTLY to the content length —
    reused spans never ride the wire."""
    entry = _load()["published"]["config11_delta"]
    assert entry["accounting_exact"] is True
    delta, cold = entry["delta"], entry["cold"]
    assert cold["bytes"] == entry["content_bytes"]
    assert delta["reused_bytes"] + delta["fetched_bytes"] == \
        entry["content_bytes"]
    # The headline: a 1%-mutation update moves <5% of a cold broadcast.
    assert 0 < entry["delta_bytes_ratio"] <= 0.05, entry
    assert entry["delta_bytes_ratio"] == pytest.approx(
        delta["fetched_bytes"] / cold["bytes"], abs=1e-4)
    assert 0 < entry["mutation"]["frac"] <= 0.02
    assert entry["mutation"]["sites"] >= 2, "scattered edits, not one blob"
    # Paired shape: both modes ran the same number of rounds.
    assert len(cold["runs_s"]) == entry["rounds"] == len(delta["runs_s"])
    for runs in (cold["runs_s"], delta["runs_s"]):
        assert all(w > 0 for w in runs)
    assert delta["chunks_fetched"] > 0 and delta["chunks_reused"] > 0
    assert entry["chunking"]["chunks"] == \
        delta["chunks_fetched"] + delta["chunks_reused"]
    # The published manifest build ran on a real backend, named for the
    # record (the box with the toolchain publishes native).
    assert entry["chunking"]["chunker_backend"] in \
        ("native", "numpy", "python")
    assert entry["chunking"]["chunk_mb_s"] > 0


def test_qos_entry_paired_shape():
    """config13_qos is the QoS plane's three-round evidence: wfq is a
    PAIRED run (interactive pull p99 contended vs uncontended through
    the DWRR gate, order-alternating rounds, headline = median of
    per-pair ratios, bound <= 1.2x) with the background sweep provably
    not starved; surge pins bounded queueing under a 10x admission
    surge with zero collateral denials and completion 1.0; the upload
    round pins EXACT per-tenant byte accounting."""
    entry = _load()["published"]["config13_qos"]
    wfq = entry["wfq"]
    assert wfq["contended_p99_ms"] > 0 and wfq["uncontended_p99_ms"] > 0
    assert wfq["bg_workers"] > wfq["gate_capacity"], \
        "the sweep must oversubscribe the gate or nothing contends"
    assert wfq["bg_queue_peak"] > 0, "contention never materialized"
    # Recompute the headline from the published per-pair ratios — the
    # config9 estimator (order-alternating rounds, even count).
    ratios = sorted(wfq["pair_ratios"])
    assert len(ratios) == wfq["rounds"] and len(ratios) % 2 == 0
    median = (ratios[len(ratios) // 2 - 1] + ratios[len(ratios) // 2]) / 2
    assert wfq["p99_ratio"] == pytest.approx(median, abs=1e-3)
    assert wfq["p99_ratio"] <= 1.2, wfq
    # Work conservation: isolation must not come from starving the
    # background class (a priority mutex would also pass the p99 bound).
    assert wfq["bg_pieces_per_s"] > 0

    surge = entry["surge"]
    assert surge["surge_x"] >= 10
    assert surge["denied_429"] > 0, "the surge never tripped admission"
    assert surge["well_behaved_denied"] == 0, surge
    assert surge["max_queue_admission_on"] <= \
        0.5 * surge["max_queue_admission_off"], surge
    assert surge["queue_bound_frac"] == pytest.approx(
        surge["max_queue_admission_on"]
        / surge["max_queue_admission_off"], abs=1e-3)
    assert surge["completion_rate"] == 1.0
    lo, hi = surge["retry_after_range_s"]
    assert 0 < lo <= hi <= 30.0, "Retry-After outside the ladder's cap"

    acct = entry["upload_accounting"]
    assert acct["exact"] is True
    assert set(acct["expected_bytes"]) == set(acct["metric_bytes"])
    assert len(acct["expected_bytes"]) >= 2, "need >=2 tenants to prove split"
    for tenant, want in acct["expected_bytes"].items():
        assert want > 0
        assert acct["metric_bytes"][tenant] == want, (tenant, acct)


def test_wire_entry_paired_shape():
    """config14_wire is the announce-wire-diet evidence: packed report
    bytes per host <= 1/3 of the dict wire on the timed (common-case)
    profile, the resume bitmap well under the int list, and the ingest
    rounds PAIRED order-alternating (the config9 estimator — recompute
    the median) with the exactness oracle asserted on both shapes. The
    storm (task-sized recovery drain) headline needs the native rung,
    which the publishing box carries."""
    entry = _load()["published"]["config14_wire"]
    w = entry["wire"]
    assert w["packed_bytes_per_host"] > 0
    assert w["ratio"] == pytest.approx(
        w["dict_bytes_per_host"] / w["packed_bytes_per_host"], abs=1e-2)
    assert w["ratio"] >= 3.0, w
    assert w["plain"]["ratio"] >= 2.5, w["plain"]
    assert w["resume_ratio"] >= 3.0, w
    if entry["report_backend"] != "native":
        # The published baseline comes from a box with the toolchain.
        pytest.fail(f"published wire entry lacks native rung: {entry}")
    for name, floor in (("ingest_storm", 5.0), ("ingest_steady", 1.0)):
        block = entry[name]
        assert block["state_identical"] is True, name
        assert block["packed_us_per_piece"] > 0, name
        assert block["dict_us_per_piece"] > 0, name
        ratios = sorted(block["pair_ratios"])
        assert len(ratios) == block["rounds"] >= 5, name
        mid = len(ratios) // 2
        median = (ratios[mid - 1] + ratios[mid]) / 2 \
            if len(ratios) % 2 == 0 else ratios[mid]
        assert block["ratio_median"] == pytest.approx(median, abs=1e-2), name
        assert block["ratio_median"] >= floor, (name, block)


def test_pod_sim_churn_16k_scale_pair_shape():
    """config5_pod_sim_churn_16k is the flat-per-event-cost acceptance:
    16384 hosts under sustained churn on the packed wire, completion
    1.0, the loop-lag SLO never breached mid-storm, and cpu-per-
    announce-event within 1.15x of the in-process 4k pair."""
    entry = _load()["published"]["config5_pod_sim_churn_16k"]
    assert entry["hosts"] >= 16384
    assert entry["packed_wire"] is True
    assert entry["report_batch"] >= 2
    assert entry["completion_rate"] == 1.0
    assert entry["origin_fetches"] <= 3
    assert entry["slo"]["breached"] == [], entry["slo"]
    pair = entry["pair_4k"]
    assert pair["hosts"] == 4096
    assert pair["completion_rate"] == 1.0
    assert pair["cpu_per_event_us"] > 0
    assert entry["per_event_ratio_vs_4k"] == pytest.approx(
        entry["cpu_per_event_us"] / pair["cpu_per_event_us"], abs=1e-2)
    assert entry["per_event_ratio_vs_4k"] <= 1.15, entry
    # The churn invariants hold at 16k too.
    assert entry["straggler_dead_parent_picks"] == 0
    assert entry["peers_after_gc"] == 0
    assert entry["tasks_after_gc"] == 0
    assert entry["hosts_after_gc"] == 0


def test_cluster_entry_paired_shape():
    """config15_cluster is the control tower's overhead evidence: a
    PAIRED storm (frame build + manager ingest ON vs the same scheduler
    churn with no tower) interleaved at per-scheduler-chunk granularity,
    order-alternating — recompute the median from the published per-
    round ratios — within the <=3% budget; every frame built in the
    storm stayed under the wire cap; the frame-bounds round proves the
    halving-until-fit cap on absurd host sets; and the spool round
    proves the shipped window survives a real sqlite close/reopen."""
    entry = _load()["published"]["config15_cluster"]
    storm = entry["storm"]
    assert storm["schedulers"] >= 16
    assert storm["frames_per_round"] > 0
    runs = storm["runs_cpu_s"]
    assert len(runs["on"]) == len(runs["off"]) == storm["rounds"]
    assert all(v > 0 for v in runs["on"] + runs["off"])
    ratios = sorted(storm["pair_ratios"])
    assert len(ratios) == storm["rounds"] and len(ratios) % 2 == 0
    median = (ratios[len(ratios) // 2 - 1] + ratios[len(ratios) // 2]) / 2
    assert storm["cpu_overhead_frac"] == pytest.approx(
        median - 1.0, abs=1e-3)
    assert storm["cpu_overhead_frac"] <= 0.03, storm["cpu_overhead_frac"]
    # Every frame the storm built fit the keepalive wire cap.
    assert 0 < storm["frame_bytes_peak"] <= storm["frame_bytes_max"], storm
    bounds = entry["frame_bounds"]
    assert bounds["truncated"] is True, "cap never engaged — no evidence"
    assert 0 < bounds["frame_bytes"] <= storm["frame_bytes_max"], bounds
    assert bounds["hosts_offered"] > bounds["stragglers_kept"], bounds
    spool = entry["spool_reopen"]
    assert spool["survives"] is True, spool
    assert spool["restored_frames"] == spool["frames_stored"] > 0, spool


def test_stripe_sim_meets_acceptance_bounds():
    """The recorded sim pair keeps the published claim: per-host DCN
    bytes <= file/S + piece slack, and >= 1.5x aggregate throughput vs
    the unstriped control."""
    entry = _load()["published"]["config6_stripe_sim"]
    s = entry["striped"]
    bound = s["content_mb"] / s["hosts_per_slice"] + s["piece_mb"]
    assert s["max_host_dcn_mb"] <= bound, (s["max_host_dcn_mb"], bound)
    assert entry["speedup"] >= 1.5, entry["speedup"]
