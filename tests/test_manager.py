"""Manager tests: database CRUD, searcher affinity, auth, REST API, RPC
registry + keepalive, job queue. Mirrors the reference's per-handler tests
(manager/handlers/*_test.go) and searcher_test.go."""

from __future__ import annotations

import asyncio

import aiohttp
import pytest

from dragonfly2_tpu.manager import auth, jobqueue
from dragonfly2_tpu.manager.client import ManagerClient
from dragonfly2_tpu.manager.config import DatabaseConfig, ManagerConfig
from dragonfly2_tpu.manager.database import Database
from dragonfly2_tpu.manager.searcher import Searcher, SearchRequest
from dragonfly2_tpu.manager.server import ManagerServer
from dragonfly2_tpu.manager.service import ManagerService
from dragonfly2_tpu.pkg.types import NetAddr


# -- database ---------------------------------------------------------------

def test_database_crud_json_roundtrip():
    db = Database()
    row = db.insert("scheduler_clusters", {
        "name": "c1", "config": {"x": 1}, "scopes": {"idc": "idc-a"}})
    assert row["config"] == {"x": 1}
    got = db.find("scheduler_clusters", name="c1")
    assert got["scopes"]["idc"] == "idc-a"
    db.update("scheduler_clusters", row["id"], {"config": {"x": 2}})
    assert db.get("scheduler_clusters", row["id"])["config"] == {"x": 2}
    assert db.count("scheduler_clusters") == 1
    assert db.delete("scheduler_clusters", row["id"])
    assert db.get("scheduler_clusters", row["id"]) is None


def test_database_cluster_links():
    db = Database()
    db.link_seed_peer_cluster(1, 7)
    db.link_seed_peer_cluster(1, 7)  # idempotent
    db.link_seed_peer_cluster(1, 9)
    assert sorted(db.seed_peer_clusters_of(1)) == [7, 9]


# -- searcher ---------------------------------------------------------------

def _cluster(name, scopes, is_default=0):
    return {"id": hash(name) % 1000, "name": name, "scopes": scopes,
            "is_default": is_default}


def test_searcher_prefers_scope_matches():
    s = Searcher()
    default = _cluster("default", {}, is_default=1)
    idc = _cluster("idc", {"idc": "tpu-v5p"})
    cidr = _cluster("cidr", {"cidrs": ["10.0.0.0/8"]})
    req = SearchRequest(hostname="host-1", ip="10.1.2.3", idc="tpu-v5p")
    ranked = s.find_scheduler_clusters([default, idc, cidr], req)
    # Both scope clusters outrank the default; cidr (0.3) == idc (0.3).
    assert {c["name"] for c in ranked[:2]} == {"idc", "cidr"}


def test_searcher_falls_back_to_default():
    s = Searcher()
    default = _cluster("default", {}, is_default=1)
    other = _cluster("other", {"idc": "nope"})
    req = SearchRequest(hostname="h", ip="192.168.1.1", idc="different")
    ranked = s.find_scheduler_clusters([other, default], req)
    assert ranked[0]["name"] == "default"


def test_searcher_location_prefix_and_hostname_regex():
    s = Searcher()
    loc = _cluster("loc", {"location": "us|west|zone-a"})
    host = _cluster("host", {"hostnames": ["^tpu-worker-\\d+$"]})
    req = SearchRequest(hostname="tpu-worker-17", location="us|west|zone-b")
    assert s.evaluate(req, loc) == pytest.approx(0.08 * 2 / 5)
    assert s.evaluate(req, host) == pytest.approx(0.3)


# -- auth -------------------------------------------------------------------

def test_password_and_token_roundtrip():
    enc = auth.hash_password("s3cret")
    assert auth.verify_password("s3cret", enc)
    assert not auth.verify_password("wrong", enc)
    signer = auth.TokenSigner()
    token = signer.sign(1, "root", ["root"])
    payload = signer.verify(token)
    assert payload["name"] == "root" and payload["roles"] == ["root"]
    assert signer.verify(token + "x") is None
    assert auth.can(["root"], "DELETE")
    assert auth.can(["guest"], "GET")
    assert not auth.can(["guest"], "POST")


def test_service_signup_signin_and_pat():
    svc = ManagerService()
    svc.signup("alice", "pw", email="a@b.c")
    token = svc.signin("alice", "pw")
    ident = svc.verify_token(token)
    assert ident["name"] == "alice" and auth.ROLE_GUEST in ident["roles"]
    with pytest.raises(Exception):
        svc.signin("alice", "bad")


def test_service_defaults_seeded():
    svc = ManagerService()
    assert svc.db.find("users", name="root") is not None
    sc = svc.db.find("scheduler_clusters", name="default")
    assert sc["is_default"]
    assert svc.db.seed_peer_clusters_of(sc["id"])


# -- registry + keepalive over real RPC ------------------------------------

def test_manager_rpc_registry_and_keepalive(run_async):
    run_async(_rpc_registry_and_keepalive())


async def _rpc_registry_and_keepalive():
    server = ManagerServer(ManagerConfig())
    await server.start()
    client = ManagerClient(NetAddr.tcp("127.0.0.1", server.grpc_port()))
    try:
        sched = await client.update_scheduler(
            hostname="sched-1", ip="127.0.0.1", port=8002, idc="tpu-v5p")
        assert sched["state"] == "active"
        cluster_id = sched["scheduler_cluster_id"]

        seed = await client.update_seed_peer(
            hostname="seed-1", ip="127.0.0.1", port=65000, download_port=65002)
        assert seed["state"] == "active"

        # dynconfig read paths
        listed = await client.list_schedulers(hostname="worker", ip="10.0.0.1")
        assert any(s["hostname"] == "sched-1" for s in listed)
        seeds = await client.list_seed_peers(cluster_id)
        assert any(s["hostname"] == "seed-1" for s in seeds)
        cfg = await client.get_scheduler_cluster_config(cluster_id)
        assert cfg["client_config"]["load_limit"] == 200

        # keepalive stream: close -> inactive
        stream = await client._client.open_stream("Manager.KeepAlive", {
            "source_type": "scheduler", "hostname": "sched-1",
            "ip": "127.0.0.1", "cluster_id": cluster_id})
        await stream.send({})
        await asyncio.sleep(0.05)
        await stream.close()
        await asyncio.sleep(0.1)
        row = server.db.find("schedulers", hostname="sched-1", ip="127.0.0.1",
                             scheduler_cluster_id=cluster_id)
        assert row["state"] == "inactive"
    finally:
        await client.close()
        await server.stop()


# -- job queue --------------------------------------------------------------

def test_job_queue_group_aggregation(run_async):
    run_async(_job_queue_group_aggregation())


async def _job_queue_group_aggregation():
    svc = ManagerService()
    job = svc.jobs.enqueue_job(jobqueue.PREHEAT_JOB, {"urls": ["http://x/f"]},
                               [1, 2])
    i1 = await svc.jobs.poll(jobqueue.queue_name(1), timeout=1.0)
    i2 = await svc.jobs.poll(jobqueue.queue_name(2), timeout=1.0)
    assert i1.type == jobqueue.PREHEAT_JOB and i2.group_id == i1.group_id
    svc.jobs.complete(i1.group_id, i1.task_uuid, jobqueue.SUCCESS, {"n": 1})
    assert svc.db.get("jobs", job["id"])["state"] == jobqueue.STARTED
    svc.jobs.complete(i2.group_id, i2.task_uuid, jobqueue.SUCCESS, {"n": 2})
    done = svc.db.get("jobs", job["id"])
    assert done["state"] == jobqueue.SUCCESS
    assert len(done["result"]["group_results"]) == 2


def test_job_queue_failure_propagates(run_async):
    run_async(_job_queue_failure_propagates())


async def _job_queue_failure_propagates():
    svc = ManagerService()
    job = svc.jobs.enqueue_job(jobqueue.SYNC_PEERS_JOB, {}, [1])
    item = await svc.jobs.poll(jobqueue.queue_name(1), timeout=1.0)
    svc.jobs.complete(item.group_id, item.task_uuid, jobqueue.FAILURE,
                      {"error": "boom"})
    assert svc.db.get("jobs", job["id"])["state"] == jobqueue.FAILURE


# -- REST -------------------------------------------------------------------

def test_rest_auth_and_crud(run_async):
    run_async(_rest_auth_and_crud())


async def _rest_auth_and_crud():
    server = ManagerServer(ManagerConfig())
    await server.start()
    base = f"http://127.0.0.1:{server.rest_port}"
    try:
        async with aiohttp.ClientSession() as http:
            # unauthenticated rejected
            resp = await http.get(f"{base}/api/v1/scheduler-clusters")
            assert resp.status == 401
            # signin as root
            resp = await http.post(f"{base}/api/v1/users/signin",
                                   json={"name": "root", "password": "dragonfly"})
            assert resp.status == 200
            token = (await resp.json())["token"]
            hdr = {"Authorization": f"Bearer {token}"}

            # CRUD a scheduler cluster
            resp = await http.post(f"{base}/api/v1/scheduler-clusters", headers=hdr,
                                   json={"name": "tpu", "scopes": {"idc": "v5p"}})
            assert resp.status == 200
            cluster = await resp.json()
            resp = await http.patch(
                f"{base}/api/v1/scheduler-clusters/{cluster['id']}",
                headers=hdr, json={"bio": "tpu pod cluster"})
            assert (await resp.json())["bio"] == "tpu pod cluster"
            resp = await http.get(f"{base}/api/v1/scheduler-clusters", headers=hdr)
            assert len(await resp.json()) == 2  # default + tpu

            # guest is read-only
            resp = await http.post(f"{base}/api/v1/users/signup",
                                   json={"name": "bob", "password": "pw"})
            assert resp.status == 200
            resp = await http.post(f"{base}/api/v1/users/signin",
                                   json={"name": "bob", "password": "pw"})
            guest_hdr = {"Authorization": f"Bearer {(await resp.json())['token']}"}
            resp = await http.get(f"{base}/api/v1/scheduler-clusters",
                                  headers=guest_hdr)
            assert resp.status == 200
            resp = await http.post(f"{base}/api/v1/scheduler-clusters",
                                   headers=guest_hdr, json={"name": "x"})
            assert resp.status == 403

            # personal access token auth
            resp = await http.post(f"{base}/api/v1/personal-access-tokens",
                                   headers=hdr, json={"name": "ci"})
            pat = (await resp.json())["token"]
            resp = await http.get(f"{base}/api/v1/schedulers",
                                  headers={"Authorization": f"Bearer {pat}"})
            assert resp.status == 200

            # jobs endpoint enqueues to per-cluster queues
            resp = await http.post(f"{base}/api/v1/jobs", headers=hdr, json={
                "type": "preheat",
                "args": {"type": "file", "url": "http://origin/blob"},
            })
            assert resp.status == 200
            job = await resp.json()
            assert job["state"] == "PENDING"
            resp = await http.get(f"{base}/api/v1/jobs/{job['id']}", headers=hdr)
            assert (await resp.json())["args"]["urls"] == ["http://origin/blob"]
    finally:
        await server.stop()


def test_job_rate_limit_shared_across_faces(run_async):
    """Distributed job rate limiting (reference internal/ratelimiter +
    manager/middlewares/ratelimiter.go): the per-cluster bucket lives at
    the manager — the deployment's shared coordination point — so the
    REST Open API and every scheduler instance's drpc draws debit ONE
    budget. Config changes take effect on the next take."""
    from dragonfly2_tpu.manager.client import ManagerClient
    from dragonfly2_tpu.pkg.types import NetAddr

    async def run():
        server = ManagerServer(ManagerConfig())
        await server.start()
        base = f"http://127.0.0.1:{server.rest_port}"
        cluster_id = server.db.find("scheduler_clusters", name="default")["id"]
        # Pin the default cluster's budget to 2 jobs/s.
        cfg = server.db.get("scheduler_clusters", cluster_id)["config"]
        server.db.update("scheduler_clusters", cluster_id,
                         {"config": {**cfg, "job_rate_limit": 2}})
        # Two drpc clients = two scheduler instances sharing the budget.
        cli_a = ManagerClient(NetAddr.tcp("127.0.0.1", server.grpc_port()))
        cli_b = ManagerClient(NetAddr.tcp("127.0.0.1", server.grpc_port()))
        try:
            r = await cli_a.take_job_tokens([cluster_id], tokens=1)
            assert r["granted"], r
            r = await cli_b.take_job_tokens([cluster_id], tokens=1)
            assert r["granted"], r
            # Budget exhausted: the OTHER instance is told to wait.
            r = await cli_b.take_job_tokens([cluster_id], tokens=1)
            assert not r["granted"] and r["retry_after_s"] > 0, r

            # The REST face debits the same bucket: with the budget dry, a
            # job POST is 429 with Retry-After; once tokens regenerate the
            # same POST succeeds.
            import aiohttp

            async with aiohttp.ClientSession() as http:
                resp = await http.post(
                    f"{base}/api/v1/users/signin",
                    json={"name": "root", "password": "dragonfly"})
                hdr = {"Authorization":
                       f"Bearer {(await resp.json())['token']}"}
                body = {"type": "preheat",
                        "args": {"type": "file", "url": "http://o/x"},
                        "scheduler_cluster_ids": [cluster_id]}
                resp = await http.post(f"{base}/api/v1/jobs", headers=hdr,
                                       json=body)
                assert resp.status == 429, await resp.text()
                assert float(resp.headers["Retry-After"]) > 0
                await asyncio.sleep(0.6)  # 2/s → >1 token back
                resp = await http.post(f"{base}/api/v1/jobs", headers=hdr,
                                       json=body)
                assert resp.status == 200, await resp.text()

            # Operator raises the limit: next takes see the new rate.
            server.db.update("scheduler_clusters", cluster_id,
                             {"config": {**cfg, "job_rate_limit": 1000}})
            # Retuning preserves depletion (no free burst on a config
            # change); give the 1000/s refill a beat before expecting
            # grants.
            await cli_a.take_job_tokens([cluster_id])  # apply new rate
            await asyncio.sleep(0.05)
            granted = 0
            for _ in range(20):
                r = await cli_a.take_job_tokens([cluster_id])
                granted += bool(r["granted"])
            assert granted == 20, granted

            # All-or-nothing across clusters: a deny on a dry cluster
            # must not debit the healthy one's bucket.
            dry = server.service.db.insert(
                "scheduler_clusters",
                {"name": "dry", "config": {"job_rate_limit": 1}})
            r = await cli_a.take_job_tokens([dry["id"]])
            assert r["granted"]
            for _ in range(5):   # mixed takes all denied by the dry cluster
                r = await cli_a.take_job_tokens([cluster_id, dry["id"]])
                assert not r["granted"]
            r = await cli_a.take_job_tokens([cluster_id])
            assert r["granted"], "healthy bucket was drained by denied takes"
            # Negative token counts must never CREDIT a bucket.
            r = await cli_a.take_job_tokens([dry["id"]], tokens=-1000)
            assert not r["granted"], r
        finally:
            await cli_a.close()
            await cli_b.close()
            await server.stop()

    run_async(run())


def test_job_rate_limit_unknown_and_duplicate_clusters():
    """Unknown/duplicate cluster-id hardening (advisor round 5):
    a request whose cluster ids ALL resolve to nonexistent clusters must
    be rejected, not granted with zero debit (rate-limit bypass); and
    duplicate ids must neither double-debit nor slip past the
    all-or-nothing check when only one token remains."""
    from dragonfly2_tpu.pkg.errors import Code, DfError

    svc = ManagerService()
    cluster_id = svc.db.find("scheduler_clusters", name="default")["id"]
    cfg = svc.db.get("scheduler_clusters", cluster_id)["config"]
    svc.db.update("scheduler_clusters", cluster_id,
                  {"config": {**cfg, "job_rate_limit": 2}})

    # All listed ids nonexistent: rejected (the empty limiter list used to
    # grant with no debit — a full bypass of the job limit).
    with pytest.raises(DfError) as ei:
        svc.take_job_tokens([987654, 987655])
    assert ei.value.code == Code.NotFound

    # Duplicates collapse to one debit: burst is 2, so [id, id] granted
    # once leaves exactly one token, and the next single take still works.
    granted, _ = svc.take_job_tokens([cluster_id, cluster_id])
    assert granted
    granted, _ = svc.take_job_tokens([cluster_id])
    assert granted, "duplicate ids double-debited one job"
    # Bucket now empty: [id, id] with zero tokens must be denied (per-
    # occurrence can_allow with one token would still pass each check).
    granted, retry_after = svc.take_job_tokens([cluster_id, cluster_id])
    assert not granted and retry_after > 0

    # Malformed ids are a coded client error, not a ValueError escape.
    with pytest.raises(DfError) as ei:
        svc.take_job_tokens(["abc"])
    assert ei.value.code == Code.InvalidArgument


def test_job_create_rejects_bad_cluster_ids(run_async):
    """REST face of the same hardening: non-numeric scheduler_cluster_ids
    → 400 (was a 500 path), all-nonexistent → 404, and neither enqueues a
    job or expands preheat args."""

    async def run():
        server = ManagerServer(ManagerConfig())
        await server.start()
        base = f"http://127.0.0.1:{server.rest_port}"
        try:
            async with aiohttp.ClientSession() as http:
                resp = await http.post(
                    f"{base}/api/v1/users/signin",
                    json={"name": "root", "password": "dragonfly"})
                hdr = {"Authorization":
                       f"Bearer {(await resp.json())['token']}"}
                body = {"type": "preheat",
                        "args": {"type": "file", "url": "http://o/x"},
                        "scheduler_cluster_ids": ["abc"]}
                resp = await http.post(f"{base}/api/v1/jobs", headers=hdr,
                                       json=body)
                assert resp.status == 400, await resp.text()
                body["scheduler_cluster_ids"] = [987654]
                resp = await http.post(f"{base}/api/v1/jobs", headers=hdr,
                                       json=body)
                assert resp.status == 404, await resp.text()
                resp = await http.get(f"{base}/api/v1/jobs", headers=hdr)
                assert await resp.json() == [], "rejected job was enqueued"
        finally:
            await server.stop()

    run_async(run())
