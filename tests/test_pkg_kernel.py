"""Unit tests for the shared kernel (pkg/)."""

import io

import pytest

from dragonfly2_tpu.pkg import digest, idgen, piece
from dragonfly2_tpu.pkg.cache import TTLCache
from dragonfly2_tpu.pkg.dag import DAG, CycleError, DAGError
from dragonfly2_tpu.pkg.errors import Code, DfError, NeedBackSourceError, error_from_wire
from dragonfly2_tpu.pkg.fsm import FSM, EventDesc, TransitionError
from dragonfly2_tpu.pkg.types import HostType, parse_size
from dragonfly2_tpu.rpc.balancer import HashRing


class TestDigest:
    def test_parse_roundtrip(self):
        d = digest.parse("sha256:" + "a" * 64)
        assert d.algorithm == "sha256"
        assert str(d) == "sha256:" + "a" * 64

    def test_parse_rejects_bad(self):
        with pytest.raises(digest.InvalidDigestError):
            digest.parse("sha256:xyz")
        with pytest.raises(digest.InvalidDigestError):
            digest.parse("nosep")
        with pytest.raises(digest.InvalidDigestError):
            digest.parse("whirlpool:" + "a" * 64)

    def test_hash_bytes_known_vector(self):
        d = digest.hash_bytes("md5", b"hello")
        assert d.encoded == "5d41402abc4b2a76b9719d911017c592"
        d = digest.hash_bytes("sha256", b"")
        assert d.encoded == "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"

    def test_crc32c_known_vectors(self):
        # RFC 3720 test vectors.
        assert digest.crc32c(b"") == 0x00000000
        assert digest.crc32c(b"\x00" * 32) == 0x8A9136AA
        assert digest.crc32c(bytes(range(32))) == 0x46DD794E

    def test_crc32c_incremental(self):
        data = bytes(range(256)) * 7
        whole = digest.crc32c(data)
        c = digest.crc32c(data[:100])
        c = digest.crc32c(data[100:], c)
        assert c == whole

    def test_hashing_reader(self):
        r = digest.HashingReader(io.BytesIO(b"hello world"), "sha256")
        assert r.read() == b"hello world"
        assert r.digest().encoded == digest.hash_bytes("sha256", b"hello world").encoded

    def test_sha256_from_strings(self):
        assert digest.sha256_from_strings("a", "b") == digest.sha256_from_strings("ab")


class TestIdgen:
    def test_task_id_v2_stable(self):
        a = idgen.task_id_v2("http://x/y?b=2&a=1", "tag", "app")
        b = idgen.task_id_v2("http://x/y?a=1&b=2", "tag", "app")
        assert a == b  # param order canonicalized

    def test_task_id_filters(self):
        a = idgen.task_id_v2("http://x/y?sig=123&a=1", filtered_query_params=["sig"])
        b = idgen.task_id_v2("http://x/y?sig=999&a=1", filtered_query_params=["sig"])
        assert a == b

    def test_task_id_v1_range(self):
        whole = idgen.task_id_v1("http://x/f")
        ranged = idgen.task_id_v1("http://x/f", range_header="bytes=0-9")
        parent = idgen.parent_task_id_v1("http://x/f", range_header="bytes=0-9")
        assert whole != ranged
        assert whole == parent

    def test_peer_ids(self):
        pid = idgen.peer_id_v1("1.2.3.4")
        assert pid.startswith("1.2.3.4-")
        assert not idgen.is_seed_peer_id(pid)
        assert idgen.is_seed_peer_id(idgen.seed_peer_id_v1("1.2.3.4"))

    def test_host_id(self):
        assert idgen.host_id("h1") == "h1"
        assert idgen.host_id("h1", 8080) == "h1-8080"


class TestPiece:
    def test_piece_size_scaling(self):
        # Steeper than reference util.go: ~32 pieces per task above 128 MiB
        # (per-piece control-plane cost dominates small hops here).
        assert piece.compute_piece_size(-1) == 4 << 20
        assert piece.compute_piece_size(100 << 20) == 4 << 20
        assert piece.compute_piece_size(128 << 20) == 4 << 20
        assert piece.compute_piece_size(256 << 20) == 8 << 20
        assert piece.compute_piece_size(1 << 30) == 32 << 20
        assert piece.compute_piece_size(10 << 30) == 32 << 20  # capped
        # Piece count stays near the target across the scaling band;
        # beyond the 32 MiB cap the count grows instead (memory bound on
        # the non-native pull path wins over the 32-piece target).
        for mb in (129, 200, 256, 512, 1024):
            n = piece.compute_piece_count(
                mb << 20, piece.compute_piece_size(mb << 20))
            assert 16 <= n <= 33, (mb, n)
        assert piece.compute_piece_count(
            2048 << 20, piece.compute_piece_size(2048 << 20)) == 64

    def test_piece_count(self):
        assert piece.compute_piece_count(10, 4) == 3
        assert piece.compute_piece_count(8, 4) == 2

    def test_piece_length(self):
        assert piece.piece_length(0, 4, 10) == 4
        assert piece.piece_length(2, 4, 10) == 2
        assert piece.piece_length(3, 4, 10) == 0

    def test_range_parse(self):
        r = piece.Range.parse_http("bytes=0-99")
        assert (r.start, r.length) == (0, 100)
        r = piece.Range.parse_http("bytes=10-", content_length=50)
        assert (r.start, r.length) == (10, 40)
        r = piece.Range.parse_http("bytes=-10", content_length=50)
        assert (r.start, r.length) == (40, 10)
        assert piece.Range(0, 100).to_http() == "bytes=0-99"

    def test_size_scope(self):
        assert piece.SizeScope.of(0, 4 << 20) == piece.SizeScope.EMPTY
        assert piece.SizeScope.of(100, 4 << 20) == piece.SizeScope.TINY
        assert piece.SizeScope.of(1 << 20, 4 << 20) == piece.SizeScope.SMALL
        assert piece.SizeScope.of(100 << 20, 4 << 20) == piece.SizeScope.NORMAL
        assert piece.SizeScope.of(-1, 4 << 20) == piece.SizeScope.UNKNOW

    def test_bitmap(self):
        bm = piece.PieceBitmap(total=3)
        bm.mark(0)
        bm.mark(2)
        assert not bm.complete()
        assert bm.missing() == [1]
        bm.mark(1)
        assert bm.complete()
        rt = piece.PieceBitmap.from_wire(bm.to_wire())
        assert rt.complete()


class TestErrors:
    def test_wire_roundtrip(self):
        e = DfError(Code.SchedNeedBackSource, "go to source")
        e2 = error_from_wire(e.to_wire())
        assert isinstance(e2, NeedBackSourceError)
        assert e2.code == Code.SchedNeedBackSource


class TestDAG:
    def test_edges_and_cycles(self):
        d = DAG()
        for v in "abc":
            d.add_vertex(v, v.upper())
        d.add_edge("a", "b")
        d.add_edge("b", "c")
        with pytest.raises(CycleError):
            d.add_edge("c", "a")
        assert not d.can_add_edge("c", "a")
        assert d.can_add_edge("a", "c")
        with pytest.raises(DAGError):
            d.add_edge("a", "b")  # duplicate

    def test_delete_vertex_cleans_edges(self):
        d = DAG()
        for v in "abc":
            d.add_vertex(v, None)
        d.add_edge("a", "b")
        d.add_edge("b", "c")
        d.delete_vertex("b")
        assert d.get_vertex("a").out_degree() == 0
        assert d.get_vertex("c").in_degree() == 0

    def test_delete_in_edges(self):
        d = DAG()
        for v in "abc":
            d.add_vertex(v, None)
        d.add_edge("a", "c")
        d.add_edge("b", "c")
        d.delete_vertex_in_edges("c")
        assert d.get_vertex("c").in_degree() == 0
        assert d.get_vertex("a").out_degree() == 0

    def test_random_sampling(self):
        d = DAG()
        for i in range(20):
            d.add_vertex(str(i), i)
        sample = d.random_vertices(5)
        assert len(sample) == 5
        assert len(d.random_vertices(50)) == 20


class TestFSM:
    def test_transitions(self):
        f = FSM("pending", [
            EventDesc("run", ("pending",), "running"),
            EventDesc("done", ("running",), "succeeded"),
        ])
        assert f.can("run")
        f.event("run")
        assert f.current == "running"
        with pytest.raises(TransitionError):
            f.event("run")
        f.event("done")
        assert f.is_state("succeeded")


class TestTTLCache:
    def test_expiry(self):
        c = TTLCache()
        c.set("a", 1, ttl=1000)
        v, ok = c.get("a")
        assert ok and v == 1
        c.set("b", 2, ttl=-1)  # no expiration
        _, ok = c.get("b")
        assert ok
        c.set("c", 3, ttl=0.0)
        import time

        time.sleep(0.01)
        _, ok = c.get("c")
        assert not ok


class TestHashRing:
    def test_pick_stability(self):
        ring = HashRing(["s1", "s2", "s3"])
        key = "task-abc"
        first = ring.pick(key)
        for _ in range(10):
            assert ring.pick(key) == first

    def test_remove_minimal_disruption(self):
        ring = HashRing(["s1", "s2", "s3"])
        keys = [f"task-{i}" for i in range(200)]
        before = {k: ring.pick(k) for k in keys}
        ring.remove("s2")
        moved = sum(1 for k in keys if before[k] != ring.pick(k) and before[k] != "s2")
        assert moved == 0  # only keys owned by s2 move
        assert all(ring.pick(k) != "s2" for k in keys)

    def test_pick_n(self):
        ring = HashRing(["s1", "s2", "s3"])
        picks = ring.pick_n("k", 3)
        assert sorted(picks) == ["s1", "s2", "s3"]


class TestTypes:
    def test_host_type(self):
        assert HostType.parse("super") == HostType.SUPER_SEED
        assert HostType.SUPER_SEED.is_seed()
        assert not HostType.NORMAL.is_seed()

    def test_parse_size(self):
        assert parse_size("4MiB") == 4 << 20
        assert parse_size("1.5K") == 1536
        assert parse_size(42) == 42


class TestLimiter:
    def test_burst_floor_never_hangs(self, run_async):
        from dragonfly2_tpu.pkg.ratelimit import Limiter

        async def body():
            lim = Limiter(limit=0.5)  # would be burst=0 without the floor
            assert lim._burst >= 1
            lim2 = Limiter(limit=10_000, burst=0)
            await lim2.wait(3)  # must terminate

        run_async(body(), timeout=10)

    def test_cancelled_wait_refunds_tokens(self, run_async):
        import asyncio

        from dragonfly2_tpu.pkg.ratelimit import Limiter

        async def body():
            lim = Limiter(limit=100, burst=10)
            await lim.wait(10)  # drain the bucket
            t = asyncio.ensure_future(lim.wait(10))
            await asyncio.sleep(0.01)
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass
            # The cancelled reservation must be refunded: a fresh waiter
            # should need ~0.1s (10 tokens @ 100/s), not ~0.2s.
            import time

            start = time.monotonic()
            await lim.wait(10)
            assert time.monotonic() - start < 0.15

        run_async(body(), timeout=10)

    def test_throughput_shaping(self, run_async):
        import time

        from dragonfly2_tpu.pkg.ratelimit import Limiter

        async def body():
            lim = Limiter(limit=1000, burst=100)
            start = time.monotonic()
            total = 0
            while total < 300:
                await lim.wait(100)
                total += 100
            # 300 tokens @ 1000/s with 100 burst → ≥ ~0.2s
            assert time.monotonic() - start >= 0.15

        run_async(body(), timeout=10)


def test_range_inverted_rejected():
    import pytest as _pytest

    from dragonfly2_tpu.pkg.piece import Range

    with _pytest.raises(ValueError):
        Range.parse_http("bytes=9-0")


def test_dflog_late_configure_adds_file_handler(tmp_path):
    import logging

    from dragonfly2_tpu.pkg import dflog

    dflog.get("late-test").info("before configure")
    dflog.configure(log_dir=str(tmp_path))
    dflog.get("late-test").info("after configure")
    root = logging.getLogger("df")
    assert any(isinstance(h, logging.handlers.RotatingFileHandler) for h in root.handlers)


def test_metrics_server_endpoints(run_async):
    """Prometheus + /debug surfaces (reference: per-binary metrics servers
    scheduler.go:219 + pprof dashboards dependency.go:95-114)."""
    import aiohttp

    from dragonfly2_tpu.pkg import metrics
    from dragonfly2_tpu.pkg.metrics_server import MetricsServer

    async def run():
        c = metrics.counter("test_metrics_server_hits", "test counter")
        c.inc(3)
        srv = MetricsServer()
        port = await srv.serve("127.0.0.1", 0)
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.get(f"http://127.0.0.1:{port}/metrics") as r:
                    text = await r.text()
                    assert "test_metrics_server_hits_total 3" in text
                async with sess.get(f"http://127.0.0.1:{port}/debug/stacks") as r:
                    assert "thread" in await r.text()
                async with sess.get(f"http://127.0.0.1:{port}/debug/tasks") as r:
                    assert r.status == 200
                async with sess.get(f"http://127.0.0.1:{port}/healthy") as r:
                    assert (await r.json())["ok"]
        finally:
            await srv.close()

    run_async(run())


class TestParseLabeledSamples:
    def test_parses_only_the_named_metric(self):
        from dragonfly2_tpu.pkg.metrics import parse_labeled_samples

        text = "\n".join([
            "# HELP x_total doc",
            "# TYPE x_total counter",
            'x_total{locality="intra"} 12.0',
            'x_total{locality="cross",other="y"} 3',
            'x_created{locality="intra"} 1.7e+09',
            'x_total_more{locality="intra"} 99',
            "no_labels_total 5",
        ])
        got = parse_labeled_samples(text, "x_total", "locality")
        assert got == {"intra": 12, "cross": 3}


class TestRangeNormalizeHeader:
    def test_canonicalizes_equivalent_spans(self):
        from dragonfly2_tpu.pkg.piece import Range

        for raw in ("0-65535", "bytes=0-65535", " 0 - 65535 ",
                    "bytes=000-65535"):
            assert Range.normalize_header(raw) == "bytes=0-65535", raw
        assert Range.normalize_header("5-") == "bytes=5-"
        assert Range.normalize_header("") == ""

    def test_rejects_malformed(self):
        import pytest

        from dragonfly2_tpu.pkg.piece import Range

        for bad in ("10-5", "-1024", "nonsense", "1,2-3"):
            with pytest.raises(ValueError):
                Range.normalize_header(bad)


class TestRangeNormalizeProperties:
    def test_idempotent_and_parse_equivalent(self):
        pytest.importorskip(
            "hypothesis",
            reason="property tests need hypothesis (absent in slim images)")
        from hypothesis import given, settings, strategies as st_h

        from dragonfly2_tpu.pkg.piece import Range

        @settings(max_examples=200, deadline=None)
        @given(a=st_h.integers(0, 1 << 40), span=st_h.integers(0, 1 << 30),
               pad=st_h.sampled_from(["", " ", "0", "00"]),
               prefix=st_h.sampled_from(["", "bytes="]))
        def prop(a, span, pad, prefix):
            raw = f"{prefix}{pad}{a}-{a + span}"
            norm = Range.normalize_header(raw)
            # Idempotent: canonical form is a fixed point.
            assert Range.normalize_header(norm) == norm
            # Parse-equivalent: the canonical header selects the same
            # bytes as the raw input.
            r1 = Range.parse_http(raw)
            r2 = Range.parse_http(norm)
            assert (r1.start, r1.length) == (r2.start, r2.length)
            assert norm == f"bytes={a}-{a + span}"

        prop()
