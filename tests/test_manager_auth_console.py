"""Fine RBAC, OAuth sign-in, embedded console, profiling endpoints.

Reference: manager/permission/rbac/rbac.go (casbin role policies),
manager/auth (oauth2 providers), manager console submodule,
cmd/dependency/dependency.go:95-114 (pprof endpoints).
"""

from __future__ import annotations

import asyncio

import aiohttp
from aiohttp import web

from dragonfly2_tpu.manager.rest import RestServer
from dragonfly2_tpu.manager.service import ManagerService


async def _start_rest(svc: ManagerService) -> tuple[RestServer, int]:
    rest = RestServer(svc)
    port = await rest.serve("127.0.0.1", 0)
    return rest, port


async def _signin(http, port, name, password) -> str:
    async with http.post(f"http://127.0.0.1:{port}/api/v1/users/signin",
                         json={"name": name, "password": password}) as r:
        assert r.status == 200, await r.text()
        return (await r.json())["token"]


def test_rbac_custom_role_policies(run_async):
    """A custom role grants exactly its policies: job-operator can manage
    jobs but only read schedulers; guests stay read-only everywhere."""
    async def run():
        svc = ManagerService()
        rest, port = await _start_rest(svc)
        try:
            async with aiohttp.ClientSession() as http:
                root = await _signin(http, port, "root", "dragonfly")
                h_root = {"Authorization": f"Bearer {root}"}

                # Root defines the role and creates an operator user.
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/roles",
                        json={"role": "job-operator", "object": "jobs",
                              "action": "*"}, headers=h_root) as r:
                    assert r.status == 200
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/users/signup",
                        json={"name": "op", "password": "pw"}) as r:
                    uid = (await r.json())["id"]
                async with http.put(
                        f"http://127.0.0.1:{port}/api/v1/users/{uid}/roles/job-operator",
                        headers=h_root) as r:
                    assert r.status == 200, await r.text()

                # Re-signin picks up the new role.
                op = await _signin(http, port, "op", "pw")
                h_op = {"Authorization": f"Bearer {op}"}
                # Can create jobs...
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/jobs",
                        json={"type": "preheat",
                              "args": {"type": "file", "url": "http://x/y"}},
                        headers=h_op) as r:
                    assert r.status == 200, await r.text()
                # ...can read schedulers (guest role came with signup)...
                async with http.get(
                        f"http://127.0.0.1:{port}/api/v1/schedulers",
                        headers=h_op) as r:
                    assert r.status == 200
                # ...but cannot create scheduler clusters.
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/scheduler-clusters",
                        json={"name": "x"}, headers=h_op) as r:
                    assert r.status == 403
                # Revoking the role closes the jobs door again.
                async with http.delete(
                        f"http://127.0.0.1:{port}/api/v1/users/{uid}/roles/job-operator",
                        headers=h_root) as r:
                    assert r.status == 200
                op2 = await _signin(http, port, "op", "pw")
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/jobs",
                        json={"type": "preheat", "args": {}},
                        headers={"Authorization": f"Bearer {op2}"}) as r:
                    assert r.status == 403
        finally:
            await rest.close()

    run_async(run())


def test_oauth_flow_against_fake_provider(run_async):
    """Full authorization-code flow against an in-process provider:
    authorize URL → code → token exchange → user info → local user with a
    session token."""
    async def run():
        codes = {"good-code": {"id": 4242, "email": "a@b.c"}}

        async def token_ep(request: web.Request) -> web.Response:
            form = await request.post()
            if form["code"] in codes and form["client_secret"] == "s3cr3t":
                return web.json_response({"access_token": "at-xyz"})
            return web.json_response({}, status=400)

        async def userinfo_ep(request: web.Request) -> web.Response:
            if request.headers.get("Authorization") == "Bearer at-xyz":
                return web.json_response(codes["good-code"])
            return web.json_response({}, status=401)

        app = web.Application()
        app.router.add_post("/token", token_ep)
        app.router.add_get("/userinfo", userinfo_ep)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        pport = site._server.sockets[0].getsockname()[1]

        svc = ManagerService()
        rest, port = await _start_rest(svc)
        try:
            async with aiohttp.ClientSession() as http:
                root = await _signin(http, port, "root", "dragonfly")
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/oauth",
                        json={"name": "fakehub", "client_id": "cid",
                              "client_secret": "s3cr3t",
                              "redirect_url": "http://localhost/cb",
                              "auth_url": f"http://127.0.0.1:{pport}/authorize",
                              "token_url": f"http://127.0.0.1:{pport}/token",
                              "user_info_url": f"http://127.0.0.1:{pport}/userinfo"},
                        headers={"Authorization": f"Bearer {root}"}) as r:
                    assert r.status == 200, await r.text()

                async with http.get(
                        f"http://127.0.0.1:{port}/api/v1/users/signin/oauth/fakehub") as r:
                    assert r.status == 200
                    redirect = (await r.json())["redirect_url"]
                assert redirect.startswith(f"http://127.0.0.1:{pport}/authorize?")
                state = redirect.split("state=")[1].split("&")[0]

                async with http.get(
                        f"http://127.0.0.1:{port}/api/v1/oauth/fakehub/callback",
                        params={"code": "good-code", "state": state}) as r:
                    assert r.status == 200, await r.text()
                    token = (await r.json())["token"]
                ident = svc.verify_token(token)
                assert ident and ident["name"] == "oauth-fakehub-4242"

                # Replayed state is rejected.
                async with http.get(
                        f"http://127.0.0.1:{port}/api/v1/oauth/fakehub/callback",
                        params={"code": "good-code", "state": state}) as r:
                    assert r.status == 401
        finally:
            await rest.close()
            await runner.cleanup()

    run_async(run())


def test_console_served_and_lists_resources(run_async):
    async def run():
        svc = ManagerService()
        rest, port = await _start_rest(svc)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(f"http://127.0.0.1:{port}/") as r:
                    assert r.status == 200
                    body = await r.text()
                assert "dragonfly2-tpu console" in body
                assert "scheduler-clusters" in body
        finally:
            await rest.close()

    run_async(run())


def test_profiling_endpoints(run_async):
    from dragonfly2_tpu.pkg.metrics_server import MetricsServer

    async def run():
        ms = MetricsServer()
        port = await ms.serve("127.0.0.1", 0)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{port}/debug/profile",
                        params={"seconds": "0.2"}) as r:
                    assert r.status == 200
                    assert "cumulative" in await r.text()
                # First heap call arms tracemalloc, second snapshots.
                async with http.get(f"http://127.0.0.1:{port}/debug/heap") as r:
                    assert r.status == 200
                _ = bytearray(2 << 20)  # allocate something traceable
                async with http.get(f"http://127.0.0.1:{port}/debug/heap") as r:
                    text = await r.text()
                    assert "traced current=" in text
        finally:
            await ms.close()

    run_async(run())


def test_reset_password_root_or_self_only(run_async):
    """A role granted (users, *) must NOT reset other users' passwords —
    that grant would otherwise escalate to root takeover. Root and the
    user themself may (ADVICE r2, manager/rest.py _reset_password)."""
    async def run():
        svc = ManagerService()
        rest, port = await _start_rest(svc)
        try:
            async with aiohttp.ClientSession() as http:
                root = await _signin(http, port, "root", "dragonfly")
                h_root = {"Authorization": f"Bearer {root}"}
                root_id = svc.db.find("users", name="root")["id"]

                # A user-manager role with full users access.
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/roles",
                        json={"role": "user-mgr", "object": "users",
                              "action": "*"}, headers=h_root) as r:
                    assert r.status == 200
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/users/signup",
                        json={"name": "mgr", "password": "pw"}) as r:
                    mgr_id = (await r.json())["id"]
                async with http.put(
                        f"http://127.0.0.1:{port}/api/v1/users/{mgr_id}/roles/user-mgr",
                        headers=h_root) as r:
                    assert r.status == 200

                mgr = await _signin(http, port, "mgr", "pw")
                h_mgr = {"Authorization": f"Bearer {mgr}"}
                # Cannot reset root's password.
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/users/{root_id}/reset_password",
                        json={"new_password": "owned"}, headers=h_mgr) as r:
                    assert r.status == 403
                # Can reset their own.
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/users/{mgr_id}/reset_password",
                        json={"new_password": "pw2"}, headers=h_mgr) as r:
                    assert r.status == 200, await r.text()
                await _signin(http, port, "mgr", "pw2")
                # Root can reset anyone's.
                async with http.post(
                        f"http://127.0.0.1:{port}/api/v1/users/{mgr_id}/reset_password",
                        json={"new_password": "pw3"}, headers=h_root) as r:
                    assert r.status == 200
                await _signin(http, port, "mgr", "pw3")
        finally:
            await rest.close()

    run_async(run())


def test_console_write_paths(run_async):
    """The console's mutation fetch paths (create cluster, trigger
    preheat, create user, grant role) work for root and are RBAC-denied
    for guests — VERDICT r2 item 9 (reference console is full CRUD)."""
    async def run():
        from dragonfly2_tpu.manager.console import INDEX_HTML

        # The console page actually wires these paths.
        for needle in ("scheduler-clusters", "jobs", "users/signup",
                       "/roles/", "createCluster", "createPreheat",
                       "grantRole"):
            assert needle in INDEX_HTML, needle

        svc = ManagerService()
        rest, port = await _start_rest(svc)
        base = f"http://127.0.0.1:{port}/api/v1"
        try:
            async with aiohttp.ClientSession() as http:
                root = await _signin(http, port, "root", "dragonfly")
                h_root = {"Authorization": f"Bearer {root}"}

                # create cluster (console createCluster path)
                async with http.post(f"{base}/scheduler-clusters",
                                     json={"name": "pod-b"},
                                     headers=h_root) as r:
                    assert r.status == 200, await r.text()
                    cluster = await r.json()
                assert cluster["name"] == "pod-b"

                # trigger preheat (console createPreheat path)
                async with http.post(
                        f"{base}/jobs",
                        json={"type": "preheat",
                              "args": {"type": "file", "url": "http://o/b"}},
                        headers=h_root) as r:
                    assert r.status == 200, await r.text()
                    job = await r.json()
                assert job["type"] == "preheat"

                # create user + grant role (console createUser/grantRole)
                async with http.post(f"{base}/users/signup",
                                     json={"name": "op2", "password": "pw"},
                                     headers=h_root) as r:
                    uid = (await r.json())["id"]
                async with http.post(f"{base}/roles",
                                     json={"role": "ops", "object": "jobs",
                                           "action": "*"},
                                     headers=h_root) as r:
                    assert r.status == 200
                async with http.put(f"{base}/users/{uid}/roles/ops",
                                    headers=h_root) as r:
                    assert r.status == 200, await r.text()

                # Guests (console signed in as a guest) get 403 on writes.
                guest = await _signin(http, port, "op2", "pw")
                h_guest = {"Authorization": f"Bearer {guest}"}
                async with http.post(f"{base}/scheduler-clusters",
                                     json={"name": "nope"},
                                     headers=h_guest) as r:
                    assert r.status == 403
                async with http.put(f"{base}/users/{uid}/roles/root",
                                    headers=h_guest) as r:
                    assert r.status == 403
        finally:
            await rest.close()

    run_async(run())
