"""Dry-run validation of the k8s deployment (deploy/k8s).

The reference ships compose + helm role wiring
(/root/reference/deploy/docker-compose/docker-compose.yaml:51-93,
hack/install-e2e-test.sh); this validates the same invariants for the TPU
nodepool manifests without a cluster: YAML parses, every role is present,
the cross-role addresses (scheduler → manager, daemons → scheduler ring)
agree with the Services that serve them, and the daemon's ConfigMap ports
match its advertised container ports.
"""

from __future__ import annotations

import glob
import os

import yaml

K8S_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "deploy", "k8s")


def _load_all() -> list[dict]:
    docs = []
    for path in sorted(glob.glob(os.path.join(K8S_DIR, "*.yaml"))):
        with open(path) as f:
            for doc in yaml.safe_load_all(f):
                if doc:
                    docs.append(doc)
    return docs


def _by_kind(docs, kind):
    return [d for d in docs if d.get("kind") == kind]


def _named(docs, kind, name):
    for d in _by_kind(docs, kind):
        if d["metadata"]["name"] == name:
            return d
    raise AssertionError(f"no {kind}/{name}")


def _container(doc, name=None):
    cs = doc["spec"]["template"]["spec"]["containers"]
    return cs[0] if name is None else next(c for c in cs if c["name"] == name)


def _service_ports(svc) -> dict[str, int]:
    return {p["name"]: p["port"] for p in svc["spec"]["ports"]}


class TestManifests:
    def setup_method(self):
        self.docs = _load_all()

    def test_all_roles_present(self):
        kinds = {(d["kind"], d.get("metadata", {}).get("name"))
                 for d in self.docs}
        for want in [("Namespace", "dragonfly-system"),
                     ("Deployment", "manager"),
                     ("StatefulSet", "scheduler"),
                     ("StatefulSet", "seed-peer"),
                     ("DaemonSet", "daemon"),
                     ("Service", "manager"),
                     ("Service", "scheduler"),
                     ("Service", "seed-peer"),
                     ("ConfigMap", "daemon-config")]:
            assert want in kinds, f"missing {want}"

    def test_everything_namespaced(self):
        for d in self.docs:
            if d["kind"] in ("Namespace", "Kustomization"):
                continue
            assert d["metadata"].get("namespace") == "dragonfly-system", (
                d["kind"], d["metadata"]["name"])

    def test_scheduler_points_at_manager_service(self):
        sched = _named(self.docs, "StatefulSet", "scheduler")
        args = _container(sched)["args"]
        manager_ref = args[args.index("--manager") + 1]
        host, _, port = manager_ref.partition(":")
        svc = _named(self.docs, "Service", "manager")
        assert host == svc["metadata"]["name"]
        assert int(port) in _service_ports(svc).values()

    def test_daemons_point_at_scheduler_ring(self):
        svc = _named(self.docs, "Service", "scheduler")
        assert svc["spec"].get("clusterIP") == "None", "ring needs pod DNS"
        sched = _named(self.docs, "StatefulSet", "scheduler")
        replicas = sched["spec"]["replicas"]
        drpc_port = _service_ports(svc)["drpc"]
        for role, kind in [("seed-peer", "StatefulSet"),
                           ("daemon", "DaemonSet")]:
            args = _container(_named(self.docs, kind, role))["args"]
            ring = args[args.index("--scheduler") + 1].split(",")
            assert len(ring) == replicas, (role, ring)
            for i, member in enumerate(ring):
                host, _, port = member.partition(":")
                assert host.startswith(f"scheduler-{i}.scheduler"), member
                assert int(port) == drpc_port, member

    def test_daemon_config_ports_match_container_ports(self):
        cm = _named(self.docs, "ConfigMap", "daemon-config")
        cfg = yaml.safe_load(cm["data"]["daemon.yaml"])
        ds = _named(self.docs, "DaemonSet", "daemon")
        ports = {p["name"]: p for p in _container(ds)["ports"]}
        assert cfg["download"]["peer_port"] == ports["peer"]["containerPort"]
        assert cfg["upload"]["port"] == ports["upload"]["containerPort"]
        # hostNetwork peers: hostPort must equal containerPort.
        for p in ports.values():
            assert p.get("hostPort", p["containerPort"]) == p["containerPort"]
        assert ds["spec"]["template"]["spec"].get("hostNetwork") is True

    def test_daemon_config_is_loadable_by_daemon(self):
        from dragonfly2_tpu.daemon.config import DaemonConfig

        cm = _named(self.docs, "ConfigMap", "daemon-config")
        cfg = DaemonConfig.from_dict(yaml.safe_load(cm["data"]["daemon.yaml"]))
        assert cfg.download.peer_port == 65000
        assert cfg.upload.port == 65002
        assert cfg.tpu_sink.enabled is True
        args = _container(_named(self.docs, "DaemonSet", "daemon"))["args"]
        assert args[args.index("--config") + 1] == "/etc/dragonfly/daemon.yaml"

    def test_daemon_pinned_to_tpu_nodepool(self):
        ds = _named(self.docs, "DaemonSet", "daemon")
        spec = ds["spec"]["template"]["spec"]
        assert any("tpu" in str(v) for v in
                   (spec.get("nodeSelector") or {}).values())
        assert any("tpu" in (t.get("key") or "")
                   for t in spec.get("tolerations") or [])

    def test_sqlite_owners_never_scale_past_their_storage(self):
        mgr = _named(self.docs, "Deployment", "manager")
        assert mgr["spec"]["replicas"] == 1
        assert mgr["spec"]["strategy"]["type"] == "Recreate"
        seed = _named(self.docs, "StatefulSet", "seed-peer")
        assert seed["spec"].get("volumeClaimTemplates"), \
            "seeds need per-pod stores"

    def test_kustomization_lists_every_file(self):
        kust = [d for d in self.docs if d.get("kind") == "Kustomization"]
        assert kust, "kustomization.yaml missing"
        listed = set(kust[0]["resources"])
        have = {os.path.basename(p)
                for p in glob.glob(os.path.join(K8S_DIR, "*.yaml"))}
        assert listed == have - {"kustomization.yaml"}, (listed, have)
