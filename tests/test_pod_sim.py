"""Pod-scale scheduler simulation (BASELINE config #5 at test scale).

96 simulated hosts across 6 slices with real topology labels drive one
task through the scheduler; asserts origin economy (~1 fetch), engaged
ICI locality (same-slice parent picks far above the random base rate —
benchmarks/pod_sim_bench.py publishes the 256-host numbers), schedule
latency, and event-loop stall bounds.
"""

from __future__ import annotations

import asyncio

import sys

from benchmarks.pod_sim_bench import (
    check,
    check_churn,
    latency_budget_ms,
    run_sim,
)


def test_pod_sim_96_hosts(run_async):
    async def body():
        # One retry: the sim asserts SCHEDULING behavior, but its timing
        # bounds can trip under an unrelated CPU spike on this shared
        # 1-core host (background benches, sibling tests).
        for attempt in range(2):
            try:
                result = await run_sim(96, piece_latency_s=0.001,
                                       arrival_window_s=0.5)
                check(result)
                assert result["schedule_p99_ms"] < \
                    latency_budget_ms(result, 1000), result
                return
            except AssertionError:
                if attempt:
                    raise

    run_async(body(), timeout=240)


def test_pod_sim_1024_hosts_sustained_churn(run_async):
    """Pod scale (1024 hosts / 64 slices) under SUSTAINED churn: three
    different slices die at staggered times, each replaced by a straggler
    wave. Origin stays one copy, no straggler gets a dead parent, healthy
    slices keep ICI locality, the loop absorbs a 1024-register storm
    without stalling, and the TTL sweep drains all ~1100 peers/hosts
    afterwards (VERDICT r04 item 5; measured p50 1.2 ms / p99 6.2 ms /
    lag 7.8 ms / RSS +5 MiB on the 1-core CI host). Latency bounds are
    budgeted from the run's own observed per-op cost and ambient loop lag
    (latency_budget_ms) — fixed wall-clock bounds flaked under full-suite
    contention (failed all 3 retries in round 5)."""

    async def body():
        for attempt in range(3):   # see test_pod_sim_96_hosts; the 1024-host
            # storm is the most load-sensitive test in the suite, so give
            # an external CPU spike time to pass between attempts.
            try:
                result = await run_sim(1024, piece_latency_s=0.001,
                                       arrival_window_s=0.5, churn=True,
                                       churn_waves=3)
                check_churn(result)
                assert result["schedule_p99_ms"] < \
                    latency_budget_ms(result, 2000), result
                return
            except AssertionError:
                if attempt == 2:
                    raise
                await asyncio.sleep(3)

    run_async(body(), timeout=360)


def test_pod_sim_churn_slice_kill_and_stragglers(run_async):
    """Kill a whole slice mid-fan-out; a straggler wave re-joins that
    slice late. Origin stays ~one copy, no straggler is handed a dead
    parent, and surviving slices keep their ICI locality."""

    async def body():
        for attempt in range(2):   # see test_pod_sim_96_hosts
            try:
                result = await run_sim(96, piece_latency_s=0.001,
                                       arrival_window_s=0.5, churn=True)
                check_churn(result)
                return
            except AssertionError:
                if attempt:
                    raise

    run_async(body(), timeout=240)
