"""Pod-scale scheduler simulation (BASELINE config #5 at test scale).

96 simulated hosts across 6 slices with real topology labels drive one
task through the scheduler; asserts origin economy (~1 fetch), engaged
ICI locality (same-slice parent picks far above the random base rate —
benchmarks/pod_sim_bench.py publishes the 256-host numbers), schedule
latency, and event-loop stall bounds.

Behavioral invariants (origin fetches, dead-parent handouts, GC drain)
assert UNCONDITIONALLY — they are load-independent. Timing bounds
(p99/loop-lag) assert only when the run's own ambient-contention
measurement says they were meaningful (``timing_assertable``); under
full-suite CPU contention they are recorded, not asserted — the
dedicated bench, which runs alone, always asserts both (round-5 verdict:
the old retry-the-whole-body loop converted suite-load flake into CI
noise without ever isolating a real scheduler regression).
"""

from __future__ import annotations

import asyncio

import sys

import pytest

from benchmarks.pod_sim_bench import (
    check_behavior,
    check_churn_behavior,
    check_restart_behavior,
    check_timing,
    latency_budget_ms,
    run_sim,
    timing_assertable,
)


def _assert_or_record_timing(result: dict, idle_budget_ms: float) -> None:
    """Timing bounds, gated on observed host load: a contended run prints
    the numbers (visible in -rP / failure triage) instead of failing on
    its neighbors' CPU usage."""
    if timing_assertable(result):
        check_timing(result)
        assert result["schedule_p99_ms"] < \
            latency_budget_ms(result, idle_budget_ms), result
    else:
        print(f"pod-sim timing recorded, not asserted (host slowdown "
              f"{result.get('loop_lag_p50_ms', 0.0):.1f}ms ambient lag): "
              f"p99={result.get('schedule_p99_ms')}ms "
              f"max_lag={result.get('max_loop_lag_ms')}ms",
              file=sys.stderr)


def test_pod_sim_96_hosts(run_async):
    async def body():
        result = await run_sim(96, piece_latency_s=0.001,
                               arrival_window_s=0.5)
        check_behavior(result)
        _assert_or_record_timing(result, 1000)

    run_async(body(), timeout=240)


def test_pod_sim_1024_hosts_sustained_churn(run_async):
    """Pod scale (1024 hosts / 64 slices) under SUSTAINED churn: three
    different slices die at staggered times, each replaced by a straggler
    wave. Origin stays one copy, no straggler gets a dead parent, healthy
    slices keep ICI locality, and the TTL sweep drains all ~1100
    peers/hosts afterwards (VERDICT r04 item 5; measured p50 1.2 ms /
    p99 6.2 ms / lag 7.8 ms / RSS +5 MiB on the 1-core CI host). Loop-lag
    and p99 assert only when the host was quiet enough for the numbers to
    mean anything (timing_assertable) — the round-5 full-suite flake was
    exactly these bounds tripping on sibling-test CPU spikes."""

    async def body():
        result = await run_sim(1024, piece_latency_s=0.001,
                               arrival_window_s=0.5, churn=True,
                               churn_waves=3)
        check_churn_behavior(result)
        _assert_or_record_timing(result, 2000)

    run_async(body(), timeout=360)


def test_pod_sim_churn_with_scheduler_restart(run_async):
    """Churn + a mid-sim scheduler crash/restore (ISSUE 9): the service
    is snapshot-flushed and replaced mid-fan-out; every live peer
    re-registers with resume state. Completion holds, every resume
    answer is normal_task (no origin storm), the restored service's view
    of each peer's landed set covers reality (zero re-downloaded landed
    bytes), and origin economy + GC drain still hold."""

    async def body():
        result = await run_sim(96, piece_latency_s=0.002,
                               arrival_window_s=0.5, churn=True,
                               restart=True)
        check_churn_behavior(result)
        check_restart_behavior(result)
        # No timing asserts on restart runs: the crash window (restore +
        # whole-fleet re-register) is a deliberate stall, not a
        # pathology — behavioral invariants are the contract here.

    run_async(body(), timeout=240)


@pytest.mark.slow
def test_pod_sim_4096_hosts_churn_restart(run_async):
    """The 4k acceptance sim (config5_pod_sim_churn_4k's geometry at
    test cadence): 4096 hosts / 256 slices, three slices die at
    staggered times with straggler waves, and the scheduler restarts
    mid-sim. The 1024-host variant's load-independent invariants are
    promoted wholesale (satellite 5) plus the restart invariants; timing
    is recorded, never asserted (the crash window is a deliberate
    stall)."""

    async def body():
        result = await run_sim(4096, piece_latency_s=0.001,
                               arrival_window_s=1.0, churn=True,
                               churn_waves=3, restart=True)
        check_churn_behavior(result)
        check_restart_behavior(result)
        # Timing recorded, never asserted: the restart window is a
        # deliberate stall (see the bench's main()).

    run_async(body(), timeout=900)


def test_pod_sim_churn_slice_kill_and_stragglers(run_async):
    """Kill a whole slice mid-fan-out; a straggler wave re-joins that
    slice late. Origin stays ~one copy, no straggler is handed a dead
    parent, and surviving slices keep their ICI locality."""

    async def body():
        result = await run_sim(96, piece_latency_s=0.001,
                               arrival_window_s=0.5, churn=True)
        check_churn_behavior(result)
        _assert_or_record_timing(result, 1000)

    run_async(body(), timeout=240)
