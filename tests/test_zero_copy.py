"""Zero-copy data plane: hash-while-receiving, buffer reuse, and the
no-re-read guarantee.

The acceptance bar for the single-pass pipeline: piece verification on the
download path performs ZERO re-reads of landed bytes — digests stream over
the bytes as they arrive (reference Dragonfly2 pkg/digest/digest_reader.go
hashes in the reader, not off a landed copy), and the completion-time
whole-content digest is fed from the same in-memory bytes, never from a
disk read-back.
"""

from __future__ import annotations

import hashlib
import os
import random

import pytest

from dragonfly2_tpu.daemon.peer.piece_downloader import assemble_piece
from dragonfly2_tpu.daemon.peer.piece_manager import (
    PieceManager,
    PieceManagerOption,
)
from dragonfly2_tpu.pkg import digest as pkgdigest
from dragonfly2_tpu.pkg.bufpool import BufferPool
from dragonfly2_tpu.pkg.errors import DfError
from dragonfly2_tpu.source import Request, ResourceClient, Response
from dragonfly2_tpu.source import default_registry
from dragonfly2_tpu.storage.local_store import (
    LocalTaskStore,
    StorageError,
    TaskStoreMetadata,
)

PIECE = 256 * 1024
CONTENT = bytes(random.Random(5).randbytes(4 * PIECE + 12345))


class _ReadTap:
    """Counts every path that could re-read landed bytes during landing."""

    def __init__(self, monkeypatch):
        self.preads = 0
        self.piece_reads = 0
        real_pread = os.pread
        real_preadv = os.preadv

        def pread(fd, n, off):
            self.preads += 1
            return real_pread(fd, n, off)

        def preadv(fd, bufs, off):
            self.preads += 1
            return real_preadv(fd, bufs, off)

        real_read_piece = LocalTaskStore.read_piece

        def read_piece(store, num):
            self.piece_reads += 1
            return real_read_piece(store, num)

        monkeypatch.setattr(os, "pread", pread)
        monkeypatch.setattr(os, "preadv", preadv)
        monkeypatch.setattr(LocalTaskStore, "read_piece", read_piece)

    @property
    def total(self) -> int:
        return self.preads + self.piece_reads


async def _chunks(data, chunk=64 * 1024):
    view = memoryview(data)
    for off in range(0, len(data), chunk):
        yield bytes(view[off:off + chunk])


def _store(tmp_path, name, piece_size=PIECE) -> LocalTaskStore:
    return LocalTaskStore.create(
        str(tmp_path / name),
        TaskStoreMetadata(task_id=f"zc-{name}", piece_size=piece_size))


def test_p2p_verified_landing_performs_zero_store_reads(
        tmp_path, monkeypatch, run_async):
    """The peer download path: parent-advertised digests verify against
    the hash computed WHILE the body streamed — landing touches the
    store's write path only."""

    async def run():
        piece_count = (len(CONTENT) + PIECE - 1) // PIECE
        digests = [
            f"crc32c:{pkgdigest.crc32c(CONTENT[n * PIECE:(n + 1) * PIECE]):08x}"
            for n in range(piece_count)]
        store = _store(tmp_path, "p2p")
        store.update_task(content_length=len(CONTENT),
                          total_piece_count=piece_count)
        tap = _ReadTap(monkeypatch)
        for n in range(piece_count):
            piece = CONTENT[n * PIECE:(n + 1) * PIECE]
            chunks, size, received = await assemble_piece(
                _chunks(piece), len(piece), digests[n])
            rec = store.write_piece_chunks(n, chunks, received,
                                           expected_digest=digests[n])
            assert rec.size == size == len(piece)
            assert rec.digest == digests[n]
        assert tap.total == 0, \
            f"verified landing re-read landed bytes {tap.total} times"
        # Every piece carries its verified-against digest: the certified
        # completion skip engages with zero additional reads.
        store.certified_digests = dict(enumerate(digests))
        assert store.pieces_all_digest_verified()
        assert tap.total == 0
        # Sanity OUTSIDE the landing window: the bytes on disk are real.
        monkeypatch.undo()
        assert store.read_range(0, len(CONTENT)) == CONTENT
        store.destroy()

    run_async(run())


def test_p2p_wrong_body_rejected_before_commit(tmp_path, run_async):
    """Hash-while-receiving must still fail a corrupt body exactly like
    the in-store verify did: coded error, nothing recorded."""

    async def run():
        store = _store(tmp_path, "bad")
        good = CONTENT[:PIECE]
        want = f"crc32c:{pkgdigest.crc32c(good):08x}"
        corrupt = bytearray(good)
        corrupt[100] ^= 0xFF
        chunks, _size, received = await assemble_piece(
            _chunks(bytes(corrupt)), PIECE, want)
        with pytest.raises(StorageError):
            store.write_piece_chunks(0, chunks, received,
                                     expected_digest=want)
        assert 0 not in store.metadata.pieces
        # Non-crc algorithms stream their digest during receive and are
        # refused by comparison at the same commit point.
        md5_want = str(pkgdigest.hash_bytes("md5", good))
        chunks, _size, received = await assemble_piece(
            _chunks(bytes(corrupt)), PIECE, md5_want)
        assert received and received != md5_want
        with pytest.raises(StorageError):
            store.write_piece_chunks(0, chunks, received,
                                     expected_digest=md5_want)
        assert 0 not in store.metadata.pieces
        # Undersized and oversized bodies are coded failures too.
        with pytest.raises(DfError):
            await assemble_piece(_chunks(good[:100]), PIECE, want)
        with pytest.raises(DfError):
            await assemble_piece(_chunks(good + b"x"), PIECE, want)
        store.destroy()

    run_async(run())


class _MemClient(ResourceClient):
    def __init__(self, content):
        self.content = content

    async def download(self, request: Request) -> Response:
        data = self.content
        status = 200
        rng = request.header.get("Range")
        if rng:
            from dragonfly2_tpu.pkg.piece import Range

            r = Range.parse_http(rng, len(data))
            data = data[r.start:r.start + r.length]
            status = 206
        return Response(_chunks(data), status=status,
                        content_length=len(data), support_range=True)

    async def get_content_length(self, request):
        return len(self.content)

    async def is_support_range(self, request):
        return True

    async def probe(self, request):
        return len(self.content), True


def test_backsource_completion_digest_needs_no_disk_readback(
        tmp_path, monkeypatch, run_async):
    """Sequential back-to-source: per-piece digests stream over the wire
    chunks, and the completion whole-content sha256 is fed the same
    in-memory bytes at commit time — download + validate_digest with ZERO
    reads of the data file (the old pipeline re-read every committed
    piece through the prefix hasher)."""

    async def run():
        default_registry().register("memzc", _MemClient(CONTENT))
        sha = hashlib.sha256(CONTENT).hexdigest()
        store = _store(tmp_path, "origin")
        pm = PieceManager(PieceManagerOption(concurrency=1))
        tap = _ReadTap(monkeypatch)
        store.start_prefix_hasher(f"sha256:{sha}")
        ph = store._prefix_hasher
        assert ph is not None
        await pm.download_source(store, "memzc://origin/blob")
        assert store.validate_digest(f"sha256:{sha}") == f"sha256:{sha}"
        assert tap.total == 0 and ph.disk_reads == 0, \
            (tap.preads, tap.piece_reads, ph.disk_reads)
        monkeypatch.undo()
        assert store.read_range(0, len(CONTENT)) == CONTENT
        store.destroy()

    run_async(run())


def test_buffer_pool_recycles_and_refuses_double_release():
    pool = BufferPool(max_retained_bytes=1 << 20)
    a = pool.acquire(1000)
    a[:4] = b"abcd"
    backing = a.obj
    pool.release(a)
    b = pool.acquire(500)
    assert b.obj is backing, "pool did not recycle the buffer"
    with pytest.raises(ValueError):
        a[0]   # released view must not be readable
    pool.release(b)
    # Oversized buffers beyond the retention cap are dropped, not leaked.
    big = pool.acquire(2 << 20)
    pool.release(big)
    assert pool.stats()["retained_bytes"] <= 1 << 20
