"""PEX gossip: membership convergence, task possession, schedulerless P2P.

Reference: client/daemon/pex/ — memberlist gossip + per-peer task
possession broadcast so peers find each other without the scheduler
(peer_exchange.go:114, peer_pool.go).
"""

from __future__ import annotations

import asyncio
import hashlib
import random

from aiohttp import web

from dragonfly2_tpu.daemon.config import DaemonConfig
from dragonfly2_tpu.daemon.daemon import Daemon
from dragonfly2_tpu.daemon.pex import PeerExchange
from dragonfly2_tpu.pkg.piece import Range

from tests.test_p2p_e2e import daemon_config

CONTENT = bytes(random.Random(41).randbytes(3 * 1024 * 1024))
SHA = "sha256:" + hashlib.sha256(CONTENT).hexdigest()


async def _wait(predicate, timeout: float = 10.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(0.05)
    return False


def test_membership_and_possession_gossip(run_async):
    async def run():
        a = PeerExchange(ip="127.0.0.1", peer_port=1, upload_port=2,
                         gossip_interval=0.1)
        b = PeerExchange(ip="127.0.0.1", peer_port=3, upload_port=4,
                         gossip_interval=0.1)
        c = PeerExchange(ip="127.0.0.1", peer_port=5, upload_port=6,
                         gossip_interval=0.1)
        try:
            port_a = await a.start(0)
            await b.start(0, seeds=[f"127.0.0.1:{port_a}"])
            await c.start(0, seeds=[f"127.0.0.1:{port_a}"])
            # b and c learn each other transitively through a.
            assert await _wait(lambda: len(b.members) == 2 and len(c.members) == 2)

            a.add_task("t-1")
            b.add_task("t-2")
            assert await _wait(
                lambda: [m.node_id for m in c.find_holders("t-1")] == [a.node_id]
                and [m.node_id for m in c.find_holders("t-2")] == [b.node_id])
            # Possession removal gossips too (versioned, no regression).
            a.remove_task("t-1")
            assert await _wait(lambda: c.find_holders("t-1") == [])
        finally:
            await a.stop()
            await b.stop()
            await c.stop()

    run_async(run())


def test_dead_member_expires(run_async):
    async def run():
        import dragonfly2_tpu.daemon.pex as pexmod

        a = PeerExchange(ip="127.0.0.1", gossip_interval=0.05)
        b = PeerExchange(ip="127.0.0.1", gossip_interval=0.05)
        old_dead = pexmod.DEAD_AFTER
        pexmod.DEAD_AFTER = 0.5
        try:
            port_a = await a.start(0)
            await b.start(0, seeds=[f"127.0.0.1:{port_a}"])
            assert await _wait(lambda: len(a.members) == 1)
            await b.stop()
            assert await _wait(lambda: len(a.members) == 0, timeout=5.0)
        finally:
            pexmod.DEAD_AFTER = old_dead
            await a.stop()

    run_async(run())


async def _start_origin():
    hits = {"n": 0}

    async def blob(request: web.Request) -> web.Response:
        hits["n"] += 1
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(CONTENT))
            return web.Response(status=206, body=CONTENT[r.start:r.start + r.length],
                                headers={"Accept-Ranges": "bytes",
                                         "Content-Range":
                                         f"bytes {r.start}-{r.start + r.length - 1}/{len(CONTENT)}"})
        return web.Response(body=CONTENT, headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/blob", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1], hits


def _pex_daemon_config(tmp_path, name: str, seeds: list[str]) -> DaemonConfig:
    cfg = daemon_config(tmp_path, name, scheduler_port=0)
    cfg.scheduler.addrs = []            # NO scheduler: pure PEX mode
    cfg.pex.enabled = True
    cfg.pex.seeds = seeds
    return cfg


def test_schedulerless_p2p_download_via_pex(run_async, tmp_path):
    """Daemon A fetches from origin; daemon B (no scheduler) gets the same
    task from A via gossip — origin served exactly one copy."""

    async def run():
        from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest
        from dragonfly2_tpu.proto.common import UrlMeta

        runner, port, hits = await _start_origin()
        d_a = Daemon(_pex_daemon_config(tmp_path, "pex-a", []))
        await d_a.start()
        d_a.pex.gossip_interval = 0.1
        d_b = Daemon(_pex_daemon_config(
            tmp_path, "pex-b", [f"127.0.0.1:{d_a.pex.port}"]))
        await d_b.start()
        d_b.pex.gossip_interval = 0.1
        try:
            url = f"http://127.0.0.1:{port}/blob"
            req = FileTaskRequest(url=url, output=str(tmp_path / "a.bin"),
                                  meta=UrlMeta(digest=SHA))
            async for _ in d_a.task_manager.start_file_task(req):
                pass
            hits_after_a = hits["n"]
            assert hits_after_a >= 1
            task_id = req.task_id()
            # B hears about A's possession via gossip.
            assert await _wait(lambda: d_b.pex.find_holders(task_id) != [])

            req_b = FileTaskRequest(url=url, output=str(tmp_path / "b.bin"),
                                    meta=UrlMeta(digest=SHA),
                                    disable_back_source=True)
            async for _ in d_b.task_manager.start_file_task(req_b):
                pass
            assert (tmp_path / "b.bin").read_bytes() == CONTENT
            assert hits["n"] == hits_after_a  # no extra origin traffic
            # B now gossips possession as well.
            assert await _wait(
                lambda: any(m.node_id == d_b.pex.node_id
                            for m in d_a.pex.find_holders(task_id)))
        finally:
            await d_b.stop()
            await d_a.stop()
            await runner.cleanup()

    run_async(run())


def test_stale_holders_fall_back_to_source(run_async, tmp_path):
    """Regression: gossip lists a dead holder -> the download must fall
    back to origin instead of failing the task."""

    async def run():
        from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest
        from dragonfly2_tpu.daemon.pex import Member
        from dragonfly2_tpu.proto.common import UrlMeta

        runner, port, hits = await _start_origin()
        d = Daemon(_pex_daemon_config(tmp_path, "pex-stale", []))
        await d.start()
        try:
            url = f"http://127.0.0.1:{port}/blob"
            req = FileTaskRequest(url=url, output=str(tmp_path / "o.bin"),
                                  meta=UrlMeta(digest=SHA))
            # Forge possession pointing at a dead address.
            ghost = Member("ghost", "127.0.0.1", 1, peer_port=9,
                           upload_port=9)
            d.pex.members["ghost"] = ghost
            d.pex._possession["ghost"] = (1, {req.task_id()})
            async for _ in d.task_manager.start_file_task(req):
                pass
            assert (tmp_path / "o.bin").read_bytes() == CONTENT
            assert hits["n"] >= 1
        finally:
            await d.stop()
            await runner.cleanup()

    run_async(run())


def test_gossip_hmac_auth(run_async):
    """With a shared secret set, authenticated nodes converge while forged
    (secretless) datagrams are dropped — ADVICE round 1: unauthenticated
    UDP let any sender inject membership/possession state."""
    async def run():
        a = PeerExchange(ip="127.0.0.1", peer_port=1, gossip_interval=0.1,
                         secret="cluster-key")
        b = PeerExchange(ip="127.0.0.1", peer_port=2, gossip_interval=0.1,
                         secret="cluster-key")
        intruder = PeerExchange(ip="127.0.0.1", peer_port=3,
                                gossip_interval=0.1)  # no secret
        try:
            port_a = await a.start(0)
            await b.start(0, seeds=[f"127.0.0.1:{port_a}"])
            assert await _wait(lambda: len(a.members) == 1 and len(b.members) == 1)

            await intruder.start(0, seeds=[f"127.0.0.1:{port_a}"])
            intruder.add_task("forged-task")
            await asyncio.sleep(0.5)
            # Unauthenticated joins/pings never entered the cluster view.
            assert len(a.members) == 1 and len(b.members) == 1
            assert a.find_holders("forged-task") == []
            # And the intruder learned nothing either (acks are MAC'd).
            assert len(intruder.members) == 0
        finally:
            await a.stop()
            await b.stop()
            await intruder.stop()

    run_async(run())


def test_gossip_replay_rejected(run_async):
    """Sealed datagrams embed a MAC'd timestamp; a captured datagram older
    than the freshness window is dropped on receipt, so replay cannot
    resurrect departed peers or stale possession (ADVICE round 2)."""
    async def run():
        a = PeerExchange(ip="127.0.0.1", peer_port=1, gossip_interval=0.1,
                         secret="cluster-key")
        try:
            await a.start(0)
            payload = b"\x81\xa1t\xa4ping"  # any bytes; seal/authenticate only

            fresh = a._seal(payload)
            assert a._authenticate(fresh) == payload

            # Forge a datagram stamped outside the freshness window.
            import time as _t

            old_ts = int((_t.time() - a._FRESHNESS_S - 5) * 1000)
            ts = old_ts.to_bytes(a._TS_LEN, "big")
            import hashlib as _h
            import hmac as _hm

            mac = _hm.new(a.secret, ts + payload, _h.sha256).digest()[: a._MAC_LEN]
            assert a._authenticate(mac + ts + payload) is None

            # Tampered timestamp (fresh time, stale MAC) also fails.
            ts2 = int(_t.time() * 1000).to_bytes(a._TS_LEN, "big")
            assert a._authenticate(mac + ts2 + payload) is None
        finally:
            await a.stop()

    run_async(run())
