"""TPU ops + parallel plans on the virtual 8-device CPU mesh."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dragonfly2_tpu.ops.checksum import checksum_numpy, chunk_checksums  # noqa: E402
from dragonfly2_tpu.ops.hbm_sink import HBMSink  # noqa: E402
from dragonfly2_tpu.parallel.ici import (  # noqa: E402
    StripedBroadcast,
    all_gather_shards,
    bitcast_landed_bytes,
    chunked_ring_all_gather,
    make_mesh,
    replicate_to_mesh,
    ring_all_gather,
    scatter_shards,
)
from dragonfly2_tpu.parallel.topology import TpuTopology, detect_topology  # noqa: E402


class TestChecksum:
    def test_numpy_reference(self):
        s, x = checksum_numpy(b"\x01\x00\x00\x00\x02\x00\x00\x00")
        assert s == 3 and x == 3
        s, x = checksum_numpy(b"\xff\xff\xff\xff" * 2)
        assert s == (2 * 0xFFFFFFFF) % (1 << 32)
        assert x == 0

    def test_tail_padding_neutral(self):
        # Trailing zero bytes change nothing (HBM sink tail pieces).
        a = checksum_numpy(b"hello world!")
        b = checksum_numpy(b"hello world!" + b"\x00" * 8)
        assert a == b

    def test_device_matches_numpy(self):
        rng = np.random.RandomState(0)
        piece_words = 256
        n = 4
        data = rng.randint(0, 2**31, size=(n * piece_words,)).astype(np.uint32)
        sums, xors = chunk_checksums(jnp.asarray(data), piece_words)
        for i in range(n):
            piece = data[i * piece_words : (i + 1) * piece_words].tobytes()
            want_s, want_x = checksum_numpy(piece)
            assert int(sums[i]) == want_s
            assert int(xors[i]) == want_x


class TestHBMSink:
    def test_land_verify_roundtrip(self):
        rng = np.random.RandomState(1)
        content = rng.bytes(40_000)  # not piece-aligned → tail piece
        sink = HBMSink(len(content), piece_size=16_384, batch_pieces=2)
        piece = 16_384
        nums = list(range((len(content) + piece - 1) // piece))
        rng.shuffle(nums)
        for n in nums:
            sink.land_piece(n, content[n * piece : (n + 1) * piece])
        assert sink.complete()
        assert sink.verify()
        out = np.asarray(sink.as_bytes_array()).tobytes()
        assert out == content

    def test_corruption_detected(self):
        content = np.random.RandomState(2).bytes(16_384 * 2)
        sink = HBMSink(len(content), piece_size=16_384)
        sink.land_piece(0, content[:16_384])
        # Lie about the host checksum → device verify must catch it.
        sink.host_checksums[0] = (123, 456)
        sink.land_piece(1, content[16_384:])
        with pytest.raises(ValueError, match="piece 0 corrupt"):
            sink.verify()

    def test_as_tensor_bitcast(self):
        vals = np.arange(64, dtype=np.float32)
        content = vals.tobytes()
        sink = HBMSink(len(content), piece_size=64)
        for n in range(len(content) // 64):
            sink.land_piece(n, content[n * 64 : (n + 1) * 64])
        t = sink.as_tensor("float32", (8, 8))
        np.testing.assert_array_equal(np.asarray(t).reshape(-1), vals)

    def test_shard_to_mesh(self):
        mesh = make_mesh(8)
        content = np.random.RandomState(3).bytes(8 * 1024)
        sink = HBMSink(len(content), piece_size=1024)
        for n in range(8):
            sink.land_piece(n, content[n * 1024 : (n + 1) * 1024])
        sharded = sink.shard_to_mesh(mesh)
        assert len(sharded.sharding.device_set) == 8
        np.testing.assert_array_equal(
            np.asarray(sharded), np.frombuffer(content, "<u4"))

    def test_ring_replicate(self):
        # The striped broadcast's ICI leg: shard the landed content over
        # the mesh, complete the copy with the chunked ppermute ring.
        mesh = make_mesh(8)
        content = np.random.RandomState(4).bytes(8 * 1024 + 100)  # tail pad
        sink = HBMSink(len(content), piece_size=1024)
        for n in range((len(content) + 1023) // 1024):
            sink.land_piece(n, content[n * 1024:(n + 1) * 1024])
        out = sink.ring_replicate(mesh, n_chunks=3)
        assert out.sharding.is_fully_replicated
        got = np.asarray(out).view("<u1")[:len(content)].tobytes()
        assert got == content


class TestICI:
    def test_scatter_then_all_gather(self):
        mesh = make_mesh(8)
        data = np.arange(8 * 16, dtype=np.uint32)
        sharded = scatter_shards(mesh, data)
        assert len(sharded.sharding.device_set) == 8
        full = all_gather_shards(mesh, sharded)
        np.testing.assert_array_equal(np.asarray(full), data)

    def test_replicate(self):
        mesh = make_mesh(8)
        data = np.arange(32, dtype=np.float32)
        rep = replicate_to_mesh(mesh, data)
        assert rep.sharding.is_fully_replicated

    def test_ring_all_gather_matches(self):
        mesh = make_mesh(8)
        data = np.arange(8 * 8, dtype=np.uint32)
        sharded = scatter_shards(mesh, data)
        ringed = ring_all_gather(mesh, sharded)
        # Every device's logical row is the full gather.
        out = np.asarray(ringed)
        np.testing.assert_array_equal(out.reshape(8, -1)[0], data)

    def test_bitcast_landed_bytes(self):
        vals = np.arange(16, dtype=np.float32)
        words = jnp.asarray(np.frombuffer(vals.tobytes(), "<u1"))
        t = bitcast_landed_bytes(words, "float32", (4, 4))
        np.testing.assert_array_equal(np.asarray(t).reshape(-1), vals)

    def test_chunked_ring_all_gather_matches_all_gather(self):
        mesh = make_mesh(8)
        data = np.arange(8 * 24 * 3, dtype=np.uint32).reshape(8 * 24, 3)
        sharded = scatter_shards(mesh, data)
        for n_chunks in (1, 3, 4, 24, 100):
            out = chunked_ring_all_gather(mesh, sharded, n_chunks=n_chunks)
            assert out.sharding.is_fully_replicated
            np.testing.assert_array_equal(np.asarray(out), data)

    def test_striped_broadcast_pipelines_chunks(self):
        # The DCN/ICI overlap driver: chunks fed in landing order come
        # back as the full content, replicated, regardless of chunk size
        # vs mesh-size alignment.
        mesh = make_mesh(8)
        content = np.arange(101, dtype=np.uint32)
        sb = StripedBroadcast(mesh, n_chunks=2)
        for lo in range(0, 101, 17):
            sb.feed(content[lo:lo + 17])
        out = sb.result()
        np.testing.assert_array_equal(np.asarray(out), content)

    def test_striped_broadcast_empty_raises(self):
        with pytest.raises(ValueError):
            StripedBroadcast(make_mesh(8)).result()


class TestTopology:
    def test_env_detection(self, monkeypatch):
        monkeypatch.setenv("DF_TPU_SLICE", "v5p-slice-3")
        monkeypatch.setenv("DF_TPU_WORKER", "7")
        monkeypatch.setenv("DF_TPU_POD", "pod-a")
        monkeypatch.setenv("DF_ZONE", "us-east5-a")
        topo = detect_topology()
        assert topo.present
        assert topo.worker_index == 7
        assert topo.location_path() == "us-east5-a|pod-a|v5p-slice-3|w7"

    def test_apply_to_host_config(self, monkeypatch):
        from dragonfly2_tpu.daemon.config import HostOption
        from dragonfly2_tpu.parallel.topology import apply_to_host_config

        monkeypatch.setenv("DF_TPU_SLICE", "s1")
        monkeypatch.setenv("DF_TPU_WORKER", "2")
        host = HostOption()
        apply_to_host_config(host)
        assert host.tpu_slice == "s1"
        assert host.tpu_worker_index == 2
        assert host.idc == "s1"


def test_graft_entry_single_chip():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    flat, sums, xors = fn(*args)
    all_pieces = np.concatenate([np.asarray(b) for b in args])
    assert flat.shape[0] == all_pieces.size
    np.testing.assert_array_equal(np.asarray(flat),
                                  all_pieces.reshape(-1))
    # Checksums must match the host reference for each landed piece.
    for i in range(all_pieces.shape[0]):
        want_s, want_x = checksum_numpy(all_pieces[i].tobytes())
        assert int(sums[i]) == want_s
        assert int(xors[i]) == want_x


def test_graft_entry_dryrun_multichip():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_cpu_mesh_env_scrubs_accelerator_triggers(monkeypatch):
    """The dryrun subprocess env must be hermetic: no accelerator-plugin
    trigger vars, no plugin site dirs — regardless of the parent env."""
    import __graft_entry__

    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5e")
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    monkeypatch.setenv("LIBTPU_INIT_ARGS", "--x")
    monkeypatch.setenv(
        "PYTHONPATH", os.pathsep.join(["/root/.axon_site", "/srv/lib"]))
    env = __graft_entry__._cpu_mesh_env(8)
    for key in env:
        assert not key.startswith(("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU"))
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    assert "axon_site" not in env.get("PYTHONPATH", "")
    assert "/srv/lib" in env.get("PYTHONPATH", "")


def test_dryrun_survives_hanging_sitecustomize(tmp_path):
    """Round-3 regression (MULTICHIP_r03 rc=124): a sitecustomize that
    dials a wedged TPU relay whenever PALLAS_AXON_POOL_IPS is set must NOT
    wedge the CPU dryrun — the dryrun's subprocess env scrubs the trigger.

    The outer interpreter runs with -S (site disabled) so the fake
    sitecustomize cannot hang the test itself; the inner dryrun subprocess
    runs with site enabled and imports it, proving hermeticity end to end.
    """
    import subprocess
    import sys
    import sysconfig

    fake_site = tmp_path / "fake_site"
    fake_site.mkdir()
    (fake_site / "sitecustomize.py").write_text(
        "import os, time\n"
        "if os.environ.get('PALLAS_AXON_POOL_IPS'):\n"
        "    time.sleep(600)  # simulated wedged TPU relay dial\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    purelib = sysconfig.get_paths()["purelib"]
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join([str(fake_site), repo])
    env["PALLAS_AXON_POOL_IPS"] = "127.0.0.1"
    env["JAX_PLATFORMS"] = "axon"   # forces dryrun onto its subprocess path
    env.pop("XLA_FLAGS", None)
    env["GRAFT_DRYRUN_TIMEOUT"] = "150"
    code = (f"import sys; sys.path[:0] = [{repo!r}, {purelib!r}]; "
            "import __graft_entry__ as g; g.dryrun_multichip(8); "
            "print('SURVIVED')")
    proc = subprocess.run([sys.executable, "-S", "-c", code], env=env,
                          capture_output=True, text=True, timeout=200)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-2000:])
    assert "SURVIVED" in proc.stdout


def test_dryrun_survives_driver_cpu_env_with_jax_trap(tmp_path):
    """Round-4 regression (MULTICHIP_r04 rc=124): the DRIVER sets
    JAX_PLATFORMS=cpu + the device-count flag in its own env, but the
    sandbox sitecustomize (site ENABLED in the driver's parent process)
    has already armed the axon plugin, so importing jax in that parent
    dials the wedged tunnel and hangs. dryrun_multichip must therefore
    never import jax in a process it does not control — only a live,
    config-pinned CPU jax (the conftest case) may be reused in-process;
    everything else goes to a ``python -S`` child that never imports
    sitecustomize at all.

    The fake sitecustomize arms an import trap that hangs the first
    ``import jax`` — the honest analog of the wedged relay dial.
    """
    import subprocess
    import sys

    fake_site = tmp_path / "driver_site"
    fake_site.mkdir()
    (fake_site / "sitecustomize.py").write_text(
        "import os, sys, time\n"
        "if os.environ.get('PALLAS_AXON_POOL_IPS'):\n"
        "    class _Trap:\n"
        "        def find_spec(self, name, path=None, target=None):\n"
        "            if name == 'jax':\n"
        "                time.sleep(600)  # wedged tunnel dial at jax init\n"
        "            return None\n"
        "    sys.meta_path.insert(0, _Trap())\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join([str(fake_site), repo])
    env["PALLAS_AXON_POOL_IPS"] = "10.255.255.1"
    env["JAX_PLATFORMS"] = "cpu"      # the driver's own override (r04)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["GRAFT_DRYRUN_TIMEOUT"] = "70"
    # Site ENABLED in the parent — exactly how the driver runs it.
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); "
         "print('SURVIVED_R4')"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=100)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-2000:])
    assert "SURVIVED_R4" in proc.stdout


def test_land_and_checksum_verify_on_land():
    """Fused sink step: scatter + checksums OF THE LANDED BATCH (verify-on-
    land); partial batches leave other slots untouched."""
    import numpy as np
    import jax.numpy as jnp

    from dragonfly2_tpu.ops.checksum import checksum_numpy
    from dragonfly2_tpu.ops.hbm_sink import land_and_checksum

    pw = 1024
    n_slots = 8
    rng = np.random.RandomState(3)
    pieces_np = rng.randint(0, 2**31, size=(2, pw)).astype(np.uint32)
    offsets = jnp.asarray(np.array([3 * pw, 6 * pw], np.int32))
    base = np.arange(n_slots * pw, dtype=np.uint32)
    buf, sums, xors = land_and_checksum(
        jnp.asarray(base.copy()), jnp.asarray(pieces_np), offsets, pw)
    out = np.asarray(buf)
    assert np.array_equal(out[3 * pw:4 * pw], pieces_np[0])
    assert np.array_equal(out[6 * pw:7 * pw], pieces_np[1])
    assert np.array_equal(out[:3 * pw], base[:3 * pw])  # untouched slots
    for i in range(2):
        want_s, want_x = checksum_numpy(pieces_np[i].tobytes())
        assert int(np.asarray(sums)[i]) == want_s
        assert int(np.asarray(xors)[i]) == want_x


def test_hbm_sink_contiguous_runs(tmp_path):
    """flush() collapses contiguous runs into single copies and scatters
    stragglers; landed content and verification stay correct."""
    import numpy as np

    from dragonfly2_tpu.ops.hbm_sink import HBMSink

    piece_size = 4096
    total = 10 * piece_size
    rng = np.random.RandomState(5)
    blobs = [rng.bytes(piece_size) for _ in range(10)]
    sink = HBMSink(total, piece_size, batch_pieces=100)  # manual flush
    # contiguous run 0..4, straggler 7, run 8..9
    for n in (0, 1, 2, 3, 4, 7, 8, 9):
        sink.land_piece(n, blobs[n])
    sink.flush()
    out = np.asarray(sink.as_bytes_array())
    for n in (0, 1, 2, 3, 4, 7, 8, 9):
        assert out[n * piece_size:(n + 1) * piece_size].tobytes() == blobs[n], n
    # remaining pieces
    sink.land_piece(5, blobs[5])
    sink.land_piece(6, blobs[6])
    assert sink.complete()
    assert sink.verify()
    assert np.asarray(sink.as_bytes_array()).tobytes() == b"".join(blobs)


def test_hbm_sink_rejects_out_of_range_piece():
    """A stray out-of-range piece must raise, not poison a (possibly
    already drained) sink — code-review regression r3."""
    sink = HBMSink(4096, 1024)
    with pytest.raises(ValueError, match="out of range"):
        sink.land_piece(4, b"\x00" * 1024)
    with pytest.raises(ValueError, match="out of range"):
        sink.land_piece(-1, b"\x00" * 1024)


def test_hbm_sink_fragmented_gather_path():
    """Badly scrambled arrival falls back to the traced-permutation
    gather (fixed graph) — content and verification must stay exact."""
    rng = np.random.RandomState(9)
    piece = 512
    total_pieces = 64
    content = rng.bytes(piece * total_pieces - 123)  # tail piece
    sink = HBMSink(len(content), piece, batch_pieces=1)
    sink._SEGMENT_CAP = 4          # force the gather path
    nums = list(range(total_pieces))
    rng.shuffle(nums)              # every piece its own batch, scrambled
    for n in nums:
        sink.land_piece(n, content[n * piece:(n + 1) * piece])
    assert sink.complete()
    assert sink.verify()
    assert np.asarray(sink.as_bytes_array()).tobytes() == content


def test_hbm_sink_gather_path_with_missing_pieces():
    """The gather fallback zero-fills not-landed slots."""
    rng = np.random.RandomState(10)
    piece = 512
    content = rng.bytes(piece * 16)
    sink = HBMSink(len(content), piece, batch_pieces=1)
    sink._SEGMENT_CAP = 2
    for n in (0, 3, 5, 11, 2, 9):
        sink.land_piece(n, content[n * piece:(n + 1) * piece])
    out = np.asarray(sink.as_bytes_array()).tobytes()
    for n in range(16):
        got = out[n * piece:(n + 1) * piece]
        if n in (0, 3, 5, 11, 2, 9):
            assert got == content[n * piece:(n + 1) * piece], n
        else:
            assert got == b"\x00" * piece, n


def test_hbm_sink_consolidates_batches_at_scale():
    """Checkpoint-scale staging (many batches) consolidates into
    superbatches so assembly never compiles a 1000-operand concat —
    content and verification stay exact."""
    rng = np.random.RandomState(11)
    piece = 1024
    n_batches = 80            # > 2 merge groups of 32
    total_pieces = n_batches * 4
    content = rng.bytes(piece * total_pieces - 77)   # tail piece
    sink = HBMSink(len(content), piece, batch_pieces=4)
    for n in range(total_pieces):
        sink.land_piece(n, content[n * piece:(n + 1) * piece])
    # 2 supers (64 batches) + 16 recent fulls.
    assert len(sink._batches) <= 2 + 16
    assert sink.complete()
    assert sink.verify()
    assert np.asarray(sink.as_bytes_array()).tobytes() == content


class TestMultihostAssembly:
    """parallel/multihost.py on the virtual 8-device mesh: the seam from
    per-host fabric landings to one pod-global jax.Array (single-process
    here; make_array_from_single_device_arrays spans processes on a pod)."""

    def test_global_replicated_roundtrip(self):
        import numpy as np

        from dragonfly2_tpu.parallel import multihost

        mesh = multihost.global_mesh()
        content = np.arange(4096, dtype=np.uint32)
        arr = multihost.global_replicated(mesh, content)
        assert arr.shape == content.shape
        assert arr.sharding.is_fully_replicated
        np.testing.assert_array_equal(np.asarray(arr), content)

    def test_global_from_local_shards(self):
        import numpy as np

        from dragonfly2_tpu.parallel import multihost

        mesh = multihost.global_mesh()
        local = np.arange(8 * 16, dtype=np.float32).reshape(8 * 2, 8)
        arr = multihost.global_from_local_shards(mesh, local)
        assert arr.shape == local.shape  # single process: global == local
        np.testing.assert_array_equal(np.asarray(arr), local)
        # downstream consumers can re-shard without surprises
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        out = jax.jit(lambda x: x * 2,
                      out_shardings=NamedSharding(mesh, P()))(arr)
        np.testing.assert_array_equal(np.asarray(out), local * 2)

    def test_factored_mesh_and_validation(self):
        import pytest as _pytest

        from dragonfly2_tpu.parallel import multihost

        mesh = multihost.global_mesh({"dp": 2, "tp": 4})
        assert mesh.shape == {"dp": 2, "tp": 4}
        with _pytest.raises(ValueError):
            multihost.global_mesh({"dp": 3})

    def test_initialize_single_process_noop(self):
        from dragonfly2_tpu.parallel import multihost

        # Single-process runtime: must be a no-op, not an error.
        multihost.initialize_distributed()
        multihost.initialize_distributed()

    def test_global_from_local_shards_factored_mesh(self):
        """P(axis) on a factored mesh: the other axis holds replicated
        copies — the assembly must not try to split rows across it."""
        import numpy as np

        import jax
        from dragonfly2_tpu.parallel import multihost
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = multihost.global_mesh({"dp": 2, "tp": 4})
        local = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
        arr = multihost.global_from_local_shards(mesh, local, axis_name="dp")
        assert arr.shape == local.shape
        np.testing.assert_array_equal(np.asarray(arr), local)
        out = jax.jit(lambda x: x + 1,
                      out_shardings=NamedSharding(mesh, P()))(arr)
        np.testing.assert_array_equal(np.asarray(out), local + 1)
