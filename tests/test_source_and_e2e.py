"""Source clients, piece-manager back-to-source paths, and the minimum
end-to-end slice: dfget → daemon (unix drpc) → origin → store → output.

The hermetic origin is an aiohttp server with range support plus a
no-content-length endpoint (reference test fixtures: file server +
no-content-length server, hack/install-e2e-test.sh:42-60).
"""

import asyncio
import hashlib
import os
import random

import pytest
from aiohttp import web

from dragonfly2_tpu.daemon.config import DaemonConfig
from dragonfly2_tpu.daemon.daemon import Daemon
from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager, PieceManagerOption
from dragonfly2_tpu.pkg import digest as pkgdigest
from dragonfly2_tpu.pkg.errors import DfError, SourceError
from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.proto.common import UrlMeta
from dragonfly2_tpu.source import Request as SourceRequest
from dragonfly2_tpu.source import get_client
from dragonfly2_tpu.storage import StorageManager, StorageOption, TaskStoreMetadata

CONTENT = bytes(random.Random(42).randbytes(10 * 1024 * 1024))  # 10 MiB deterministic
SMALL = b"tiny payload"


async def start_origin() -> tuple[web.AppRunner, int, dict]:
    """Hermetic origin: /blob (ranged), /small, /chunked (no content length),
    /flaky (fails first N requests), and request counting."""
    stats = {"blob_gets": 0, "flaky_fails_left": 2}

    async def blob(request: web.Request) -> web.StreamResponse:
        stats["blob_gets"] += 1
        stats.setdefault("blob_ranges", []).append(
            request.headers.get("Range"))
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(CONTENT))
            data = CONTENT[r.start : r.start + r.length]
            resp = web.Response(
                status=206,
                body=data,
                headers={
                    "Content-Range": f"bytes {r.start}-{r.start + r.length - 1}/{len(CONTENT)}",
                    "Accept-Ranges": "bytes",
                },
            )
            return resp
        return web.Response(body=CONTENT, headers={"Accept-Ranges": "bytes"})

    async def small(request: web.Request) -> web.Response:
        return web.Response(body=SMALL)

    async def chunked(request: web.Request) -> web.StreamResponse:
        resp = web.StreamResponse()
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        for i in range(0, len(CONTENT) // 2, 1 << 20):
            await resp.write(CONTENT[i : i + (1 << 20)])
        await resp.write_eof()
        return resp

    async def flaky(request: web.Request) -> web.Response:
        if stats["flaky_fails_left"] > 0:
            stats["flaky_fails_left"] -= 1
            return web.Response(status=503)
        return web.Response(body=SMALL)

    app = web.Application()
    app.router.add_get("/blob", blob)
    app.router.add_get("/small", small)
    app.router.add_get("/chunked", chunked)
    app.router.add_get("/flaky", flaky)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, port, stats


class TestFileSource:
    def test_download_and_range(self, run_async, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"0123456789")

        async def body():
            client = get_client("file:///x")
            url = f"file://{p}"
            resp = await client.download(SourceRequest(url))
            assert await resp.read_all() == b"0123456789"
            resp = await client.download(SourceRequest(url, {"Range": "bytes=2-5"}))
            assert await resp.read_all() == b"2345"
            assert await client.get_content_length(SourceRequest(url)) == 10
            assert await client.is_support_range(SourceRequest(url))

        run_async(body())

    def test_list_metadata(self, run_async, tmp_path):
        (tmp_path / "a.txt").write_bytes(b"a")
        (tmp_path / "sub").mkdir()
        (tmp_path / "sub" / "b.txt").write_bytes(b"bb")

        async def body():
            client = get_client("file:///x")
            entries = await client.list_metadata(SourceRequest(f"file://{tmp_path}"))
            names = {e.name: e for e in entries}
            assert names["a.txt"].content_length == 1
            assert names["sub"].is_dir

        run_async(body())

    def test_missing_file(self, run_async, tmp_path):
        async def body():
            client = get_client("file:///x")
            with pytest.raises(SourceError):
                await client.download(SourceRequest(f"file://{tmp_path}/nope"))

        run_async(body())


class TestHTTPSource:
    def test_content_length_and_range_probe(self, run_async):
        async def body():
            runner, port, _ = await start_origin()
            try:
                client = get_client("http://x")
                url = f"http://127.0.0.1:{port}/blob"
                assert await client.get_content_length(SourceRequest(url)) == len(CONTENT)
                assert await client.is_support_range(SourceRequest(url))
                resp = await client.download(SourceRequest(url, {"Range": "bytes=0-1023"}))
                data = await resp.read_all()
                assert data == CONTENT[:1024]
            finally:
                await runner.cleanup()

        run_async(body())

    def test_404_maps_to_not_found(self, run_async):
        async def body():
            runner, port, _ = await start_origin()
            try:
                client = get_client("http://x")
                with pytest.raises(SourceError) as ei:
                    await client.download(SourceRequest(f"http://127.0.0.1:{port}/nope"))
                from dragonfly2_tpu.pkg.errors import Code

                assert ei.value.code == Code.SourceNotFound
            finally:
                await runner.cleanup()

        run_async(body())


def _store_for(tmp_path, task_id="t1"):
    sm = StorageManager(StorageOption(data_dir=str(tmp_path / "data")))
    return sm, sm.register_task(TaskStoreMetadata(task_id=task_id, url="u"))


class TestPieceManagerBackSource:
    def test_known_length_sequential(self, run_async, tmp_path):
        async def body():
            runner, port, _ = await start_origin()
            try:
                sm, store = _store_for(tmp_path)
                pm = PieceManager(PieceManagerOption(concurrency=1))
                pieces_seen = []

                async def on_piece(st, rec):
                    pieces_seen.append(rec.num)

                await pm.download_source(store, f"http://127.0.0.1:{port}/blob",
                                         on_piece=on_piece)
                assert store.is_complete()
                assert pieces_seen == sorted(pieces_seen)
                store.mark_done()
                out = tmp_path / "o.bin"
                store.store_to(str(out))
                assert hashlib.sha256(out.read_bytes()).digest() == hashlib.sha256(CONTENT).digest()
            finally:
                await runner.cleanup()

        run_async(body())

    def test_concurrent_piece_groups(self, run_async, tmp_path):
        async def body():
            runner, port, stats = await start_origin()
            try:
                sm, store = _store_for(tmp_path)
                pm = PieceManager(PieceManagerOption(concurrency=4, concurrent_min_length=1 << 20))
                await pm.download_source(store, f"http://127.0.0.1:{port}/blob")
                assert store.is_complete()
                # exactly 1 combined probe + one stream per piece group
                # (10 MiB / 4 MiB pieces = 3 groups)
                assert stats["blob_gets"] == 4
                store.mark_done()
                out = tmp_path / "o.bin"
                store.store_to(str(out))
                assert out.read_bytes() == CONTENT
            finally:
                await runner.cleanup()

        run_async(body())

    def test_concurrent_resume_skips_landed_prefix(self, run_async, tmp_path):
        """Resume economy (reference continuePieceNum,
        piece_manager.go:804-815): a partially-landed store must only
        fetch the missing tail from origin — every range request starts at
        or after the landed prefix, and landed bytes are not re-sent."""

        async def body():
            runner, port, stats = await start_origin()
            try:
                sm, store = _store_for(tmp_path)
                pm = PieceManager(PieceManagerOption(
                    concurrency=4, concurrent_min_length=1 << 20))
                piece = 4 << 20
                store.update_task(content_length=len(CONTENT),
                                  piece_size=piece,
                                  total_piece_count=3)
                for n in range(2):  # landed prefix: pieces 0,1 of 3
                    store.write_piece(n, CONTENT[n * piece:(n + 1) * piece])
                stats["blob_ranges"] = []
                await pm.download_source(store,
                                         f"http://127.0.0.1:{port}/blob")
                assert store.is_complete()
                data_ranges = [r for r in stats["blob_ranges"] if r]
                # Every data request starts at/after the landed prefix;
                # the only sub-prefix request allowed is the 1-byte probe.
                for r in data_ranges:
                    start = int(r.split("=")[1].split("-")[0])
                    assert start >= 2 * piece or r == "bytes=0-0", data_ranges
                assert any(int(r.split("=")[1].split("-")[0]) == 2 * piece
                           for r in data_ranges), data_ranges
                store.mark_done()
                out = tmp_path / "r.bin"
                store.store_to(str(out))
                assert out.read_bytes() == CONTENT
            finally:
                await runner.cleanup()

        run_async(body())

    def test_unknown_length_streaming(self, run_async, tmp_path):
        async def body():
            runner, port, _ = await start_origin()
            try:
                sm, store = _store_for(tmp_path)
                pm = PieceManager()
                await pm.download_source(store, f"http://127.0.0.1:{port}/chunked")
                assert store.is_complete()
                assert store.metadata.content_length == len(CONTENT) // 2
                store.mark_done()
                out = tmp_path / "o.bin"
                store.store_to(str(out))
                assert out.read_bytes() == CONTENT[: len(CONTENT) // 2]
            finally:
                await runner.cleanup()

        run_async(body())

    def test_ranged_task(self, run_async, tmp_path):
        async def body():
            runner, port, _ = await start_origin()
            try:
                sm, store = _store_for(tmp_path)
                pm = PieceManager(PieceManagerOption(concurrency=1))
                await pm.download_source(store, f"http://127.0.0.1:{port}/blob",
                                         content_range=Range(1024, 4096))
                assert store.is_complete()
                store.mark_done()
                out = tmp_path / "o.bin"
                store.store_to(str(out))
                assert out.read_bytes() == CONTENT[1024 : 1024 + 4096]
            finally:
                await runner.cleanup()

        run_async(body())


class TestE2ESlice:
    """BASELINE config #1: dfget single-URL download, no P2P."""

    def _daemon_config(self, tmp_path) -> DaemonConfig:
        cfg = DaemonConfig()
        cfg.work_home = str(tmp_path / "home")
        cfg.__post_init__()
        cfg.download.unix_sock = str(tmp_path / "d.sock")
        return cfg

    def test_dfget_through_daemon(self, run_async, tmp_path):
        async def body():
            runner, port, stats = await start_origin()
            daemon = Daemon(self._daemon_config(tmp_path))
            serve = asyncio.ensure_future(daemon.serve())
            await asyncio.sleep(0.1)
            try:
                from dragonfly2_tpu.client import dfget as dfget_lib

                url = f"http://127.0.0.1:{port}/blob"
                digest = "sha256:" + hashlib.sha256(CONTENT).hexdigest()
                out = tmp_path / "out.bin"
                progress = []
                result = await dfget_lib.download(
                    dfget_lib.DfgetConfig(
                        url=url, output=str(out),
                        daemon_sock=daemon.config.download.unix_sock,
                        meta=UrlMeta(digest=digest),
                        allow_source_fallback=False,
                    ),
                    on_progress=progress.append,
                )
                assert result["state"] == "done"
                assert out.read_bytes() == CONTENT
                assert result["content_length"] == len(CONTENT)
                first_gets = stats["blob_gets"]

                # Second download: served from reuse, origin untouched.
                out2 = tmp_path / "out2.bin"
                result2 = await dfget_lib.download(
                    dfget_lib.DfgetConfig(
                        url=url, output=str(out2),
                        daemon_sock=daemon.config.download.unix_sock,
                        meta=UrlMeta(digest=digest),
                        allow_source_fallback=False,
                    ),
                )
                assert result2["from_reuse"]
                assert out2.read_bytes() == CONTENT
                assert stats["blob_gets"] == first_gets
            finally:
                await daemon.stop()
                serve.cancel()
                await runner.cleanup()

        run_async(body())

    def test_dfget_digest_mismatch_fails(self, run_async, tmp_path):
        async def body():
            runner, port, _ = await start_origin()
            daemon = Daemon(self._daemon_config(tmp_path))
            serve = asyncio.ensure_future(daemon.serve())
            await asyncio.sleep(0.1)
            try:
                from dragonfly2_tpu.client import dfget as dfget_lib

                out = tmp_path / "bad.bin"
                with pytest.raises(DfError):
                    await dfget_lib.download(
                        dfget_lib.DfgetConfig(
                            url=f"http://127.0.0.1:{port}/small", output=str(out),
                            daemon_sock=daemon.config.download.unix_sock,
                            meta=UrlMeta(digest="sha256:" + "0" * 64),
                            allow_source_fallback=False,
                        ),
                    )
                assert not out.exists()
            finally:
                await daemon.stop()
                serve.cancel()
                await runner.cleanup()

        run_async(body())

    def test_daemon_restart_resumes_storage(self, run_async, tmp_path):
        async def body():
            runner, port, stats = await start_origin()
            cfg = self._daemon_config(tmp_path)
            daemon = Daemon(cfg)
            serve = asyncio.ensure_future(daemon.serve())
            await asyncio.sleep(0.1)
            from dragonfly2_tpu.client import dfget as dfget_lib

            url = f"http://127.0.0.1:{port}/blob"
            out = tmp_path / "o1.bin"
            await dfget_lib.download(
                dfget_lib.DfgetConfig(url=url, output=str(out),
                                      daemon_sock=cfg.download.unix_sock,
                                      allow_source_fallback=False))
            gets = stats["blob_gets"]
            await daemon.stop()
            serve.cancel()

            # Restart daemon over the same work home: task reloads, second
            # download reuses without touching origin.
            daemon2 = Daemon(cfg)
            serve2 = asyncio.ensure_future(daemon2.serve())
            await asyncio.sleep(0.1)
            try:
                out2 = tmp_path / "o2.bin"
                result = await dfget_lib.download(
                    dfget_lib.DfgetConfig(url=url, output=str(out2),
                                          daemon_sock=cfg.download.unix_sock,
                                          allow_source_fallback=False))
                assert result["from_reuse"]
                assert out2.read_bytes() == CONTENT
                assert stats["blob_gets"] == gets
            finally:
                await daemon2.stop()
                serve2.cancel()
                await runner.cleanup()

        run_async(body())

    def test_source_fallback_when_daemon_dead(self, run_async, tmp_path):
        async def body():
            runner, port, _ = await start_origin()
            try:
                from dragonfly2_tpu.client import dfget as dfget_lib

                out = tmp_path / "direct.bin"
                result = await dfget_lib.download(
                    dfget_lib.DfgetConfig(
                        url=f"http://127.0.0.1:{port}/small", output=str(out),
                        daemon_sock=str(tmp_path / "missing.sock"),
                    ),
                )
                assert result.get("from_source")
                assert out.read_bytes() == SMALL
            finally:
                await runner.cleanup()

        run_async(body())


class TestTruncationSafety:
    def test_short_stream_does_not_persist_trailing_piece(self, run_async, tmp_path):
        """A dropped origin connection must not record a truncated piece."""

        async def body():
            from aiohttp import web as _web

            async def truncated(request: _web.Request) -> _web.StreamResponse:
                # Claim the full length, stream 6 MiB, then kill the socket —
                # a mid-transfer connection drop.
                resp = _web.StreamResponse(
                    headers={"Content-Length": str(len(CONTENT)), "Accept-Ranges": "bytes"}
                )
                await resp.prepare(request)
                await resp.write(CONTENT[: 6 << 20])
                request.transport.close()
                return resp

            app = _web.Application()
            app.router.add_get("/trunc", truncated)
            runner = _web.AppRunner(app)
            await runner.setup()
            site = _web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            try:
                sm, store = _store_for(tmp_path)
                pm = PieceManager(PieceManagerOption(concurrency=1))
                with pytest.raises(Exception):
                    await pm.download_source(store, f"http://127.0.0.1:{port}/trunc")
                # Only full 4MiB pieces may be recorded; no truncated tail.
                for rec in store.metadata.pieces.values():
                    assert rec.size == store.metadata.piece_size
            finally:
                await runner.cleanup()

        run_async(body())


def test_limiter_pause_resume(run_async):
    from dragonfly2_tpu.pkg.ratelimit import Limiter

    async def body():
        lim = Limiter(limit=1000)
        lim.set_limit(0)  # pause
        waiter = asyncio.ensure_future(lim.wait(10))
        await asyncio.sleep(0.05)
        assert not waiter.done()
        lim.set_limit(10_000)  # resume
        await asyncio.wait_for(waiter, 2)

    run_async(body())
