"""Cross-backend CDC chunker oracle.

delta/chunker.py promises that its candidate-scan backend ladder (native
dfchunk.cc > numpy > pure python) can only change SPEED, never cut
points: min/max/forced-cut selection is shared Python, and every backend
must report identical candidate positions. This suite pins that promise
— every backend produces byte-identical chunk sequences (offsets,
lengths, sha256 digests) over adversarial content and arbitrary feed()
splits — plus the ladder's degrade path when the native library is
absent.
"""

from __future__ import annotations

import random

import pytest

from dragonfly2_tpu.delta import chunker as chk
from dragonfly2_tpu.delta.chunker import CDCParams, GearChunker

# Small geometry so a few hundred KiB exercises many cuts, min-size
# skips, and forced max-size cuts.
PARAMS = CDCParams(mask_bits=10, min_size=2 << 10, max_size=16 << 10)


def _backends():
    """(name, scan_fn) for every backend available on this box. numpy and
    python always exist in CI; native joins when the toolchain does."""
    out = [("python", chk._scan_python)]
    if chk.np is not None:
        out.append(("numpy", chk._scan_numpy))
    native = chk._native_scanner()
    if native is not None:
        out.append(("native", native))
    return out


@pytest.fixture
def force_backend(monkeypatch):
    """Returns a setter that pins the module-global scanner (GearChunker
    reads it at call time); monkeypatch restores the real selection."""

    def setit(name, fn):
        monkeypatch.setattr(chk, "_scanner", fn)
        monkeypatch.setattr(chk, "_backend_name", name)

    return setit


def _chunks_with(setit, name, fn, data, params, splits=None):
    setit(name, fn)
    g = GearChunker(params)
    if splits is None:
        g.feed(data)
    else:
        prev = 0
        for cut in splits:
            g.feed(data[prev:cut])
            prev = cut
        g.feed(data[prev:])
    g.finish()
    return [(c.offset, c.length, c.sha256) for c in g.chunks]


CASES = {
    "random": lambda: random.Random(3).randbytes(256 << 10),
    "zeros": lambda: bytes(192 << 10),
    # Repeating content: every period gets the same candidates, heavy on
    # the min-size skip logic.
    "periodic": lambda: (random.Random(5).randbytes(1 << 10)) * 200,
    # Below-min tail: ends 300 bytes after the last likely cut.
    "short_tail": lambda: random.Random(7).randbytes((64 << 10) + 300),
    # Tiny inputs around the window/min boundaries.
    "tiny": lambda: random.Random(9).randbytes(31),
    "empty": lambda: b"",
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_backends_agree_one_shot(case, force_backend):
    data = CASES[case]()
    results = {name: _chunks_with(force_backend, name, fn, data, PARAMS)
               for name, fn in _backends()}
    ref = results["python"]
    for name, got in results.items():
        assert got == ref, f"{name} diverged from python on {case}"
    # chunks exactly tile the input
    assert sum(ln for _, ln, _ in ref) == len(data)


def test_backends_agree_forced_max_cuts(force_backend):
    # mask_bits=20 over 96 KiB with max_size=8 KiB: candidates are so
    # rare that nearly every cut is a forced max-size cut.
    data = random.Random(11).randbytes(96 << 10)
    params = CDCParams(mask_bits=20, min_size=1 << 10, max_size=8 << 10)
    ref = None
    for name, fn in _backends():
        got = _chunks_with(force_backend, name, fn, data, params)
        if ref is None:
            ref = got
        assert got == ref, f"{name} diverged under forced cuts"
    assert ref and max(ln for _, ln, _ in ref) == 8 << 10


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_backends_agree_arbitrary_feed_splits(seed, force_backend):
    data = random.Random(100 + seed).randbytes(128 << 10)
    rng = random.Random(200 + seed)
    splits = sorted(rng.sample(range(1, len(data)), 40))
    one_shot = _chunks_with(
        force_backend, "python", chk._scan_python, data, PARAMS)
    for name, fn in _backends():
        got = _chunks_with(force_backend, name, fn, data, PARAMS, splits)
        assert got == one_shot, f"{name} split-dependent chunking"


def test_scan_candidates_identical_across_ctx():
    # The scan layer itself (below _emit): same candidates for every
    # backend at every context depth, including out-cap refills native
    # exercises internally.
    data = random.Random(13).randbytes(40 << 10)
    for ctx in (0, 1, 17, 31):
        for mask_bits in (6, 10, 14):
            ref = chk._scan_python(data, ctx, mask_bits)
            for name, fn in _backends():
                assert fn(data, ctx, mask_bits) == ref, (
                    f"{name} candidates differ at ctx={ctx} "
                    f"mask_bits={mask_bits}")


def test_ladder_falls_back_without_native(monkeypatch):
    # Native gone: selection lands on numpy (or python without numpy)
    # and chunking still matches the python reference.
    monkeypatch.setattr(chk, "_native_scanner", lambda: None)
    monkeypatch.setattr(chk, "_scanner", None)
    monkeypatch.setattr(chk, "_backend_name", "unset")
    monkeypatch.delenv("DF_CHUNKER_BACKEND", raising=False)
    assert chk.chunker_backend() in ("numpy", "python")
    data = random.Random(17).randbytes(64 << 10)
    g = GearChunker(PARAMS)
    g.feed(data)
    g.finish()
    monkeypatch.setattr(chk, "_scanner", chk._scan_python)
    ref = GearChunker(PARAMS)
    ref.feed(data)
    ref.finish()
    assert [(c.offset, c.length, c.sha256) for c in g.chunks] == \
        [(c.offset, c.length, c.sha256) for c in ref.chunks]
    assert g.chunks  # sanity: the fallback actually chunked


def test_backend_env_pins_rung(monkeypatch):
    monkeypatch.setattr(chk, "_scanner", None)
    monkeypatch.setattr(chk, "_backend_name", "unset")
    monkeypatch.setenv("DF_CHUNKER_BACKEND", "python")
    assert chk.chunker_backend() == "python"
    monkeypatch.setattr(chk, "_scanner", None)
    monkeypatch.setattr(chk, "_backend_name", "unset")
    monkeypatch.setenv("DF_CHUNKER_BACKEND", "numpy")
    if chk.np is not None:
        assert chk.chunker_backend() == "numpy"
