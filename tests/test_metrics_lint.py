"""Metrics-name lint: every registered family follows the
``{component}_{noun}[_{unit}][_total]`` convention and is documented in
docs/OBSERVABILITY.md.

Undocumented or misnamed telemetry rots fastest: a dashboard built on a
family nobody wrote down breaks silently on the next rename. This test
imports every metric-defining module (so the registry is fully
populated), then walks ``metrics.families()`` and fails on any family
that (a) is not snake_case, (b) has the wrong suffix discipline for its
kind, (c) starts with an unknown component, or (d) has no row in the
docs page.
"""

from __future__ import annotations

import importlib
import os
import re

import pytest

# Every module that registers a metric family. A new metric in a new
# module must be added here (the scrape tests would miss it silently
# otherwise) — grep for `metrics.counter|gauge|histogram` when in doubt.
METRIC_MODULES = (
    "dragonfly2_tpu.pkg.bufpool",
    "dragonfly2_tpu.pkg.chaos",
    "dragonfly2_tpu.pkg.flight",
    "dragonfly2_tpu.pkg.fleet",
    "dragonfly2_tpu.pkg.cluster",
    "dragonfly2_tpu.pkg.prof",
    "dragonfly2_tpu.pkg.slo",
    "dragonfly2_tpu.pkg.tracing",
    "dragonfly2_tpu.daemon.proxy",
    "dragonfly2_tpu.daemon.upload",
    "dragonfly2_tpu.daemon.objectstorage",
    "dragonfly2_tpu.daemon.peer.conductor",
    "dragonfly2_tpu.daemon.peer.task_manager",
    "dragonfly2_tpu.daemon.peer.device_sink",
    "dragonfly2_tpu.scheduler.service",
    "dragonfly2_tpu.manager.client",
    "dragonfly2_tpu.proto.reportcodec",
    "dragonfly2_tpu.qos.wfq",
    "dragonfly2_tpu.qos.admission",
    "dragonfly2_tpu.delta.chunker",
    "dragonfly2_tpu.delta.manifest",
    "dragonfly2_tpu.delta.resolver",
    "dragonfly2_tpu.storage.io_ring",
    "dragonfly2_tpu.dataset.loader",
    "dragonfly2_tpu.dataset.shard_reader",
    "dragonfly2_tpu.dataset.tar_index",
    "dragonfly2_tpu.dataset.device_feed",
)

# The documented component vocabulary (docs/OBSERVABILITY.md "Metric
# families"). Adding a component means documenting it there first.
COMPONENTS = ("bufpool", "chaos", "dataset", "delta", "device_sink",
              "fleet", "manager", "objectstorage", "peer", "proxy", "qos",
              "runtime", "scheduler", "storage", "tracing", "upload")

# Histogram families must name their unit; counters use _total; gauges
# may end in a unit but never _total. "pieces" is a unit here: batch-size
# histograms (scheduler_ingest_batch_pieces) count pieces, not time/bytes.
UNITS = ("seconds", "bytes", "ms", "pieces")

DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "OBSERVABILITY.md")

SNAKE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")


@pytest.fixture(scope="module")
def all_families():
    for mod in METRIC_MODULES:
        importlib.import_module(mod)
    from dragonfly2_tpu.pkg import metrics

    fams = metrics.families()
    assert len(fams) >= 30, "registry suspiciously small — import miss?"
    return fams


def test_names_are_snake_case(all_families):
    bad = [f["name"] for f in all_families if not SNAKE.match(f["name"])]
    assert not bad, f"non-snake_case metric names: {bad}"


def test_component_prefix_is_documented(all_families):
    bad = [f["name"] for f in all_families
           if not any(f["name"].startswith(c + "_") for c in COMPONENTS)]
    assert not bad, (
        f"metric families outside the documented component vocabulary "
        f"{COMPONENTS}: {bad} — extend docs/OBSERVABILITY.md first")


def test_suffix_discipline_per_kind(all_families):
    bad = []
    for f in all_families:
        name, kind = f["name"], f["kind"]
        if kind == "counter" and not name.endswith("_total"):
            bad.append((name, "counter must end in _total"))
        elif kind == "gauge" and name.endswith("_total"):
            bad.append((name, "gauge must not end in _total"))
        elif kind == "histogram" and not name.endswith(
                tuple(f"_{u}" for u in UNITS)):
            bad.append((name, f"histogram must end in a unit {UNITS}"))
    assert not bad, f"suffix convention violations: {bad}"


def test_every_family_documented(all_families):
    with open(DOCS) as f:
        doc = f.read()
    undocumented = [f["name"] for f in all_families
                    if f"`{f['name']}`" not in doc]
    assert not undocumented, (
        f"metric families missing from docs/OBSERVABILITY.md: "
        f"{undocumented} — every family needs a table row there")


def test_every_family_has_help_text(all_families):
    thin = [f["name"] for f in all_families if len(f["doc"]) < 10]
    assert not thin, f"metric families with no real help text: {thin}"


# --------------------------------------------------------------------- #
# Exposition round trips (OpenMetrics conformance satellite)
# --------------------------------------------------------------------- #

def test_prometheus_exposition_round_trips_families(all_families):
    """Strict-parse our own classic exposition and cross-check every
    registered family appears with # HELP/# TYPE and the right kind —
    a silent serialization bug would otherwise only surface when an
    external scraper chokes."""
    from prometheus_client import parser

    from dragonfly2_tpu.pkg import metrics

    text = metrics.render()[0].decode()
    assert "# HELP" in text and "# TYPE" in text
    parsed = {f.name: f for f in parser.text_string_to_metric_families(text)}
    for fam in all_families:
        full = f"dragonfly_tpu_{fam['name']}"
        # The parser names counters without the _total suffix.
        key = full[:-len("_total")] if fam["kind"] == "counter" else full
        assert key in parsed, f"{full} missing from exposition"
        assert parsed[key].type == fam["kind"], full
        assert parsed[key].documentation, full


def test_openmetrics_round_trip_and_label_escaping():
    """The OpenMetrics content negotiation: render with the OpenMetrics
    Accept type, parse with the STRICT OpenMetrics parser (it rejects
    missing # EOF, bad escapes, suffix violations), and recover a label
    value containing every character class the escaping rules cover —
    in an isolated registry so the process registry stays lint-clean."""
    from prometheus_client import CollectorRegistry, Counter
    from prometheus_client.openmetrics import parser as om_parser

    from dragonfly2_tpu.pkg import metrics

    reg = CollectorRegistry()
    c = Counter("scheduler_escape_probe", "Label escaping probe",
                ("note",), namespace="dragonfly_tpu", registry=reg)
    tricky = 'quote " backslash \\ newline \n tab \t end'
    c.labels(tricky).inc(3)

    body, ctype = metrics.render("application/openmetrics-text",
                                 registry=reg)
    assert "openmetrics" in ctype
    text = body.decode()
    assert text.rstrip().endswith("# EOF")
    fams = list(om_parser.text_string_to_metric_families(text))
    samples = [s for f in fams for s in f.samples
               if s.name == "dragonfly_tpu_scheduler_escape_probe_total"]
    assert samples, fams
    assert samples[0].labels["note"] == tricky
    assert samples[0].value == 3

    # The classic format negotiates too, and round-trips the same value.
    from prometheus_client import parser as classic_parser

    body, ctype = metrics.render("", registry=reg)
    assert "openmetrics" not in ctype
    fams = list(classic_parser.text_string_to_metric_families(
        body.decode()))
    samples = [s for f in fams for s in f.samples
               if s.name == "dragonfly_tpu_scheduler_escape_probe_total"]
    assert samples[0].labels["note"] == tricky


def test_metrics_endpoint_negotiates_openmetrics(run_async):
    import aiohttp

    from dragonfly2_tpu.pkg.metrics_server import MetricsServer

    async def body():
        srv = MetricsServer()
        port = await srv.serve("127.0.0.1", 0)
        try:
            async with aiohttp.ClientSession() as sess:
                headers = {"Accept":
                           "application/openmetrics-text; version=1.0.0"}
                async with sess.get(f"http://127.0.0.1:{port}/metrics",
                                    headers=headers) as r:
                    assert "openmetrics" in r.headers["Content-Type"]
                    text = await r.text()
                assert text.rstrip().endswith("# EOF")
                async with sess.get(
                        f"http://127.0.0.1:{port}/metrics") as r:
                    assert "openmetrics" not in r.headers["Content-Type"]
        finally:
            await srv.close()

    run_async(body(), timeout=60)


# --------------------------------------------------------------------- #
# Debug-route documentation lint (routes introspected, not hand-listed)
# --------------------------------------------------------------------- #

def test_every_debug_route_documented():
    """Every /debug route the MetricsServer registers must appear in
    docs/OBSERVABILITY.md. Routes come from MetricsServer.ROUTES — the
    same table serve() registers from — so an endpoint cannot ship
    undocumented and this list cannot rot."""
    from dragonfly2_tpu.pkg.metrics_server import MetricsServer

    routes = MetricsServer.debug_routes()
    assert "/debug/pod/{task_id}/timeline" in routes
    assert "/debug/slo" in routes
    with open(DOCS) as f:
        doc = f.read()
    missing = []
    for route in routes:
        needle = route.replace("{task_id}", "<task_id>")
        if needle not in doc:
            missing.append(route)
    assert not missing, (
        f"debug routes missing from docs/OBSERVABILITY.md: {missing}")
