"""Metrics-name lint: every registered family follows the
``{component}_{noun}[_{unit}][_total]`` convention and is documented in
docs/OBSERVABILITY.md.

Undocumented or misnamed telemetry rots fastest: a dashboard built on a
family nobody wrote down breaks silently on the next rename. This test
imports every metric-defining module (so the registry is fully
populated), then walks ``metrics.families()`` and fails on any family
that (a) is not snake_case, (b) has the wrong suffix discipline for its
kind, (c) starts with an unknown component, or (d) has no row in the
docs page.
"""

from __future__ import annotations

import importlib
import os
import re

import pytest

# Every module that registers a metric family. A new metric in a new
# module must be added here (the scrape tests would miss it silently
# otherwise) — grep for `metrics.counter|gauge|histogram` when in doubt.
METRIC_MODULES = (
    "dragonfly2_tpu.pkg.bufpool",
    "dragonfly2_tpu.pkg.chaos",
    "dragonfly2_tpu.pkg.flight",
    "dragonfly2_tpu.pkg.fleet",
    "dragonfly2_tpu.pkg.tracing",
    "dragonfly2_tpu.daemon.proxy",
    "dragonfly2_tpu.daemon.upload",
    "dragonfly2_tpu.daemon.objectstorage",
    "dragonfly2_tpu.daemon.peer.conductor",
    "dragonfly2_tpu.daemon.peer.task_manager",
    "dragonfly2_tpu.daemon.peer.device_sink",
    "dragonfly2_tpu.scheduler.service",
    "dragonfly2_tpu.dataset.loader",
    "dragonfly2_tpu.dataset.shard_reader",
    "dragonfly2_tpu.dataset.tar_index",
    "dragonfly2_tpu.dataset.device_feed",
)

# The documented component vocabulary (docs/OBSERVABILITY.md "Metric
# families"). Adding a component means documenting it there first.
COMPONENTS = ("bufpool", "chaos", "dataset", "device_sink", "fleet",
              "objectstorage", "peer", "proxy", "scheduler", "tracing",
              "upload")

# Histogram families must name their unit; counters use _total; gauges
# may end in a unit but never _total.
UNITS = ("seconds", "bytes", "ms")

DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "OBSERVABILITY.md")

SNAKE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")


@pytest.fixture(scope="module")
def all_families():
    for mod in METRIC_MODULES:
        importlib.import_module(mod)
    from dragonfly2_tpu.pkg import metrics

    fams = metrics.families()
    assert len(fams) >= 30, "registry suspiciously small — import miss?"
    return fams


def test_names_are_snake_case(all_families):
    bad = [f["name"] for f in all_families if not SNAKE.match(f["name"])]
    assert not bad, f"non-snake_case metric names: {bad}"


def test_component_prefix_is_documented(all_families):
    bad = [f["name"] for f in all_families
           if not any(f["name"].startswith(c + "_") for c in COMPONENTS)]
    assert not bad, (
        f"metric families outside the documented component vocabulary "
        f"{COMPONENTS}: {bad} — extend docs/OBSERVABILITY.md first")


def test_suffix_discipline_per_kind(all_families):
    bad = []
    for f in all_families:
        name, kind = f["name"], f["kind"]
        if kind == "counter" and not name.endswith("_total"):
            bad.append((name, "counter must end in _total"))
        elif kind == "gauge" and name.endswith("_total"):
            bad.append((name, "gauge must not end in _total"))
        elif kind == "histogram" and not name.endswith(
                tuple(f"_{u}" for u in UNITS)):
            bad.append((name, f"histogram must end in a unit {UNITS}"))
    assert not bad, f"suffix convention violations: {bad}"


def test_every_family_documented(all_families):
    with open(DOCS) as f:
        doc = f.read()
    undocumented = [f["name"] for f in all_families
                    if f"`{f['name']}`" not in doc]
    assert not undocumented, (
        f"metric families missing from docs/OBSERVABILITY.md: "
        f"{undocumented} — every family needs a table row there")


def test_every_family_has_help_text(all_families):
    thin = [f["name"] for f in all_families if len(f["doc"]) < 10]
    assert not thin, f"metric families with no real help text: {thin}"
