"""Packed piece-report wire codec (proto/reportcodec) + announce diet.

The packed ``pieces_finished`` form is only allowed to change *speed*:
- encode → decode must reconstruct the exact dict batch (or the encoder
  must refuse), fuzzed over seeded random report streams;
- every decode backend (native / numpy / python) must return the same
  batch and aggregates;
- the scheduler's bulk apply must land the exact FSM state the per-piece
  dict walk lands, fuzzed at SchedulerService level;
- a malformed packed body is dropped, never a stream-killer;
- the conductor only emits packed after the scheduler advertised
  ``packed_reports`` on a stamped answer, and downgrades on failover;
- a failed flush restores the un-sent batch in order (the deque
  ``extendleft(reversed(batch))`` pin).
"""

from __future__ import annotations

import asyncio
import math
import random

import pytest

from dragonfly2_tpu.proto import reportcodec
from dragonfly2_tpu.proto.reportcodec import (
    CodecError,
    bitmap_to_nums,
    decode_packed,
    encode_reports,
    nums_to_bitmap,
)
from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.service import SchedulerService


def mk_body(host: str, peer: str, task: str = "t", slice_: str = "") -> dict:
    return {
        "host": {"id": host, "hostname": host, "ip": "10.0.0.1",
                 "port": 1, "upload_port": 2, "tpu_slice": slice_},
        "peer_id": peer, "task_id": task, "url": "http://origin/f"}


def _normalize(report: dict) -> dict:
    """What to_dicts() reconstructs: every column present, timings only
    when the original carried a truthy dict (None values → 0, the dict
    walk's own coercion)."""
    d = {"piece_num": report["piece_num"],
         "range_start": report["range_start"],
         "range_size": report["range_size"],
         "digest": report.get("digest", ""),
         "download_cost_ms": report.get("download_cost_ms", 0),
         "dst_peer_id": report.get("dst_peer_id", "")}
    t = report.get("timings")
    if t:
        d["timings"] = {k: int(t.get(k) or 0)
                        for k in ("dcn_ms", "stall_ms", "store_ms")}
    return d


def _rand_report(rng: random.Random, num: int, parents: list) -> dict:
    r = {"piece_num": num,
         "range_start": num * 4096,
         "range_size": rng.choice((0, 512, 4096, (1 << 32) - 1)),
         "dst_peer_id": rng.choice(parents),
         "download_cost_ms": rng.choice((0, 1, 7, 25, (1 << 32) - 1))}
    digest_kind = rng.randrange(4)
    if digest_kind == 1:
        r["digest"] = f"crc32c:{rng.randrange(1 << 32):08x}"
    elif digest_kind == 2:
        r["digest"] = "sha256:" + "".join(
            rng.choice("0123456789abcdef") for _ in range(16))
    elif digest_kind == 3:
        r["digest"] = f"crc32c:{rng.randrange(1 << 32):08X}"  # spills: uppercase
    timing_kind = rng.randrange(4)
    if timing_kind == 1:
        r["timings"] = {}
    elif timing_kind == 2:
        r["timings"] = {"dcn_ms": rng.randrange(1 << 20),
                        "stall_ms": rng.randrange(100),
                        "store_ms": rng.randrange(100)}
    elif timing_kind == 3:
        r["timings"] = {"dcn_ms": rng.randrange(1 << 20), "stall_ms": None}
    return r


# --------------------------------------------------------------------- #
# Encode → decode round trip
# --------------------------------------------------------------------- #

class TestRoundTrip:
    def test_basic_round_trip(self):
        reports = [
            {"piece_num": 5, "range_start": 5 << 20, "range_size": 1 << 20,
             "digest": "crc32c:00c0ffee", "download_cost_ms": 12,
             "dst_peer_id": "parent-1",
             "timings": {"dcn_ms": 9, "stall_ms": 1, "store_ms": 2}},
            {"piece_num": 2, "range_start": 2 << 20, "range_size": 1 << 20,
             "digest": "md5:deadbeef", "download_cost_ms": 0,
             "dst_peer_id": ""},
            {"piece_num": 6, "range_start": 6 << 20, "range_size": 77,
             "download_cost_ms": 3, "dst_peer_id": "parent-1"},
        ]
        packed = encode_reports(reports)
        assert packed is not None and packed["v"] == 1 and packed["n"] == 3
        # The crc32c digest rides the column word, only md5 spills.
        assert packed["digests"] == {1: "md5:deadbeef"}
        batch = decode_packed(packed)
        assert batch.to_dicts() == [_normalize(r) for r in reports]
        # Batch aggregates match a hand fold.
        assert batch.cost_total == 15
        assert batch.bytes_total == (1 << 20) * 2 + 77
        assert batch.phase_ms == (9 + 0 + 3, 1, 2)   # untimed cost → dcn
        # parent_aggs in peer-intern order: parent-1 then "".
        assert batch.peers == ["parent-1", ""]
        assert batch.parent_aggs == [[2, 15, (1 << 20) + 77],
                                     [1, 0, 1 << 20]]

    def test_wire_size_beats_dict_form(self):
        import msgpack

        rng = random.Random(7)
        reports = [_rand_report(rng, n, ["p-long-peer-id-000001"])
                   for n in range(256)]
        for r in reports:       # all crc digests: the common verified case
            r["digest"] = f"crc32c:{rng.randrange(1 << 32):08x}"
            r.pop("timings", None)
        packed = encode_reports(reports)
        dict_bytes = len(msgpack.packb({"type": "pieces_finished",
                                        "pieces": reports},
                                       use_bin_type=True))
        packed_bytes = len(msgpack.packb({"type": "pieces_finished",
                                          "packed": packed},
                                         use_bin_type=True))
        assert packed_bytes * 3 <= dict_bytes

    @pytest.mark.parametrize("bad", [
        {"piece_num": 0, "range_start": 0, "range_size": 1, "extra": 1},
        {"piece_num": 0.0, "range_start": 0, "range_size": 1},
        {"piece_num": True, "range_start": 0, "range_size": 1},
        {"piece_num": -1, "range_start": 0, "range_size": 1},
        {"piece_num": 1 << 63, "range_start": 0, "range_size": 1},
        {"piece_num": 0, "range_size": 1},                    # no range_start
        {"piece_num": 0, "range_start": 0},                   # no range_size
        {"piece_num": 0, "range_start": -1, "range_size": 1},
        {"piece_num": 0, "range_start": 1 << 64, "range_size": 1},
        {"piece_num": 0, "range_start": 0, "range_size": 1 << 32},
        {"piece_num": 0, "range_start": 0, "range_size": 1,
         "download_cost_ms": 2.5},
        {"piece_num": 0, "range_start": 0, "range_size": 1,
         "download_cost_ms": -3},
        {"piece_num": 0, "range_start": 0, "range_size": 1,
         "dst_peer_id": 7},
        {"piece_num": 0, "range_start": 0, "range_size": 1, "digest": 9},
        {"piece_num": 0, "range_start": 0, "range_size": 1,
         "timings": {"dcn_ms": 1, "surprise_ms": 2}},
        {"piece_num": 0, "range_start": 0, "range_size": 1,
         "timings": {"dcn_ms": 1.5}},
        {"piece_num": 0, "range_start": 0, "range_size": 1,
         "timings": [1, 2, 3]},
        "not-a-dict",
    ])
    def test_encoder_refuses_inexact_reports(self, bad):
        good = {"piece_num": 1, "range_start": 0, "range_size": 4}
        assert encode_reports([good, bad]) is None

    def test_empty_batch_refused(self):
        assert encode_reports([]) is None

    def test_peer_intern_table_overflow_refused(self):
        reports = [{"piece_num": i, "range_start": 0, "range_size": 1,
                    "dst_peer_id": f"p{i}"} for i in range(0x10000)]
        assert encode_reports(reports) is None
        assert encode_reports(reports[:0xFFFF]) is not None

    def test_none_timings_values_coerce_like_dict_walk(self):
        # The dict walk does int(timings.get(k, 0) or 0): None → 0. The
        # encoder must represent that exactly, not refuse it.
        r = {"piece_num": 3, "range_start": 0, "range_size": 8,
             "timings": {"dcn_ms": 5, "stall_ms": None}}
        batch = decode_packed(encode_reports([r]))
        assert batch.to_dicts()[0]["timings"] == {
            "dcn_ms": 5, "stall_ms": 0, "store_ms": 0}

    def test_empty_timings_dict_treated_as_absent(self):
        r = {"piece_num": 3, "range_start": 0, "range_size": 8,
             "download_cost_ms": 4, "timings": {}}
        batch = decode_packed(encode_reports([r]))
        assert "timings" not in batch.to_dicts()[0]
        assert batch.phase_ms == (4, 0, 0)   # whole cost lands in dcn


# --------------------------------------------------------------------- #
# Backend ladder: every rung returns the same batch
# --------------------------------------------------------------------- #

class TestBackends:
    def test_a_backend_selected(self):
        assert reportcodec.report_backend() in ("native", "numpy", "python")

    def test_rungs_agree_on_fuzzed_batches(self):
        rungs = [("python", reportcodec._decode_python)]
        if reportcodec.np is not None:
            rungs.append(("numpy", reportcodec._decode_numpy))
        native = reportcodec._native_decoder()
        if native is not None:
            rungs.append(("native", native))
        rng = random.Random(0xD1E7)
        parents = ["", "peer-a", "peer-b", "peer-with-a-long-identity"]
        for round_no in range(25):
            n = rng.randrange(1, 200)
            nums = rng.sample(range(1 << 20), n)
            reports = [_rand_report(rng, num, parents) for num in nums]
            packed = encode_reports(reports)
            assert packed is not None, reports
            spill = dict(packed.get("digests") or {})
            ref = None
            for name, decode in rungs:
                got = decode(packed["nums"], packed["cols"], packed["n"],
                             list(packed["peers"]), dict(spill))
                if ref is None:
                    ref = got
                    assert got.to_dicts() == [_normalize(r) for r in reports]
                    continue
                assert got.to_dicts() == ref.to_dicts(), (name, round_no)
                assert got.parent_aggs == ref.parent_aggs, (name, round_no)
                assert got.phase_ms == ref.phase_ms, (name, round_no)
                assert (got.cost_total, got.bytes_total, got.min_cost) == (
                    ref.cost_total, ref.bytes_total, ref.min_cost), name


# --------------------------------------------------------------------- #
# Structural decode rejects (CodecError, never a crash)
# --------------------------------------------------------------------- #

def _valid_packed() -> dict:
    return encode_reports([
        {"piece_num": i, "range_start": i * 64, "range_size": 64,
         "dst_peer_id": "p", "download_cost_ms": 1} for i in range(4)])


class TestDecodeRejects:
    @pytest.mark.parametrize("mutate", [
        lambda p: p.update(v=2),
        lambda p: p.update(n="4"),
        lambda p: p.update(n=True),
        lambda p: p.update(n=-1),
        lambda p: p.update(n=5),                      # cols length mismatch
        lambda p: p.update(peers=[b"bytes-peer"]),
        lambda p: p.update(peers="p"),
        lambda p: p.update(nums="not-bytes"),
        lambda p: p.update(cols=None),
        lambda p: p.update(nums=p["nums"][:-1]),      # truncated varint
        lambda p: p.update(nums=p["nums"] + b"\x00"),  # trailing bytes
        lambda p: p.update(nums=b"\xff" * 12),        # varint overlong
        lambda p: p.update(nums=b"\x01" + p["nums"][1:]),  # goes negative
        lambda p: p.update(cols=p["cols"][:-1]),
        lambda p: p.update(digests={"0": "x"}),
        lambda p: p.update(digests={9: "x"}),         # spill index >= n
        lambda p: p.update(digests=[("a", 1)]),
    ])
    def test_malformed_packed_raises_codec_error(self, mutate):
        packed = _valid_packed()
        mutate(packed)
        with pytest.raises(CodecError):
            decode_packed(packed)

    def test_peer_index_out_of_range(self):
        packed = _valid_packed()
        packed["peers"] = []          # every column's peer_idx=0 now dangles
        with pytest.raises(CodecError):
            decode_packed(packed)

    def test_non_dict_body(self):
        with pytest.raises(CodecError):
            decode_packed("nope")


# --------------------------------------------------------------------- #
# RESUME bitmap
# --------------------------------------------------------------------- #

class TestBitmap:
    def test_round_trip_fuzz(self):
        rng = random.Random(99)
        for _ in range(50):
            nums = sorted(rng.sample(range(5000), rng.randrange(1, 300)))
            bitmap = nums_to_bitmap(nums)
            assert len(bitmap) == (max(nums) >> 3) + 1
            assert bitmap_to_nums(bitmap) == nums

    def test_empty(self):
        assert nums_to_bitmap([]) == b""
        assert bitmap_to_nums(b"") == []
        assert bitmap_to_nums(b"\x00\x00") == []

    def test_dense_range_is_one_bit_per_piece(self):
        nums = list(range(4096))
        assert len(nums_to_bitmap(nums)) == 512


# --------------------------------------------------------------------- #
# Scheduler FSM equivalence: packed apply ≡ dict walk
# --------------------------------------------------------------------- #

def _service_with_parents(slices=("s1", "s2", "")):
    svc = SchedulerService(SchedulerConfig())
    _h, task, child = svc._resolve(mk_body("host-c", "peer-c", slice_="s1"))
    parents = []
    for i, sl in enumerate(slices):
        _h2, _t, parent = svc._resolve(
            mk_body(f"host-{i}", f"parent-{i}", slice_=sl))
        parents.append(parent.id)
    return svc, task, child, parents


def _dump(svc, task, peers_ids):
    peers = {pid: svc.peers.load(pid) for pid in peers_ids}
    return {
        "peers": {pid: {
            "fin": sorted(p.finished_pieces),
            "costs": list(p.piece_costs),
            "upload": p.host.upload_count,
        } for pid, p in peers.items() if p is not None},
        "pieces": {num: (pi.range_start, pi.range_size, pi.digest,
                         pi.download_cost_ms, pi.dst_peer_id)
                   for num, pi in task.pieces.items()},
        "pod": {tid: entry["hosts"]
                for tid, entry in svc.pod_flight._tasks.items()},
        "fleet": (svc.fleet.series.window(300)["totals"]
                  if svc.fleet is not None else {}),
    }


class TestFsmEquivalence:
    def test_fuzz_packed_vs_dict_state(self, run_async):
        async def body():
            rng = random.Random(0xBEEF)
            svc_d, task_d, child_d, parents = _service_with_parents()
            svc_p, task_p, child_p, parents_p = _service_with_parents()
            assert parents == parents_p
            pool = parents + ["", "ghost-peer"]   # unknown parent too
            all_ids = [child_d.id] + parents
            seen: list = []
            for _ in range(20):
                if seen and rng.random() < 0.3:
                    # Re-report: dup pieces must bridge to the dict walk
                    # on the packed side and still match.
                    nums = rng.sample(seen, min(len(seen), 5))
                    if rng.random() < 0.5:
                        nums += rng.sample(
                            [n for n in range(4000) if n not in seen], 3)
                else:
                    nums = rng.sample(
                        [n for n in range(4000) if n not in seen],
                        rng.randrange(1, 40))
                if rng.random() < 0.1 and len(nums) > 2:
                    nums[1] = nums[0]            # dup WITHIN the batch
                seen.extend(n for n in nums if n not in seen)
                reports = [_rand_report(rng, num, pool) for num in nums]
                packed = encode_reports(reports)
                assert packed is not None
                svc_d._handle_pieces_finished(
                    {"pieces": reports}, task_d, child_d)
                svc_p._handle_pieces_finished(
                    {"packed": packed}, task_p, child_p)
                assert _dump(svc_d, task_d, all_ids) == \
                    _dump(svc_p, task_p, all_ids)

        run_async(body(), timeout=60)

    def test_malformed_packed_dropped_stream_survives(self, run_async):
        async def body():
            svc, task, child, parents = _service_with_parents()
            packed = _valid_packed()
            packed["cols"] = packed["cols"][:-1]
            before = _dump(svc, task, [child.id] + parents)
            svc._handle_pieces_finished({"packed": packed}, task, child)
            assert _dump(svc, task, [child.id] + parents) == before
            # The stream keeps working: a well-formed batch still lands.
            svc._handle_pieces_finished({"packed": _valid_packed()},
                                        task, child)
            assert sorted(child.finished_pieces) == [0, 1, 2, 3]

        run_async(body(), timeout=30)

    def test_resume_register_accepts_bitmap(self, run_async):
        class _Stream:
            def __init__(self):
                self.sent: list = []

            async def send(self, m):
                self.sent.append(m)

        async def body():
            svc, task, child, _ = _service_with_parents()
            nums = [0, 1, 2, 5, 9, 700]
            _h, t2, p2 = svc._resolve(mk_body("host-r", "peer-r"))
            p2.announce_stream = _Stream()
            await svc._handle_resume_register(t2, p2, {
                "piece_nums": [],
                "piece_bitmap": nums_to_bitmap(nums),
                "content_length": 701 * 4, "piece_size": 4,
                "total_piece_count": 701})
            assert sorted(p2.finished_pieces) == nums
            ans = p2.announce_stream.sent[-1]
            assert ans["type"] == "normal_task"
            assert ans.get("packed_reports") is True   # capability stamped

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# Conductor: negotiation, adaptive batching, requeue order
# --------------------------------------------------------------------- #

from dragonfly2_tpu.storage import StorageManager, StorageOption, TaskStoreMetadata  # noqa: E402


def _make_conductor(tmp_path, *, pieces=2, piece_size=4, report_batch=32):
    from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor
    from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager

    sm = StorageManager(StorageOption(data_dir=str(tmp_path / "data")))
    content_length = pieces * piece_size
    store = sm.register_task(TaskStoreMetadata(
        task_id="codec-t", peer_id="p1", url="http://x/f",
        piece_size=piece_size, content_length=content_length,
        total_piece_count=math.ceil(content_length / piece_size)))
    for i in range(pieces):
        store.write_piece(i, b"a" * piece_size)
    return PeerTaskConductor(
        task_id="codec-t", peer_id="p1", url="http://x/f", store=store,
        scheduler_client=None, piece_manager=PieceManager(),
        host_info={"id": "h1"}, report_batch=report_batch)


class _DeadStream:
    closed = True


class _RecordingStream:
    closed = False

    def __init__(self):
        self.sent: list = []

    async def send(self, body):
        self.sent.append(body)


def _report(num: int) -> dict:
    return {"piece_num": num, "range_start": num * 4, "range_size": 4,
            "download_cost_ms": 1, "dst_peer_id": "parent-x"}


class TestConductorWire:
    def test_packed_only_after_negotiation(self, run_async, tmp_path):
        async def body():
            c = _make_conductor(tmp_path)
            batch = [_report(0), _report(1)]
            # Before any stamped answer: legacy dict list.
            assert "pieces" in c._batch_msg(batch)
            # Scheduler advertises the capability on a stamped answer.
            c._note_clock_sample(0.0, {"type": "normal_task",
                                       "packed_reports": True})
            msg = c._batch_msg(batch)
            assert "packed" in msg and "pieces" not in msg
            assert decode_packed(msg["packed"]).to_dicts() == \
                [_normalize(r) for r in batch]
            # Failover to an old scheduler: the next answer lacks the
            # flag and the conductor downgrades.
            c._note_clock_sample(0.0, {"type": "normal_task"})
            assert "pieces" in c._batch_msg(batch)

        run_async(body(), timeout=30)

    def test_single_report_rides_piece_finished(self, run_async, tmp_path):
        async def body():
            c = _make_conductor(tmp_path)
            c._packed_ok = True
            msg = c._batch_msg([_report(3)])
            assert msg["type"] == "piece_finished"

        run_async(body(), timeout=30)

    def test_unpackable_batch_falls_back_to_dicts(self, run_async, tmp_path):
        async def body():
            c = _make_conductor(tmp_path)
            c._packed_ok = True
            batch = [_report(0),
                     dict(_report(1), download_cost_ms=1.5)]   # float: refuse
            assert "pieces" in c._batch_msg(batch)

        run_async(body(), timeout=30)

    def test_failed_flush_requeues_in_order(self, run_async, tmp_path):
        async def body():
            c = _make_conductor(tmp_path, report_batch=2)
            c._stream = _DeadStream()
            reports = [_report(i) for i in range(5)]
            c._pending_reports.extend(reports)
            assert await c._flush_reports() is False
            # The popped batch went back IN ORDER at the head: a resend
            # after recovery replays reports in original arrival order.
            assert list(c._pending_reports) == reports

        run_async(body(), timeout=30)

    def test_cancelled_flush_requeues_in_order(self, run_async, tmp_path):
        async def body():
            c = _make_conductor(tmp_path, report_batch=8)
            reports = [_report(i) for i in range(3)]
            c._pending_reports.extend(reports)

            async def boom(msg):
                raise asyncio.CancelledError

            c._safe_send = boom
            with pytest.raises(asyncio.CancelledError):
                await c._flush_reports()
            assert list(c._pending_reports) == reports

        run_async(body(), timeout=30)

    def test_flush_drains_in_capped_messages(self, run_async, tmp_path):
        async def body():
            c = _make_conductor(tmp_path, report_batch=4)
            c._packed_ok = True
            stream = _RecordingStream()
            c._stream = stream
            c._pending_reports.extend(_report(i) for i in range(10))
            assert await c._flush_reports() is True
            assert not c._pending_reports
            sizes = []
            for msg in stream.sent:
                if msg["type"] == "piece_finished":
                    sizes.append(1)
                else:
                    sizes.append(decode_packed(msg["packed"]).n)
            assert sizes == [4, 4, 2]

        run_async(body(), timeout=30)

    def test_resume_state_bitmap_negotiated_and_dense(
            self, run_async, tmp_path):
        async def body():
            c = _make_conductor(tmp_path, pieces=24)
            # Not negotiated: plain int list.
            resume = c._resume_state()
            assert resume["piece_nums"] == list(range(24))
            assert "piece_bitmap" not in resume
            # Negotiated + dense: the bitmap replaces the list.
            c._packed_ok = True
            resume = c._resume_state()
            assert resume["piece_nums"] == []
            assert bitmap_to_nums(resume["piece_bitmap"]) == list(range(24))

        run_async(body(), timeout=30)

    def test_resume_state_sparse_set_keeps_list_form(
            self, run_async, tmp_path):
        async def body():
            c = _make_conductor(tmp_path, pieces=2)
            c._packed_ok = True
            # Fake a pathologically sparse landed set: bitmap would be
            # huge, the density gate keeps the int list.
            c.store.metadata.pieces = {i * 10000: None for i in range(20)}
            resume = c._resume_state()
            assert "piece_bitmap" not in resume
            assert len(resume["piece_nums"]) == 20

        run_async(body(), timeout=30)
