"""Storage manager + local task store tests."""

import os

import pytest

from dragonfly2_tpu.pkg import digest as pkgdigest
from dragonfly2_tpu.pkg.errors import StorageError
from dragonfly2_tpu.storage import StorageManager, StorageOption, TaskStoreMetadata
from dragonfly2_tpu.storage.local_store import LocalTaskStore


def make_manager(tmp_path, **kw):
    return StorageManager(StorageOption(data_dir=str(tmp_path / "data"), **kw))


def meta(task_id="t1", piece_size=4, content_length=10):
    import math

    return TaskStoreMetadata(
        task_id=task_id,
        peer_id="p1",
        url="http://x/f",
        piece_size=piece_size,
        content_length=content_length,
        total_piece_count=math.ceil(content_length / piece_size) if content_length >= 0 else -1,
    )


class TestLocalStore:
    def test_write_read_roundtrip(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        store.write_piece(0, b"aaaa")
        store.write_piece(1, b"bbbb")
        store.write_piece(2, b"cc")
        assert store.read_piece(0) == b"aaaa"
        assert store.read_piece(2) == b"cc"
        assert store.is_complete()
        assert store.downloaded_bytes() == 10

    def test_piece_digest_verified_on_write(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        good = str(pkgdigest.hash_bytes("md5", b"aaaa"))
        store.write_piece(0, b"aaaa", expected_digest=good)
        with pytest.raises(StorageError):
            store.write_piece(1, b"bbbb", expected_digest=good)

    def test_out_of_order_writes(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        store.write_piece(2, b"cc")
        store.write_piece(0, b"aaaa")
        store.write_piece(1, b"bbbb")
        assert store.is_complete()
        out = tmp_path / "out.bin"
        store.mark_done()
        store.store_to(str(out))
        assert out.read_bytes() == b"aaaabbbbcc"

    def test_store_to_hardlink(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        for i, d in enumerate([b"aaaa", b"bbbb", b"cc"]):
            store.write_piece(i, d)
        store.mark_done()
        dest = tmp_path / "out" / "f.bin"
        store.store_to(str(dest))
        assert dest.read_bytes() == b"aaaabbbbcc"
        # hardlink: same inode as the data file
        data_inode = os.stat(os.path.join(store.dir, "data")).st_ino
        assert os.stat(dest).st_ino == data_inode

    def test_store_incomplete_refused(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        store.write_piece(0, b"aaaa")
        with pytest.raises(StorageError):
            store.store_to(str(tmp_path / "o"))

    def test_validate_whole_digest(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        for i, d in enumerate([b"aaaa", b"bbbb", b"cc"]):
            store.write_piece(i, d)
        want = "sha256:" + pkgdigest.hash_bytes("sha256", b"aaaabbbbcc").encoded
        assert store.validate_digest(want) == want
        with pytest.raises(StorageError):
            store.validate_digest("sha256:" + "0" * 64)

    def test_get_pieces_listing(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta(content_length=-1))
        store.update_task(piece_size=4)
        store.write_piece(0, b"aaaa")
        store.write_piece(1, b"bbbb")
        recs = store.get_pieces(0)
        assert [r.num for r in recs] == [0, 1]
        recs = store.get_pieces(1, limit=1)
        assert [r.num for r in recs] == [1]


class TestManager:
    def test_reload_restores_tasks(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        for i, d in enumerate([b"aaaa", b"bbbb", b"cc"]):
            store.write_piece(i, d)
        store.mark_done()
        sm.close()
        # New manager over the same dir (daemon restart).
        sm2 = make_manager(tmp_path)
        assert sm2.reload() == 1
        found = sm2.find_completed_task("t1")
        assert found is not None
        assert found.read_piece(1) == b"bbbb"

    def test_reload_sweeps_invalid(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        store.mark_invalid()
        sm.close()
        sm2 = make_manager(tmp_path)
        assert sm2.reload() == 0
        assert sm2.try_get("t1") is None

    def test_ttl_gc(self, tmp_path):
        sm = make_manager(tmp_path, task_ttl=0.0)
        store = sm.register_task(meta())
        store.write_piece(0, b"aaaa")
        store.metadata.last_access -= 10
        reclaimed = sm.gc()
        assert reclaimed == ["t1"]
        assert sm.try_get("t1") is None
        assert not os.path.exists(store.dir)

    def test_lru_quota_gc(self, tmp_path):
        import time

        sm = make_manager(tmp_path, disk_gc_threshold=25)
        now = time.time()
        for n in range(3):
            st = sm.register_task(meta(task_id=f"t{n}"))
            for i, d in enumerate([b"aaaa", b"bbbb", b"cc"]):
                st.write_piece(i, d)
            st.metadata.last_access = now - (3 - n)  # t0 oldest
        reclaimed = sm.gc()
        assert "t0" in reclaimed
        assert sm.try_get("t2") is not None

    def test_find_partial(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        store.write_piece(0, b"aaaa")
        assert sm.find_completed_task("t1") is None
        assert sm.find_partial_completed_task("t1") is not None


class TestGCPinning:
    def test_pinned_store_survives_gc(self, tmp_path):
        sm = make_manager(tmp_path, task_ttl=0.0)
        store = sm.register_task(meta())
        store.write_piece(0, b"aaaa")
        store.metadata.last_access -= 10
        with store:  # pinned
            assert sm.gc() == []
        assert sm.gc() == ["t1"]  # unpinned → reclaimed

    def test_invalid_store_recreated_on_register(self, tmp_path):
        sm = make_manager(tmp_path)
        store = sm.register_task(meta())
        store.write_piece(0, b"aaaa")
        store.mark_invalid()
        fresh = sm.register_task(meta())
        assert not fresh.metadata.invalid
        assert not fresh.metadata.pieces  # clean slate, no poisoned pieces


def test_concurrent_writes_and_reads_threadsafe(tmp_path):
    """write_piece runs on worker threads (asyncio.to_thread in the piece
    paths) while the event loop reads the piece map — no 'dict changed
    size during iteration', no lost pieces (code-review regression r3)."""
    import threading

    from dragonfly2_tpu.storage.local_store import (
        LocalTaskStore,
        TaskStoreMetadata,
    )

    piece = 4096
    total = 64
    store = LocalTaskStore(
        str(tmp_path / "t"),
        TaskStoreMetadata(task_id="t-threads", content_length=piece * total,
                          piece_size=piece, total_piece_count=total))
    blob = b"\xab" * piece
    errors = []

    def writer(nums):
        try:
            for n in nums:
                store.write_piece(n, blob)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            for _ in range(300):
                store.get_pieces()
                store.covers_range(0, piece * total)
                store.downloaded_bytes()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(range(i, total, 4),))
               for i in range(4)] + [threading.Thread(target=reader)
                                     for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(store.metadata.pieces) == total
    assert store.is_complete()


def test_gc_closes_idle_store_fds(tmp_path):
    """Idle (but un-expired) stores drop their data-file fd at GC time and
    reopen lazily — a long-lived daemon must not hold one fd per task it
    ever served (benchmarks/soak.py measures the drift)."""
    import time as _time

    from dragonfly2_tpu.storage.manager import StorageManager, StorageOption

    mgr = StorageManager(StorageOption(data_dir=str(tmp_path / "d"),
                                       task_ttl=3600.0, gc_interval=10.0))
    store = mgr.register_task(TaskStoreMetadata(
        task_id="fd-task", content_length=8, piece_size=8,
        total_piece_count=1))
    store.write_piece(0, b"12345678")
    assert store._fd is not None
    # Fresh store: GC must keep the fd (recently used).
    mgr.gc()
    assert store._fd is not None
    # Idle past gc_interval but under TTL: fd closes, store survives.
    store.metadata.last_access = _time.time() - 60
    mgr.gc()
    assert store._fd is None
    assert mgr.try_get("fd-task") is store
    # Lazy reopen serves reads.
    assert store.read_piece(0) == b"12345678"
    # Pinned stores are never touched.
    store.metadata.last_access = _time.time() - 60
    with store:
        mgr.gc()
        assert store._fd is not None
    mgr.close()


def test_pieces_all_digest_verified_tracking(tmp_path):
    """The completion-time re-hash skip needs exact provenance: verified
    means 'matched an externally-announced digest at landing', never
    self-computed."""
    from dragonfly2_tpu.pkg import digest as pkgdigest

    mgr = make_manager(tmp_path)
    store = mgr.register_task(meta("t-verified", content_length=9))
    store.update_task(content_length=9, piece_size=4, total_piece_count=3)
    d0 = pkgdigest.hash_bytes(pkgdigest.ALGORITHM_CRC32C, b"aaaa")
    store.write_piece(0, b"aaaa", expected_digest=str(d0))
    assert not store.pieces_all_digest_verified()  # incomplete
    store.write_piece(1, b"bbbb")                  # self-computed digest
    crc2 = int(pkgdigest.hash_bytes(
        pkgdigest.ALGORITHM_CRC32C, b"c").encoded, 16)
    store.record_piece(2, 1, crc2, verified=True)
    assert store.is_complete()
    # Piece 1 was never externally verified -> no skip.
    assert not store.pieces_all_digest_verified()

    store2 = mgr.register_task(meta("t-verified2", content_length=8))
    store2.update_task(content_length=8, piece_size=4, total_piece_count=2)
    d = pkgdigest.hash_bytes(pkgdigest.ALGORITHM_CRC32C, b"xxxx")
    store2.write_piece(0, b"xxxx", expected_digest=str(d))
    crc = int(pkgdigest.hash_bytes(
        pkgdigest.ALGORITHM_CRC32C, b"yyyy").encoded, 16)
    store2.record_piece(1, 4, crc, verified=True)
    # All pieces verified but no completed parent certified the digest
    # map yet -> still no skip.
    assert not store2.pieces_all_digest_verified()
    # Certification is per-piece provenance: the certified map must MATCH
    # what each piece was verified against, or the skip stays off (a
    # corrupt parent's digests cannot be laundered by an honest done).
    good = {0: str(d), 1: f"crc32c:{crc:08x}"}
    store2.certified_digests = {0: str(d), 1: "crc32c:deadbeef"}
    assert not store2.pieces_all_digest_verified()
    store2.certified_digests = good
    assert store2.pieces_all_digest_verified()

    # apply_certification tries every done parent's map: a corrupt early
    # finisher cannot mask an honest one.
    corrupt = {0: str(d), 1: "crc32c:deadbeef"}
    store2.certified_digests = None
    assert store2.apply_certification([corrupt, good]) is True
    assert store2.certified_digests == good
    assert store2.pieces_all_digest_verified()
    # An installed verifying map is never downgraded by later candidates.
    assert store2.apply_certification([corrupt]) is True
    assert store2.certified_digests == good
    # Only corrupt candidates from scratch: nothing installed — the
    # completion decision re-hashes either way.
    store2.certified_digests = None
    assert store2.apply_certification([corrupt]) is False
    assert store2.certified_digests is None
    assert not store2.pieces_all_digest_verified()
    # Empty candidate list: nothing installed, nothing clobbered.
    assert store2.apply_certification([]) is False
    assert store2.certified_digests is None


class TestPrefixHasher:
    """Hash-as-you-backsource: the contiguous-prefix hasher must produce
    the same completion digest as the full re-hash, and any anomaly must
    poison it into the fallback path, never a wrong digest."""

    def _content(self, n=3 * 65536 + 123):
        import random
        return bytes(random.Random(5).randbytes(n))

    def test_out_of_order_pieces_match_full_hash(self, tmp_path):
        import hashlib

        content = self._content()
        piece = 65536
        store = LocalTaskStore(str(tmp_path / "s1"),
                               meta("t-ph1", piece_size=piece,
                                    content_length=len(content)))
        want = "sha256:" + hashlib.sha256(content).hexdigest()
        store.start_prefix_hasher(want)
        assert store._prefix_hasher is not None
        order = [2, 0, 3, 1]
        for n in order:
            store.write_piece(n, content[n * piece:(n + 1) * piece])
        assert store.is_complete()
        assert store.validate_digest(want) == want
        assert store._prefix_hasher is None  # consumed

    def test_mismatch_still_raises(self, tmp_path):
        content = self._content()
        piece = 65536
        store = LocalTaskStore(str(tmp_path / "s2"),
                               meta("t-ph2", piece_size=piece,
                                    content_length=len(content)))
        want = "sha256:" + "0" * 64
        store.start_prefix_hasher(want)
        for n in range(4):
            store.write_piece(n, content[n * piece:(n + 1) * piece])
        with pytest.raises(StorageError):
            store.validate_digest(want)

    def test_rerecorded_piece_poisons_to_fallback(self, tmp_path):
        import hashlib
        import time as _time

        content = self._content()
        piece = 65536
        store = LocalTaskStore(str(tmp_path / "s3"),
                               meta("t-ph3", piece_size=piece,
                                    content_length=len(content)))
        want = "sha256:" + hashlib.sha256(content).hexdigest()
        store.start_prefix_hasher(want)
        store.write_piece(0, content[:piece])
        # Let the hasher pass piece 0, then re-record it behind the
        # frontier: the hasher must poison, and validate_digest must
        # fall back to the (still correct) full re-hash.
        deadline = _time.monotonic() + 5
        while (store._prefix_hasher._next < 1
               and _time.monotonic() < deadline):
            _time.sleep(0.01)
        assert store._prefix_hasher._next >= 1
        for n in range(4):
            store.write_piece(n, content[n * piece:(n + 1) * piece])
        assert store.validate_digest(want) == want

    def test_unknown_algorithm_is_noop(self, tmp_path):
        store = LocalTaskStore(str(tmp_path / "s4"),
                               meta("t-ph4", piece_size=4, content_length=8))
        store.start_prefix_hasher("whirlpool999:beef")
        assert store._prefix_hasher is None
