"""pkg/ratelimit Limiter edge cases: unlimited/zero rates, burst
exhaustion + refill timing, oversized requests, cancellation refunds,
and FIFO fairness under concurrent acquires.

The reservation model under test (mirrors golang.org/x/time/rate):
tokens go NEGATIVE when a waiter reserves ahead of refill, the lock is
held through the maturation sleep (that is what makes waiters FIFO),
and a cancelled waiter returns its reservation.
"""

from __future__ import annotations

import asyncio

import pytest

from dragonfly2_tpu.pkg.ratelimit import INF, Limiter


# -- unlimited -------------------------------------------------------------

def test_unlimited_never_waits(run_async):
    async def body():
        lim = Limiter(INF)
        assert lim.limit == INF
        for n in (1, 1 << 40):
            assert await lim.wait(n) == 0.0
            assert lim.allow(n)

    run_async(body())


def test_unlimited_can_allow_is_non_mutating():
    lim = Limiter(INF)
    for _ in range(3):
        assert lim.can_allow(1 << 50)
    assert lim.allow(1 << 50)  # nothing was debited by the checks


# -- zero limit: park until resumed ---------------------------------------

def test_zero_limit_parks_until_set_limit_resumes(run_async):
    async def body():
        lim = Limiter(0.0, burst=4)
        woke = asyncio.Event()

        async def waiter():
            await lim.wait(1)
            woke.set()

        t = asyncio.create_task(waiter())
        await asyncio.sleep(0.05)
        assert not woke.is_set(), "limit=0 must park the waiter"
        lim.set_limit(1000.0)
        await asyncio.wait_for(woke.wait(), 2.0)
        await t

    run_async(body())


def test_zero_limit_allow_denies_after_burst_drains():
    # allow() still spends the initial burst; refill rate 0 never tops up.
    lim = Limiter(0.0, burst=2)
    assert lim.allow(2)
    assert not lim.allow(1)
    assert not lim.can_allow(1)


# -- burst exhaustion + refill timing --------------------------------------

def test_burst_exhaustion_then_timed_refill(run_async):
    async def body():
        # 100 tokens/s, bucket 10: draining the bucket is free; the next
        # 10-token take must wait ~0.1s for the refill.
        lim = Limiter(100.0, burst=10)
        assert await lim.wait(10) == pytest.approx(0.0, abs=1e-3)
        waited = await lim.wait(10)
        assert 0.05 <= waited <= 0.5, f"expected ~0.1s refill, got {waited}"

    run_async(body())


def test_allow_recovers_after_refill_interval(run_async):
    async def body():
        lim = Limiter(200.0, burst=10)
        assert lim.allow(10)
        assert not lim.allow(10)
        await asyncio.sleep(0.1)  # 200/s * 0.1s = 20 >= bucket (10): full
        assert lim.allow(10)

    run_async(body())


def test_wait_larger_than_burst_chunks_instead_of_deadlocking(run_async):
    async def body():
        # n > burst would never fit the bucket at once: wait() pays across
        # multiple fills. 30 tokens at 300/s from a 10-bucket ~= 20/300s.
        lim = Limiter(300.0, burst=10)
        waited = await asyncio.wait_for(lim.wait(30), 5.0)
        assert waited >= 0.03

    run_async(body())


def test_cancelled_waiter_returns_reservation(run_async):
    async def body():
        lim = Limiter(10.0, burst=10)
        assert lim.allow(10)  # drain

        t = asyncio.create_task(lim.wait(10))  # reserve -> tokens negative
        await asyncio.sleep(0.05)
        t.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t
        # The refund plus ~1s of refill must make 10 tokens available in
        # ~1s; without the refund this would take ~2s.
        waited = await asyncio.wait_for(lim.wait(10), 5.0)
        assert waited <= 1.5

    run_async(body())


# -- concurrent-acquire fairness -------------------------------------------

def test_concurrent_waiters_complete_fifo(run_async):
    async def body():
        # Lock-held-through-sleep means grant order == arrival order even
        # though every reservation matures at a different instant.
        lim = Limiter(200.0, burst=10)
        assert lim.allow(10)  # start everyone from an empty bucket
        order: list[int] = []

        async def worker(i: int) -> None:
            await lim.wait(5)
            order.append(i)

        tasks = []
        for i in range(6):
            tasks.append(asyncio.create_task(worker(i)))
            await asyncio.sleep(0.005)  # deterministic arrival order
        await asyncio.wait_for(asyncio.gather(*tasks), 10.0)
        assert order == sorted(order), f"grants out of order: {order}"

    run_async(body())


def test_set_limit_rescales_bucket_and_clamps_tokens():
    lim = Limiter(1000.0, burst=100)
    # Shrink: tokens must clamp to the new bucket, denying a burst the
    # old bucket would have allowed.
    lim.set_limit(10.0)
    assert not lim.allow(50)
    assert lim.allow(10)
