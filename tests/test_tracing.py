"""Tracing: span scoping, cross-RPC propagation, JSONL export.

Reference: otel wiring per binary (cmd/dependency/dependency.go:263-271)
with gRPC auto-instrumentation — here the drpc frame metadata carries the
traceparent and servers wrap handlers in child spans.
"""

from __future__ import annotations

import json

from dragonfly2_tpu.pkg import tracing
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.rpc import Client, Server


def test_span_nesting_and_attrs():
    tracing.exporter().clear()
    with tracing.span("outer", kind="test") as outer:
        assert tracing.current() is not None
        trace_id = tracing.current().trace_id
        with tracing.span("inner") as inner:
            assert tracing.current().trace_id == trace_id
            assert inner.parent_id == outer.context.span_id
    assert tracing.current() is None
    spans = tracing.exporter().find(trace_id=trace_id)
    assert {s.name for s in spans} == {"outer", "inner"}
    assert all(s.end >= s.start for s in spans)


def test_error_status():
    tracing.exporter().clear()
    try:
        with tracing.span("boom"):
            raise ValueError("x")
    except ValueError:
        pass
    assert tracing.exporter().find(name="boom")[0].status == "error"


def test_traceparent_roundtrip():
    ctx = tracing.SpanContext(trace_id="a" * 32, span_id="b" * 16)
    back = tracing.SpanContext.from_traceparent(ctx.to_traceparent())
    assert back == ctx
    assert tracing.SpanContext.from_traceparent("garbage") is None


def test_rpc_propagation(run_async):
    async def run():
        tracing.exporter().clear()
        server = Server("traced")

        async def handler(body, ctx):
            cur = tracing.current()
            return {"trace_id": cur.trace_id if cur else ""}

        server.register_unary("T.Echo", handler)
        await server.serve(NetAddr.tcp("127.0.0.1", 0))
        cli = Client(NetAddr.tcp("127.0.0.1", server.port()))
        try:
            with tracing.span("client.op") as sp:
                resp = await cli.call("T.Echo", {})
            # The server handler ran inside OUR trace.
            assert resp["trace_id"] == sp.context.trace_id
            server_spans = tracing.exporter().find(name="rpc.T.Echo")
            assert server_spans and \
                server_spans[0].context.trace_id == sp.context.trace_id
            # Untraced calls still work (no metadata).
            resp2 = await cli.call("T.Echo", {})
            assert resp2["trace_id"]  # server starts its own root
        finally:
            await cli.close()
            await server.close()

    run_async(run())


def test_duration_survives_wall_clock_step(monkeypatch):
    """An NTP step mid-span must not produce negative/garbage durations:
    duration derives from perf_counter, and the exported end timestamp is
    reconstructed from it (end = start + duration, always >= start)."""
    import time as time_mod

    tracing.exporter().clear()
    real_time = time_mod.time
    with tracing.span("stepped") as sp:
        # The wall clock jumps BACK 1 hour mid-span.
        monkeypatch.setattr(time_mod, "time", lambda: real_time() - 3600.0)
    monkeypatch.setattr(time_mod, "time", real_time)
    row = sp.to_json()
    assert 0 <= row["duration_ms"] < 5000, row
    assert sp.end >= sp.start
    # And a forward step is equally harmless.
    with tracing.span("stepped-fwd") as sp2:
        monkeypatch.setattr(time_mod, "time", lambda: real_time() + 3600.0)
    monkeypatch.setattr(time_mod, "time", real_time)
    assert 0 <= sp2.to_json()["duration_ms"] < 5000


def test_otlp_health_metric_counts_sent_and_dropped(run_async):
    """Exporter health is scrapeable: tracing_otlp_spans_total{result}
    moves with sent and dropped spans, so silent span loss is visible on
    /metrics instead of only on the exporter object."""
    from aiohttp import web

    from dragonfly2_tpu.pkg import metrics as metrics_mod

    def scrape():
        text = metrics_mod.render()[0].decode()
        return metrics_mod.parse_labeled_samples(
            text, "dragonfly_tpu_tracing_otlp_spans_total", "result")

    async def run():
        import asyncio

        async def v1_traces(request: web.Request) -> web.Response:
            await request.json()
            return web.json_response({"partialSuccess": {}})

        app = web.Application()
        app.router.add_post("/v1/traces", v1_traces)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        before = scrape()
        exp = tracing.exporter()
        otlp = exp.set_otlp(f"http://127.0.0.1:{port}",
                            service_name="df-health", flush_interval=0.05)
        try:
            with tracing.span("counted"):
                pass
            for _ in range(100):
                if otlp.sent_spans >= 1:
                    break
                await asyncio.sleep(0.05)
            assert otlp.sent_spans >= 1
            after = scrape()
            assert after.get("sent", 0) >= before.get("sent", 0) + 1
            # Post-close enqueues count as dropped — on the metric too.
            await asyncio.to_thread(otlp.close)
            otlp.enqueue(tracing.Span(
                "late", tracing.SpanContext("a" * 32, "b" * 16), end=1.0))
            assert scrape().get("dropped", 0) >= before.get("dropped", 0) + 1
        finally:
            exp.set_otlp("")
            await runner.cleanup()

    run_async(run())


def test_jsonl_export(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tracing.exporter().set_file(path)
    try:
        with tracing.span("exported", x=1):
            pass
        rows = [json.loads(line) for line in open(path)]
        assert rows[-1]["name"] == "exported"
        assert rows[-1]["attrs"] == {"x": 1}
        assert rows[-1]["duration_ms"] >= 0
    finally:
        tracing.exporter().set_file("")


def test_otlp_export_lands_in_collector(run_async):
    """Spans reach a live OTLP/HTTP collector endpoint in the standard
    ExportTraceServiceRequest JSON shape: hex ids, nano timestamps as
    strings, mapped attribute types, status ERROR on failed spans
    (reference wires the same interop through the otel SDK,
    cmd/dependency/dependency.go:263-271)."""
    import asyncio

    from aiohttp import web

    async def run():
        received: list[dict] = []

        async def v1_traces(request: web.Request) -> web.Response:
            assert request.content_type == "application/json"
            received.append(await request.json())
            return web.json_response({"partialSuccess": {}})

        app = web.Application()
        app.router.add_post("/v1/traces", v1_traces)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        exp = tracing.exporter()
        otlp = exp.set_otlp(f"http://127.0.0.1:{port}",
                            service_name="df-test", flush_interval=0.05)
        try:
            with tracing.span("parent", peers=3, rate=0.5, seed=True) as sp:
                with tracing.span("child"):
                    pass
            try:
                with tracing.span("broken"):
                    raise RuntimeError("boom")
            except RuntimeError:
                pass
            # Drain off-loop: the worker thread posts to THIS loop's server.
            for _ in range(100):
                if otlp.sent_spans >= 3:
                    break
                await asyncio.sleep(0.05)
            assert otlp.sent_spans >= 3, (otlp.sent_spans, otlp.dropped_spans)

            spans = [s
                     for payload in received
                     for rs in payload["resourceSpans"]
                     for ss in rs["scopeSpans"]
                     for s in ss["spans"]]
            by_name = {s["name"]: s for s in spans}
            assert set(by_name) >= {"parent", "child", "broken"}
            svc = received[0]["resourceSpans"][0]["resource"]["attributes"]
            assert {"key": "service.name",
                    "value": {"stringValue": "df-test"}} in svc
            parent, child = by_name["parent"], by_name["child"]
            assert len(parent["traceId"]) == 32 and len(parent["spanId"]) == 16
            assert child["traceId"] == parent["traceId"]
            assert child["parentSpanId"] == parent["spanId"]
            assert int(parent["endTimeUnixNano"]) >= int(parent["startTimeUnixNano"])
            attrs = {a["key"]: a["value"] for a in parent["attributes"]}
            assert attrs["peers"] == {"intValue": "3"}
            assert attrs["rate"] == {"doubleValue": 0.5}
            assert attrs["seed"] == {"boolValue": True}
            assert by_name["broken"]["status"]["code"] == 2
            assert parent["status"]["code"] == 1
        finally:
            exp.set_otlp("")
            await runner.cleanup()

    run_async(run())


def test_otlp_flush_waits_for_inflight_post_and_close_joins(run_async):
    """Shutdown race (advisor round 5): flush() must wait for the POST the
    worker already popped from the queue — queue-empty plus a fixed 50 ms
    is not "drained" when a collector takes hundreds of ms — and close()
    must join the worker thread so nothing posts after teardown."""
    import asyncio
    import threading

    from aiohttp import web

    async def run():
        received: list[int] = []
        release = asyncio.Event()

        async def v1_traces(request: web.Request) -> web.Response:
            payload = await request.json()
            # Hold the POST well past the old flush's 50 ms grace.
            await release.wait()
            received.append(sum(
                len(ss["spans"])
                for rs in payload["resourceSpans"]
                for ss in rs["scopeSpans"]))
            return web.json_response({"partialSuccess": {}})

        app = web.Application()
        app.router.add_post("/v1/traces", v1_traces)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        exp = tracing.exporter()
        otlp = exp.set_otlp(f"http://127.0.0.1:{port}",
                            service_name="df-flush-test",
                            flush_interval=0.02)
        try:
            with tracing.span("held"):
                pass
            # Let the worker pop the batch and enter the slow POST.
            for _ in range(100):
                if otlp._q.empty():
                    break
                await asyncio.sleep(0.01)
            flushed = asyncio.ensure_future(asyncio.to_thread(otlp.flush, 5.0))
            await asyncio.sleep(0.3)
            # Queue is empty but the POST is mid-flight: the old flush
            # (queue-empty + 50 ms) has already returned by now.
            assert not flushed.done(), \
                "flush returned while the final batch's POST was in flight"
            assert otlp.sent_spans == 0
            release.set()
            await flushed
            assert otlp.sent_spans == 1, (otlp.sent_spans,
                                          otlp.dropped_spans)
            assert received == [1]
            await asyncio.to_thread(otlp.close)
            assert not otlp._thread.is_alive(), \
                "close() returned with the worker thread still running"
            # A post-close enqueue is dropped, never stranded in flight.
            before = otlp.dropped_spans
            otlp.enqueue(tracing.Span(
                "late", tracing.SpanContext("c" * 32, "d" * 16), end=1.0))
            assert otlp.dropped_spans == before + 1
            otlp.flush(timeout=0.5)   # returns promptly, nothing pending
        finally:
            exp.set_otlp("")
            await runner.cleanup()

    run_async(run())
