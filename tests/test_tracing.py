"""Tracing: span scoping, cross-RPC propagation, JSONL export.

Reference: otel wiring per binary (cmd/dependency/dependency.go:263-271)
with gRPC auto-instrumentation — here the drpc frame metadata carries the
traceparent and servers wrap handlers in child spans.
"""

from __future__ import annotations

import json

from dragonfly2_tpu.pkg import tracing
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.rpc import Client, Server


def test_span_nesting_and_attrs():
    tracing.exporter().clear()
    with tracing.span("outer", kind="test") as outer:
        assert tracing.current() is not None
        trace_id = tracing.current().trace_id
        with tracing.span("inner") as inner:
            assert tracing.current().trace_id == trace_id
            assert inner.parent_id == outer.context.span_id
    assert tracing.current() is None
    spans = tracing.exporter().find(trace_id=trace_id)
    assert {s.name for s in spans} == {"outer", "inner"}
    assert all(s.end >= s.start for s in spans)


def test_error_status():
    tracing.exporter().clear()
    try:
        with tracing.span("boom"):
            raise ValueError("x")
    except ValueError:
        pass
    assert tracing.exporter().find(name="boom")[0].status == "error"


def test_traceparent_roundtrip():
    ctx = tracing.SpanContext(trace_id="a" * 32, span_id="b" * 16)
    back = tracing.SpanContext.from_traceparent(ctx.to_traceparent())
    assert back == ctx
    assert tracing.SpanContext.from_traceparent("garbage") is None


def test_rpc_propagation(run_async):
    async def run():
        tracing.exporter().clear()
        server = Server("traced")

        async def handler(body, ctx):
            cur = tracing.current()
            return {"trace_id": cur.trace_id if cur else ""}

        server.register_unary("T.Echo", handler)
        await server.serve(NetAddr.tcp("127.0.0.1", 0))
        cli = Client(NetAddr.tcp("127.0.0.1", server.port()))
        try:
            with tracing.span("client.op") as sp:
                resp = await cli.call("T.Echo", {})
            # The server handler ran inside OUR trace.
            assert resp["trace_id"] == sp.context.trace_id
            server_spans = tracing.exporter().find(name="rpc.T.Echo")
            assert server_spans and \
                server_spans[0].context.trace_id == sp.context.trace_id
            # Untraced calls still work (no metadata).
            resp2 = await cli.call("T.Echo", {})
            assert resp2["trace_id"]  # server starts its own root
        finally:
            await cli.close()
            await server.close()

    run_async(run())


def test_jsonl_export(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tracing.exporter().set_file(path)
    try:
        with tracing.span("exported", x=1):
            pass
        rows = [json.loads(line) for line in open(path)]
        assert rows[-1]["name"] == "exported"
        assert rows[-1]["attrs"] == {"x": 1}
        assert rows[-1]["duration_ms"] >= 0
    finally:
        tracing.exporter().set_file("")
