"""The unified serve-side read path (ISSUE 6 tentpole).

One preadv engine (LocalTaskStore.read_into / read_spans_into /
read_piece_into over pooled buffers) now sits under every serve surface;
these tests pin:

  * primitive contracts — span packing, short-read/EOF edges, buffer
    sizing, StorageManager's pinned task-id wrappers;
  * byte-identical serving across the aiohttp upload server, the native
    fused read, and the coalesced span stream, each against the
    ``read_piece`` oracle;
  * the in-progress sendfile window: landed windows of a still-
    downloading task serve via sendfile with honest Content-Range
    denominators, exact at piece boundaries;
  * the leak guard: acquire/release balance across the new read path,
    including the fault paths (truncated file mid-stream, closed
    consumer), under chaos-style corruption;
  * pool observability: bufpool_* metrics scrapeable via the shared
    registry that pkg/metrics_server serves.
"""

from __future__ import annotations

import asyncio
import os
import random

import aiohttp
import pytest

from dragonfly2_tpu.daemon.transport import P2PTransport
from dragonfly2_tpu.daemon.upload import UploadManager
from dragonfly2_tpu.pkg import metrics
from dragonfly2_tpu.pkg.bufpool import BufferPool
from dragonfly2_tpu.pkg.errors import StorageError
from dragonfly2_tpu.storage.local_store import (
    LocalTaskStore,
    TaskStoreMetadata,
    read_buffer_stats,
)
from dragonfly2_tpu.storage.manager import StorageManager, StorageOption

PIECE = 128 * 1024


def _store_with_content(tmp_path, name="rp-task", pieces=4, tail=1000,
                        done=True):
    content = random.Random(5).randbytes((pieces - 1) * PIECE + tail)
    total = pieces
    store = LocalTaskStore.create(
        str(tmp_path / name),
        TaskStoreMetadata(task_id=name, content_length=len(content),
                          piece_size=PIECE, total_piece_count=total))
    for n in range(total):
        store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
    if done:
        store.mark_done()
    return store, content


# -- primitives --------------------------------------------------------------

def test_read_spans_into_packs_disjoint_spans(tmp_path):
    store, content = _store_with_content(tmp_path)
    buf = bytearray(PIECE)
    spans = [(0, 100), (2 * PIECE + 7, 50), (PIECE, 200)]
    n = store.read_spans_into(spans, buf)
    assert n == 350
    want = content[:100] + content[2 * PIECE + 7:2 * PIECE + 57] \
        + content[PIECE:PIECE + 200]
    assert bytes(buf[:n]) == want


def test_read_spans_into_short_read_eof(tmp_path):
    """A span reaching past EOF must raise, never hand back partial bytes
    silently — the serve path's integrity depends on it."""
    store, content = _store_with_content(tmp_path)
    buf = bytearray(4096)
    with pytest.raises(StorageError, match="short read|EOF"):
        store.read_spans_into([(len(content) - 10, 4096)], buf)
    # Zero-length spans are a no-op, not an error.
    assert store.read_spans_into([(0, 0)], buf) == 0


def test_read_spans_into_buffer_too_small(tmp_path):
    store, _ = _store_with_content(tmp_path)
    with pytest.raises(StorageError, match="too small"):
        store.read_spans_into([(0, 100)], bytearray(50))
    with pytest.raises(StorageError, match="too small"):
        store.read_into(0, 100, bytearray(120), at=40)


def test_read_piece_into_matches_oracle(tmp_path):
    store, content = _store_with_content(tmp_path)
    buf = bytearray(PIECE)
    for n in range(4):
        rec = store.read_piece_into(n, buf)
        assert bytes(buf[:rec.size]) == store.read_piece(n)
    with pytest.raises(StorageError, match="not found"):
        store.read_piece_into(99, buf)


def test_storage_manager_read_wrappers(tmp_path):
    storage = StorageManager(StorageOption(data_dir=str(tmp_path / "d")))
    content = random.Random(6).randbytes(2 * PIECE)
    store = storage.register_task(TaskStoreMetadata(
        task_id="mgr-task", content_length=len(content), piece_size=PIECE,
        total_piece_count=2))
    for n in range(2):
        store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
    buf = bytearray(2 * PIECE)
    rec = storage.read_piece_into("mgr-task", 1, buf)
    assert bytes(buf[:rec.size]) == content[PIECE:]
    n = storage.read_spans_into("mgr-task", [(100, 300), (PIECE, 64)], buf)
    assert bytes(buf[:n]) == content[100:400] + content[PIECE:PIECE + 64]
    with pytest.raises(StorageError):
        storage.read_piece_into("ghost", 0, buf)


# -- byte-identical serve across paths vs the read_piece oracle --------------

def test_native_read_into_matches_oracle(tmp_path):
    from dragonfly2_tpu.storage.local_store import _native

    nb = _native()
    if nb is None:
        pytest.skip("native library unavailable")
    store, content = _store_with_content(tmp_path)
    from dragonfly2_tpu.pkg import digest as pkgdigest

    buf = bytearray(PIECE)
    fd = store.data_fd()
    for n in range(4):
        rec = store.metadata.pieces[n]
        got, crc = nb.read_piece_crc_into(fd, rec.offset, buf)
        # read_piece_crc_into reads to the buffer's capacity or EOF;
        # compare the piece window against the oracle.
        assert got >= rec.size or rec.offset + got == len(content)
        assert bytes(buf[:rec.size]) == store.read_piece(n)
        if got == rec.size:
            assert crc == pkgdigest.crc32c(store.read_piece(n))


def test_aiohttp_upload_serve_matches_oracle(run_async, tmp_path):
    """The aiohttp upload server (forced off the native fast path via a
    rate limit) serves every piece and arbitrary ranges byte-identical to
    the oracle, for a completed AND an in-progress store."""

    async def body():
        storage = StorageManager(StorageOption(data_dir=str(tmp_path / "d")))
        content = random.Random(7).randbytes(3 * PIECE + 999)
        store = storage.register_task(TaskStoreMetadata(
            task_id="up-task", content_length=len(content), piece_size=PIECE,
            total_piece_count=4))
        for n in range(3):   # tail piece NOT landed: in-progress store
            store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
        upload = UploadManager(storage, rate_limit=1 << 40)
        port = await upload.serve("127.0.0.1", 0)
        assert upload._native_srv is None, "aiohttp path expected"
        base = f"http://127.0.0.1:{port}/download/up/up-task"
        try:
            async with aiohttp.ClientSession() as http:
                for n in range(3):
                    async with http.get(base, params={"pieceNum": str(n)}) as r:
                        assert r.status == 200 or r.status == 206
                        assert await r.read() == store.read_piece(n)
                # A landed window of the IN-PROGRESS store, spanning two
                # pieces, with the Content-Range denominator naming the
                # full content length (not the partial file size).
                lo, hi = PIECE - 37, 2 * PIECE + 36
                async with http.get(
                        base, headers={"Range": f"bytes={lo}-{hi}"}) as r:
                    assert r.status == 206
                    assert await r.read() == content[lo:hi + 1]
                    assert r.headers["Content-Range"].endswith(
                        f"/{len(content)}")
                # A window crossing the unlanded tail → 416.
                async with http.get(
                        base,
                        headers={"Range":
                                 f"bytes={3 * PIECE - 10}-{3 * PIECE + 10}"}) as r:
                    assert r.status == 416
        finally:
            await upload.close()

    run_async(body(), timeout=60)


def _make_tm(storage):
    from dragonfly2_tpu.daemon.peer.piece_manager import (
        PieceManager,
        PieceManagerOption,
    )
    from dragonfly2_tpu.daemon.peer.task_manager import TaskManager

    return TaskManager(storage, PieceManager(PieceManagerOption()))


def test_stream_span_path_matches_oracle(run_async, tmp_path):
    """The coalesced pooled span stream (completed-store reuse) emits the
    exact oracle bytes — whole object and ranges cut mid-piece on both
    ends — and every pooled view it borrowed goes back to the pool
    (acquire/release balance; rule 6 of docs/ZERO_COPY.md)."""

    async def body():
        from dragonfly2_tpu.daemon.peer.task_manager import StreamTaskRequest
        from dragonfly2_tpu.pkg.piece import Range

        storage = StorageManager(StorageOption(data_dir=str(tmp_path / "d")))
        req = StreamTaskRequest(url="mem://span-oracle")
        content = random.Random(8).randbytes(7 * PIECE + 123)
        store = storage.register_task(TaskStoreMetadata(
            task_id=req.task_id(), url=req.url,
            content_length=len(content), piece_size=PIECE,
            total_piece_count=8))
        for n in range(8):
            store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
        store.mark_done()
        tm = _make_tm(storage)
        oracle = b"".join(store.read_piece(n) for n in range(8))
        assert oracle == content
        before = read_buffer_stats()
        attrs, body_iter = await tm.start_stream_task(req)
        assert attrs["from_reuse"] and attrs["local_store"] is store
        got = b"".join([bytes(c) async for c in body_iter])
        assert got == oracle
        for rng in (Range(PIECE // 2, 3 * PIECE),       # mid-piece cuts
                    Range(0, PIECE),                    # exact piece
                    Range(6 * PIECE, 2 * PIECE)):       # tail overshoot
            attrs, body_iter = await tm.start_stream_task(
                StreamTaskRequest(url=req.url, range=rng))
            got = b"".join([bytes(c) async for c in body_iter])
            end = min(rng.start + rng.length, len(content))
            assert got == content[rng.start:end], rng
        after = read_buffer_stats()
        assert after["outstanding"] == before["outstanding"], (before, after)

    run_async(body(), timeout=60)


# -- in-progress sendfile windows --------------------------------------------

def test_sendfile_window_in_progress_piece_boundaries(tmp_path):
    """sendfile_window on a mid-download store: landed windows (including
    exact piece-boundary edges) are served; anything touching an unlanded
    piece streams instead; whole-object still requires completion."""
    from dragonfly2_tpu.pkg.piece import Range

    content = random.Random(9).randbytes(4 * PIECE)
    store = LocalTaskStore.create(
        str(tmp_path / "ip-task"),
        TaskStoreMetadata(task_id="ip-task", content_length=len(content),
                          piece_size=PIECE, total_piece_count=4))
    for n in (0, 1, 3):   # piece 2 missing: landed prefix is [0, 2*PIECE)
        store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
    attrs = {"local_store": store}
    total = len(content)

    def window(rng):
        return P2PTransport.sendfile_window(attrs, rng, total)

    # Landed prefix, exact piece boundary.
    assert window(Range(0, 2 * PIECE)) == (store, 0, 2 * PIECE)
    # One byte over the boundary into the missing piece → stream.
    assert window(Range(0, 2 * PIECE + 1)) is None
    # Window fully inside the landed tail piece.
    assert window(Range(3 * PIECE, PIECE)) == (store, 3 * PIECE, PIECE)
    # Window starting at the last landed byte of the prefix.
    assert window(Range(2 * PIECE - 1, 1)) == (store, 2 * PIECE - 1, 1)
    # Whole object on an incomplete store → stream.
    assert window(None) is None
    # Landing the gap piece makes the store complete → whole object ok.
    store.write_piece(2, content[2 * PIECE:3 * PIECE])
    assert window(None) == (store, 0, total)
    assert window(Range(0, 2 * PIECE + 1)) == (store, 0, 2 * PIECE + 1)


def test_sendfile_window_completed_semantics_unchanged(tmp_path):
    """The pre-existing completed-store contract: file size must equal the
    content total for whole-object windows; EOF-overshooting ranges clamp."""
    from dragonfly2_tpu.pkg.piece import Range

    store, content = _store_with_content(tmp_path, name="cw-task")
    attrs = {"local_store": store}
    total = len(content)
    assert P2PTransport.sendfile_window(attrs, None, total) == (store, 0, total)
    w = P2PTransport.sendfile_window(attrs, Range(total - 10, 100), total)
    assert w == (store, total - 10, 10)
    assert P2PTransport.sendfile_window(attrs, Range(total, 10), total) is None
    assert P2PTransport.sendfile_window({}, None, total) is None
    assert P2PTransport.sendfile_window(attrs, None, -1) is None


# -- leak guard under faults -------------------------------------------------

def test_read_path_leak_guard_under_faults(run_async, tmp_path):
    """Acquire/release balance across the unified read path when reads
    FAIL mid-serve: a data file truncated under the store (the chaos
    truncate fault's storage-visible shape) and a consumer that abandons
    the stream early must both return every borrowed pooled view."""

    async def body():
        from dragonfly2_tpu.daemon.peer.task_manager import StreamTaskRequest

        storage = StorageManager(StorageOption(data_dir=str(tmp_path / "d")))
        req = StreamTaskRequest(url="mem://leak-guard")
        content = random.Random(10).randbytes(6 * PIECE)
        store = storage.register_task(TaskStoreMetadata(
            task_id=req.task_id(), url=req.url,
            content_length=len(content), piece_size=PIECE,
            total_piece_count=6))
        for n in range(6):
            store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
        store.mark_done()
        tm = _make_tm(storage)
        before = read_buffer_stats()

        # Early-abandoning consumer: one chunk, then aclose.
        attrs, body_iter = await tm.start_stream_task(req)
        async for c in body_iter:
            assert bytes(c) == content[:len(c)]
            break
        await body_iter.aclose()

        # Truncated-under-us data file: the stream raises, views return.
        attrs, body_iter = await tm.start_stream_task(req)
        store.close()
        with open(store.data_path, "r+b") as f:
            f.truncate(PIECE // 2)
        with pytest.raises(Exception):
            async for c in body_iter:
                pass

        # Direct primitive fault paths.
        with pytest.raises(StorageError):
            store.read_range(0, 2 * PIECE)
        with pytest.raises((StorageError, OSError)):
            store.export_range(str(tmp_path / "out.bin"), 0, 2 * PIECE)
        after = read_buffer_stats()
        assert after["outstanding"] == before["outstanding"], (before, after)

    run_async(body(), timeout=60)


# -- pool observability ------------------------------------------------------

def test_bufpool_metrics_scrapeable():
    """bufpool_* metrics land in the shared registry (what
    pkg/metrics_server serves at /metrics), and stats() balances."""
    pool = BufferPool(name="rp_test_pool")
    v1 = pool.acquire(1024)
    v2 = pool.acquire(2048)
    pool.release(v1)
    pool.release(v2)
    v3 = pool.acquire(512)   # pooled hit
    pool.release(v3)
    s = pool.stats()
    assert s["acquires"] == 3 and s["releases"] == 3
    assert s["outstanding"] == 0
    assert s["retained_bytes"] >= 1024 + 2048
    body, _ = metrics.render()
    text = body.decode()
    acq = metrics.parse_labeled_samples(
        text, "dragonfly_tpu_bufpool_acquires_total", "pool")
    assert acq.get("rp_test_pool", 0) == 3
    retained = [ln for ln in text.splitlines()
                if ln.startswith("dragonfly_tpu_bufpool_retained_bytes")
                and 'pool="rp_test_pool"' in ln]
    assert retained and float(retained[0].rsplit(" ", 1)[1]) >= 3072
    # The storage read pool is registered under its well-known name.
    assert isinstance(read_buffer_stats()["retained_bytes"], int)

