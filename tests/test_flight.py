"""Flight recorder: event rings, critical-path autopsy, debug endpoints,
pod-level straggler attribution, and the chaos-seeded black-box e2e.

The acceptance case: a degraded download (stalled parent + one corrupt
body, seeded via pkg/chaos) must yield a /debug/flight/<task_id> autopsy
whose phase breakdown sums to the task's wall time (±5%) with ``stall``
dominant — and ``dfget --explain`` renders the same waterfall end-to-end.
"""

from __future__ import annotations

import asyncio
import dis
import gc
import json
import math
import weakref

import pytest

from dragonfly2_tpu.pkg import chaos as chaos_mod
from dragonfly2_tpu.pkg import flight
from dragonfly2_tpu.pkg import tracing
from dragonfly2_tpu.storage import (
    StorageManager,
    StorageOption,
    TaskStoreMetadata,
)


@pytest.fixture(autouse=True)
def _chaos_disabled():
    chaos_mod.disable()
    yield
    chaos_mod.disable()


def synthetic(events, wall):
    """A TaskFlight with a hand-authored event timeline (tuples of
    (t, code, piece, aux, note)) — analyzer tests need exact clocks."""
    tf = flight.TaskFlight("synthetic")
    for e in events:
        tf._ring[tf._n % tf._cap] = e
        tf._n += 1
    tf.state = "done"
    tf._end_pc = wall
    return tf


# --------------------------------------------------------------------- #
# Recorder core: bounds, eviction, hot-path allocation guard
# --------------------------------------------------------------------- #

class TestRecorderBounds:
    def test_ring_and_index_stay_capped_under_soak(self):
        """Thousands of pieces across dozens of tasks: the per-task ring
        never grows, the piece-timing index stays capped, and the global
        task index evicts instead of growing."""
        rec = flight.FlightRecorder(capacity=128, max_tasks=8)
        for t in range(40):
            tf = rec.task(f"soak-{t}")
            for n in range(3000):
                tf.record(flight.EV_REQUEST, n, 0.0, "10.0.0.1:80")
                tf.record(flight.EV_LANDED, n, 1.0, "cross")
            if t % 2 == 0:
                rec.finish_task(f"soak-{t}", "done")
        assert len(rec._tasks) <= 8
        for tf in rec._tasks.values():
            assert len(tf._ring) == 128          # preallocated, never grew
            assert len(tf._piece_track) <= tf._piece_cap
            assert tf.events_total == 6000
            assert tf.events_dropped == 6000 - 128
            assert len(tf.events()) == 128

    def test_eviction_releases_memory(self):
        rec = flight.FlightRecorder(capacity=32, max_tasks=4)
        probe = rec.task("probe")
        probe.finish("done")
        ref = weakref.ref(probe)
        del probe
        for i in range(8):
            rec.task(f"filler-{i}")
        gc.collect()
        assert ref() is None, "evicted TaskFlight still referenced"

    def test_eviction_prefers_finished_tasks(self):
        rec = flight.FlightRecorder(capacity=32, max_tasks=2)
        rec.task("running-1")
        rec.task("done-1").finish("done")
        rec.task("new-1")
        assert "running-1" in rec._tasks and "done-1" not in rec._tasks

    def test_record_allocates_no_dicts_on_hot_path(self):
        """The always-on contract: one tuple per event, no per-event dict
        construction in the record bytecode."""
        ops = {i.opname
               for i in dis.get_instructions(flight.TaskFlight.record)}
        assert "BUILD_MAP" not in ops and "MAP_ADD" not in ops, ops

    def test_finish_is_idempotent_and_observes_histogram(self):
        from dragonfly2_tpu.pkg import metrics as metrics_mod

        rec = flight.FlightRecorder()
        tf = rec.task("hist-t")
        tf.record(flight.EV_REQUEST, 0, 0.0, "p")
        tf.record(flight.EV_LANDED, 0, 5.0, "cross")
        rec.finish_task("hist-t", "done")
        wall_first = tf.wall_s()
        rec.finish_task("hist-t", "failed")   # no-op: already terminal
        assert tf.state == "done"
        assert tf.wall_s() == wall_first
        text = metrics_mod.render()[0].decode()
        assert "dragonfly_tpu_peer_task_phase_seconds" in text


# --------------------------------------------------------------------- #
# Analyzer: the phase fold
# --------------------------------------------------------------------- #

class TestAnalyzer:
    def test_phases_partition_wall_exactly(self):
        tf = synthetic([
            (0.0, flight.EV_REGISTER, -1, 0.0, ""),
            (1.0, flight.EV_SCHEDULED, -1, 0.0, "normal_task"),
            (1.0, flight.EV_REQUEST, 0, 0.0, "1.1.1.1:80"),
            (1.1, flight.EV_FIRST_BYTE, 0, 0.0, ""),
            (2.0, flight.EV_LANDED, 0, 900.0, "cross"),
            (2.0, flight.EV_STORE_START, 0, 0.0, ""),
            (2.5, flight.EV_STORED, 0, 0.0, ""),
            (2.5, flight.EV_VERIFY_START, -1, 0.0, ""),
            (3.0, flight.EV_VERIFIED, -1, 0.0, ""),
        ], wall=4.0)
        rep = flight.analyze(tf)
        p = rep["phases"]
        assert p["sched_wait"] == pytest.approx(1.0)
        assert p["dcn"] == pytest.approx(1.0)
        assert p["store"] == pytest.approx(0.5)
        assert p["verify"] == pytest.approx(0.5)
        assert rep["other_s"] == pytest.approx(1.0)
        assert sum(p.values()) + rep["other_s"] == pytest.approx(4.0)
        assert rep["dominant_phase"] in ("dcn", "sched_wait")

    def test_overlap_priority_work_beats_waiting(self):
        """A stall that overlaps a concurrent healthy transfer did not
        cost wall time: the dcn segment wins the overlap."""
        tf = synthetic([
            (0.0, flight.EV_REQUEST, 0, 0.0, "a:1"),
            # piece 0 never produces: request..failed(stall) at 2.0
            (0.0, flight.EV_REQUEST, 1, 0.0, "b:1"),
            (0.1, flight.EV_FIRST_BYTE, 1, 0.0, ""),
            (1.0, flight.EV_LANDED, 1, 1000.0, "cross"),
            (2.0, flight.EV_FAILED, 0, 0.0, "stall"),
        ], wall=2.0)
        rep = flight.analyze(tf)
        assert rep["phases"]["dcn"] == pytest.approx(1.0)
        assert rep["phases"]["stall"] == pytest.approx(1.0)
        assert rep["dominant_phase"] in ("dcn", "stall")

    def test_slow_first_byte_splits_into_stall(self):
        tf = synthetic([
            (0.0, flight.EV_REQUEST, 0, 0.0, "a:1"),
            (1.0, flight.EV_FIRST_BYTE, 0, 0.0, ""),
            (1.2, flight.EV_LANDED, 0, 1200.0, "cross"),
        ], wall=1.2)
        rep = flight.analyze(tf)
        assert rep["phases"]["stall"] == pytest.approx(1.0)
        assert rep["phases"]["dcn"] == pytest.approx(0.2)
        assert rep["dominant_phase"] == "stall"

    def test_intra_slice_transfers_fold_into_ici(self):
        tf = synthetic([
            (0.0, flight.EV_REQUEST, 0, 0.0, "a:1"),
            (0.5, flight.EV_LANDED, 0, 500.0, "intra"),
        ], wall=0.5)
        rep = flight.analyze(tf)
        assert rep["phases"]["ici"] == pytest.approx(0.5)
        assert rep["dominant_phase"] == "ici"

    def test_origin_interval_from_cost(self):
        tf = synthetic([
            (2.0, flight.EV_SOURCE_LANDED, 0, 1500.0, ""),
        ], wall=2.0)
        rep = flight.analyze(tf)
        assert rep["phases"]["origin"] == pytest.approx(1.5)

    def test_open_request_tail_is_the_black_box_stall(self):
        """A request still unanswered when the task ends (the classic
        black-box failure) classifies its tail as stall."""
        tf = synthetic([
            (0.0, flight.EV_REQUEST, 0, 0.0, "a:1"),
        ], wall=3.0)
        rep = flight.analyze(tf)
        assert rep["phases"]["stall"] == pytest.approx(3.0)

    def test_waterfall_rows_and_render(self):
        tf = synthetic([
            (0.0, flight.EV_REQUEST, 0, 0.0, "a:1"),
            (0.5, flight.EV_FAILED, 0, 0.0, "corrupt"),
            (0.5, flight.EV_REQUEST, 0, 0.0, "b:1"),
            (0.9, flight.EV_LANDED, 0, 400.0, "cross"),
        ], wall=1.0)
        rep = flight.analyze(tf)
        row = rep["pieces"][0]
        assert row["attempts"] == 2
        assert row["status"] == "ok"
        assert row["reason"] == "corrupt"   # the retry's cause stays visible
        assert row["parent"] == "b:1"
        text = flight.render_waterfall(rep)
        assert "phase breakdown:" in text
        assert "p0" in text and "x2 ok" in text

    def test_waterfall_truncates_past_cap(self):
        events = []
        for n in range(600):
            events.append((n * 0.001, flight.EV_REQUEST, n, 0.0, "a:1"))
            events.append((n * 0.001 + 0.0005, flight.EV_LANDED, n, 1.0,
                           "cross"))
        tf = synthetic(events, wall=1.0)
        rep = flight.analyze(tf)
        assert len(rep["pieces"]) == 256 and rep["pieces_truncated"]


# --------------------------------------------------------------------- #
# Post-mortem bundles
# --------------------------------------------------------------------- #

def _read_bundle(path):
    import gzip

    if str(path).endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    return json.loads(path.read_text())


class TestPostmortem:
    def test_failure_dumps_bounded_gzip_bundles(self, tmp_path):
        rec = flight.FlightRecorder(capacity=64, max_tasks=64,
                                    dump_dir=str(tmp_path), keep_bundles=5)
        rec.scorecard_snapshot = {"serve_ewma_ms": 12.5, "straggler": False}
        for i in range(9):
            tf = rec.task(f"boom-{i}")
            tf.record(flight.EV_REQUEST, 0, 0.0, "a:1")
            rec.finish_task(f"boom-{i}", "failed", note="chaos ate it")
        bundles = sorted(tmp_path.glob("flight-*.json.gz"))
        assert 0 < len(bundles) <= 5, bundles
        doc = _read_bundle(bundles[-1])
        assert doc["report"]["state"] == "failed"
        assert doc["report"]["note"] == "chaos ate it"
        names = [e["event"] for e in doc["events"]]
        assert "request" in names and "task_failed" in names
        # The subject host's fleet scorecard rides in the bundle — the
        # failure autopsy carries the host's fleet-wide standing.
        assert doc["scorecard"]["serve_ewma_ms"] == 12.5

    def test_rotation_keeps_the_newest_bundles(self, tmp_path):
        """The dump dir is a ring, not a landfill: with keep_bundles=3,
        nine failures leave exactly the three NEWEST bundles on disk
        (mtime-ordered; same-second ties break on the filename stamp)."""
        import os

        rec = flight.FlightRecorder(dump_dir=str(tmp_path), keep_bundles=3)
        for i in range(9):
            tf = rec.task(f"rot-{i}")
            tf.record(flight.EV_REQUEST, 0, 0.0, "a:1")
            rec.finish_task(f"rot-{i}", "failed")
            # Force a strict mtime order even on coarse filesystems.
            for j, p in enumerate(sorted(
                    tmp_path.glob("flight-*.json.gz"))):
                os.utime(p, (1000 + j, 1000 + j))
        survivors = sorted(tmp_path.glob("flight-*.json.gz"))
        assert len(survivors) == 3
        kept_tasks = {_read_bundle(p)["report"]["task_id"]
                      for p in survivors}
        assert kept_tasks == {"rot-6", "rot-7", "rot-8"}

    def test_rotation_counts_json_and_gz_alike(self, tmp_path):
        """Pre-gzip-era ``.json`` bundles and fresh ``.json.gz`` ones
        share ONE rotation budget: with keep_bundles=4, three legacy
        plain bundles plus four fresh failures leave exactly the four
        newest files — the oldest legacies are reaped, not grandfathered
        into a second budget."""
        import os

        for i in range(3):
            p = tmp_path / f"flight-legacy-{i}-{i}.json"
            p.write_text(json.dumps({"report": {"task_id": f"legacy-{i}"}}))
            os.utime(p, (500 + i, 500 + i))
        rec = flight.FlightRecorder(dump_dir=str(tmp_path), keep_bundles=4)
        for i in range(4):
            tf = rec.task(f"mix-{i}")
            tf.record(flight.EV_REQUEST, 0, 0.0, "a:1")
            rec.finish_task(f"mix-{i}", "failed")
            for j, p in enumerate(sorted(
                    tmp_path.glob("flight-mix-*.json.gz"))):
                os.utime(p, (1000 + j, 1000 + j))
        rec._prune()
        survivors = sorted(str(p.name) for p in tmp_path.glob("flight-*"))
        assert len(survivors) == 4, survivors
        kept = {_read_bundle(tmp_path / name)["report"]["task_id"]
                for name in survivors}
        assert kept == {"mix-0", "mix-1", "mix-2", "mix-3"}

    def test_default_rotation_budget_is_32(self):
        assert flight.FlightRecorder().keep_bundles == 32
        from dragonfly2_tpu.daemon.config import DaemonConfig

        assert DaemonConfig().flight_keep_bundles == 32

    def test_success_does_not_dump(self, tmp_path):
        rec = flight.FlightRecorder(dump_dir=str(tmp_path))
        rec.task("fine")
        rec.finish_task("fine", "done")
        assert not list(tmp_path.glob("flight-*"))


# --------------------------------------------------------------------- #
# Pod aggregation (scheduler side)
# --------------------------------------------------------------------- #

class TestPodAggregator:
    def test_straggler_attribution_and_quarantine_correlation(self):
        agg = flight.PodAggregator()
        # Host A: fast, dcn-bound; host B: few pieces, stall-bound.
        for _ in range(10):
            agg.note_piece("t1", "host-a", {"dcn_ms": 20, "stall_ms": 0,
                                            "store_ms": 5})
        for _ in range(2):
            agg.note_piece("t1", "host-b", {"dcn_ms": 30, "stall_ms": 900,
                                            "store_ms": 5})
        agg.note_failure("t1", "host-c", "corrupt")
        agg.note_quarantine("t1", "host-c", "corrupt")
        rep = agg.report("t1")
        assert rep["slowest_host"] == "host-b"
        assert rep["dominant_phase"] == "stall"
        by_host = {h["host"]: h for h in rep["hosts"]}
        assert by_host["host-b"]["dominant_phase"] == "stall"
        assert by_host["host-c"]["failures"] == {"corrupt": 1}
        assert rep["quarantine"] == [{"host": "host-c", "reason": "corrupt"}]
        assert agg.report("nope") is None

    def test_legacy_report_without_timings_counts_as_dcn(self):
        agg = flight.PodAggregator()
        agg.note_piece("t2", "h", None, cost_ms=40)
        rep = agg.report("t2")
        assert rep["hosts"][0]["ms"]["dcn"] == 40

    def test_bounded_task_index(self):
        agg = flight.PodAggregator(max_tasks=4)
        for i in range(20):
            agg.note_piece(f"t{i}", "h", None, 1)
        assert len(agg._tasks) <= 4

    def test_scheduler_feeds_aggregator_from_piece_reports(self, run_async):
        from dragonfly2_tpu.scheduler.config import SchedulerConfig
        from dragonfly2_tpu.scheduler.service import SchedulerService

        async def body():
            svc = SchedulerService(SchedulerConfig())
            mk = lambda host, peer: {  # noqa: E731
                "host": {"id": host, "hostname": host, "ip": "10.0.0.1",
                         "port": 1, "upload_port": 2},
                "peer_id": peer, "task_id": "pod-task", "url": "http://o/f"}
            host_a, task, peer_a = svc._resolve(mk("host-a", "peer-a"))
            _hb, _t, peer_b = svc._resolve(mk("host-b", "peer-b"))
            svc._handle_pieces_finished({"pieces": [
                {"piece_num": 0, "range_start": 0, "range_size": 4,
                 "download_cost_ms": 25,
                 "timings": {"dcn_ms": 20, "stall_ms": 0, "store_ms": 5}},
                {"piece_num": 1, "range_start": 4, "range_size": 4,
                 "download_cost_ms": 1000,
                 "timings": {"dcn_ms": 100, "stall_ms": 880,
                             "store_ms": 20}},
            ]}, task, peer_a)
            svc._handle_piece_finished({"piece": {
                "piece_num": 0, "range_start": 0, "range_size": 4,
                "download_cost_ms": 7,
                "timings": {"dcn_ms": 7, "stall_ms": 0}}}, task, peer_b)
            # Typed failure against a known parent host.
            svc._handle_piece_failed({"piece_num": 2, "parent_id": "peer-b",
                                      "temporary": False,
                                      "reason": "corrupt"}, task, peer_a)
            rep = svc.pod_flight.report("pod-task")
            assert rep is not None
            by_host = {h["host"]: h for h in rep["hosts"]}
            assert by_host["host-a"]["pieces"] == 2
            assert by_host["host-a"]["dominant_phase"] == "stall"
            assert rep["slowest_host"] == "host-a"
            assert by_host["host-b"]["failures"] == {"corrupt": 1}
            # One corrupt strike quarantines the host — correlated.
            assert rep["quarantine"] == [{"host": "host-b",
                                          "reason": "corrupt"}]

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# Debug endpoints
# --------------------------------------------------------------------- #

class TestDebugEndpoints:
    def test_flight_and_pod_routes(self, run_async):
        import aiohttp

        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        async def body():
            rec = flight.FlightRecorder()
            tf = rec.task("dbg-task")
            tf.record(flight.EV_REQUEST, 0, 0.0, "a:1")
            tf.record(flight.EV_LANDED, 0, 12.0, "cross")
            rec.finish_task("dbg-task", "done")
            agg = flight.PodAggregator()
            agg.note_piece("dbg-task", "host-a",
                           {"dcn_ms": 12, "stall_ms": 0})
            srv = MetricsServer(flight=rec, pod_flight=agg)
            port = await srv.serve("127.0.0.1", 0)
            base = f"http://127.0.0.1:{port}"
            try:
                async with aiohttp.ClientSession() as sess:
                    async with sess.get(f"{base}/debug/flight") as r:
                        assert r.status == 200
                        idx = await r.json()
                    assert any(t["task_id"] == "dbg-task"
                               for t in idx["tasks"])
                    async with sess.get(f"{base}/debug/flight/dbg-task") as r:
                        assert r.status == 200
                        rep = await r.json()
                    assert rep["state"] == "done"
                    assert set(rep["phases"]) == set(flight.PHASES)
                    async with sess.get(
                            f"{base}/debug/flight/dbg-task?format=text") as r:
                        text = await r.text()
                    assert "phase breakdown:" in text
                    async with sess.get(f"{base}/debug/flight/absent") as r:
                        assert r.status == 404
                    async with sess.get(f"{base}/debug/pod/dbg-task") as r:
                        assert r.status == 200
                        pod = await r.json()
                    assert pod["hosts"][0]["host"] == "host-a"
            finally:
                await srv.close()

        run_async(body(), timeout=60)

    def test_routes_404_without_providers(self, run_async):
        import aiohttp

        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        async def body():
            srv = MetricsServer()
            port = await srv.serve("127.0.0.1", 0)
            try:
                async with aiohttp.ClientSession() as sess:
                    for path in ("/debug/flight", "/debug/flight/x",
                                 "/debug/pod/x"):
                        async with sess.get(
                                f"http://127.0.0.1:{port}{path}") as r:
                            assert r.status == 404, path
            finally:
                await srv.close()

        run_async(body(), timeout=60)


# --------------------------------------------------------------------- #
# Wire schema
# --------------------------------------------------------------------- #

class TestWireSchema:
    def test_piece_timings_field(self):
        from dragonfly2_tpu.proto import wire

        wire.validate_stream_msg("Scheduler.AnnouncePeer", {
            "type": "piece_finished",
            "piece": {"piece_num": 1, "range_start": 0, "range_size": 4,
                      "download_cost_ms": 9,
                      "timings": {"dcn_ms": 7, "stall_ms": 0,
                                  "store_ms": 2}}})
        with pytest.raises(wire.SchemaError, match="timings"):
            wire.validate_stream_msg("Scheduler.AnnouncePeer", {
                "type": "piece_finished",
                "piece": {"piece_num": 1, "timings": 7}})

    def test_flight_report_schema(self):
        from dragonfly2_tpu.proto import wire

        wire.validate_unary("Daemon.FlightReport", {"task_id": "t"})
        with pytest.raises(wire.SchemaError, match="task_id"):
            wire.validate_unary("Daemon.FlightReport", {})


# --------------------------------------------------------------------- #
# Chaos-seeded degraded download: the /debug/flight acceptance case
# --------------------------------------------------------------------- #

class _ParentDaemon:
    """Minimal real parent: storage with the completed task + the REAL
    peer rpc (SyncPieceTasks) and upload (piece HTTP) servers."""

    def __init__(self, rpc, upload, storage, peer_id):
        self.rpc = rpc
        self.upload = upload
        self.storage = storage
        self.peer_id = peer_id

    @property
    def wire(self):
        return {"id": self.peer_id,
                "host": {"ip": "127.0.0.1",
                         "port": self.rpc.peer_server.port(),
                         "upload_port": self.upload.port}}

    async def close(self):
        await self.rpc.close()
        await self.upload.close()
        self.storage.close()


async def _start_parent(tmp_path, name, task_id, content, piece_size):
    from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager
    from dragonfly2_tpu.daemon.peer.task_manager import TaskManager
    from dragonfly2_tpu.daemon.rpcserver import DaemonRpcServer
    from dragonfly2_tpu.daemon.upload import UploadManager
    from dragonfly2_tpu.pkg.types import NetAddr

    storage = StorageManager(
        StorageOption(data_dir=str(tmp_path / f"{name}-data")))
    total = math.ceil(len(content) / piece_size)
    store = storage.register_task(TaskStoreMetadata(
        task_id=task_id, peer_id=name, url="http://origin/blob",
        piece_size=piece_size, content_length=len(content),
        total_piece_count=total))
    for n in range(total):
        store.write_piece(n, content[n * piece_size:(n + 1) * piece_size])
    store.mark_done()
    tm = TaskManager(storage, PieceManager())
    rpc = DaemonRpcServer(tm)
    await rpc.serve_peer(NetAddr.tcp("127.0.0.1", 0))
    upload = UploadManager(storage)
    await upload.serve("127.0.0.1", 0)
    return _ParentDaemon(rpc, upload, storage, name)


class TestChaosAutopsyE2E:
    def test_stalled_parent_autopsy_names_stall(self, run_async, tmp_path):
        """Stalled parent + one corrupt body: the task still completes;
        the autopsy's phases sum to wall time (±5%) and name ``stall``
        dominant; /debug/flight serves the same report."""
        import random

        import aiohttp

        from tests.test_chaos import FakeAnnounceStream, FakeSchedulerClient
        from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor
        from dragonfly2_tpu.daemon.peer.piece_downloader import (
            PieceDownloader,
        )
        from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager
        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        piece_size = 8192
        content = bytes(random.Random(42).randbytes(6 * piece_size))
        task_id = "flight-chaos-task"

        async def body():
            parent_a = await _start_parent(tmp_path, "parent-a", task_id,
                                           content, piece_size)
            parent_b = await _start_parent(tmp_path, "parent-b", task_id,
                                           content, piece_size)
            child_storage = StorageManager(
                StorageOption(data_dir=str(tmp_path / "child-data")))
            store = child_storage.register_task(TaskStoreMetadata(
                task_id=task_id, peer_id="child-peer",
                url="http://origin/blob"))
            announce = FakeAnnounceStream([{
                "type": "normal_task",
                "task": {"content_length": len(content),
                         "piece_size": piece_size,
                         "total_piece_count": 6},
                "parents": [parent_a.wire, parent_b.wire],
            }])
            sched = FakeSchedulerClient([announce])
            conductor = PeerTaskConductor(
                task_id=task_id, peer_id="child-peer",
                url="http://origin/blob", store=store,
                scheduler_client=sched, piece_manager=PieceManager(),
                host_info={"id": "child-host"}, disable_back_source=True)
            # A fast watchdog so the seeded stall trips in ~1s, not 10.
            await conductor.downloader.close()
            conductor.downloader = PieceDownloader(idle_timeout=1.0)

            # The seeded schedule: parent A's FIRST piece body goes silent
            # (30s > the watchdog) and one body anywhere arrives corrupt.
            chaos_mod.enable(chaos_mod.parse_spec({"seed": 7, "rules": [
                {"site": "piece.body", "kind": "stall", "rate": 1.0,
                 "stall_s": 30.0, "max_fires": 1,
                 "key_substr": f":{parent_a.upload.port}|"},
                {"site": "piece.body", "kind": "corrupt", "at": [1],
                 "max_fires": 1,
                 "key_substr": f":{parent_b.upload.port}|"},
            ]}))
            try:
                await conductor.run()
                assert store.is_complete()
                assert store.read_range(0, len(content)) == content
                flight.recorder().finish_task(task_id, "done")

                fabric = chaos_mod.enabled()
                kinds = fabric.injected_by_kind()
                assert kinds.get("stall", 0) == 1, kinds
                assert kinds.get("corrupt", 0) == 1, kinds

                # The autopsy, served exactly as operators reach it.
                srv = MetricsServer(flight=flight.recorder())
                port = await srv.serve("127.0.0.1", 0)
                try:
                    async with aiohttp.ClientSession() as sess:
                        async with sess.get(
                                f"http://127.0.0.1:{port}/debug/flight/"
                                f"{task_id}") as r:
                            assert r.status == 200
                            rep = await r.json()
                        async with sess.get(
                                f"http://127.0.0.1:{port}/debug/flight/"
                                f"{task_id}?format=text") as r:
                            text = await r.text()
                finally:
                    await srv.close()

                # Phase breakdown sums to the task wall time (±5%) ...
                covered = sum(rep["phases"].values())
                assert covered + rep["other_s"] == \
                    pytest.approx(rep["wall_s"], rel=1e-6)
                assert covered >= 0.95 * rep["wall_s"], rep
                # ... and the stalled parent is named the dominant cause.
                assert rep["dominant_phase"] == "stall", rep["phases"]
                assert rep["phases"]["stall"] >= 0.5 * rep["wall_s"]
                counts = rep["event_counts"]
                assert counts.get("failed", 0) >= 2   # stall + corrupt
                rows = {p["piece"]: p for p in rep["pieces"]}
                assert len(rows) == 6
                assert all(p["status"] == "ok" for p in rows.values())
                assert any(p["reason"] == "stall" for p in rows.values())
                assert "dominant=stall" in text
                # Piece reports carried the per-phase timings upstream for
                # the scheduler's pod aggregation.
                reported = []
                for m in announce.sent:
                    if m.get("type") == "piece_finished":
                        reported.append(m["piece"])
                    elif m.get("type") == "pieces_finished":
                        reported.extend(m["pieces"])
                assert len(reported) == 6
                assert any("timings" in p and p["timings"].get("dcn_ms", -1)
                           >= 0 for p in reported), reported
            finally:
                chaos_mod.disable()
                await parent_a.close()
                await parent_b.close()
                child_storage.close()

        run_async(body(), timeout=120)


# --------------------------------------------------------------------- #
# dfget --explain: the same waterfall end-to-end
# --------------------------------------------------------------------- #

class TestDfgetExplain:
    def test_explain_renders_waterfall_end_to_end(self, run_async,
                                                  tmp_path):
        from dragonfly2_tpu.client import dfget as dfget_lib
        from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager
        from dragonfly2_tpu.daemon.peer.task_manager import TaskManager
        from dragonfly2_tpu.daemon.rpcserver import DaemonRpcServer
        from dragonfly2_tpu.pkg.testing import start_range_origin
        from dragonfly2_tpu.pkg.types import NetAddr

        async def body():
            content = b"flight" * 4096
            origin, url, _stats = await start_range_origin(content)
            storage = StorageManager(
                StorageOption(data_dir=str(tmp_path / "d-data")))
            tm = TaskManager(storage, PieceManager())
            rpc = DaemonRpcServer(tm)
            sock = str(tmp_path / "daemon.sock")
            await rpc.serve_download(NetAddr.unix(sock))
            out = str(tmp_path / "out.bin")
            try:
                result = await dfget_lib.download(dfget_lib.DfgetConfig(
                    url=url, output=out, daemon_sock=sock, explain=True,
                    allow_source_fallback=False))
                assert result["state"] == "done"
                assert open(out, "rb").read() == content
                fl = result["flight"]
                rep = fl["report"]
                assert rep["task_id"] == result["task_id"]
                assert rep["state"] == "done"
                # No scheduler: the time went to origin, and the autopsy
                # says so.
                assert rep["phases"]["origin"] > 0
                assert rep["dominant_phase"] == "origin"
                # The CLI prints fl["text"]; it is EXACTLY the renderer's
                # output for this report — the same waterfall /debug/flight
                # serves.
                assert fl["text"] == flight.render_waterfall(rep)
                assert "phase breakdown:" in fl["text"]
            finally:
                from dragonfly2_tpu.source.client import default_registry

                await rpc.close()
                storage.close()
                await origin.cleanup()
                await default_registry().close_all()

        run_async(body(), timeout=120)


# --------------------------------------------------------------------- #
# Traceparent across the piece HTTP hop
# --------------------------------------------------------------------- #

class TestPieceHopTracing:
    def test_upload_serve_joins_the_requesters_trace(self, run_async,
                                                     tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_downloader import (
            PieceDownloader,
        )
        from dragonfly2_tpu.daemon.upload import UploadManager

        async def body():
            storage = StorageManager(
                StorageOption(data_dir=str(tmp_path / "p-data")))
            store = storage.register_task(TaskStoreMetadata(
                task_id="trace-task", peer_id="p", url="http://o/f",
                piece_size=4, content_length=4, total_piece_count=1))
            store.write_piece(0, b"abcd")
            store.mark_done()
            # rate_limit forces the aiohttp server (the native one cannot
            # extract headers).
            upload = UploadManager(storage, rate_limit=1 << 40)
            port = await upload.serve("127.0.0.1", 0)
            dl = PieceDownloader()
            tracing.exporter().clear()
            try:
                with tracing.span("client.pull") as sp:
                    chunks, size, _cost, _dig = await dl.download_piece(
                        "127.0.0.1", port, "trace-task", 0, expected_size=4)
                assert size == 4
                serve = tracing.exporter().find(name="upload.serve")
                assert serve, "upload server recorded no serve span"
                # Same trace id: the hop no longer severs the trace.
                assert serve[0].context.trace_id == sp.context.trace_id
                assert serve[0].attrs.get("bytes") == 4
            finally:
                await dl.close()
                await upload.close()
                storage.close()

        run_async(body(), timeout=60)

    def test_untraced_pull_still_serves(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_downloader import (
            PieceDownloader,
        )
        from dragonfly2_tpu.daemon.upload import UploadManager

        async def body():
            storage = StorageManager(
                StorageOption(data_dir=str(tmp_path / "u-data")))
            store = storage.register_task(TaskStoreMetadata(
                task_id="plain-task", peer_id="p", url="http://o/f",
                piece_size=4, content_length=4, total_piece_count=1))
            store.write_piece(0, b"wxyz")
            store.mark_done()
            upload = UploadManager(storage, rate_limit=1 << 40)
            port = await upload.serve("127.0.0.1", 0)
            dl = PieceDownloader()
            try:
                chunks, size, _c, _d = await dl.download_piece(
                    "127.0.0.1", port, "plain-task", 0, expected_size=4)
                assert size == 4
            finally:
                await dl.close()
                await upload.close()
                storage.close()

        run_async(body(), timeout=60)
