"""Cluster control tower units + manager integration: frame builder
bounds, decision deltas, event journal, burst detector, telemetry spool,
ClusterSeries merge/attribution/edge events, mixed-version ``no_data``
degrade over the real keepalive wire, the keepalive payload counter
satellite, and the manager MetricsServer /debug/cluster* routes
(including scrape-under-load)."""

from __future__ import annotations

import asyncio
import time

import pytest

from dragonfly2_tpu.manager.client import ManagerClient
from dragonfly2_tpu.manager.config import ManagerConfig
from dragonfly2_tpu.manager.database import Database
from dragonfly2_tpu.manager.server import ManagerServer
from dragonfly2_tpu.pkg import cluster as clusterlib
from dragonfly2_tpu.pkg import fleet as fleetlib
from dragonfly2_tpu.pkg import metrics
from dragonfly2_tpu.pkg.cluster import (
    FRAME_MAX_BYTES,
    AdmissionBurstDetector,
    ClusterEventJournal,
    ClusterSeries,
    FrameBuilder,
    TelemetrySpool,
    render_cluster,
)
from dragonfly2_tpu.pkg.metrics import parse_labeled_samples
from dragonfly2_tpu.pkg.types import NetAddr


def _mk_obs(**kw):
    kw.setdefault("bucket_s", 0.5)
    kw.setdefault("buckets", 60)
    kw.setdefault("sampler", lambda: {"hosts_total": 8, "hosts_seed": 1,
                                      "peers_running": 3})
    return fleetlib.FleetObservatory(**kw)


def _frame(**kw):
    f = {"v": 1, "host": "s", "ts": time.time(), "window_s": 1.0,
         "counters": {}, "gauges": {}, "stragglers": [], "quarantined": [],
         "decisions": {}, "resident_bytes": 1000}
    f.update(kw)
    return f


def _samples(full_name: str, label: str) -> dict:
    return parse_labeled_samples(metrics.render()[0].decode(),
                                 full_name, label)


# -- frame builder ----------------------------------------------------------

class TestFrameBuilder:
    def test_rollup_and_window(self):
        obs = _mk_obs()
        obs.note_pieces("h1", 4, 32.0,
                        by_parent={"h2": [4, 32.0, 1 << 20,
                                          fleetlib.C_BYTES_INTRA]},
                        timings={"dcn_ms": 2, "stall_ms": 0, "store_ms": 1})
        b = FrameBuilder(obs, hostname="sched-a")
        frame = b.build()
        assert frame["v"] == 1 and frame["host"] == "sched-a"
        assert frame["counters"]["pieces_landed"] == 4
        # Zero columns are omitted, not shipped as zeros.
        assert "back_source" not in frame["counters"]
        assert frame["gauges"]["hosts_total"] == 8
        assert frame["bytes"] == clusterlib._enc_len(
            {k: v for k, v in frame.items() if k != "bytes"})
        assert frame["window_s"] >= obs.series.bucket_s
        assert frame["resident_bytes"] > 0

    def test_decision_deltas_sum_cleanly(self):
        obs = _mk_obs()
        b = FrameBuilder(obs, hostname="s")
        obs.note_handout("t1", "p1", "h1", chosen=("h2",), rejected=())
        f1 = b.build()
        assert f1["decisions"].get("handout") == 1
        f2 = b.build()                # nothing new since f1
        assert f2["decisions"] == {}
        obs.note_handout("t1", "p2", "h1", chosen=("h2",), rejected=())
        obs.note_handout("t1", "p3", "h1", chosen=("h2",), rejected=())
        f3 = b.build()
        assert f3["decisions"]["handout"] == 2
        total = sum(f["decisions"].get("handout", 0) for f in (f1, f2, f3))
        assert total == dict(obs.decisions.kind_counts)["handout"] == 3

    def test_cap_halves_host_sets(self):
        obs = _mk_obs()
        obs.scorecards._stragglers.update(
            f"straggler-host-{i:04d}.example" for i in range(512))
        b = FrameBuilder(
            obs, hostname="s", max_bytes=2048,
            quarantined=lambda: [f"bad-host-{i:04d}" for i in range(512)])
        frame = b.build()
        assert frame["bytes"] <= 2048
        assert frame["truncated"] is True
        assert 0 < len(frame["stragglers"]) < 512
        assert 0 < len(frame["quarantined"]) < 512

    def test_no_observatory_returns_none(self):
        assert FrameBuilder(None).build() is None

    def test_resident_bytes_cached_between_builds(self):
        obs = _mk_obs()
        clock = [100.0]
        b = FrameBuilder(obs, hostname="s", clock=lambda: clock[0])
        calls = []
        real = obs.resident_bytes
        obs.resident_bytes = lambda: calls.append(1) or real()
        b.build()
        clock[0] += 1.0
        b.build()                     # inside the refresh window: cached
        assert len(calls) == 1
        clock[0] += FrameBuilder.RESIDENT_REFRESH_S
        b.build()
        assert len(calls) == 2


# -- event journal + burst detector ----------------------------------------

class TestJournal:
    def test_record_query_filters_and_bounds(self):
        j = ClusterEventJournal(cap=8)
        j.record("bogus_kind", scheduler="x")   # rejected, not recorded
        assert j.recorded_total == 0
        t0 = time.time()
        for i in range(12):
            j.record("lapse" if i % 2 else "straggler",
                     scheduler=f"sched-{i % 3}", subject=f"h{i}")
        assert j.recorded_total == 12
        page = j.query(limit=256)
        assert len(page["events"]) == 8         # ring cap
        assert page["dropped"] == 4
        assert page["events"][0]["subject"] == "h11"   # newest first
        only = j.query(kind="lapse")
        assert {e["kind"] for e in only["events"]} == {"lapse"}
        sched = j.query(scheduler="sched-1")
        assert all(e["scheduler"] == "sched-1" for e in sched["events"])
        capped = j.query(limit=3)
        assert len(capped["events"]) == 3 and capped["truncated"] is True
        assert j.query(since=time.time() + 60)["events"] == []
        assert j.query(before=t0)["events"] == []

    def test_admission_burst_edge_triggered(self):
        j = ClusterEventJournal()
        clock = [0.0]
        d = AdmissionBurstDetector(j, threshold=4, window_s=10.0,
                                   clock=lambda: clock[0])
        for _ in range(10):
            d.note_429("tenant-a")
        assert j.recorded_total == 1            # one event, not one per 429
        assert j.query()["events"][0]["kind"] == "admission_burst"
        # Rate falls under half the threshold -> re-arms -> next storm is
        # a NEW event.
        clock[0] += 60.0
        d.note_429()
        for _ in range(4):
            d.note_429()
        assert j.recorded_total == 2


# -- telemetry spool --------------------------------------------------------

class TestSpool:
    def test_store_load_roundtrip_and_prune(self, tmp_path):
        db = Database(str(tmp_path / "m.db"))
        spool = TelemetrySpool(db, max_bytes=4096)
        for i in range(200):
            spool.store("sched-a", "10.0.0.1",
                        _frame(ts=1000.0 + i, counters={"pieces_landed": i}))
        assert spool.bytes <= 4096
        loaded = spool.load()
        assert spool.frame_count() == len(loaded) < 200   # oldest pruned
        # Oldest-first, and the newest frame survived.
        assert loaded[0][0] < loaded[-1][0]
        assert loaded[-1][3]["counters"]["pieces_landed"] == 199
        db.close()

    def test_reopen_restores_without_edge_events(self, tmp_path):
        path = str(tmp_path / "m.db")
        db = Database(path)
        series = ClusterSeries(spool=TelemetrySpool(db))
        assert series.ingest("sched-a", "10.0.0.1", _frame(
            stragglers=["h-slow"], breached=["serve_p99"],
            slo={"serve_p99": {"state": "breach", "burn": 2.0}},
            counters={"pieces_landed": 7})) == 1
        events_before = series.journal.recorded_total
        assert events_before >= 2               # straggler + slo_breach
        db.close()

        db2 = Database(path)
        restored = ClusterSeries(spool=TelemetrySpool(db2))
        assert restored.restored_frames == 1
        # Restored history is context, not news: no replayed edge events,
        # and re-ingesting the same straggler stays edge-less.
        assert restored.journal.recorded_total == 0
        assert restored.ingest("sched-a", "10.0.0.1", _frame(
            stragglers=["h-slow"])) == 1
        assert restored.journal.recorded_total == 0
        rep = restored.report(3600.0)
        assert rep["totals"]["pieces_landed"] == 7
        assert rep["restored_frames"] == 1
        db2.close()


# -- cluster series ---------------------------------------------------------

class TestClusterSeries:
    def test_merge_totals_and_attribution(self):
        s = ClusterSeries()
        s.ingest("sched-a", "10.0.0.1", _frame(
            counters={"pieces_landed": 10, "back_source": 1},
            gauges={"hosts_total": 4}, stragglers=["h-slow"],
            decisions={"handout": 3}))
        s.ingest("sched-b", "10.0.0.2", _frame(
            counters={"pieces_landed": 5}, gauges={"hosts_total": 2},
            quarantined=["h-bad"], decisions={"handout": 2}))
        rep = s.report(600.0)
        assert rep["totals"]["pieces_landed"] == 15
        assert rep["totals"]["back_source"] == 1
        assert rep["gauges"]["hosts_total"] == 6
        assert rep["decisions"]["handout"] == 5
        assert rep["stragglers"] == {"h-slow": "sched-a@10.0.0.1"}
        assert rep["quarantined"] == {"h-bad": "sched-b@10.0.0.2"}
        assert [x["scheduler"] for x in rep["schedulers"]] == [
            "sched-a@10.0.0.1", "sched-b@10.0.0.2"]
        text = render_cluster(rep)
        assert "h-slow -> sched-a@10.0.0.1" in text
        assert "pieces_landed=15" in text

    def test_ingest_fail_open_counts_malformed(self):
        s = ClusterSeries()
        before = _samples("dragonfly_tpu_manager_fleet_frames_total",
                          "result")
        assert s.ingest("x", "1.2.3.4", None) == 0
        assert s.ingest("x", "1.2.3.4", "not a dict") == 0
        assert s.ingest("x", "1.2.3.4", {"v": 99}) == 0
        after = _samples("dragonfly_tpu_manager_fleet_frames_total",
                         "result")
        assert after.get("malformed", 0) - before.get("malformed", 0) == 3
        assert s.report(60.0)["schedulers"] == []

    def test_edge_events_straggler_slo_quarantine(self):
        s = ClusterSeries(quarantine_storm=3)
        s.ingest("a", "", _frame(stragglers=["h1"]))
        s.ingest("a", "", _frame(stragglers=["h1"]))       # no re-trigger
        s.ingest("a", "", _frame(stragglers=["h1", "h2"]))  # h2 is new
        kinds = [e["kind"] for e in s.journal.query()["events"]]
        assert kinds.count("straggler") == 2
        s.ingest("a", "", _frame(
            breached=["serve_p99"],
            slo={"serve_p99": {"state": "breach", "burn": 3.5}}))
        ev = s.journal.query(kind="slo_breach")["events"]
        assert len(ev) == 1 and "3.5" in ev[0]["detail"]
        s.ingest("a", "", _frame(quarantined=["q1", "q2", "q3", "q4"]))
        assert len(s.journal.query(kind="quarantine_storm")["events"]) == 1

    def test_lapse_return_events_and_state_gauge(self):
        s = ClusterSeries()
        s.ingest("a", "10.0.0.1", _frame())
        s.note_lapse("a", "10.0.0.1")
        s.note_lapse("a", "10.0.0.1")    # dedup: one lapse event
        assert len(s.journal.query(kind="lapse")["events"]) == 1
        gauge = _samples("dragonfly_tpu_manager_cluster_schedulers",
                         "state")
        assert gauge["inactive"] == 1 and gauge["active"] == 0
        s.note_return("a", "10.0.0.1")
        assert len(s.journal.query(kind="return")["events"]) == 1
        gauge = _samples("dragonfly_tpu_manager_cluster_schedulers",
                         "state")
        assert gauge["active"] == 1 and gauge["inactive"] == 0

    def test_mixed_version_no_data_never_invents_zeros(self):
        s = ClusterSeries()
        s.mark_seen("old-wire", "10.0.0.9")
        rep = s.report(600.0)
        assert rep["schedulers"][0]["state"] == "no_data"
        assert rep["totals"] == {} and rep["gauges"] == {}
        # A lapse/return cycle keeps no_data (still no frames ever).
        s.note_lapse("old-wire", "10.0.0.9")
        s.note_return("old-wire", "10.0.0.9")
        assert s.report(600.0)["schedulers"][0]["state"] == "no_data"
        assert s.slo_report(600.0)["schedulers"][
            "old-wire@10.0.0.9"]["state"] == "no_data"


# -- manager integration over the real keepalive wire -----------------------

class TestManagerWire:
    def test_keepalive_frame_ingest_and_frameless_degrade(self, run_async):
        run_async(self._frame_ingest_and_degrade(), timeout=60)

    async def _frame_ingest_and_degrade(self):
        server = ManagerServer(ManagerConfig())
        await server.start()
        client = ManagerClient(NetAddr.tcp("127.0.0.1", server.grpc_port()))
        try:
            sched = await client.update_scheduler(
                hostname="sched-new", ip="127.0.0.1", port=8002)
            cluster_id = sched["scheduler_cluster_id"]
            await client.update_scheduler(
                hostname="sched-old", ip="127.0.0.1", port=8003,
                scheduler_cluster_id=cluster_id)

            # New wire: keepalive carries a fleet frame.
            s1 = await client._client.open_stream("Manager.KeepAlive", {
                "source_type": "scheduler", "hostname": "sched-new",
                "ip": "127.0.0.1", "cluster_id": cluster_id})
            await s1.send({"fleet_frame": _frame(
                counters={"pieces_landed": 3}, stragglers=["h-slow"])})
            # Old wire: same stream shape, no frame — full liveness.
            s2 = await client._client.open_stream("Manager.KeepAlive", {
                "source_type": "scheduler", "hostname": "sched-old",
                "ip": "127.0.0.1", "cluster_id": cluster_id})
            await s2.send({})
            await asyncio.sleep(0.2)

            rows = {r["hostname"]: r["state"]
                    for r in server.db.list("schedulers")}
            assert rows["sched-new"] == rows["sched-old"] == "active"
            rep = server.service.cluster.report(600.0)
            by = {x["hostname"]: x for x in rep["schedulers"]}
            assert by["sched-new"]["state"] == "active"
            assert by["sched-old"]["state"] == "no_data"
            assert "frame_bytes" not in by["sched-old"]
            assert rep["totals"] == {"pieces_landed": 3}
            assert rep["stragglers"]["h-slow"] == "sched-new@127.0.0.1"
            await s1.close()
            await s2.close()
        finally:
            await client.close()
            await server.stop()

    def test_cluster_view_rpc_renders_text(self, run_async):
        run_async(self._cluster_view_rpc(), timeout=60)

    async def _cluster_view_rpc(self):
        server = ManagerServer(ManagerConfig())
        await server.start()
        client = ManagerClient(NetAddr.tcp("127.0.0.1", server.grpc_port()))
        try:
            server.service.cluster.ingest("sched-a", "10.0.0.1", _frame(
                counters={"pieces_landed": 4}))
            view = await client.cluster_view(window_s=300.0)
            assert view["report"]["totals"]["pieces_landed"] == 4
            assert view["report"]["window_s"] == 300.0
            assert "cluster view" in view["text"]
            assert "sched-a@10.0.0.1" in view["text"]
        finally:
            await client.close()
            await server.stop()

    def test_keepalive_payload_counter_and_rate_limited_warn(
            self, run_async, monkeypatch):
        run_async(self._payload_counter(monkeypatch), timeout=60)

    async def _payload_counter(self, monkeypatch):
        from dragonfly2_tpu.manager import client as mclient

        server = ManagerServer(ManagerConfig())
        await server.start()
        client = ManagerClient(NetAddr.tcp("127.0.0.1", server.grpc_port()))
        warns = []
        monkeypatch.setattr(
            mclient.log, "warning",
            lambda *a, **k: warns.append(a))
        try:
            sched = await client.update_scheduler(
                hostname="sched-err", ip="127.0.0.1", port=8002)

            def bad_payload():
                raise RuntimeError("boom")

            client.start_keepalive(
                source_type="scheduler", hostname="sched-err",
                ip="127.0.0.1",
                cluster_id=sched["scheduler_cluster_id"],
                interval=0.05, payload=bad_payload)
            before = _samples(
                "dragonfly_tpu_manager_keepalive_payload_total", "result")
            await asyncio.sleep(0.4)
            after = _samples(
                "dragonfly_tpu_manager_keepalive_payload_total", "result")
            # The provider raised on several ticks: every one counted,
            # but the per-tick warning collapsed to ONE rate-limited line.
            assert after.get("error", 0) - before.get("error", 0) >= 3
            assert len(warns) == 1
        finally:
            await client.close()
            await server.stop()


# -- manager MetricsServer routes ------------------------------------------

class TestClusterRoutes:
    def test_routes_answer_and_404_without_provider(self, run_async):
        run_async(self._routes(), timeout=60)

    async def _routes(self):
        import aiohttp

        from dragonfly2_tpu.pkg.metrics_server import MetricsServer

        series = ClusterSeries()
        series.ingest("sched-a", "10.0.0.1", _frame(
            counters={"pieces_landed": 2}, stragglers=["h-slow"],
            breached=["serve_p99"],
            slo={"serve_p99": {"state": "breach", "burn": 1.5}}))
        srv = MetricsServer(cluster=series)
        bare = MetricsServer()          # scheduler/daemon binary: no tower
        port = await srv.serve("127.0.0.1", 0)
        bport = await bare.serve("127.0.0.1", 0)
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.get(base + "/debug/cluster?window=60") as r:
                    assert r.status == 200
                    rep = await r.json()
                assert rep["totals"]["pieces_landed"] == 2
                assert rep["window_s"] == 60.0
                async with sess.get(
                        base + "/debug/cluster?format=text") as r:
                    text = await r.text()
                assert "cluster view" in text and "h-slow" in text
                async with sess.get(
                        base + "/debug/cluster/schedulers") as r:
                    assert r.status == 200
                    scheds = (await r.json())["schedulers"]
                assert scheds[0]["scheduler"] == "sched-a@10.0.0.1"
                async with sess.get(base + "/debug/cluster/slo") as r:
                    slo = await r.json()
                assert slo["breached"] == ["serve_p99"]
                async with sess.get(
                        base + "/debug/cluster/events?kind=straggler") as r:
                    ev = await r.json()
                assert ev["events"][0]["subject"] == "h-slow"
                async with sess.get(
                        base + "/debug/cluster?window=nope") as r:
                    assert r.status == 400
                async with sess.get(
                        base + "/debug/cluster/events?n=nope") as r:
                    assert r.status == 400
                for path in ("/debug/cluster", "/debug/cluster/schedulers",
                             "/debug/cluster/slo", "/debug/cluster/events"):
                    async with sess.get(
                            f"http://127.0.0.1:{bport}{path}") as r:
                        assert r.status == 404, path
        finally:
            await srv.close()
            await bare.close()

    def test_manager_scrape_under_load(self, run_async):
        run_async(self._scrape_under_load(), timeout=120)

    async def _scrape_under_load(self):
        """The manager's own metrics surface answers inside the 1s bound
        while keepalive frames storm in — the TestScrapeUnderLoad
        discipline extended to the manager binary."""
        import time as time_mod

        import aiohttp

        cfg = ManagerConfig()
        cfg.metrics_port = 0            # ephemeral manager MetricsServer
        server = ManagerServer(cfg)
        await server.start()
        assert server.metrics_port() > 0
        base = f"http://127.0.0.1:{server.metrics_port()}"
        done = asyncio.Event()

        async def storm(i: int):
            n = 0
            while not done.is_set():
                server.service.ingest_fleet_frame(
                    f"sched-{i}", "10.0.0.1", _frame(
                        counters={"pieces_landed": 1},
                        stragglers=[f"h{n % 7}"]))
                n += 1
                await asyncio.sleep(0.002)

        storms = [asyncio.ensure_future(storm(i)) for i in range(8)]
        await asyncio.sleep(0.1)
        try:
            async with aiohttp.ClientSession() as sess:
                for path, kind in (
                        ("/metrics", "prom"),
                        ("/debug/cluster?window=60", "json"),
                        ("/debug/cluster/schedulers", "json"),
                        ("/debug/cluster/slo", "json"),
                        ("/debug/cluster/events?n=64", "json"),
                        ("/debug/cluster?format=text", "text")):
                    t0 = time_mod.perf_counter()
                    async with sess.get(base + path) as r:
                        assert r.status == 200, path
                        raw = await r.read()
                    dt = time_mod.perf_counter() - t0
                    assert dt < 1.0, f"{path} took {dt:.2f}s under load"
                    if kind == "json":
                        import json as json_mod

                        json_mod.loads(raw)
                    elif kind == "prom":
                        assert b"dragonfly_tpu" in raw
        finally:
            done.set()
            await asyncio.gather(*storms, return_exceptions=True)
            await server.stop()
