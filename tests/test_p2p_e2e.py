"""P2P integration: scheduler + seed + N peer daemons on localhost.

BASELINE config #2 analog (8-peer fan-out, origin fetched ~once) — the
hermetic multi-process harness from SURVEY.md §4 realized in-process: one
origin, one scheduler, one seed daemon, N peer daemons, all on one loop.
"""

import asyncio
import hashlib
import random

import pytest
from aiohttp import web

from dragonfly2_tpu.client import dfget as dfget_lib
from dragonfly2_tpu.daemon.config import DaemonConfig
from dragonfly2_tpu.daemon.daemon import Daemon
from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.server import SchedulerServer

CONTENT = bytes(random.Random(99).randbytes(10 * 1024 * 1024))
SHA = "sha256:" + hashlib.sha256(CONTENT).hexdigest()


async def start_origin():
    stats = {"blob_streams": 0, "blob_bytes": 0}

    async def blob(request: web.Request) -> web.StreamResponse:
        stats["blob_streams"] += 1
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(CONTENT))
            data = CONTENT[r.start : r.start + r.length]
            stats["blob_bytes"] += len(data)
            return web.Response(
                status=206, body=data,
                headers={
                    "Content-Range": f"bytes {r.start}-{r.start + r.length - 1}/{len(CONTENT)}",
                    "Accept-Ranges": "bytes",
                })
        stats["blob_bytes"] += len(CONTENT)
        return web.Response(body=CONTENT, headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/blob", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1], stats


async def start_scheduler() -> SchedulerServer:
    cfg = SchedulerConfig()
    cfg.server.port = 0
    cfg.scheduling.retry_interval = 0.05   # fast tests
    cfg.scheduling.no_source_patience = 0.5
    cfg.gc.interval = 3600
    server = SchedulerServer(cfg)
    await server.start()
    return server


def daemon_config(tmp_path, name: str, scheduler_port: int, *, seed=False) -> DaemonConfig:
    cfg = DaemonConfig()
    cfg.work_home = str(tmp_path / name)
    cfg.__post_init__()
    cfg.host.hostname = name
    cfg.host.ip = "127.0.0.1"
    cfg.scheduler.addrs = [f"127.0.0.1:{scheduler_port}"]
    cfg.seed_peer = seed
    cfg.gc_interval = 3600
    cfg.download.piece_concurrency = 1          # deterministic origin counting
    cfg.download.concurrent_min_length = 1 << 40
    return cfg


async def start_daemon(tmp_path, name, scheduler_port, *, seed=False) -> Daemon:
    d = Daemon(daemon_config(tmp_path, name, scheduler_port, seed=seed))
    await d.start()
    return d


async def dfget_via(daemon: Daemon, url: str, out: str, digest: str = SHA,
                    *, allow_source_fallback: bool = False,
                    timeout: float = 60.0) -> dict:
    from dragonfly2_tpu.proto.common import UrlMeta

    return await dfget_lib.download(
        dfget_lib.DfgetConfig(
            url=url, output=out,
            daemon_sock=daemon.config.unix_sock,
            meta=UrlMeta(digest=digest),
            allow_source_fallback=allow_source_fallback,
            timeout=timeout,
        ))


class TestP2PFanout:
    def test_seed_plus_peers_single_origin_fetch(self, run_async, tmp_path):
        """8 peers + 1 seed: origin serves ~one content copy; every peer's
        output sha-verifies; peers report from_p2p."""

        async def body():
            origin, oport, stats = await start_origin()
            sched = await start_scheduler()
            url = f"http://127.0.0.1:{oport}/blob"
            daemons = []
            try:
                seed = await start_daemon(tmp_path, "seed", sched.port(), seed=True)
                daemons.append(seed)
                peers = []
                for i in range(8):
                    d = await start_daemon(tmp_path, f"peer{i}", sched.port())
                    daemons.append(d)
                    peers.append(d)

                results = await asyncio.gather(*[
                    dfget_via(d, url, str(tmp_path / f"out{i}.bin"))
                    for i, d in enumerate(peers)
                ])
                for i, r in enumerate(results):
                    assert r["state"] == "done"
                    data = (tmp_path / f"out{i}.bin").read_bytes()
                    assert hashlib.sha256(data).hexdigest() == SHA.split(":")[1]
                # Origin economy: one probe + one content stream (seed only).
                assert stats["blob_streams"] <= 3, stats
                assert stats["blob_bytes"] <= len(CONTENT) + (1 << 20), stats
                # At least some peers rode P2P (the rest may have deduped
                # onto a running conductor of the same daemon — not here,
                # every daemon is distinct, so all should be P2P).
                assert all(r["from_p2p"] for r in results), results
            finally:
                for d in daemons:
                    await d.stop()
                await sched.stop()
                await origin.cleanup()

        run_async(body(), timeout=120)

    def test_first_peer_back_source_without_seed(self, run_async, tmp_path):
        """No seed daemon: first peer falls back to origin, second peer
        pulls pieces from the first over P2P."""

        async def body():
            origin, oport, stats = await start_origin()
            sched = await start_scheduler()
            sched.config.seed_peer_enabled = False
            url = f"http://127.0.0.1:{oport}/blob"
            daemons = []
            try:
                d1 = await start_daemon(tmp_path, "p1", sched.port())
                d2 = await start_daemon(tmp_path, "p2", sched.port())
                daemons += [d1, d2]
                r1 = await dfget_via(d1, url, str(tmp_path / "o1.bin"))
                assert r1["state"] == "done"
                streams_after_first = stats["blob_streams"]

                r2 = await dfget_via(d2, url, str(tmp_path / "o2.bin"))
                assert r2["state"] == "done"
                assert r2["from_p2p"]
                assert (tmp_path / "o2.bin").read_bytes() == CONTENT
                # Second download never touched origin.
                assert stats["blob_streams"] == streams_after_first
            finally:
                for d in daemons:
                    await d.stop()
                await sched.stop()
                await origin.cleanup()

        run_async(body(), timeout=60)

    def test_seed_reannounce_serves_after_scheduler_restart(self, run_async, tmp_path):
        """Scheduler restarts (loses all state); seed re-announce path lets a
        new peer still fetch via P2P without a fresh origin fetch."""

        async def body():
            origin, oport, stats = await start_origin()
            sched = await start_scheduler()
            url = f"http://127.0.0.1:{oport}/blob"
            daemons = []
            try:
                seed = await start_daemon(tmp_path, "seed", sched.port(), seed=True)
                daemons.append(seed)
                d1 = await start_daemon(tmp_path, "p1", sched.port())
                daemons.append(d1)
                await dfget_via(d1, url, str(tmp_path / "o1.bin"))
                bytes_after = stats["blob_bytes"]

                # Scheduler dies and comes back empty on the same port.
                port = sched.port()
                await sched.stop()
                cfg = SchedulerConfig()
                cfg.server.port = port
                cfg.scheduling.retry_interval = 0.05
                cfg.gc.interval = 3600
                sched2 = SchedulerServer(cfg)
                await sched2.start()
                # Daemons re-announce their host records.
                for d in daemons:
                    await d.announcer.announce_once()

                d2 = await start_daemon(tmp_path, "p2", sched2.port())
                daemons.append(d2)
                r = await dfget_via(d2, url, str(tmp_path / "o2.bin"))
                assert r["state"] == "done"
                assert (tmp_path / "o2.bin").read_bytes() == CONTENT
                # Origin payload untouched: seed re-announced local pieces.
                assert stats["blob_bytes"] == bytes_after, stats
                await sched2.stop()
            finally:
                for d in daemons:
                    await d.stop()
                await origin.cleanup()

        run_async(body(), timeout=60)


def test_broker_no_channel_leak(run_async, tmp_path):
    from dragonfly2_tpu.daemon.peer.broker import PieceBroker, PieceEvent

    async def body():
        b = PieceBroker()
        for i in range(100):
            b.publish(f"task{i}", PieceEvent([1]))
        assert len(b._tasks) == 0  # no subscribers → no channels
        q = b.subscribe("t")
        b.publish("t", PieceEvent([1]))
        assert (await q.get()).piece_nums == [1]
        b.unsubscribe("t", q)
        assert len(b._tasks) == 0

    run_async(body())


def test_dispatcher_peek_does_not_reserve():
    from dragonfly2_tpu.daemon.peer.piece_dispatcher import PieceDispatcher

    d = PieceDispatcher()
    d.total_piece_count = 2
    d.piece_size = 4
    d.content_length = 8
    d.upsert_parent("p1", "127.0.0.1", 9000)
    d.on_parent_pieces("p1", [0, 1])
    assert d.has_assignable()
    assert d.has_assignable()  # peek twice, nothing reserved
    a1 = d.try_get()
    a2 = d.try_get()
    assert {a1.piece_num, a2.piece_num} == {0, 1}  # both still assignable


def test_seed_death_mid_transfer_peers_recover(run_async, tmp_path):
    """Resilience: the seed daemon dies while peers are mid-download. Peers
    must still finish sha-exact — rescheduling onto each other for pieces
    already spread, and a bounded back-to-source for the remainder (the
    reference e2e counts pod restarts for the same reason)."""

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            # Rate-limit the seed's serving (this also selects the
            # limiter-honoring aiohttp upload path over the native server)
            # so the kill deterministically lands mid-transfer.
            seed_cfg = daemon_config(tmp_path, "seed", sched.port(), seed=True)
            seed_cfg.upload.rate_limit = 4 * 1024 * 1024
            seed = Daemon(seed_cfg)
            await seed.start()
            daemons.append(seed)  # killer() stops it; stop() is idempotent
            daemons.append(p1 := await start_daemon(tmp_path, "p1", sched.port()))
            daemons.append(p2 := await start_daemon(tmp_path, "p2", sched.port()))

            async def killer():
                # Wait until at least one peer has a piece, then kill.
                for _ in range(200):
                    for d in (p1, p2):
                        for s in d.storage.tasks():
                            if s.metadata.pieces:
                                await seed.stop()
                                return
                    await asyncio.sleep(0.02)
                await seed.stop()  # nothing landed; kill anyway

            kill_task = asyncio.ensure_future(killer())
            try:
                results = await asyncio.gather(
                    dfget_via(p1, url, str(tmp_path / "k1.bin")),
                    dfget_via(p2, url, str(tmp_path / "k2.bin")))
                await kill_task
            finally:
                kill_task.cancel()
            for i, r in enumerate(results):
                assert r["state"] == "done", r
                got = (tmp_path / f"k{i + 1}.bin").read_bytes()
                assert hashlib.sha256(got).hexdigest() == SHA.split(":")[1]
            # Recovery is allowed to re-touch origin, but boundedly: the
            # seed's partial fetch plus at most one remainder per peer
            # (BOTH peers may legitimately demote if they stall at the
            # same instant — the scheduler allows it).
            assert stats["blob_bytes"] <= 3 * len(CONTENT) + (1 << 20), stats
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_scheduler_death_mid_transfer_download_still_lands(run_async, tmp_path):
    """Resilience: the scheduler dies while a peer is mid-download. The
    user-visible guarantee: with source fallback permitted, the download
    still lands sha-exact (conductor-level back-source demotion or the
    client library's daemon-side fallback — either path is acceptable;
    losing the download is not)."""

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            seed_cfg = daemon_config(tmp_path, "seed", sched.port(), seed=True)
            seed_cfg.upload.rate_limit = 4 * 1024 * 1024  # slow serving
            seed = Daemon(seed_cfg)
            await seed.start()
            daemons.append(seed)
            daemons.append(p1 := await start_daemon(tmp_path, "p1",
                                                    sched.port()))

            async def killer():
                for _ in range(200):
                    for s in p1.storage.tasks():
                        if s.metadata.pieces:
                            await sched.stop()
                            return
                    await asyncio.sleep(0.02)
                await sched.stop()

            kill_task = asyncio.ensure_future(killer())
            result = await dfget_via(p1, url, str(tmp_path / "s1.bin"),
                                     allow_source_fallback=True, timeout=90.0)
            # Await the killer: a silently-failed kill would leave the
            # scheduler alive and this test would stop testing anything.
            await kill_task
            assert result["state"] == "done", result
            got = (tmp_path / "s1.bin").read_bytes()
            assert hashlib.sha256(got).hexdigest() == SHA.split(":")[1]
        finally:
            for d in daemons:
                await d.stop()
            try:
                await sched.stop()
            except Exception:
                pass
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_dead_scheduler_at_register_degrades_to_back_source(run_async, tmp_path):
    """Scheduler unreachable at registration: the DAEMON demotes to
    back-to-source (reference behavior) instead of failing the task — no
    client-side source fallback needed, and the piece store is populated
    for reuse."""

    async def body():
        origin, oport, stats = await start_origin()
        url = f"http://127.0.0.1:{oport}/blob"
        # Point the daemon at a port nothing listens on.
        d = None
        try:
            cfg = daemon_config(tmp_path, "p1", scheduler_port=1)
            d = Daemon(cfg)
            await d.start()
            r = await dfget_via(d, url, str(tmp_path / "o.bin"))
            assert r["state"] == "done", r
            assert not r["from_p2p"]
            assert (tmp_path / "o.bin").read_bytes() == CONTENT
            # The store is populated and reusable.
            r2 = await dfget_via(d, url, str(tmp_path / "o2.bin"))
            assert r2["from_reuse"], r2
        finally:
            if d is not None:
                await d.stop()
            await origin.cleanup()

    run_async(body(), timeout=60)


def test_certified_digests_provenance():
    """certified_digest_maps returns only DONE parents' own maps — a
    corrupt still-downloading parent's announced digests must not be
    certified by an honest parent's completion."""
    from dragonfly2_tpu.daemon.peer.piece_dispatcher import PieceDispatcher

    d = PieceDispatcher()
    d.upsert_parent("corrupt", "10.0.0.1", 1)
    d.upsert_parent("honest", "10.0.0.2", 1)
    d.on_parent_pieces("corrupt", [0, 1],
                       digests={0: "crc32c:bad00000", 1: "crc32c:bad00001"})
    assert d.certified_digest_maps() == []        # nobody done yet
    d.on_parent_pieces("honest", [0, 1],
                       digests={0: "crc32c:00000aaa", 1: "crc32c:00000bbb"})
    d.note_parent_done("honest")
    assert d.certified_digest_maps() == [
        {0: "crc32c:00000aaa", 1: "crc32c:00000bbb"}]
    # The merged view (scheduling convenience) may hold the corrupt
    # values, but certification never reads it.
    assert d.piece_digests[0] in ("crc32c:bad00000", "crc32c:00000aaa")
    # certified_digest_maps exposes EVERY done parent's map so the store
    # can pick the one that verifies — done-ness alone does not elect one.
    d.note_parent_done("corrupt")
    maps = d.certified_digest_maps()
    assert {0: "crc32c:00000aaa", 1: "crc32c:00000bbb"} in maps
    assert {0: "crc32c:bad00000", 1: "crc32c:bad00001"} in maps


class _CertStubStore:
    """Minimal store for _await_certification unit tests: a pluggable
    certifies predicate plus the REAL apply_certification (one scan-and-
    install implementation, not a test copy)."""

    from dragonfly2_tpu.storage.local_store import LocalTaskStore as _LTS
    apply_certification = _LTS.apply_certification

    def __init__(self, content_length: int, pieces_verified: bool, certifies):
        import types

        self.metadata = types.SimpleNamespace(content_length=content_length)
        self._pieces_verified = pieces_verified
        self._certifies = certifies
        self.certified_digests = None

    def pieces_verified_against_digests(self):
        return self._pieces_verified

    def certifies(self, m):
        return bool(m) and self._certifies(m)


def _await_cert_conductor(content_length: int, meta: dict, *,
                          pieces_verified: bool = True, certifies=None):
    """Minimal conductor for _await_certification unit tests: the method
    touches only meta, content_range, the stub store and the dispatcher."""
    from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor

    c = PeerTaskConductor(
        task_id="t", peer_id="p", url="http://x/",
        store=_CertStubStore(content_length, pieces_verified,
                             certifies or (lambda m: True)),
        scheduler_client=None, piece_manager=None, host_info={}, meta=meta)
    return c


class TestAwaitCertification:
    """Cold-race closer: a child that completes moments before its
    certifying parent waits (bounded by the estimated re-hash cost) for
    the parent's done instead of paying a redundant whole-content hash."""

    def test_catches_a_late_done(self, run_async):
        async def body():
            # 512 MiB -> ~1.07s bound; the done at 0.03s must end the
            # wait far earlier (generous slack for loaded runners).
            c = _await_cert_conductor(512 << 20, {"digest": "sha256:x"})
            c.dispatcher.upsert_parent("seed", "10.0.0.1", 1)
            digests = {0: "crc32c:0000000a"}

            async def late_done():
                await asyncio.sleep(0.03)
                c.dispatcher.on_parent_pieces("seed", [0], digests=digests)
                c.dispatcher.note_parent_done("seed")

            t = asyncio.ensure_future(late_done())
            t0 = asyncio.get_running_loop().time()
            assert await c._await_certification() is True
            elapsed = asyncio.get_running_loop().time() - t0
            await t
            assert c.store.certified_digests == digests
            assert elapsed < 0.5, "wait must end at the done, not the bound"

        run_async(body(), timeout=10)

    def test_corrupt_early_done_does_not_eat_the_budget(self, run_async):
        async def body():
            # Corrupt parent done at t=0 (its map doesn't certify); honest
            # parent's done lands mid-wait — the wait must ride past the
            # corrupt map and return the honest one.
            honest = {0: "crc32c:0000000a"}
            corrupt = {0: "crc32c:deadbeef"}
            c = _await_cert_conductor(
                512 << 20, {"digest": "sha256:x"},
                certifies=lambda m: m == honest)
            c.dispatcher.upsert_parent("bad", "10.0.0.1", 1)
            c.dispatcher.upsert_parent("good", "10.0.0.2", 1)
            c.dispatcher.on_parent_pieces("bad", [0], digests=corrupt)
            c.dispatcher.note_parent_done("bad")

            async def honest_done():
                await asyncio.sleep(0.03)
                c.dispatcher.on_parent_pieces("good", [0], digests=honest)
                c.dispatcher.note_parent_done("good")

            t = asyncio.ensure_future(honest_done())
            assert await c._await_certification() is True
            await t
            assert c.store.certified_digests == honest

        run_async(body(), timeout=10)

    def test_bound_formula_stays_near_break_even(self):
        from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor

        bound = PeerTaskConductor._cert_wait_bound
        assert bound(1 << 20) < 0.06        # tiny: epsilon + ~2 ms hash
        assert 0.15 < bound(64 << 20) < 0.25
        assert bound(8 << 30) == 3.0        # capped
        # Monotonic in content: never cheaper to wait longer for less.
        assert bound(1 << 20) < bound(64 << 20) <= bound(8 << 30)

    def test_bound_is_the_estimated_rehash_cost(self, run_async):
        async def body():
            # 64 MiB -> 0.05 + 2 * 0.067 = ~0.18s bound. The lower bound
            # proves the wait ran its budget; the upper is loose slack.
            c = _await_cert_conductor(64 << 20, {"digest": "sha256:x"})
            c.dispatcher.upsert_parent("seed", "10.0.0.1", 1)  # never done
            t0 = asyncio.get_running_loop().time()
            assert await c._await_certification() is False
            elapsed = asyncio.get_running_loop().time() - t0
            assert 0.15 <= elapsed < 1.5, elapsed

        run_async(body(), timeout=10)

    def test_unverified_piece_makes_the_wait_futile(self, run_async):
        async def body():
            # A piece landed without a verified-against digest: no
            # certified map can engage the skip, so no wait at all.
            c = _await_cert_conductor(512 << 20, {"digest": "sha256:x"},
                                      pieces_verified=False)
            c.dispatcher.upsert_parent("seed", "10.0.0.1", 1)
            t0 = asyncio.get_running_loop().time()
            assert await c._await_certification() is False
            assert asyncio.get_running_loop().time() - t0 < 0.05

        run_async(body(), timeout=10)

    def test_scheduler_demotion_ends_the_wait(self, run_async):
        async def body():
            # A need_back_source push blocks every parent via drop_parent:
            # the waiter must wake immediately, not sleep out the bound.
            c = _await_cert_conductor(8 << 30, {"digest": "sha256:x"})
            c.dispatcher.upsert_parent("a", "10.0.0.1", 1)
            c.dispatcher.upsert_parent("b", "10.0.0.2", 1)

            async def demote():
                await asyncio.sleep(0.03)
                for pid in list(c.dispatcher.parents):
                    c.dispatcher.drop_parent(pid)

            t = asyncio.ensure_future(demote())
            t0 = asyncio.get_running_loop().time()
            assert await c._await_certification() is False
            elapsed = asyncio.get_running_loop().time() - t0
            await t
            assert elapsed < 1.0, elapsed

        run_async(body(), timeout=10)

    def test_no_rehash_pending_no_wait(self, run_async):
        async def body():
            c = _await_cert_conductor(64 << 20, {})  # no whole-content digest
            c.dispatcher.upsert_parent("seed", "10.0.0.1", 1)
            t0 = asyncio.get_running_loop().time()
            assert await c._await_certification() is False
            assert asyncio.get_running_loop().time() - t0 < 0.05

        run_async(body(), timeout=10)

    def test_last_certifier_dropping_ends_the_wait(self, run_async):
        async def body():
            # 8 GiB -> bound clamps to 3s; the drop must end the wait early.
            c = _await_cert_conductor(8 << 30, {"digest": "sha256:x"})
            c.dispatcher.upsert_parent("seed", "10.0.0.1", 1)

            async def drop():
                await asyncio.sleep(0.03)
                c.dispatcher.drop_parent("seed")

            t = asyncio.ensure_future(drop())
            t0 = asyncio.get_running_loop().time()
            assert await c._await_certification() is False
            elapsed = asyncio.get_running_loop().time() - t0
            await t
            assert elapsed < 1.0, elapsed

        run_async(body(), timeout=10)


def test_ranged_task_seed_trigger_fetches_the_slice(run_async, tmp_path):
    """A ranged dfget through a scheduler with a live seed: the triggered
    seed must fetch exactly the slice under the ranged task id (the range
    rides announce open body -> scheduler Task -> trigger spec), and the
    client's output must be the byte-exact slice — not the whole object."""

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            daemons.append(seed := await start_daemon(
                tmp_path, "seed", sched.port(), seed=True))
            daemons.append(p1 := await start_daemon(
                tmp_path, "p1", sched.port()))

            from dragonfly2_tpu.proto.common import UrlMeta

            start, length = 2 * 1024 * 1024, 1024 * 1024
            out = str(tmp_path / "slice.bin")
            r = await dfget_lib.download(dfget_lib.DfgetConfig(
                url=url, output=out, daemon_sock=p1.config.unix_sock,
                meta=UrlMeta(range=f"{start}-{start + length - 1}"),
                allow_source_fallback=False, timeout=60.0))
            assert r["state"] == "done", r
            got = open(out, "rb").read()
            assert got == CONTENT[start:start + length]

            # The seed holds the SLICE under the ranged id: content_length
            # is the range length, bytes are the slice.
            slices = [s for d in daemons for s in d.storage.tasks()
                      if s.metadata.content_length == length
                      and s.metadata.done]
            assert slices, "no daemon holds the completed ranged task"
            for s in slices:
                data = b"".join(s.read_piece(n)
                                for n in sorted(s.metadata.pieces))
                assert data == CONTENT[start:start + length]
            # Origin served the slice (possibly via the seed), never the
            # whole object for this request.
            assert stats["blob_bytes"] <= 2 * length, stats
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_cold_race_child_waits_for_seed_certification(run_async, tmp_path,
                                                      monkeypatch):
    """Cold fan-out race: the child's last piece lands BEFORE the seed's
    completion gate (whole-content validation) passes — the profile's
    whole_content_digest_validation cost. The child must wait (bounded)
    for the seed's done instead of paying its own O(content) re-hash, so
    N children × content hashing collapses into the seed's single
    validation (conductor._await_certification)."""
    import time as _time

    from dragonfly2_tpu.daemon.peer.conductor import PeerTaskConductor
    from dragonfly2_tpu.storage.local_store import LocalTaskStore

    calls: list[str] = []
    real = LocalTaskStore.validate_digest

    def spy(self, expected=""):
        calls.append(self.dir)
        if "/seed/" in self.dir:
            _time.sleep(0.02)  # widen the race: the child completes first
        return real(self, expected)

    monkeypatch.setattr(LocalTaskStore, "validate_digest", spy)
    # Decouple the pass margin from CONTENT's size: the 10 MiB bound
    # (~71 ms) is thinner than spy-sleep + sha256 + propagation on a
    # loaded runner. The test exercises the WAKE-ON-DONE mechanism, not
    # the budget arithmetic (test_bound_formula_stays_near_break_even
    # covers that), so give the wait generous room.
    monkeypatch.setattr(PeerTaskConductor, "_cert_wait_bound",
                        staticmethod(lambda content_length: 2.0))

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            daemons.append(seed := await start_daemon(
                tmp_path, "seed", sched.port(), seed=True))
            daemons.append(p1 := await start_daemon(
                tmp_path, "p1", sched.port()))
            seed_task = asyncio.ensure_future(
                dfget_via(seed, url, str(tmp_path / "s.bin")))
            # The child joins once the seed is a viable parent (has landed
            # its first piece) and then trails it piece by piece.
            for _ in range(500):
                if any(s.metadata.pieces for s in seed.storage.tasks()):
                    break
                await asyncio.sleep(0.01)
            r1 = await dfget_via(p1, url, str(tmp_path / "c.bin"))
            rs = await seed_task
            assert r1["state"] == "done", r1
            assert rs["state"] == "done", rs
            assert open(tmp_path / "c.bin", "rb").read() == CONTENT
            assert stats["blob_streams"] >= 1
            assert [c for c in calls if "/seed/" in c], \
                "seed (trust anchor) must validate"
            assert not [c for c in calls if "/p1/" in c], \
                "child re-hashed despite the certification wait"
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_warm_pull_skips_whole_content_rehash(run_async, tmp_path, monkeypatch):
    """A child pulling from a DONE (validated) seed must skip the
    O(content) completion re-hash: every piece verified against the
    seed's announced digests + the seed's certified map. The seed itself
    (trust anchor) must still validate."""
    from dragonfly2_tpu.storage.local_store import LocalTaskStore

    calls: list[str] = []
    real = LocalTaskStore.validate_digest

    def spy(self, expected=""):
        calls.append(self.dir)
        return real(self, expected)

    monkeypatch.setattr(LocalTaskStore, "validate_digest", spy)

    async def body():
        origin, oport, stats = await start_origin()
        sched = await start_scheduler()
        url = f"http://127.0.0.1:{oport}/blob"
        daemons = []
        try:
            daemons.append(seed := await start_daemon(
                tmp_path, "seed", sched.port(), seed=True))
            daemons.append(p1 := await start_daemon(
                tmp_path, "p1", sched.port()))
            # Warm the seed: completes + VALIDATES (the anchor).
            r = await dfget_via(seed, url, str(tmp_path / "w0.bin"))
            assert r["state"] == "done", r
            seed_validations = [c for c in calls if "/seed/" in c]
            assert seed_validations, "seed (anchor) must validate"

            from dragonfly2_tpu.daemon.peer.task_manager import (
                COMPLETION_REHASH,
            )
            skipped_before = COMPLETION_REHASH.labels("skipped")._value.get()
            hashed_before = COMPLETION_REHASH.labels("hashed")._value.get()

            # Child pulls from the done seed: pure P2P, skip engaged.
            r = await dfget_via(p1, url, str(tmp_path / "w1.bin"))
            assert r["state"] == "done", r
            import hashlib as _h
            got = open(tmp_path / "w1.bin", "rb").read()
            assert "sha256:" + _h.sha256(got).hexdigest() == SHA
            p1_validations = [c for c in calls if "/p1/" in c]
            assert not p1_validations, \
                f"child re-hashed despite certified chain: {p1_validations}"
            # The child's store still records the verified digest.
            stores = [s for s in p1.storage.tasks() if s.metadata.done]
            assert stores and stores[0].metadata.digest == SHA
            # The decision is operator-visible: exactly one skip counted
            # for this pull, and the hashed branch did not move (deltas
            # against the pre-pull snapshot — the counter is process-
            # global across the suite).
            assert COMPLETION_REHASH.labels("skipped")._value.get() \
                == skipped_before + 1
            assert COMPLETION_REHASH.labels("hashed")._value.get() \
                == hashed_before
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)
