"""Dataset plane end-to-end: pod-sharded loader, sample-ranged P2P reads,
device feed, and metrics exposure.

Runs against the in-process gateway fixture (pkg/testing) — a REAL
TaskManager behind the object gateway — so the assertions about task
ranges and reuse are about the actual P2P machinery, not mocks.
"""

from __future__ import annotations

import io
import tarfile

import aiohttp
import pytest

from dragonfly2_tpu.client.dfstore import Dfstore
from dragonfly2_tpu.dataset import (
    DaemonRangeFetcher,
    LoaderOptions,
    PodShardedLoader,
    ShardReader,
    epoch_order,
    host_partition,
    index_tar_bytes,
    interleave_shards,
    plan_host_epoch,
)
from dragonfly2_tpu.dataset.tar_index import fetch_or_build_index, index_object_key
from dragonfly2_tpu.pkg import metrics
from dragonfly2_tpu.pkg.testing import start_gateway_fixture


def make_shard(shard_no: int, n_samples: int, payload_base: int = 64) -> bytes:
    """A webdataset shard: numbered (jpg, cls) samples, deterministic
    payloads so content assertions are exact."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        for i in range(n_samples):
            payload = bytes([(shard_no * 31 + i) % 256]) * (payload_base + i)
            info = tarfile.TarInfo(name=f"{shard_no:03d}/{i:05d}.jpg")
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
            label = str((shard_no + i) % 10).encode()
            info = tarfile.TarInfo(name=f"{shard_no:03d}/{i:05d}.cls")
            info.size = len(label)
            tar.addfile(info, io.BytesIO(label))
    return buf.getvalue()


def expected_payload(shard_no: int, i: int, payload_base: int = 64) -> bytes:
    return bytes([(shard_no * 31 + i) % 256]) * (payload_base + i)


async def put_shards(store: Dfstore, bucket: str, n_shards: int,
                     n_samples: int) -> dict[str, bytes]:
    await store.create_bucket(bucket)
    shards = {}
    for s in range(n_shards):
        key = f"train-{s:05d}.tar"
        data = make_shard(s, n_samples)
        await store.put_object(bucket, key, data, mode="write_back")
        shards[key] = data
    return shards


# -- pure planning contract --------------------------------------------------

def test_exactly_once_partition_and_reproducibility():
    counts = [17, 3, 0, 25, 8]
    total = sum(counts)
    for num_hosts in (1, 2, 4, 7):
        flat = epoch_order(counts, seed=5, epoch=2)
        assert len(flat) == total
        union: list = []
        for h in range(num_hosts):
            opts = LoaderOptions(seed=5, num_hosts=num_hosts, host_id=h,
                                 interleave=3)
            mine = plan_host_epoch(counts, opts, epoch=2)
            # interleave permutes but never changes membership
            assert sorted(mine) == sorted(
                host_partition(flat, num_hosts, h))
            union.extend(mine)
        assert sorted(union) == sorted(
            (si, ki) for si, n in enumerate(counts) for ki in range(n))
    # Same (seed, epoch) → identical; different epoch/seed → different.
    a = epoch_order(counts, seed=5, epoch=2)
    assert a == epoch_order(counts, seed=5, epoch=2)
    assert a != epoch_order(counts, seed=5, epoch=3)
    assert a != epoch_order(counts, seed=6, epoch=2)


def test_interleave_round_robins_across_k_shards():
    items = [(0, i) for i in range(4)] + [(1, i) for i in range(4)] \
        + [(2, i) for i in range(2)]
    out = interleave_shards(items, 2)
    assert sorted(out) == sorted(items)
    # First four picks alternate between the first two open shards.
    assert [si for si, _ in out[:4]] == [0, 1, 0, 1]
    assert interleave_shards(items, 1) == items


def test_loader_options_validation():
    from dragonfly2_tpu.dataset import LoaderError

    with pytest.raises(LoaderError):
        LoaderOptions(num_hosts=0)
    with pytest.raises(LoaderError):
        LoaderOptions(num_hosts=2, host_id=2)


# -- end-to-end over the gateway ---------------------------------------------

def test_loader_smoke_over_gateway(run_async, tmp_path):
    """Tier-1 smoke: 2 tiny shards, indexes built by streaming, a full
    single-host epoch yields every sample exactly once with exact
    payloads, and a second pass with the same seed repeats the order."""

    async def run():
        fx = await start_gateway_fixture(tmp_path)
        store = Dfstore(fx.endpoint)
        try:
            await put_shards(store, "wds", 2, 6)
            loader = PodShardedLoader(
                store, "wds", ["train-00000.tar", "train-00001.tar"],
                options=LoaderOptions(seed=11, interleave=2, readahead=4))
            await loader.prepare()
            assert loader.num_samples == 12

            got = [s async for s in loader.epoch(0)]
            assert len(got) == 12
            keys = [s["__key__"] for s in got]
            assert sorted(keys) == sorted(
                f"{sh:03d}/{i:05d}" for sh in range(2) for i in range(6))
            for s in got:
                sh, i = int(s["__key__"][:3]), int(s["__key__"][4:])
                assert s["jpg"] == expected_payload(sh, i)
                assert s["cls"] == str((sh + i) % 10).encode()
                assert s["__shard__"] == f"train-{sh:05d}.tar"
            assert keys == [s["__key__"] async for s in loader.epoch(0)]
            # The published index is now a cached P2P object.
            fresh = PodShardedLoader(
                store, "wds", ["train-00000.tar"],
                options=LoaderOptions(seed=1))
            await fresh.prepare()
            assert fresh.indexes[0].num_samples == 6
        finally:
            await store.close()
            await fx.aclose()

    run_async(run())


def test_cold_read_is_ranged_and_warm_read_reuses(run_async, tmp_path):
    """Acceptance: a cold sample read creates ranged tasks covering ONLY
    that sample's member spans (never a whole-shard task); re-reading
    the same sample rides completed-task reuse (local piece store)."""

    async def run():
        fx = await start_gateway_fixture(tmp_path)
        store = Dfstore(fx.endpoint)
        try:
            shards = await put_shards(store, "wds", 1, 8)
            key = "train-00000.tar"
            shard_size = len(shards[key])
            # Index computed locally and published — the shard itself is
            # never streamed, so every shard fetch below is sample-driven.
            idx = index_tar_bytes(shards[key], key)
            await store.put_object("wds", index_object_key(key),
                                   idx.to_json_bytes(), mode="write_back")
            loader = PodShardedLoader(
                store, "wds", [key],
                options=LoaderOptions(seed=3, readahead=2))
            await loader.prepare()
            reader = loader.readers[0]
            sample = loader.indexes[0].samples[5]
            spans = reader.sample_spans(sample)
            out = await reader.read_sample(sample)
            assert out["jpg"] == expected_payload(0, 5)

            shard_url = fx.object_url("wds", key)
            shard_tasks = [t.metadata for t in fx.tm.storage.tasks()
                           if t.metadata.url == shard_url]
            assert shard_tasks, "no daemon tasks for the shard"
            # Every task over the shard is a ranged one, sized exactly as
            # the sample's coalesced spans — the whole shard never moved.
            span_lengths = sorted(e - s for s, e in spans)
            assert sorted(t.content_length for t in shard_tasks) \
                == span_lengths
            assert all(t.content_length < shard_size for t in shard_tasks)
            assert reader.fetcher.stats == {"cold": len(spans), "reuse": 0}

            # Warm: identical spans hit the completed ranged task.
            out2 = await reader.read_sample(sample)
            assert out2["jpg"] == out["jpg"]
            assert reader.fetcher.stats["reuse"] == len(spans)
            assert len([t for t in fx.tm.storage.tasks()
                        if t.metadata.url == shard_url]) == len(shard_tasks)
        finally:
            await store.close()
            await fx.aclose()

    run_async(run())


def test_daemon_fetcher_matches_gateway(run_async, tmp_path):
    """The embedded-daemon fetcher (ranged FileTasks straight on the
    TaskManager) produces identical sample bytes and dedupes with the
    gateway's ranged tasks (same tag → same task identity)."""

    async def run():
        fx = await start_gateway_fixture(tmp_path)
        store = Dfstore(fx.endpoint)
        try:
            shards = await put_shards(store, "wds", 1, 4)
            key = "train-00000.tar"
            idx = index_tar_bytes(shards[key], key)
            reader = ShardReader(
                DaemonRangeFetcher(fx.tm, fx.object_url("wds", key),
                                   tag="wds"),
                idx)
            sample = idx.samples[2]
            out = await reader.read_sample(sample)
            assert out["jpg"] == expected_payload(0, 2)
            assert reader.fetcher.stats == {"cold": 1, "reuse": 0}
            n_tasks = len(fx.tm.storage.tasks())
            # Same span over the gateway: byte-identical task id → reuse,
            # no new task store.
            _, data = await store.read_object_range(
                "wds", key, *reader.sample_spans(sample)[0])
            assert len(fx.tm.storage.tasks()) == n_tasks
            assert out["cls"] in data
        finally:
            await store.close()
            await fx.aclose()

    run_async(run())


@pytest.mark.slow
def test_multihost_exactly_once_e2e(run_async, tmp_path):
    """4 simulated hosts over one gateway: the union of their epochs
    covers every sample exactly once, each host is reproducible, and
    epoch 1 reshuffles."""

    async def run():
        fx = await start_gateway_fixture(tmp_path)
        store = Dfstore(fx.endpoint)
        try:
            await put_shards(store, "wds", 3, 5)
            keys = [f"train-{s:05d}.tar" for s in range(3)]
            all_keys = {f"{sh:03d}/{i:05d}"
                        for sh in range(3) for i in range(5)}
            per_host: list[list[str]] = []
            for h in range(4):
                loader = PodShardedLoader(
                    store, "wds", keys,
                    options=LoaderOptions(seed=42, num_hosts=4, host_id=h,
                                          interleave=2, readahead=3))
                await loader.prepare()
                got = [s["__key__"] async for s in loader.epoch(0)]
                assert got == [k for _, k in loader.plan(0)]
                per_host.append(got)
            union = [k for host in per_host for k in host]
            assert len(union) == len(all_keys)
            assert set(union) == all_keys
            # Reproducible per host; epoch advance reshuffles.
            re0 = PodShardedLoader(
                store, "wds", keys,
                options=LoaderOptions(seed=42, num_hosts=4, host_id=0,
                                      interleave=2))
            await re0.prepare()
            assert [s["__key__"] async for s in re0.epoch(0)] == per_host[0]
            assert [s["__key__"] async for s in re0.epoch(1)] != per_host[0]
        finally:
            await store.close()
            await fx.aclose()

    run_async(run())


# -- device feed -------------------------------------------------------------

async def _as_aiter(items):
    for it in items:
        yield it


def test_device_feed_numpy_fallback(run_async):
    import numpy as np

    from dragonfly2_tpu.dataset.device_feed import DeviceFeed, DeviceFeedError

    samples = [{"__key__": f"k{i}", "jpg": bytes([i]) * 10} for i in range(5)]

    async def run():
        feed = DeviceFeed("jpg", record_bytes=10, batch_size=2)
        batches = [b async for b in feed.batches(_as_aiter(samples))]
        assert [len(b.keys) for b in batches] == [2, 2, 1]
        assert all(not b.on_device for b in batches)
        np.testing.assert_array_equal(
            np.asarray(batches[0].array),
            np.stack([np.full(10, 0, np.uint8), np.full(10, 1, np.uint8)]))
        # drop_last drops the ragged tail.
        feed2 = DeviceFeed("jpg", record_bytes=10, batch_size=2,
                           drop_last=True)
        assert len([b async for b in feed2.batches(_as_aiter(samples))]) == 2
        # Oversize and (unpadded) undersize records are typed errors.
        bad = [{"__key__": "b", "jpg": b"x" * 11}]
        with pytest.raises(DeviceFeedError):
            async for _ in DeviceFeed("jpg", 10, 1).batches(_as_aiter(bad)):
                pass
        short = [{"__key__": "s", "jpg": b"x" * 3}]
        with pytest.raises(DeviceFeedError):
            async for _ in DeviceFeed("jpg", 10, 1).batches(_as_aiter(short)):
                pass
        padded = [b async for b in DeviceFeed(
            "jpg", 10, 1, pad=True).batches(_as_aiter(short))]
        assert bytes(padded[0].array[0]) == b"x" * 3 + b"\0" * 7

    run_async(run())


def test_device_feed_hbm_path(run_async):
    """force_hbm exercises the HBMSink landing (piece-per-record with
    on-device verification) on the CPU backend."""
    import numpy as np

    from dragonfly2_tpu.dataset.device_feed import DeviceFeed

    samples = [{"__key__": f"k{i}", "jpg": bytes([7 + i]) * 13}
               for i in range(4)]

    async def run():
        feed = DeviceFeed("jpg", record_bytes=13, batch_size=3,
                          force_hbm=True)
        batches = [b async for b in feed.batches(_as_aiter(samples))]
        assert [len(b.keys) for b in batches] == [3, 1]
        assert all(b.on_device for b in batches)
        arr = np.asarray(batches[0].array)
        assert arr.shape == (3, 13)
        np.testing.assert_array_equal(
            arr, np.stack([np.full(13, 7 + i, np.uint8) for i in range(3)]))
        np.testing.assert_array_equal(
            np.asarray(batches[1].array),
            np.full((1, 13), 10, np.uint8))

    run_async(run())


# -- metrics exposure --------------------------------------------------------

def test_loader_metrics_exported(run_async, tmp_path):
    """The dataset plane's metrics are visible on a pkg/metrics_server
    scrape after a loader run (the test_tracing-style liveness check)."""
    from dragonfly2_tpu.pkg.metrics_server import MetricsServer

    async def run():
        fx = await start_gateway_fixture(tmp_path)
        store = Dfstore(fx.endpoint)
        srv = MetricsServer()
        await srv.serve("127.0.0.1", 0)
        try:
            await put_shards(store, "wds", 1, 4)
            loader = PodShardedLoader(
                store, "wds", ["train-00000.tar"],
                options=LoaderOptions(seed=2, readahead=2))
            await loader.prepare()
            from dragonfly2_tpu.dataset.device_feed import DeviceFeed

            feed = DeviceFeed("cls", record_bytes=1, batch_size=2)
            n = 0
            async for batch in feed.batches(loader.epoch(0)):
                n += len(batch.keys)
            assert n == 4

            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{srv.port}/metrics") as resp:
                    assert resp.status == 200
                    text = await resp.text()
            for name in (
                    "dragonfly_tpu_dataset_samples_total",
                    "dragonfly_tpu_dataset_readahead_depth",
                    "dragonfly_tpu_dataset_epochs_total",
                    'dragonfly_tpu_dataset_index_total{result="built"}',
                    'dragonfly_tpu_dataset_range_reads_total{result="cold"}',
                    'dragonfly_tpu_dataset_device_batches_total{path=',
            ):
                assert name in text, f"{name} missing from scrape"
            by_dir = metrics.parse_labeled_samples(
                text, "dragonfly_tpu_dataset_bytes_total", "direction")
            assert by_dir.get("fetched", 0) >= by_dir.get("yielded", 0) > 0
        finally:
            await store.close()
            await srv.close()
            await fx.aclose()

    run_async(run())
