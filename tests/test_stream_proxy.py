"""Stream tasks + proxy/transport tests.

Mirrors reference test coverage: ordered piece delivery
(peertask_stream.go), shouldUseDragonfly rules (proxy_test.go), registry
mirror pull-through (containerd_test.go's proxy path) and CONNECT tunnels.
"""

from __future__ import annotations

import asyncio
import hashlib
import random

import aiohttp
from aiohttp import web

from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager, PieceManagerOption
from dragonfly2_tpu.daemon.peer.task_manager import StreamTaskRequest, TaskManager
from dragonfly2_tpu.daemon.proxy import Proxy
from dragonfly2_tpu.daemon.transport import P2PTransport, ProxyRule
from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.proto.common import UrlMeta
from dragonfly2_tpu.storage import StorageManager, StorageOption

BLOB = bytes(random.Random(11).randbytes(6 * 1024 * 1024))
BLOB_SHA = hashlib.sha256(BLOB).hexdigest()


async def start_registry():
    """Fake OCI registry: manifest + content-addressed blob, hit counting."""
    stats = {"blob_gets": 0}

    async def blob(request: web.Request) -> web.Response:
        stats["blob_gets"] += 1
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(BLOB))
            return web.Response(
                status=206, body=BLOB[r.start:r.start + r.length],
                headers={"Accept-Ranges": "bytes",
                         "Content-Range": f"bytes {r.start}-{r.start + r.length - 1}/{len(BLOB)}"})
        return web.Response(body=BLOB, headers={"Accept-Ranges": "bytes"})

    async def manifest(request: web.Request) -> web.Response:
        return web.json_response({
            "schemaVersion": 2,
            "layers": [{"digest": f"sha256:{BLOB_SHA}", "size": len(BLOB)}],
        })

    app = web.Application()
    app.router.add_get(f"/v2/library/app/blobs/sha256:{BLOB_SHA}", blob)
    app.router.add_get("/v2/library/app/manifests/latest", manifest)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, port, stats


def make_task_manager(tmp_path) -> TaskManager:
    storage = StorageManager(StorageOption(data_dir=str(tmp_path / "data")))
    pm = PieceManager(PieceManagerOption(concurrency=3))
    return TaskManager(storage, pm)


# -- stream task core -------------------------------------------------------

def test_stream_task_ordered_bytes(tmp_path, run_async):
    run_async(_stream_ordered(tmp_path))


async def _stream_ordered(tmp_path):
    runner, port, stats = await start_registry()
    tm = make_task_manager(tmp_path)
    url = f"http://127.0.0.1:{port}/v2/library/app/blobs/sha256:{BLOB_SHA}"
    try:
        attrs, body = await tm.start_stream_task(StreamTaskRequest(url=url))
        assert attrs["content_length"] == len(BLOB)
        got = b"".join([bytes(chunk) async for chunk in body])
        assert got == BLOB
        assert not attrs["from_reuse"]

        # Second stream: reuse off the completed local store, zero origin hits.
        before = stats["blob_gets"]
        attrs2, body2 = await tm.start_stream_task(StreamTaskRequest(url=url))
        got2 = b"".join([bytes(chunk) async for chunk in body2])
        assert got2 == BLOB and attrs2["from_reuse"]
        assert stats["blob_gets"] == before
    finally:
        tm.storage.close()
        await runner.cleanup()


def test_stream_task_range(tmp_path, run_async):
    run_async(_stream_range(tmp_path))


async def _stream_range(tmp_path):
    runner, port, _ = await start_registry()
    tm = make_task_manager(tmp_path)
    url = f"http://127.0.0.1:{port}/v2/library/app/blobs/sha256:{BLOB_SHA}"
    rng = Range(1_000_000, 3_000_000)
    try:
        req = StreamTaskRequest(url=url, range=rng)
        attrs, body = await tm.start_stream_task(req)
        got = b"".join([bytes(chunk) async for chunk in body])
        assert got == BLOB[1_000_000:4_000_000]
        # The ranged reader returns early; the shared whole-task download
        # keeps going. Once it lands, ranged requests reuse the local store.
        for _ in range(200):
            if not tm.is_task_running(req.task_id()):
                break
            await asyncio.sleep(0.05)
        attrs2, body2 = await tm.start_stream_task(
            StreamTaskRequest(url=url, range=Range(0, 100)))
        assert b"".join([bytes(c) async for c in body2]) == BLOB[:100]
        assert attrs2["from_reuse"]
    finally:
        tm.storage.close()
        await runner.cleanup()


def test_stream_task_concurrent_readers_share_one_download(tmp_path, run_async):
    run_async(_stream_concurrent(tmp_path))


async def _stream_concurrent(tmp_path):
    runner, port, stats = await start_registry()
    tm = make_task_manager(tmp_path)
    url = f"http://127.0.0.1:{port}/v2/library/app/blobs/sha256:{BLOB_SHA}"

    async def read_all():
        attrs, body = await tm.start_stream_task(StreamTaskRequest(url=url))
        return b"".join([bytes(chunk) async for chunk in body])

    try:
        results = await asyncio.gather(*[read_all() for _ in range(4)])
        assert all(r == BLOB for r in results)
        # One underlying download: origin hits equal the piece/range requests
        # of a single back-to-source run (not 4x).
        assert stats["blob_gets"] <= 4
    finally:
        tm.storage.close()
        await runner.cleanup()


# -- transport rules --------------------------------------------------------

def test_should_use_p2p_rules():
    tm = object.__new__(TaskManager)  # rules don't touch the manager
    # First matching rule wins (reference proxy.go shouldUseDragonfly).
    t = P2PTransport(tm, rules=[
        ProxyRule(regex=r"internal\.example", direct=True),
        ProxyRule(regex=r"\.safetensors$"),
    ])
    assert t.should_use_p2p("GET", "http://x/v2/lib/app/blobs/sha256:" + "0" * 64)
    assert t.should_use_p2p("GET", "http://host/model.safetensors")
    assert not t.should_use_p2p("GET", "http://internal.example/model.safetensors")
    assert not t.should_use_p2p("POST", "http://host/model.safetensors")
    assert not t.should_use_p2p("GET", "http://host/index.html")
    assert not t.should_use_p2p("GET", "http://host/model.safetensors",
                                {"X-Dragonfly-No-P2P": "true"})


# -- proxy ------------------------------------------------------------------

def test_proxy_registry_mirror_pull_through(tmp_path, run_async):
    run_async(_proxy_mirror(tmp_path))


async def _proxy_mirror(tmp_path):
    registry, reg_port, stats = await start_registry()
    tm = make_task_manager(tmp_path)
    proxy = Proxy(P2PTransport(tm),
                  registry_mirror=f"http://127.0.0.1:{reg_port}")
    proxy_port = await proxy.serve()
    base = f"http://127.0.0.1:{proxy_port}"
    try:
        async with aiohttp.ClientSession() as http:
            # Manifest: not a blob -> direct reverse proxy to the remote.
            resp = await http.get(f"{base}/v2/library/app/manifests/latest")
            assert resp.status == 200
            manifest = await resp.json()
            digest = manifest["layers"][0]["digest"]

            # Layer blob: P2P pull-through.
            resp = await http.get(f"{base}/v2/library/app/blobs/{digest}")
            assert resp.status == 200
            got = await resp.read()
            assert got == BLOB

            # Same layer again (another containerd node): served from cache.
            before = stats["blob_gets"]
            resp = await http.get(f"{base}/v2/library/app/blobs/{digest}")
            assert await resp.read() == BLOB
            assert stats["blob_gets"] == before
    finally:
        await proxy.close()
        tm.storage.close()
        await registry.cleanup()


def test_proxy_forward_and_range(tmp_path, run_async):
    run_async(_proxy_forward(tmp_path))


async def _proxy_forward(tmp_path):
    registry, reg_port, _ = await start_registry()
    tm = make_task_manager(tmp_path)
    proxy = Proxy(P2PTransport(tm))   # plain forward proxy, no mirror
    proxy_port = await proxy.serve()
    url = f"http://127.0.0.1:{reg_port}/v2/library/app/blobs/sha256:{BLOB_SHA}"
    try:
        async with aiohttp.ClientSession() as http:
            # Absolute-URI GET through the proxy, ranged.
            resp = await http.get(url, proxy=f"http://127.0.0.1:{proxy_port}",
                                  headers={"Range": "bytes=100-299"})
            assert resp.status == 206
            assert await resp.read() == BLOB[100:300]
            assert "Content-Range" in resp.headers
    finally:
        await proxy.close()
        tm.storage.close()
        await registry.cleanup()


def test_proxy_connect_tunnel(tmp_path, run_async):
    run_async(_proxy_tunnel(tmp_path))


async def _proxy_tunnel(tmp_path):
    registry, reg_port, _ = await start_registry()
    tm = make_task_manager(tmp_path)
    proxy = Proxy(P2PTransport(tm), white_list_ports=[reg_port])
    proxy_port = await proxy.serve()
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", proxy_port)
        writer.write(f"CONNECT 127.0.0.1:{reg_port} HTTP/1.1\r\n\r\n".encode())
        await writer.drain()
        status = await reader.readline()
        assert b"200" in status
        while (await reader.readline()) not in (b"\r\n", b""):
            pass
        # Speak plain HTTP through the tunnel.
        writer.write(b"GET /v2/library/app/manifests/latest HTTP/1.1\r\n"
                     b"Host: registry\r\nConnection: close\r\n\r\n")
        await writer.drain()
        payload = await reader.read()
        assert b"schemaVersion" in payload
        writer.close()
    finally:
        await proxy.close()
        tm.storage.close()
        await registry.cleanup()


def test_proxy_auth_and_concurrency_gate(tmp_path, run_async):
    run_async(_proxy_auth(tmp_path))


async def _proxy_auth(tmp_path):
    registry, reg_port, _ = await start_registry()
    tm = make_task_manager(tmp_path)
    proxy = Proxy(P2PTransport(tm), basic_auth=("user", "pw"),
                  registry_mirror=f"http://127.0.0.1:{reg_port}")
    proxy_port = await proxy.serve()
    try:
        async with aiohttp.ClientSession() as http:
            resp = await http.get(
                f"http://127.0.0.1:{proxy_port}/v2/library/app/manifests/latest")
            assert resp.status == 407
            resp = await http.get(
                f"http://127.0.0.1:{proxy_port}/v2/library/app/manifests/latest",
                headers={"Proxy-Authorization": aiohttp.BasicAuth("user", "pw").encode().replace("Basic", "Basic")})
            assert resp.status == 200
    finally:
        await proxy.close()
        tm.storage.close()
        await registry.cleanup()


def test_stream_task_open_ended_range(tmp_path, run_async):
    """bytes=N- (docker blob resume) must stream the tail, not empty
    (regression: unresolved length=-1 sliced everything away)."""
    run_async(_stream_open_range(tmp_path))


async def _stream_open_range(tmp_path):
    registry, reg_port, _ = await start_registry()
    tm = make_task_manager(tmp_path)
    proxy = Proxy(P2PTransport(tm))
    proxy_port = await proxy.serve()
    url = f"http://127.0.0.1:{reg_port}/v2/library/app/blobs/sha256:{BLOB_SHA}"
    try:
        async with aiohttp.ClientSession() as http:
            resp = await http.get(url, proxy=f"http://127.0.0.1:{proxy_port}",
                                  headers={"Range": "bytes=6000000-"})
            assert resp.status == 206
            assert resp.headers["Content-Range"] == \
                f"bytes 6000000-{len(BLOB) - 1}/{len(BLOB)}"
            assert await resp.read() == BLOB[6000000:]
    finally:
        await proxy.close()
        tm.storage.close()
        await registry.cleanup()


# -- code-review regressions ------------------------------------------------

def test_rules_from_config_use_dragonfly_flag():
    from dragonfly2_tpu.daemon.transport import rules_from_config

    tm = object.__new__(TaskManager)
    rules = rules_from_config([
        {"regex": r"internal\.example", "use_dragonfly": False},
        {"regex": r"\.safetensors$", "use_dragonfly": True},
        {"regex": r"\.blocked$", "direct": True},
        {"regex": ""},  # dropped
    ])
    assert len(rules) == 3
    t = P2PTransport(tm, rules=rules)
    # use_dragonfly=false must EXCLUDE from P2P, not include.
    assert not t.should_use_p2p("GET", "http://internal.example/m.safetensors")
    assert t.should_use_p2p("GET", "http://host/m.safetensors")
    assert not t.should_use_p2p("GET", "http://host/x.blocked")


def test_no_p2p_header_case_insensitive():
    tm = object.__new__(TaskManager)
    t = P2PTransport(tm, rules=[ProxyRule(regex=r"\.safetensors$")])
    assert not t.should_use_p2p("GET", "http://h/m.safetensors",
                                {"x-dragonfly-no-p2p": "1"})


def test_stream_body_aclose_before_iteration_releases_subscription(tmp_path, run_async):
    async def run():
        runner, port, _ = await start_registry()
        tm = make_task_manager(tmp_path)
        try:
            url = f"http://127.0.0.1:{port}/v2/library/app/blobs/sha256:{BLOB_SHA}"
            req = StreamTaskRequest(url=url, meta=UrlMeta())
            attrs, body = await tm.start_stream_task(req)
            task_id = attrs["task_id"]
            assert task_id in tm.broker._tasks
            await body.aclose()          # before first __anext__
            # The broker must not keep the queue alive (leak regression:
            # an unstarted async generator's finally never runs).
            ch = tm.broker._tasks.get(task_id)
            assert ch is None or not ch.queues
            # Let the background download finish so the loop closes clean.
            for _ in range(200):
                if not tm.is_task_running(task_id):
                    break
                await asyncio.sleep(0.05)
        finally:
            await runner.cleanup()

    run_async(run())


def test_stream_range_skips_leading_pieces(tmp_path, run_async):
    async def run():
        runner, port, _ = await start_registry()
        tm = make_task_manager(tmp_path)
        reads = []
        try:
            url = f"http://127.0.0.1:{port}/v2/library/app/blobs/sha256:{BLOB_SHA}"
            # Complete the task first.
            attrs, body = await tm.start_stream_task(
                StreamTaskRequest(url=url, meta=UrlMeta()))
            async for _ in body:
                pass
            # Tail range from the completed store: bytes before the range
            # must not be read off disk (the serving path reads spans via
            # read_range; instrument both it and read_piece).
            store = tm.storage.find_completed_task(attrs["task_id"])
            orig_rr = store.read_range
            orig_rp = store.read_piece

            def counting_range(off, length):
                reads.append(off)
                return orig_rr(off, length)

            def counting_piece(num):
                reads.append(num * store.metadata.piece_size)
                return orig_rp(num)

            store.read_range = counting_range
            store.read_piece = counting_piece
            start = len(BLOB) - 100
            attrs2, body2 = await tm.start_stream_task(
                StreamTaskRequest(url=url, meta=UrlMeta(),
                                  range=Range(start, -1)))
            got = b""
            async for chunk in body2:
                got += bytes(chunk)
            assert got == BLOB[start:]
            assert reads and min(reads) >= start
        finally:
            await runner.cleanup()

    run_async(run())
