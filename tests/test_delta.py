"""Checkpoint-delta plane: chunker, manifests, resolver, hot-swap.

The acceptance story (ISSUE 10): a host with version N landed receives
version N+1 by copying unchanged chunks locally (digest-verified during
the copy) and fetching ONLY changed chunks as ranged P2P tasks — reused
spans never appear on the wire, a corrupt base chunk is transparently
re-fetched, the result is a byte-identical normal completed task served
to peers, and the device flip is atomic (a reader thread observes only
complete old-or-new tensor sets).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import struct

import numpy as np
import pytest

from dragonfly2_tpu.delta.chunker import CDCParams, GearChunker, chunk_bytes
from dragonfly2_tpu.delta.manifest import (
    DeltaManifest,
    ManifestError,
    build_manifest,
)
from dragonfly2_tpu.delta.resolver import plan_delta

# Small-content chunking geometry for tests: the default 1 MiB targets
# would make an 8 MiB "checkpoint" a handful of chunks.
P = CDCParams(mask_bits=14, min_size=4 << 10, max_size=64 << 10)


def scattered_mutation(data: bytes, frac: float = 0.01, sites: int = 4,
                       seed: int = 5) -> bytes:
    """The realistic edit pattern: ``sites`` scattered small updates
    totalling ``frac`` of the bytes (not one contiguous blob)."""
    rng = random.Random(seed)
    out = bytearray(data)
    per = max(1, int(len(data) * frac / sites))
    for i in range(sites):
        at = rng.randrange(0, len(data) - per)
        out[at:at + per] = bytes(rng.getrandbits(8) for _ in range(per))
    return bytes(out)


# ------------------------------------------------------------------ #
# Chunker
# ------------------------------------------------------------------ #

class TestChunker:
    def test_tiling_and_bounds(self):
        data = os.urandom(1 << 20)
        chunks = chunk_bytes(data, P)
        assert chunks[0].offset == 0
        for a, b in zip(chunks, chunks[1:]):
            assert b.offset == a.end
        assert chunks[-1].end == len(data)
        for c in chunks[:-1]:
            assert P.min_size <= c.length <= P.max_size
        assert chunks[-1].length <= P.max_size
        for c in chunks:
            assert c.sha256 == hashlib.sha256(
                data[c.offset:c.end]).hexdigest()

    def test_feed_split_independence(self):
        data = os.urandom(600_000)
        want = chunk_bytes(data, P)
        for seed in (1, 2):
            rng = random.Random(seed)
            ch = GearChunker(P)
            i = 0
            while i < len(data):
                step = rng.randrange(1, 50_000)
                ch.feed(data[i:i + step])
                i += step
            ch.finish()
            assert ch.chunks == want
        # Degenerate: byte-at-a-time.
        small = data[:30_000]
        ch = GearChunker(P)
        for b in small:
            ch.feed(bytes([b]))
        ch.finish()
        assert ch.chunks == chunk_bytes(small, P)

    def test_shift_resistance(self):
        """An insertion re-chunks only its neighborhood: almost every
        chunk digest survives — the property dedup is built on."""
        data = os.urandom(1 << 20)
        one = {c.sha256 for c in chunk_bytes(data, P)}
        mutated = data[:400_000] + os.urandom(64) + data[400_000:]
        two = {c.sha256 for c in chunk_bytes(mutated, P)}
        assert len(one & two) >= 0.85 * len(one)

    def test_empty_and_tiny_content(self):
        assert chunk_bytes(b"", P) == []
        tiny = chunk_bytes(b"abc", P)
        assert len(tiny) == 1 and tiny[0].length == 3

    def test_forced_cut_at_max(self):
        # All-zero content has no natural boundaries: every chunk but
        # the tail must be exactly max_size.
        data = b"\0" * (P.max_size * 3 + 100)
        chunks = chunk_bytes(data, P)
        assert [c.length for c in chunks[:-1]] == [P.max_size] * 3

    def test_feed_after_finish_refused(self):
        ch = GearChunker(P)
        ch.finish()
        with pytest.raises(RuntimeError):
            ch.feed(b"x")


# ------------------------------------------------------------------ #
# Manifest
# ------------------------------------------------------------------ #

class TestManifest:
    def test_roundtrip(self):
        data = os.urandom(300_000)
        m = build_manifest(data, "v1", P)
        m2 = DeltaManifest.from_json_bytes(m.to_json_bytes())
        assert m2.chunks == m.chunks
        assert m2.params == P
        assert m2.content_length == len(data)

    def test_corrupt_rejected(self):
        with pytest.raises(ManifestError):
            DeltaManifest.from_json_bytes(b"not json")
        m = build_manifest(os.urandom(100_000), "v1", P)
        doc = json.loads(m.to_json_bytes())
        doc["chunks"][0][1] += 1          # breaks tiling
        with pytest.raises(ManifestError):
            DeltaManifest.from_json_bytes(json.dumps(doc).encode())
        doc = json.loads(m.to_json_bytes())
        doc["v"] = 99
        with pytest.raises(ManifestError):
            DeltaManifest.from_json_bytes(json.dumps(doc).encode())

    def test_plan_partition(self):
        data = os.urandom(1 << 20)
        mutated = scattered_mutation(data)
        base = build_manifest(data, "v1", P)
        new = build_manifest(mutated, "v2", P)
        plan = plan_delta(new, base)
        # Exact accounting: every new chunk in exactly one class.
        assert plan.reused_bytes + plan.fetched_bytes == len(mutated)
        assert plan.fetched, "a mutation must dirty at least one chunk"
        assert plan.reused_bytes > 0.8 * len(mutated)
        # Identical content -> all reused; disjoint -> all fetched.
        same = plan_delta(base, base)
        assert same.fetched == [] and same.reused_bytes == len(data)
        other = build_manifest(os.urandom(1 << 20), "v3", P)
        assert plan_delta(other, base).reused == []

    def test_plan_rejects_mismatched_params(self):
        base = build_manifest(b"x" * 100_000, "v1", P)
        new = build_manifest(b"x" * 100_000, "v2",
                             CDCParams(mask_bits=10, min_size=1024,
                                       max_size=8192))
        with pytest.raises(ManifestError):
            plan_delta(new, base)

    def test_fetch_spans_merge_only_adjacent(self):
        # Reused gap between two fetched chunks must NOT ride along.
        data = os.urandom(1 << 20)
        mutated = scattered_mutation(data, sites=3)
        plan = plan_delta(build_manifest(mutated, "v2", P),
                          build_manifest(data, "v1", P))
        spans = plan.fetch_spans()
        assert sum(e - s for s, e in spans) == plan.fetched_bytes
        for (s0, e0), (s1, e1) in zip(spans, spans[1:]):
            assert s1 > e0     # strictly disjoint, gaps stay local


def test_fetch_or_build_manifest_gateway_lifecycle(run_async, tmp_path):
    """The .dfidx pattern on the gateway surface: first call streams the
    object through the chunker and publishes `.dfdelta/<key>.json`;
    the second call hits the cache; replacing the object in place
    (size change) rebuilds."""
    from dragonfly2_tpu.client.dfstore import Dfstore
    from dragonfly2_tpu.delta.manifest import (
        fetch_or_build_manifest,
        manifest_object_key,
    )
    from dragonfly2_tpu.pkg.testing import start_gateway_fixture

    data = os.urandom(400_000)

    async def body():
        fx = await start_gateway_fixture(tmp_path)
        store = Dfstore(fx.endpoint)
        try:
            await store.create_bucket("ckpt")
            await store.put_object("ckpt", "shard-0", data)
            m1 = await fetch_or_build_manifest(store, "ckpt", "shard-0",
                                               params=P)
            assert m1.content_length == len(data)
            assert await store.is_object_exist(
                "ckpt", manifest_object_key("shard-0"))
            m2 = await fetch_or_build_manifest(store, "ckpt", "shard-0",
                                               params=P)
            assert m2.chunks == m1.chunks
            # Replace the object in place (write_back so the backend
            # sees it synchronously).
            await store.put_object("ckpt", "shard-0", data + b"xx",
                                   mode="write_back")
        finally:
            await store.close()
            await fx.aclose()

        # A FRESH daemon (the gateway's whole-object stream task caches
        # the old bytes until its TTL on the original) now sees the
        # cached manifest as stale by size and rebuilds it.
        fx2 = await start_gateway_fixture(tmp_path / "g2")
        store2 = Dfstore(fx2.endpoint)
        try:
            import shutil

            shutil.copytree(str(tmp_path / "buckets"),
                            str(tmp_path / "g2" / "buckets"),
                            dirs_exist_ok=True)
            m3 = await fetch_or_build_manifest(store2, "ckpt", "shard-0",
                                               params=P)
            assert m3.content_length == len(data) + 2
            assert m3.chunks[0] == m1.chunks[0]   # shared prefix chunks
        finally:
            await store2.close()
            await fx2.aclose()

    run_async(body(), timeout=60)


# ------------------------------------------------------------------ #
# Device span helper satellites (client/device.py, daemon-free)
# ------------------------------------------------------------------ #

class TestDeviceSpanHelpers:
    def test_coalesce_spans(self):
        from dragonfly2_tpu.client.device import coalesce_spans

        # Out-of-order, overlapping, adjacent and disjoint inputs.
        spans = [(50, 60), (0, 10), (10, 20), (18, 30), (40, 45)]
        assert coalesce_spans(spans) == [(0, 30), (40, 45), (50, 60)]
        assert coalesce_spans([]) == []
        assert coalesce_spans([(5, 9)]) == [(5, 9)]

    def test_covering_span(self):
        from dragonfly2_tpu.client.device import covering_span
        from dragonfly2_tpu.ops.safetensors import SafetensorsError

        cov = [(0, 100), (200, 300)]
        assert covering_span(cov, 10, 90) == (0, 100)
        assert covering_span(cov, 200, 300) == (200, 300)
        with pytest.raises(SafetensorsError):
            covering_span(cov, 90, 110)      # straddles a hole
        with pytest.raises(SafetensorsError):
            covering_span([], 0, 1)

    def test_validated_span_edges(self):
        from dragonfly2_tpu.client.device import _validated_span
        from dragonfly2_tpu.ops.safetensors import SafetensorsError

        assert _validated_span("t", {"data_offsets": [0, 8]}, 100) == (100, 108)
        assert _validated_span("t", {"data_offsets": [5, 5]}, 10) == (15, 15)
        for bad in (None, {"data_offsets": [8, 0]},      # inverted
                    {"data_offsets": [-1, 4]},           # negative
                    {"data_offsets": [0]},               # wrong arity
                    {"data_offsets": [0.0, 4]},          # float
                    {"data_offsets": [False, True]},     # bools
                    {}):                                 # missing
            with pytest.raises(SafetensorsError):
                _validated_span("t", bad, 0)


# ------------------------------------------------------------------ #
# Double-buffer flip atomicity
# ------------------------------------------------------------------ #

def _make_safetensors(tensors: dict) -> bytes:
    header, blobs, off = {}, [], 0
    for name, arr in tensors.items():
        raw = arr.tobytes()
        header[name] = {"dtype": "F32", "shape": list(arr.shape),
                        "data_offsets": [off, off + len(raw)]}
        blobs.append(raw)
        off += len(raw)
    hj = json.dumps(header).encode()
    return struct.pack("<Q", len(hj)) + hj + b"".join(blobs)


class TestDoubleBuffer:
    def test_flip_atomicity_under_reader_thread(self):
        """A reader hammering snapshot() during flips sees only complete
        generations: every tensor in a snapshot carries the same version
        sentinel, never a mix."""
        import threading

        import jax.numpy as jnp

        from dragonfly2_tpu.ops import safetensors as st
        from dragonfly2_tpu.ops.hbm_sink import DoubleBuffer

        def gen_views(version: float):
            tensors = {f"t{i}": np.full((16,), version, np.float32)
                       for i in range(4)}
            content = _make_safetensors(tensors)
            u8 = jnp.asarray(np.frombuffer(content, np.uint8))
            header, ds = st.parse_header(content)
            return u8, st.tensor_views(u8, header, ds)

        hot = DoubleBuffer()
        hot.flip(*gen_views(1.0))
        bad: list = []
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                gen, _buf, views = hot.snapshot()
                vals = {float(np.asarray(v)[0]) for v in views.values()}
                if len(vals) != 1:
                    bad.append((gen, vals))

        threads = [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            for version in range(2, 12):
                hot.flip(*gen_views(float(version)))
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not bad, f"mixed-generation snapshots observed: {bad[:3]}"
        assert hot.generation == 11

    def test_assemble_and_verify(self):
        import jax.numpy as jnp

        from dragonfly2_tpu.ops.checksum import checksum_numpy
        from dragonfly2_tpu.ops.hbm_sink import (
            assemble_delta_u8,
            verify_u8_against_host,
        )

        old = os.urandom(4096)
        fetched = os.urandom(512)
        live = jnp.asarray(np.frombuffer(old, np.uint8))
        # New layout: old[1024:2048] + fetched + old[0:1024]
        parts = [("r", 1024, 1024), ("f", fetched), ("r", 0, 1024)]
        u8 = assemble_delta_u8(live, parts)
        want = old[1024:2048] + fetched + old[:1024]
        assert bytes(np.asarray(u8)) == want
        checks = {0: checksum_numpy(want[:2048]),
                  1: checksum_numpy(want[2048:])}
        verify_u8_against_host(u8, 2048, checks)
        # A flipped byte must be caught, naming the piece.
        corrupt = bytearray(want)
        corrupt[100] ^= 0xFF
        bad = jnp.asarray(np.frombuffer(bytes(corrupt), np.uint8))
        with pytest.raises(ValueError, match="piece 0"):
            verify_u8_against_host(bad, 2048, checks)


# ------------------------------------------------------------------ #
# Real-process e2e: delta transfer + accounting + corrupt base +
# device hot-swap
# ------------------------------------------------------------------ #

async def _two_blob_origin(v1: bytes, v2: bytes):
    """Origin serving /v1 and /v2 with single-range 206 support and
    per-blob served-byte accounting."""
    from aiohttp import web

    from dragonfly2_tpu.pkg.piece import Range

    stats = {"v1": 0, "v2": 0}

    def handler(name: str, content: bytes):
        async def blob(request):
            hdr = request.headers.get("Range")
            if hdr:
                r = Range.parse_http(hdr, len(content))
                data = content[r.start:r.start + r.length]
                stats[name] += len(data)
                return web.Response(status=206, body=data, headers={
                    "Content-Range":
                        f"bytes {r.start}-{r.start + len(data) - 1}"
                        f"/{len(content)}",
                    "Accept-Ranges": "bytes"})
            stats[name] += len(content)
            return web.Response(body=content,
                                headers={"Accept-Ranges": "bytes"})
        return blob

    app = web.Application()
    app.router.add_get("/v1", handler("v1", v1))
    app.router.add_get("/v2", handler("v2", v2))
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}", stats


async def _drain_task(tm, req, base: str = ""):
    final = None
    it = (tm.start_delta_task(req, base) if base
          else tm.start_file_task(req))
    async for p in it:
        if p.state == "failed":
            from dragonfly2_tpu.pkg.errors import DfError

            raise DfError.from_wire(p.error or {})
        if p.state == "done":
            final = p
    assert final is not None
    return final


def _file_req(url: str, digest: str = "", output: str = ""):
    from dragonfly2_tpu.daemon.peer.task_manager import FileTaskRequest
    from dragonfly2_tpu.proto.common import UrlMeta

    return FileTaskRequest(url=url, output=output,
                           meta=UrlMeta(digest=digest))


def test_delta_e2e_reuse_accounting_and_corrupt_base(run_async, tmp_path):
    """Host with landed version N receives N+1 via delta: reused spans
    never cross the wire (origin byte accounting + metric), accounting
    sums exactly to the content length, the result is byte-identical and
    announced (served to a third peer), and a corrupt base chunk is
    detected during the local copy and transparently re-fetched."""
    from tests import test_p2p_e2e as e2e
    from dragonfly2_tpu.delta.resolver import publish_manifest_for
    from dragonfly2_tpu.pkg import metrics as metrics_lib
    from dragonfly2_tpu.delta import resolver as resolver_mod

    content = os.urandom(6 << 20)
    mutated = scattered_mutation(content, frac=0.01, sites=3)
    sha1 = "sha256:" + hashlib.sha256(content).hexdigest()
    sha2 = "sha256:" + hashlib.sha256(mutated).hexdigest()

    async def body():
        origin, base_url, stats = await _two_blob_origin(content, mutated)
        sched = await e2e.start_scheduler()
        daemons = []
        try:
            seed = await e2e.start_daemon(tmp_path, "seed", sched.port(),
                                         seed=True)
            peer = await e2e.start_daemon(tmp_path, "peer", sched.port())
            daemons += [seed, peer]
            url1, url2 = f"{base_url}/v1", f"{base_url}/v2"

            # Seed lands both versions and publishes their manifests.
            r1 = await _drain_task(seed.task_manager, _file_req(url1, sha1))
            r2 = await _drain_task(seed.task_manager, _file_req(url2, sha2))
            assert await publish_manifest_for(
                seed.task_manager, r1.task_id, params=P) is not None
            assert await publish_manifest_for(
                seed.task_manager, r2.task_id, params=P) is not None

            # Peer lands version N via P2P.
            p1 = await _drain_task(peer.task_manager, _file_req(url1, sha1))
            v2_origin_before = stats["v2"]

            # Version N+1 arrives as a delta.
            before = resolver_mod.DELTA_BYTES.labels("reused")._value.get()
            p2 = await _drain_task(peer.task_manager,
                                   _file_req(url2, sha2), base=p1.task_id)
            st = peer.task_manager.delta_stats[p2.task_id]
            # Exact accounting: every byte booked exactly once.
            assert st["reused_bytes"] + st["fetched_bytes"] == len(mutated)
            assert st["corrupt_base"] == 0
            # The point of the plane: a 1% scattered mutation moves a
            # small fraction of the bytes.
            assert st["fetched_bytes"] < 0.2 * len(mutated), st
            assert st["reused_bytes"] > 0.8 * len(mutated), st
            # Reused spans never on the wire: origin served ONLY the
            # fetched spans for v2 during the delta (the seed already
            # held v2, so v2 origin traffic here is the peer's ranged
            # back-sources), plus the source client's 1-byte length
            # probe per ranged task.
            assert stats["v2"] - v2_origin_before <= \
                st["fetched_bytes"] + 1024
            # Metric agrees with per-task stats.
            after = resolver_mod.DELTA_BYTES.labels("reused")._value.get()
            assert after - before == st["reused_bytes"]

            # Byte-identical result, served to peers: verify the store.
            store = peer.task_manager.storage.find_completed_task(
                p2.task_id)
            assert store is not None and store.metadata.digest == sha2
            got = bytearray()
            with store:
                for rec in store.get_pieces():
                    got += store.read_piece(rec.num)
            assert bytes(got) == mutated

            # --- corrupt base: a second host with a silently-corrupted
            # copy of v1 still lands v2 byte-identical, re-fetching the
            # poisoned chunks.
            peer2 = await e2e.start_daemon(tmp_path, "peer2", sched.port())
            daemons.append(peer2)
            q1 = await _drain_task(peer2.task_manager, _file_req(url1, sha1))
            base_store = peer2.task_manager.storage.find_completed_task(
                q1.task_id)
            # Flip bytes on disk AFTER landing (bitrot under the task).
            with open(base_store.data_path, "r+b") as f:
                f.seek(100_000)
                f.write(b"\xde\xad\xbe\xef" * 8)
            q2 = await _drain_task(peer2.task_manager,
                                   _file_req(url2, sha2), base=q1.task_id)
            st2 = peer2.task_manager.delta_stats[q2.task_id]
            assert st2["corrupt_base"] >= 1
            assert st2["reused_bytes"] + st2["fetched_bytes"] == len(mutated)
            store2 = peer2.task_manager.storage.find_completed_task(
                q2.task_id)
            assert store2.metadata.digest == sha2
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_delta_flight_events_attribute_phases(run_async, tmp_path):
    """The flight recorder books delta local copies as store time and
    span pulls as dcn time; the phase partition stays wall-time-exact
    and dfget --explain's renderer shows the delta events."""
    from tests import test_p2p_e2e as e2e
    from dragonfly2_tpu.delta.resolver import publish_manifest_for
    from dragonfly2_tpu.pkg import flight as flightlib

    content = os.urandom(2 << 20)
    mutated = scattered_mutation(content, frac=0.02, sites=2)
    sha1 = "sha256:" + hashlib.sha256(content).hexdigest()
    sha2 = "sha256:" + hashlib.sha256(mutated).hexdigest()

    async def body():
        origin, base_url, _stats = await _two_blob_origin(content, mutated)
        sched = await e2e.start_scheduler()
        daemons = []
        try:
            seed = await e2e.start_daemon(tmp_path, "seedf", sched.port(),
                                         seed=True)
            peer = await e2e.start_daemon(tmp_path, "peerf", sched.port())
            daemons += [seed, peer]
            # Per-daemon recorders: both embedded daemons share the
            # process-global recorder by default, and the seed's finished
            # flight for the same task id would clip the peer's timeline.
            seed.task_manager.flight = flightlib.FlightRecorder()
            peer.task_manager.flight = flightlib.FlightRecorder()
            r1 = await _drain_task(seed.task_manager,
                                   _file_req(f"{base_url}/v1", sha1))
            r2 = await _drain_task(seed.task_manager,
                                   _file_req(f"{base_url}/v2", sha2))
            await publish_manifest_for(seed.task_manager, r1.task_id,
                                       params=P)
            await publish_manifest_for(seed.task_manager, r2.task_id,
                                       params=P)
            p1 = await _drain_task(peer.task_manager,
                                   _file_req(f"{base_url}/v1", sha1))
            p2 = await _drain_task(peer.task_manager,
                                   _file_req(f"{base_url}/v2", sha2),
                                   base=p1.task_id)
            tf = peer.task_manager.flight.get(p2.task_id)
            assert tf is not None
            report = flightlib.analyze(tf)
            counts = report["event_counts"]
            assert counts.get("delta_reuse", 0) >= 1
            assert counts.get("delta_fetch", 0) >= 1
            # store phase (local copies) present; partition exact.
            assert report["phases"]["store"] > 0
            total = sum(report["phases"].values()) + report["other_s"]
            assert total == pytest.approx(report["wall_s"], rel=0.05)
            text = flightlib.render_waterfall(report)
            assert "store" in text
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_download_delta_device_hotswap_e2e(run_async, tmp_path):
    """The full device chain: version N lands in HBM via the fabric,
    version N+1 arrives as a delta, reused chunks are copied
    device-side out of the live buffer, the assembled spare verifies
    on-device, and the DoubleBuffer flip atomically exposes the new
    tensors."""
    from tests import test_p2p_e2e as e2e
    from tests.test_device_sink import _start_sink_daemon
    from dragonfly2_tpu.client import device as device_lib
    from dragonfly2_tpu.delta.resolver import publish_manifest_for
    from dragonfly2_tpu.ops.hbm_sink import DoubleBuffer

    rng = np.random.RandomState(3)
    tensors_v1 = {
        "w1": rng.randn(256, 256).astype(np.float32),
        "w2": rng.randn(256, 128).astype(np.float32),
        "bias": rng.randn(512).astype(np.float32),
    }
    # Version 2: scattered update — one tensor tweaked, others identical.
    tensors_v2 = {k: v.copy() for k, v in tensors_v1.items()}
    tensors_v2["bias"][7] += 1.0
    tensors_v2["w2"][3, :8] *= 1.5
    v1 = _make_safetensors(tensors_v1)
    v2 = _make_safetensors(tensors_v2)
    assert len(v1) == len(v2)
    sha1 = "sha256:" + hashlib.sha256(v1).hexdigest()
    sha2 = "sha256:" + hashlib.sha256(v2).hexdigest()
    params = CDCParams(mask_bits=12, min_size=2 << 10, max_size=32 << 10)

    async def body():
        origin, base_url, _stats = await _two_blob_origin(v1, v2)
        sched = await e2e.start_scheduler()
        daemons = []
        try:
            seed = await e2e.start_daemon(tmp_path, "seedd", sched.port(),
                                         seed=True)
            pod = await _start_sink_daemon(tmp_path, "pod", sched.port())
            daemons += [seed, pod]
            r1 = await _drain_task(seed.task_manager,
                                   _file_req(f"{base_url}/v1", sha1))
            r2 = await _drain_task(seed.task_manager,
                                   _file_req(f"{base_url}/v2", sha2))
            await publish_manifest_for(seed.task_manager, r1.task_id,
                                       params=params)
            await publish_manifest_for(seed.task_manager, r2.task_id,
                                       params=params)

            # Serve version N from HBM.
            result = await device_lib.download_to_device(
                pod, f"{base_url}/v1", digest=sha1)
            hot = DoubleBuffer()
            hot.flip(result.as_bytes_array(),
                     result.load_safetensors())
            assert hot.generation == 1
            np.testing.assert_array_equal(
                np.asarray(hot.tensors()["bias"]), tensors_v1["bias"])

            # Hot-swap to version N+1.
            swap = await device_lib.download_delta(
                pod, f"{base_url}/v2", base=result.task_id, hot=hot,
                digest=sha2)
            assert swap.flipped and hot.generation == 2
            assert swap.on_device
            # Device-side reuse actually happened: most of the content
            # moved HBM->HBM, not host->device.
            assert swap.reused_device_bytes > 0.5 * len(v2)
            assert swap.reused_device_bytes + swap.staged_bytes == len(v2)
            # Wire-side delta accounting recorded too.
            assert swap.stats and \
                swap.stats["reused_bytes"] + swap.stats["fetched_bytes"] \
                == len(v2)
            for name, want in tensors_v2.items():
                np.testing.assert_array_equal(
                    np.asarray(hot.tensors()[name]), want, err_msg=name)
        finally:
            for d in daemons:
                await d.stop()
            await sched.stop()
            await origin.cleanup()

    run_async(body(), timeout=120)


def test_example_checkpoint_hotswap_smoke():
    """The end-to-end example runs on CPU (JAX_PLATFORMS=cpu) and
    reports a successful flip."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "examples",
                                      "checkpoint_hotswap.py")],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "flipped to generation 2" in proc.stdout, proc.stdout
