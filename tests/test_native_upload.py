"""Native upload server (native/src/dfupload.cc) HTTP contract.

Must mirror the aiohttp implementation it replaces (daemon/upload.py):
pieceNum → 200 whole piece, Range → 206 window, unknown task/piece → 404,
uncovered range → 416, malformed input → 400, /healthy, /metrics. Driven
through UploadManager so the StorageManager observer plumbing (registry
mirroring, replay on attach, unregister on delete) is covered too.
"""

import asyncio
import os
import random

import aiohttp
import pytest

from dragonfly2_tpu.daemon.upload import UploadManager
from dragonfly2_tpu.storage.local_store import TaskStoreMetadata, _native
from dragonfly2_tpu.storage.manager import StorageManager, StorageOption

nb = _native()
pytestmark = pytest.mark.skipif(nb is None, reason="native library unavailable")

PIECE = 256 * 1024


async def _boot(tmp_path):
    storage = StorageManager(StorageOption(data_dir=str(tmp_path / "d")))
    content = random.Random(9).randbytes(3 * PIECE + 1000)
    store = storage.register_task(TaskStoreMetadata(
        task_id="nup-task", content_length=len(content), piece_size=PIECE,
        total_piece_count=4))
    for n in range(3):  # piece 3 (the tail) deliberately missing
        store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
    upload = UploadManager(storage)
    port = await upload.serve("127.0.0.1", 0)
    assert upload._native_srv is not None, "native path expected"
    return storage, store, content, upload, port


def test_contract(run_async, tmp_path):
    async def body():
        storage, store, content, upload, port = await _boot(tmp_path)
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as http:
                # whole piece by number
                async with http.get(f"{base}/download/nup/nup-task",
                                    params={"peerId": "p", "pieceNum": "1"}) as r:
                    assert r.status == 200
                    assert await r.read() == content[PIECE:2 * PIECE]
                # byte window via Range (within covered pieces)
                async with http.get(
                        f"{base}/download/nup/nup-task",
                        headers={"Range": f"bytes=1000-{PIECE + 999}"}) as r:
                    assert r.status == 206
                    assert await r.read() == content[1000:PIECE + 1000]
                # range crossing into the missing tail piece → 416
                async with http.get(
                        f"{base}/download/nup/nup-task",
                        headers={"Range": f"bytes={2 * PIECE}-{3 * PIECE + 500}"}) as r:
                    assert r.status == 416
                # unknown piece / unknown task → 404
                async with http.get(f"{base}/download/nup/nup-task",
                                    params={"pieceNum": "3"}) as r:
                    assert r.status == 404
                async with http.get(f"{base}/download/nup/ghost",
                                    params={"pieceNum": "0"}) as r:
                    assert r.status == 404
                # malformed input → 400
                async with http.get(f"{base}/download/nup/nup-task",
                                    params={"pieceNum": "zebra"}) as r:
                    assert r.status == 400
                async with http.get(f"{base}/download/nup/nup-task") as r:
                    assert r.status == 400
                # late-landing piece becomes servable via the observer
                store.write_piece(3, content[3 * PIECE:])
                async with http.get(f"{base}/download/nup/nup-task",
                                    params={"pieceNum": "3"}) as r:
                    assert r.status == 200
                    assert await r.read() == content[3 * PIECE:]
                # aux endpoints
                async with http.get(f"{base}/healthy") as r:
                    assert r.status == 200 and await r.text() == "ok"
                async with http.get(f"{base}/metrics") as r:
                    assert r.status == 200
                    exposition = await r.text()
                    for family in ("upload_bytes_total",
                                   "upload_requests_total{result=\"ok\"}",
                                   "upload_requests_total{result=\"not_found\"}",
                                   "upload_requests_total{result=\"piece_missing\"}",
                                   "upload_requests_total{result=\"throttled\"}",
                                   "upload_requests_total{result=\"bad_request\"}",
                                   "upload_active_transfers",
                                   "upload_registered_tasks"):
                        assert family in exposition, family
                counters = upload.native_counters()
                # ok counts served pieces only (health probes excluded)
                assert counters["ok"] >= 3 and counters["bytes_served"] > 0
                # label parity with the aiohttp server: unknown task →
                # not_found, known task with absent piece / uncovered
                # range → piece_missing
                assert counters["not_found"] >= 1
                assert counters["piece_missing"] >= 2
                # task deletion unregisters it from the serving index
                storage.delete_task("nup-task")
                async with http.get(f"{base}/download/nup/nup-task",
                                    params={"pieceNum": "0"}) as r:
                    assert r.status == 404
        finally:
            await upload.close()
            storage.close()

    run_async(body(), timeout=60)


def test_native_engine_pulls_from_native_server(run_async, tmp_path):
    """Both ends native: dfhttp.cc client fetching from dfupload.cc server,
    crc verified against the store-advertised digest."""
    from dragonfly2_tpu.daemon.peer.piece_downloader import PieceDownloader
    from dragonfly2_tpu.storage.local_store import LocalTaskStore

    async def body():
        storage, store, content, upload, port = await _boot(tmp_path)
        dst = LocalTaskStore.create(
            str(tmp_path / "dst"),
            TaskStoreMetadata(task_id="nup-task", peer_id="dst",
                              content_length=len(content), piece_size=PIECE,
                              total_piece_count=4))
        dl = PieceDownloader()
        try:
            for n in range(3):
                rec = store.metadata.pieces[n]
                got = await dl.download_piece_to_store(
                    "127.0.0.1", port, "nup-task", n, dst,
                    expected_size=rec.size, expected_digest=rec.digest)
                assert got is not None and got.digest == rec.digest
            assert b"".join(dst.read_piece(n) for n in range(3)) == \
                content[:3 * PIECE]
        finally:
            await dl.close()
            await upload.close()
            storage.close()

    run_async(body(), timeout=60)


def test_throttle_gate_under_concurrency(run_async, tmp_path):
    """concurrent_limit=1 with many simultaneous requests: the fetch_add
    reservation means at most one transfer is active at a time — some
    requests 429, every 200 succeeds bytes-exact, and the gate never
    wedges (post-storm requests still serve)."""

    async def body():
        storage = StorageManager(StorageOption(data_dir=str(tmp_path / "d")))
        content = random.Random(1).randbytes(4 * PIECE)
        store = storage.register_task(TaskStoreMetadata(
            task_id="gate-task", content_length=len(content),
            piece_size=PIECE, total_piece_count=4))
        for n in range(4):
            store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
        upload = UploadManager(storage, concurrent_limit=1)
        port = await upload.serve("127.0.0.1", 0)
        assert upload._native_srv is not None
        url = f"http://127.0.0.1:{port}/download/gat/gate-task"
        try:
            async with aiohttp.ClientSession(
                    connector=aiohttp.TCPConnector(limit=32)) as http:
                async def one(i: int) -> int:
                    async with http.get(url, params={
                            "pieceNum": str(i % 4)}) as r:
                        body_bytes = await r.read()
                        if r.status == 200:
                            want = content[(i % 4) * PIECE:
                                           (i % 4 + 1) * PIECE]
                            assert body_bytes == want
                        return r.status

                statuses = await asyncio.gather(*[one(i) for i in range(32)])
                assert all(s in (200, 429) for s in statuses), statuses
                assert statuses.count(200) >= 1
                # Gate must not wedge: a follow-up request serves. Retry
                # briefly — the server releases its slot only after the
                # last response byte, so a straggling worker can still
                # hold it when the storm's awaits complete.
                extra_429 = 0
                for _ in range(50):
                    async with http.get(url, params={"pieceNum": "0"}) as r:
                        if r.status == 200:
                            break
                        assert r.status == 429
                        extra_429 += 1
                    await asyncio.sleep(0.05)
                else:
                    raise AssertionError("gate wedged: follow-up never served")
                counters = upload.native_counters()
                assert counters["throttled"] == statuses.count(429) + extra_429
        finally:
            await upload.close()
            storage.close()

    run_async(body(), timeout=60)


def test_aiohttp_fallback_path_serves(run_async, tmp_path):
    """Rate-limited configs force the aiohttp server even with the native
    library present — pin that branch working (a class-scoping slip once
    made its handlers unreachable and only native-disabled runs caught
    it)."""

    async def body():
        storage = StorageManager(StorageOption(data_dir=str(tmp_path / "d")))
        content = random.Random(4).randbytes(2 * PIECE)
        store = storage.register_task(TaskStoreMetadata(
            task_id="fb-task", content_length=len(content),
            piece_size=PIECE, total_piece_count=2))
        for n in range(2):
            store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
        upload = UploadManager(storage, rate_limit=1 << 30)
        port = await upload.serve("127.0.0.1", 0)
        assert upload._native_srv is None, "aiohttp fallback expected"
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{port}/download/fb/fb-task",
                        params={"pieceNum": "1"}) as r:
                    assert r.status in (200, 206)
                    assert await r.read() == content[PIECE:]
                async with http.get(f"http://127.0.0.1:{port}/healthy") as r:
                    assert r.status == 200
        finally:
            await upload.close()
            storage.close()

    run_async(body(), timeout=60)


def test_reload_replay_serves_restored_tasks(run_async, tmp_path):
    """A daemon restart (storage.reload) followed by upload.serve must
    replay restored tasks+pieces into the fresh native registry."""

    async def body():
        opt = StorageOption(data_dir=str(tmp_path / "d"))
        storage = StorageManager(opt)
        content = random.Random(3).randbytes(2 * PIECE)
        store = storage.register_task(TaskStoreMetadata(
            task_id="reload-task", content_length=len(content),
            piece_size=PIECE, total_piece_count=2))
        for n in range(2):
            store.write_piece(n, content[n * PIECE:(n + 1) * PIECE])
        store.mark_done()
        storage.close()

        fresh = StorageManager(opt)
        assert fresh.reload() == 1
        upload = UploadManager(fresh)
        port = await upload.serve("127.0.0.1", 0)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{port}/download/rel/reload-task",
                        params={"pieceNum": "1"}) as r:
                    assert r.status == 200
                    assert await r.read() == content[PIECE:]
        finally:
            await upload.close()
            fresh.close()

    run_async(body(), timeout=60)
