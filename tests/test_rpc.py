"""drpc tests: unary calls, bidi streams, errors, reconnect."""

import asyncio

import pytest

from dragonfly2_tpu.pkg.errors import Code, DfError
from dragonfly2_tpu.pkg.types import NetAddr
from dragonfly2_tpu.rpc import Client, RpcError, Server


async def _make_server() -> tuple[Server, int]:
    srv = Server("test")

    async def echo(body, ctx):
        return {"echo": body}

    async def fail(body, ctx):
        raise DfError(Code.SchedNeedBackSource, "go away")

    async def crash(body, ctx):
        raise RuntimeError("boom")

    async def sum_stream(stream, ctx):
        total = 0
        while True:
            msg = await stream.recv()
            if msg is None:
                break
            total += msg["n"]
            await stream.send({"running_total": total})

    async def counter(stream, ctx):
        n = stream.open_body["count"]
        for i in range(n):
            await stream.send({"i": i})

    srv.register_unary("Test.Echo", echo)
    srv.register_unary("Test.Fail", fail)
    srv.register_unary("Test.Crash", crash)
    srv.register_stream("Test.Sum", sum_stream)
    srv.register_stream("Test.Counter", counter)
    await srv.serve(NetAddr.tcp("127.0.0.1", 0))
    return srv, srv.port()


def test_unary_echo(run_async):
    async def body():
        srv, port = await _make_server()
        cli = Client(NetAddr.tcp("127.0.0.1", port))
        try:
            res = await cli.call("Test.Echo", {"x": 1})
            assert res == {"echo": {"x": 1}}
        finally:
            await cli.close()
            await srv.close()

    run_async(body())


def test_unary_coded_error(run_async):
    async def body():
        srv, port = await _make_server()
        cli = Client(NetAddr.tcp("127.0.0.1", port))
        try:
            with pytest.raises(DfError) as ei:
                await cli.call("Test.Fail")
            assert ei.value.code == Code.SchedNeedBackSource
            with pytest.raises(DfError) as ei:
                await cli.call("Test.Crash")
            assert ei.value.code == Code.UnknownError
            with pytest.raises(DfError) as ei:
                await cli.call("No.Such")
            assert ei.value.code == Code.BadRequest
        finally:
            await cli.close()
            await srv.close()

    run_async(body())


def test_bidi_stream(run_async):
    async def body():
        srv, port = await _make_server()
        cli = Client(NetAddr.tcp("127.0.0.1", port))
        try:
            stream = await cli.open_stream("Test.Sum")
            for n in (1, 2, 3):
                await stream.send({"n": n})
                res = await stream.recv(timeout=5)
                assert res["running_total"] == sum(range(1, n + 1))
            await stream.close()
            assert await stream.recv(timeout=5) is None  # clean server close
        finally:
            await cli.close()
            await srv.close()

    run_async(body())


def test_server_stream(run_async):
    async def body():
        srv, port = await _make_server()
        cli = Client(NetAddr.tcp("127.0.0.1", port))
        try:
            stream = await cli.open_stream("Test.Counter", {"count": 5})
            got = []
            while True:
                msg = await stream.recv(timeout=5)
                if msg is None:
                    break
                got.append(msg["i"])
            assert got == list(range(5))
        finally:
            await cli.close()
            await srv.close()

    run_async(body())


def test_connect_refused(run_async):
    async def body():
        cli = Client(NetAddr.tcp("127.0.0.1", 1))  # nothing listening
        with pytest.raises(RpcError) as ei:
            await cli.call("Test.Echo")
        assert ei.value.code == Code.ClientConnectionError
        await cli.close()

    run_async(body())


def test_reconnect_after_server_restart(run_async):
    async def body():
        srv, port = await _make_server()
        cli = Client(NetAddr.tcp("127.0.0.1", port))
        assert (await cli.call("Test.Echo", 1))["echo"] == 1
        await srv.close()
        await asyncio.sleep(0.05)
        with pytest.raises(DfError):
            await cli.call("Test.Echo", 2, timeout=2)
        # New server on the same port; client reconnects lazily.
        srv2 = Server("test2")

        async def echo(body, ctx):
            return {"echo": body}

        srv2.register_unary("Test.Echo", echo)
        await srv2.serve(NetAddr.tcp("127.0.0.1", port))
        assert (await cli.call("Test.Echo", 3))["echo"] == 3
        await cli.close()
        await srv2.close()

    run_async(body())


def test_unix_socket(run_async, tmp_path):
    async def body():
        srv = Server("unix-test")

        async def echo(body, ctx):
            return body

        srv.register_unary("E", echo)
        sock = str(tmp_path / "s.sock")
        await srv.serve(NetAddr.unix(sock))
        cli = Client(NetAddr.unix(sock))
        assert await cli.call("E", "hi") == "hi"
        assert await cli.ping()
        await cli.close()
        await srv.close()

    run_async(body())


def test_concurrent_calls(run_async):
    async def body():
        srv = Server("conc")

        async def slow_echo(body, ctx):
            await asyncio.sleep(0.01 * (body % 5))
            return body

        srv.register_unary("E", slow_echo)
        await srv.serve(NetAddr.tcp("127.0.0.1", 0))
        cli = Client(NetAddr.tcp("127.0.0.1", srv.port()))
        results = await asyncio.gather(*[cli.call("E", i) for i in range(20)])
        assert results == list(range(20))
        await cli.close()
        await srv.close()

    run_async(body())


def test_vsock_netaddr_parsing():
    """vsock addr plumbing (reference pkg/rpc/vsock.go); actual AF_VSOCK
    IO needs a VM host-guest pair, so only the address surface is tested."""
    from dragonfly2_tpu.pkg.types import NetAddr

    a = NetAddr.vsock(3, 1024)
    assert a.type == "vsock" and a.cid_port() == (3, 1024)
    assert str(a) == "vsock://3:1024"
    try:
        a.host_port()
    except ValueError:
        pass
    else:
        raise AssertionError("host_port must reject vsock")
