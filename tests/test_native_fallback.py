"""A box with no C++ toolchain and no prebuilt library must degrade,
never crash: importing dragonfly2_tpu.native.binding raises a clean
ImportError (nothing else — no OSError, no BuildUnavailable leaking),
and every backend ladder that prefers the native library (pkg/digest,
delta/chunker, storage/io_ring) falls through and still works.

Run in a subprocess so the simulated bare box (empty PATH, empty native
lib cache dir) can't poison this process's already-imported binding.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = r"""
import sys

try:
    from dragonfly2_tpu.native import binding          # noqa: F401
except ImportError as e:
    msg = str(e)
    assert "native library unavailable" in msg, f"opaque reason: {msg!r}"
except BaseException as e:                             # noqa: BLE001
    sys.exit(f"import raised {type(e).__name__}, not ImportError: {e}")
else:
    sys.exit("binding imported despite no toolchain and empty lib dir")

from dragonfly2_tpu.pkg import digest
assert digest.crc32c(b"123456789") == 0xE3069283, "digest ladder broke"

from dragonfly2_tpu.delta import chunker
backend = chunker.chunker_backend()
assert backend in ("numpy", "python"), backend
chunks = chunker.chunk_bytes(
    b"q" * 300_000,
    chunker.CDCParams(mask_bits=10, min_size=2048, max_size=16384))
assert sum(c.length for c in chunks) == 300_000

from dragonfly2_tpu.storage import io_ring
ring = io_ring.ring_backend()
assert ring in ("threads", "serial"), ring

from dragonfly2_tpu.proto import reportcodec
report = reportcodec.report_backend()
assert report in ("numpy", "python"), report
packed = reportcodec.encode_reports([
    {"piece_num": 4, "range_start": 4096, "range_size": 4096,
     "digest": "crc32c:00c0ffee", "download_cost_ms": 3,
     "dst_peer_id": "peer-a"},
    {"piece_num": 5, "range_start": 8192, "range_size": 512,
     "download_cost_ms": 0, "dst_peer_id": ""},
])
batch = reportcodec.decode_packed(packed)
assert batch.nums == [4, 5] and batch.cost_total == 3, batch.to_dicts()
assert batch.to_dicts()[0]["digest"] == "crc32c:00c0ffee"

print("FALLBACK-OK", backend, ring, report)
"""


def test_binding_import_fails_clean_and_ladders_degrade(tmp_path):
    env = {
        # Empty PATH: g++ can't be found, so build() must raise
        # BuildUnavailable -> binding converts to ImportError.
        "PATH": "",
        # Empty cache dir: no prebuilt libdfnative.so to fall back on.
        "DF_NATIVE_LIB_DIR": str(tmp_path / "empty-lib"),
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "HOME": os.environ.get("HOME", "/tmp"),
    }
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 0, (
        f"bare-box probe failed\nstdout: {proc.stdout}\nstderr: {proc.stderr}")
    assert "FALLBACK-OK" in proc.stdout


def test_build_cli_skips_gracefully_without_toolchain(tmp_path):
    env = {
        "PATH": "",
        "DF_NATIVE_LIB_DIR": str(tmp_path / "empty-lib"),
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "HOME": os.environ.get("HOME", "/tmp"),
    }
    proc = subprocess.run(
        [sys.executable, "-m", "dragonfly2_tpu.native.build"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert "skipping native build" in proc.stdout
    assert "g++ not found" in proc.stdout
