"""Scheduler crash-recovery & shard failover (ISSUE 9).

Fast tier-1 battery for the server half of announce failover:

  - resume-carrying re-registration rebuilds Task/Peer state (never
    demotes a resuming peer to origin),
  - the durable snapshot store (save/load bounds, bitmap roundtrip,
    ghost re-register),
  - the convergence PROPERTY: (snapshot load ∘ partial re-registration)
    ≡ (pure re-registration) for seeded random histories including
    failed/left peers and stripe membership,
  - the RPC classification table guard (every scheduler RPC the daemon
    speaks must be classified — silent misclassification is a failover
    correctness bug),
  - ring-rebuild re-homing (manager liveness → dynconfig → ring → the
    conductor drains and re-homes with result="rehomed"),
  - the ``sched.announce`` chaos site.

The real-process crash e2e (kill the OWNING scheduler mid 4-host pod
broadcast) lives at the bottom — fast tier-1 per the acceptance bar.
"""

from __future__ import annotations

import asyncio
import random

import pytest

from dragonfly2_tpu.pkg import chaos as chaos_mod
from dragonfly2_tpu.pkg import metrics as metrics_mod
from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.resource import PeerState, TaskState
from dragonfly2_tpu.scheduler.resource.snapshot import (
    SnapshotStore,
    blob_to_pieces,
    pieces_to_blob,
)
from dragonfly2_tpu.scheduler.service import SchedulerService

N_PIECES = 16
PIECE_SIZE = 1 << 20
CONTENT_LEN = N_PIECES * PIECE_SIZE


@pytest.fixture(autouse=True)
def _chaos_disabled():
    chaos_mod.disable()
    yield
    chaos_mod.disable()


class FakeStream:
    def __init__(self, open_body):
        self.open_body = open_body
        self.to_sched: asyncio.Queue = asyncio.Queue()
        self.to_peer: asyncio.Queue = asyncio.Queue()

    async def send(self, body):
        await self.to_peer.put(body)

    async def recv(self, timeout=None):
        return await self.to_sched.get()


def _svc(snapshot_db: str = ":memory:", **scheduling_overrides):
    cfg = SchedulerConfig()
    cfg.seed_peer_enabled = False
    cfg.scheduling.retry_interval = 0.02
    cfg.ha.snapshot_db = snapshot_db
    for k, v in scheduling_overrides.items():
        setattr(cfg.scheduling, k, v)
    return SchedulerService(cfg)


def _body(host: str, peer: str, *, task: str = "ha-task",
          tpu_slice: str = "", worker: int = -1, pod_broadcast: bool = False):
    b = {"host": {"id": host, "hostname": host, "ip": "127.0.0.1",
                  "port": 7000, "upload_port": 7001,
                  "tpu_slice": tpu_slice, "tpu_worker_index": worker},
         "peer_id": peer, "task_id": task, "url": "http://o/f"}
    if pod_broadcast:
        b["pod_broadcast"] = True
    return b


def _resume(piece_nums, *, pod_broadcast: bool = False) -> dict:
    return {"piece_nums": list(piece_nums), "content_length": CONTENT_LEN,
            "piece_size": PIECE_SIZE, "total_piece_count": N_PIECES,
            "prefix_digest": "", "pod_broadcast": pod_broadcast}


async def _open_and_register(svc, body, register_msg):
    """Open an announce stream, send one register, return
    (stream, server_task, first_answer)."""
    stream = FakeStream(body)
    server = asyncio.ensure_future(svc.announce_peer(stream, None))
    await stream.to_sched.put(register_msg)
    answer = await asyncio.wait_for(stream.to_peer.get(), timeout=30)
    return stream, server, answer


async def _close(stream, server):
    await stream.to_sched.put(None)
    await asyncio.wait_for(server, timeout=30)


def _scrape(family: str, label: str) -> dict:
    text = metrics_mod.render()[0].decode()
    return metrics_mod.parse_labeled_samples(
        text, f"dragonfly_tpu_{family}", label)


# --------------------------------------------------------------------- #
# Resume re-registration
# --------------------------------------------------------------------- #

class TestResumeRegister:
    def test_resume_rebuilds_peer_and_never_back_sources(self, run_async):
        """A resume register on a scheduler that has never seen the task
        answers normal_task (not need_back_source), rebuilds the landed
        set and geometry, and counts the rebuild."""

        async def body():
            before = _scrape("scheduler_state_rebuilt_peers_total",
                             "source").get("reregister", 0)
            svc = _svc()
            stream, server, ans = await _open_and_register(
                svc, _body("h1", "p1"),
                {"type": "register", "resume": _resume(range(8))})
            assert ans["type"] == "normal_task"
            assert ans["task"]["content_length"] == CONTENT_LEN
            peer = svc.peers.load("p1")
            assert peer.fsm.current == PeerState.RUNNING
            assert peer.finished_pieces == set(range(8))
            task = svc.tasks.load("ha-task")
            assert task.piece_size == PIECE_SIZE
            assert set(task.pieces) == set(range(8))
            # Rebuilt piece metadata carries the right geometry.
            assert task.pieces[3].range_start == 3 * PIECE_SIZE
            assert task.pieces[3].range_size == PIECE_SIZE
            after = _scrape("scheduler_state_rebuilt_peers_total",
                            "source").get("reregister", 0)
            assert after == before + 1
            await _close(stream, server)

        run_async(body(), timeout=60)

    def test_resumed_peer_serves_next_registrant(self, run_async):
        """The rebuilt peer is immediately a usable parent: a fresh
        registrant gets it handed out instead of being sent to origin."""

        async def body():
            svc = _svc()
            s1, srv1, _ = await _open_and_register(
                svc, _body("h1", "p1"),
                {"type": "register", "resume": _resume(range(N_PIECES))})
            s2, srv2, ans2 = await _open_and_register(
                svc, _body("h2", "p2"), {"type": "register"})
            assert ans2["type"] == "normal_task", ans2
            assert [p["id"] for p in ans2["parents"]] == ["p1"]
            # The handed-out parent advertises its rebuilt pieces.
            assert len(ans2["parents"][0]["finished_pieces"]) == N_PIECES
            await _close(s1, srv1)
            await _close(s2, srv2)

        run_async(body(), timeout=60)

    def test_resume_idempotent_on_ghost_peer(self, run_async):
        """Re-registering a peer the scheduler already holds as a
        RUNNING ghost (snapshot restore) attaches the stream and applies
        the bitset idempotently — no TransitionError, no duplication."""

        async def body():
            svc = _svc()
            s1, srv1, _ = await _open_and_register(
                svc, _body("h1", "p1"),
                {"type": "register", "resume": _resume(range(4))})
            await _close(s1, srv1)
            peer = svc.peers.load("p1")
            # The stream-gone path failed the streamless peer; a ghost
            # from a snapshot restore is RUNNING — model that state.
            peer.fsm.restore(PeerState.RUNNING)
            s2, srv2, ans = await _open_and_register(
                svc, _body("h1", "p1"),
                {"type": "register", "resume": _resume(range(6))})
            assert ans["type"] == "normal_task"
            assert svc.peers.load("p1") is peer       # same object, no churn
            assert peer.finished_pieces == set(range(6))
            await _close(s2, srv2)

        run_async(body(), timeout=60)

    def test_duplicate_report_backfills_digest(self, run_async):
        """Resume-rebuilt piece metadata has no digests; the idempotent
        re-report that follows is where they arrive."""

        async def body():
            svc = _svc()
            stream, server, _ = await _open_and_register(
                svc, _body("h1", "p1"),
                {"type": "register", "resume": _resume(range(4))})
            task = svc.tasks.load("ha-task")
            assert task.pieces[2].digest == ""
            await stream.to_sched.put({"type": "pieces_finished", "pieces": [
                {"piece_num": 2, "range_start": 2 * PIECE_SIZE,
                 "range_size": PIECE_SIZE, "digest": "crc32c:abcd",
                 "download_cost_ms": 3, "dst_peer_id": ""}]})
            await stream.to_sched.put({"type": "piece_finished", "piece": {
                "piece_num": 3, "range_start": 3 * PIECE_SIZE,
                "range_size": PIECE_SIZE, "digest": "crc32c:ef01",
                "download_cost_ms": 3, "dst_peer_id": ""}})
            await _close(stream, server)
            assert task.pieces[2].digest == "crc32c:abcd"
            assert task.pieces[3].digest == "crc32c:ef01"
            # Idempotent: the re-report did not double-count.
            assert svc.peers.load("p1").finished_pieces == set(range(4))

        run_async(body(), timeout=60)

    def test_seed_resume_keeps_reference_path(self, run_async):
        """Seeds stay on the need_back_source path (their announce-only
        fast path re-reports with digests)."""

        async def body():
            svc = _svc()
            body_ = _body("hseed", "pseed")
            body_["is_seed"] = True
            stream, server, ans = await _open_and_register(
                svc, body_,
                {"type": "register", "resume": _resume(range(N_PIECES))})
            assert ans["type"] == "need_back_source"
            await _close(stream, server)

        run_async(body(), timeout=60)

    def test_resume_with_stripe_membership(self, run_async):
        """pod_broadcast survives the resume and the answer carries a
        stripe plan once ≥2 same-slice broadcast peers re-registered."""

        async def body():
            svc = _svc()
            streams = []
            answers = []
            for i in range(2):
                b = _body(f"h{i}", f"p{i}", tpu_slice="slice-0", worker=i,
                          pod_broadcast=True)
                s, srv, ans = await _open_and_register(
                    svc, b, {"type": "register",
                             "resume": _resume(range(i, N_PIECES, 2),
                                               pod_broadcast=True)})
                streams.append((s, srv))
                answers.append(ans)
            assert all(svc.peers.load(f"p{i}").pod_broadcast
                       for i in range(2))
            # The second re-registrant sees a full 2-slice stripe plan.
            assert answers[1].get("stripe", {}).get("slice_size") == 2
            for s, srv in streams:
                await _close(s, srv)

        run_async(body(), timeout=60)


# --------------------------------------------------------------------- #
# Snapshot store
# --------------------------------------------------------------------- #

class TestSnapshotStore:
    def test_piece_bitmap_roundtrip(self):
        rng = random.Random(7)
        for _ in range(50):
            nums = {rng.randrange(0, 30000)
                    for _ in range(rng.randrange(0, 400))}
            assert set(blob_to_pieces(pieces_to_blob(nums))) == nums
        assert pieces_to_blob(set()) == b""
        assert blob_to_pieces(b"") == []

    def test_save_restore_roundtrip(self, run_async, tmp_path):
        """States, bitsets, pod_broadcast flags and slice membership
        survive the save → fresh-service restore; terminal and
        back-sourcing peers do not (re-registration could never
        reproduce them — the convergence contract)."""
        db = str(tmp_path / "snap.db")

        async def body():
            svc = _svc(snapshot_db=db)
            opened = []
            for i, state in enumerate(
                    ["running", "succeeded", "failed", "leave"]):
                b = _body(f"h{i}", f"p{i}", tpu_slice="slice-0", worker=i,
                          pod_broadcast=(i == 0))
                s, srv, _ = await _open_and_register(
                    svc, b, {"type": "register",
                             "resume": _resume(range(4 + i),
                                               pod_broadcast=(i == 0))})
                opened.append((s, srv))
                peer = svc.peers.load(f"p{i}")
                peer.fsm.restore(getattr(PeerState, state.upper()))
            counts = svc.snapshot_flush()
            assert counts == {"hosts": 2, "tasks": 1, "peers": 2}
            for s, srv in opened:
                await _close(s, srv)

            before = _scrape("scheduler_state_rebuilt_peers_total",
                             "source").get("snapshot", 0)
            svc2 = _svc(snapshot_db=db)
            after = _scrape("scheduler_state_rebuilt_peers_total",
                            "source").get("snapshot", 0)
            assert after == before + 2
            assert {p.id for p in svc2.peers.all()} == {"p0", "p1"}
            p0, p1 = svc2.peers.load("p0"), svc2.peers.load("p1")
            assert p0.fsm.current == PeerState.RUNNING
            assert p1.fsm.current == PeerState.SUCCEEDED
            assert p0.finished_pieces == set(range(4))
            assert p1.finished_pieces == set(range(5))
            assert p0.pod_broadcast and not p1.pod_broadcast
            task = svc2.tasks.load("ha-task")
            assert task.fsm.current == TaskState.SUCCEEDED   # p1 backs it
            assert task.piece_size == PIECE_SIZE
            assert set(task.pieces) == set(range(5))
            assert task.slice_index["slice-0"] == {"p0", "p1"}
            host = svc2.hosts.load("h0")
            assert host is not None and host.upload_port == 7001
            assert host.tpu_slice == "slice-0"

        run_async(body(), timeout=60)

    def test_bounds_cap_tasks_and_peers(self, run_async, tmp_path):
        db = str(tmp_path / "snap.db")

        async def body():
            svc = _svc(snapshot_db=db)
            svc.config.ha.max_tasks = 2
            svc.config.ha.max_peers = 3
            opened = []
            for t in range(4):
                for j in range(2):
                    b = _body(f"h{t}-{j}", f"p{t}-{j}", task=f"task-{t}")
                    s, srv, _ = await _open_and_register(
                        svc, b,
                        {"type": "register", "resume": _resume(range(2))})
                    opened.append((s, srv))
                    await asyncio.sleep(0.01)   # distinct updated_at order
            counts = svc.snapshot_flush()
            assert counts["tasks"] == 2
            assert counts["peers"] <= 3
            for s, srv in opened:
                await _close(s, srv)
            # Newest tasks won the cap.
            rows = SnapshotStore(db).load()
            assert {t["task_id"] for t in rows["tasks"]} == \
                {"task-2", "task-3"}

        run_async(body(), timeout=60)

    def test_peerless_snapshot_restores_nothing(self, tmp_path):
        db = str(tmp_path / "snap.db")
        store = SnapshotStore(db)
        assert store.load() == {"hosts": [], "tasks": [], "peers": [],
                                "saved_at": 0.0}
        svc = _svc(snapshot_db=db)
        assert not svc.peers.all() and not svc.tasks.all()


# --------------------------------------------------------------------- #
# Convergence property (satellite 3)
# --------------------------------------------------------------------- #

def _canon(svc) -> dict:
    """Canonical Task/Peer/Host state for convergence comparison."""
    tasks = {t.id: (t.fsm.current, t.content_length, t.piece_size,
                    t.total_piece_count, tuple(sorted(t.pieces)),
                    {s: frozenset(m) for s, m in t.slice_index.items() if m})
             for t in svc.tasks.all()}
    peers = {p.id: (p.task.id, p.host.id, p.fsm.current,
                    tuple(sorted(p.finished_pieces)), p.pod_broadcast)
             for p in svc.peers.all()}
    hosts = {h.id: (h.ip, h.port, h.upload_port, h.tpu_slice)
             for h in svc.hosts.all()}
    return {"tasks": tasks, "peers": peers, "hosts": hosts}


async def _run_history(svc, rng) -> list[dict]:
    """Drive a seeded random history on ``svc``; returns per-peer specs
    {peer, host, slice, worker, pod_broadcast, pieces, final} where
    ``final`` is the peer's state when the 'crash' happens."""
    n_hosts = rng.randrange(6, 12)
    specs = []
    for i in range(n_hosts):
        pod = rng.random() < 0.5
        spec = {
            "peer": f"p{i}", "host": f"h{i}",
            "slice": f"slice-{i % 2}", "worker": i // 2,
            "pod_broadcast": pod,
            "pieces": sorted(rng.sample(range(N_PIECES),
                                        rng.randrange(1, N_PIECES + 1))),
            "final": rng.choice(["running", "running", "succeeded",
                                 "failed", "leave"]),
        }
        if spec["final"] == "succeeded":
            spec["pieces"] = list(range(N_PIECES))
        specs.append(spec)
    for spec in specs:
        b = _body(spec["host"], spec["peer"], tpu_slice=spec["slice"],
                  worker=spec["worker"], pod_broadcast=spec["pod_broadcast"])
        stream, server, _ = await _open_and_register(
            svc, b, {"type": "register",
                     "resume": _resume(spec["pieces"],
                                       pod_broadcast=spec["pod_broadcast"])})
        if spec["final"] == "succeeded":
            await stream.to_sched.put({
                "type": "download_finished",
                "content_length": CONTENT_LEN, "piece_size": PIECE_SIZE,
                "total_piece_count": N_PIECES})
        await _close(stream, server)
        peer = svc.peers.load(spec["peer"])
        # The stream-gone path failed still-running peers (their streams
        # just closed); restore the state the live scheduler HELD at the
        # crash instant for running ones, and the explicit terminal
        # states for failed/left peers.
        peer.fsm.restore(getattr(PeerState, spec["final"].upper()))
    return specs


async def _reregister(svc, specs, subset) -> None:
    """Re-register ``subset`` of the history's survivors onto ``svc``:
    running peers re-register with resume (the conductor recovery path),
    succeeded peers re-announce via AnnounceTask (the completed-store
    path) — both exactly as the real daemons drive them."""
    for spec in subset:
        if spec["final"] == "running":
            b = _body(spec["host"], spec["peer"], tpu_slice=spec["slice"],
                      worker=spec["worker"],
                      pod_broadcast=spec["pod_broadcast"])
            stream, server, ans = await _open_and_register(
                svc, b, {"type": "register",
                         "resume": _resume(
                             spec["pieces"],
                             pod_broadcast=spec["pod_broadcast"])})
            assert ans["type"] == "normal_task", (spec, ans)
            await _close(stream, server)
            # The model peer is still mid-download at comparison time.
            svc.peers.load(spec["peer"]).fsm.restore(PeerState.RUNNING)
        elif spec["final"] == "succeeded":
            body_ = _body(spec["host"], spec["peer"],
                          tpu_slice=spec["slice"], worker=spec["worker"],
                          pod_broadcast=spec["pod_broadcast"])
            body_.update({
                "url": "http://o/f", "content_length": CONTENT_LEN,
                "piece_size": PIECE_SIZE, "total_piece_count": N_PIECES,
                "piece_nums": spec["pieces"],
            })
            await svc.announce_task(body_, None)


class TestConvergenceProperty:
    @pytest.mark.parametrize("seed", range(8))
    def test_snapshot_plus_partial_rereg_equals_pure_rereg(
            self, run_async, tmp_path, seed):
        """THE HA contract: for a seeded random history (running,
        succeeded, failed and left peers; mixed stripe membership), a
        fresh scheduler built from (snapshot load + a random SUBSET
        re-registering) holds exactly the same Task/Peer/Host state as
        one built from EVERY survivor re-registering with no snapshot —
        so a restart with a stale-but-flushed snapshot and a failover
        with no snapshot at all converge to the same cluster view."""
        db = str(tmp_path / f"snap-{seed}.db")

        async def body():
            rng = random.Random(1000 + seed)
            svc1 = _svc(snapshot_db=db)
            specs = await _run_history(svc1, rng)
            svc1.snapshot_flush()

            survivors = [s for s in specs
                         if s["final"] in ("running", "succeeded")]
            subset = [s for s in survivors if rng.random() < 0.5]

            # Path A: snapshot restore + partial re-registration.
            svc_a = _svc(snapshot_db=db)
            await _reregister(svc_a, specs, subset)
            # Path B: pure re-registration of every survivor.
            svc_b = _svc(snapshot_db=":memory:")
            await _reregister(svc_b, specs, survivors)

            ca, cb = _canon(svc_a), _canon(svc_b)
            assert ca == cb, (seed, ca, cb)
            # And both actually reconstructed the survivors.
            assert set(ca["peers"]) == {s["peer"] for s in survivors}

        run_async(body(), timeout=120)


# --------------------------------------------------------------------- #
# RPC classification table (satellite 1)
# --------------------------------------------------------------------- #

class TestRpcTable:
    def test_every_spoken_scheduler_rpc_is_classified(self):
        """Grep the daemon/client/cli sources for ``"Scheduler.X"``
        literals: every name must appear in RPC_TABLE. A new RPC without
        a row is a failover correctness bug waiting to happen."""
        import os
        import re

        from dragonfly2_tpu.daemon import schedulerclient as sc

        root = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "dragonfly2_tpu")
        spoken = set()
        for sub in ("daemon", "client", "cli"):
            for dirpath, _dirs, files in os.walk(os.path.join(root, sub)):
                for fn in files:
                    if not fn.endswith(".py"):
                        continue
                    text = open(os.path.join(dirpath, fn)).read()
                    spoken |= set(re.findall(r'"(Scheduler\.[A-Za-z]+)"',
                                             text))
        assert spoken, "grep found no scheduler RPCs at all (moved?)"
        missing = spoken - set(sc.RPC_TABLE)
        assert not missing, (
            f"scheduler RPCs spoken by the daemon but missing from "
            f"RPC_TABLE: {sorted(missing)} — classify them "
            f"(idempotent/state_bearing/fanout/stream)")

    def test_table_values_are_known_classes(self):
        from dragonfly2_tpu.daemon import schedulerclient as sc

        assert set(sc.RPC_TABLE.values()) <= {
            sc.STREAM, sc.IDEMPOTENT, sc.STATE_BEARING, sc.FANOUT}

    def test_unary_resolves_failover_from_table(self, run_async):
        """state_bearing methods never ring-fail-over; idempotent ones
        do; an explicit override wins."""
        from dragonfly2_tpu.daemon.schedulerclient import SchedulerClient

        async def body():
            cli = SchedulerClient(["127.0.0.1:1", "127.0.0.1:2"])
            seen = []

            async def fake_routed(task_id, method, body_, timeout,
                                  idempotent=False):
                seen.append((method, idempotent))
                return {}

            cli._routed_call = fake_routed
            await cli.unary("t", "Scheduler.UploadPersistentCacheTaskStarted",
                            {})
            await cli.unary("t", "Scheduler.AnnounceTask", {})
            await cli.unary("t", "Scheduler.UnknownPluginRpc", {})
            await cli.unary("t", "Scheduler.UnknownPluginRpc", {},
                            idempotent=True)
            assert seen == [
                ("Scheduler.UploadPersistentCacheTaskStarted", False),
                ("Scheduler.AnnounceTask", True),
                ("Scheduler.UnknownPluginRpc", False),   # unknown: safe side
                ("Scheduler.UnknownPluginRpc", True),    # explicit override
            ]
            await cli.close()

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# Ring rebuild re-homing (satellite 6) + manager liveness (tentpole c)
# --------------------------------------------------------------------- #

class TestRingRehoming:
    def test_update_addrs_fires_watcher_on_ownership_move(self, run_async):
        from dragonfly2_tpu.daemon.schedulerclient import SchedulerClient
        from dragonfly2_tpu.rpc.balancer import HashRing

        async def body():
            a, b, c = "127.0.0.1:9001", "127.0.0.1:9002", "127.0.0.1:9003"
            cli = SchedulerClient([a, b])
            fired = []
            cli.watch_ring("task-x", fired.append)
            owner = HashRing([a, b]).pick("task-x")
            other = b if owner == a else a
            cli._stream_addrs["task-x"] = owner
            # Same membership: no-op, no callback.
            cli.update_addrs([b, a])
            assert fired == []
            # Ownership moves when the current owner leaves the set.
            cli.update_addrs([other, c])
            new_owner = HashRing([other, c]).pick("task-x")
            if new_owner != owner:
                assert fired == [new_owner]
            # Stream already on the owner after a further rebuild: quiet.
            cli._stream_addrs["task-x"] = new_owner
            fired.clear()
            cli.update_addrs([other, c, "127.0.0.1:9009"])
            still_owner = cli._ring.pick("task-x")
            if still_owner == new_owner:
                assert fired == []
            cli.unwatch_ring("task-x")
            assert "task-x" not in cli._watchers
            await cli.close()

        run_async(body(), timeout=30)

    def test_conductor_rehomes_gracefully(self, run_async, tmp_path,
                                          monkeypatch):
        """Ring-change callback → buffered reports drain, the old stream
        closes, recovery reconnects and books result="rehomed"."""
        from dragonfly2_tpu.pkg import retry as retrylib
        from tests.test_chaos import (
            FakeAnnounceStream,
            FakeSchedulerClient,
            _make_conductor,
        )

        monkeypatch.setattr(retrylib, "ANNOUNCE",
                            retrylib.BackoffPolicy(base=0.01, cap=0.02))

        async def body():
            before = _scrape("peer_announce_reconnects_total",
                             "result").get("rehomed", 0)
            fresh = FakeAnnounceStream([{
                "type": "normal_task",
                "task": {"content_length": 8, "piece_size": 4,
                         "total_piece_count": 2},
                "parents": []}])
            sched = FakeSchedulerClient([fresh])
            c = _make_conductor(tmp_path, sched)
            old = FakeAnnounceStream()
            c._stream = old
            rec = c.store.get_pieces()[0]
            await c._report_piece(rec, parent_id="")
            c._on_ring_change("127.0.0.1:7777")
            for _ in range(100):
                if old.closed:
                    break
                await asyncio.sleep(0.01)
            assert old.closed, "rehome must close the old stream"
            # The drain flushed the buffered report to the OLD member
            # before closing.
            assert any(m.get("type", "").startswith("piece")
                       for m in old.sent), old.sent
            # What the receiver loop would now do: recover.
            assert await c._recover_announce_stream()
            assert c._stream is fresh
            assert c._rehome_pending is False
            after = _scrape("peer_announce_reconnects_total",
                            "result").get("rehomed", 0)
            assert after == before + 1
            # The re-register carried resume state.
            assert fresh.sent[0]["type"] == "register"
            assert fresh.sent[0]["resume"]["piece_nums"] == [0, 1]

        run_async(body(), timeout=60)

    def test_manager_liveness_drives_ring_rebuild(self, run_async):
        """Tentpole (c) end-to-end minus real scheduler processes: two
        schedulers register with a REAL manager (rpc server + keepalive
        streams); one's keepalive lapses → expire_stale flips it
        inactive → the daemon dynconfig refresh returns only the
        survivor → update_addrs rebuilds the ring → the conductor-style
        watcher fires with the surviving owner."""
        import time as _time

        from dragonfly2_tpu.daemon.dynconfig import DaemonDynconfig
        from dragonfly2_tpu.daemon.schedulerclient import SchedulerClient
        from dragonfly2_tpu.manager import service as msvc_mod
        from dragonfly2_tpu.manager.rpcserver import ManagerRpcServer
        from dragonfly2_tpu.manager.service import ManagerService
        from dragonfly2_tpu.pkg.types import NetAddr
        from dragonfly2_tpu.rpc import Server

        async def body():
            msvc = ManagerService()
            server = Server("manager")
            ManagerRpcServer(msvc).register(server)
            await server.serve(NetAddr.tcp("127.0.0.1", 0))
            addr_a, addr_b = "10.0.0.1:8002", "10.0.0.2:8002"
            try:
                for host, ip in (("sched-a", "10.0.0.1"),
                                 ("sched-b", "10.0.0.2")):
                    msvc.update_scheduler(
                        {"hostname": host, "ip": ip, "port": 8002})
                dc = DaemonDynconfig(
                    local_addrs=[],
                    manager_addr=f"127.0.0.1:{server.port()}",
                    host_info={"hostname": "d1", "ip": "127.0.0.1"},
                    refresh_interval=0.05)
                addrs = sorted(await dc.scheduler_addrs())
                assert addrs == [addr_a, addr_b]
                cli = SchedulerClient(addrs)
                fired = []
                # Watch a task id OWNED by sched-a so its death must
                # re-home us to sched-b.
                task_id = next(f"t{i}" for i in range(1000)
                               if cli._ring.pick(f"t{i}") == addr_a)
                cli.watch_ring(task_id, fired.append)
                cli._stream_addrs[task_id] = addr_a

                # sched-a's keepalive lapses (backdate it), sched-b's is
                # fresh; the manager GC flips only sched-a inactive.
                row = msvc.db.find("schedulers", hostname="sched-a",
                                   ip="10.0.0.1")
                msvc.db.update("schedulers", row["id"], {
                    "last_keepalive_at":
                        _time.time() - msvc_mod.KEEPALIVE_TIMEOUT - 1})
                assert msvc.expire_stale() == 1
                msvc._cache = type(msvc._cache)(default_ttl=0.0)

                fresh = await dc._fetch()
                live = [f"{s['ip']}:{s['port']}"
                        for s in fresh["schedulers"]
                        if s.get("state") == "active"]
                assert live == [addr_b]
                cli.update_addrs(live)
                assert fired == [addr_b]
                assert cli._ring.members() == [addr_b]
                await dc.stop()
                await cli.close()
            finally:
                await server.close()

        run_async(body(), timeout=60)


# --------------------------------------------------------------------- #
# Chaos site: sched.announce
# --------------------------------------------------------------------- #

class TestSchedAnnounceChaos:
    def test_drop_severs_stream_server_side(self, run_async):
        async def body():
            chaos_mod.enable(chaos_mod.parse_spec({"seed": 3, "rules": [
                {"site": "sched.announce", "kind": "drop", "at": [2]}]}))
            svc = _svc()
            stream, server, ans = await _open_and_register(
                svc, _body("h1", "p1"),
                {"type": "register", "resume": _resume(range(4))})
            assert ans["type"] == "normal_task"
            # Second message trips the sever: the service loop exits as
            # if the stream died — the peer is failed via stream-gone.
            await stream.to_sched.put({"type": "piece_finished", "piece": {
                "piece_num": 5, "range_start": 5 * PIECE_SIZE,
                "range_size": PIECE_SIZE, "digest": "",
                "download_cost_ms": 1, "dst_peer_id": ""}})
            await asyncio.wait_for(server, timeout=30)
            peer = svc.peers.load("p1")
            assert peer.fsm.current == PeerState.FAILED
            assert 5 not in peer.finished_pieces   # dropped, not applied
            assert ("sched.announce", "p1", 2, "drop") in \
                chaos_mod.enabled().injected
            # And the SAME peer re-registering recovers (the PR4 stale-
            # replacement + resume path compose).
            chaos_mod.disable()
            s2, srv2, ans2 = await _open_and_register(
                svc, _body("h1", "p1"),
                {"type": "register", "resume": _resume(range(6))})
            assert ans2["type"] == "normal_task"
            assert svc.peers.load("p1").fsm.current == PeerState.RUNNING
            await _close(s2, srv2)

        run_async(body(), timeout=60)

    def test_service_hook_inert_by_default(self):
        from dragonfly2_tpu.scheduler import service as svc_mod

        assert svc_mod._chaos is None
        fabric = chaos_mod.parse_spec({"seed": 0, "rules": []})
        chaos_mod.enable(fabric)
        assert svc_mod._chaos is fabric
        chaos_mod.disable()
        assert svc_mod._chaos is None


# --------------------------------------------------------------------- #
# Crash e2e: kill the OWNING scheduler mid 4-host pod broadcast
# --------------------------------------------------------------------- #

E2E_CONTENT = bytes(random.Random(909).randbytes(48 * 1024 * 1024))


class TestSchedulerCrashE2E:
    """The acceptance drill (fast tier-1): two real scheduler processes,
    one real seed + four real pod daemons (same TPU slice, pod
    broadcast). When ≥50% of the pod's piece bytes have landed, the
    scheduler OWNING the task is SIGKILLed. Every host must complete
    byte-identical via the failover member, with zero re-downloads of
    landed pieces (per-locality byte accounting sums to exactly one
    content copy per host) and no back-to-source on any pod host."""

    def test_kill_owning_scheduler_mid_pod_broadcast(self, run_async,
                                                     tmp_path):
        import hashlib
        import json as _json
        import os
        import signal
        import subprocess

        import aiohttp

        from dragonfly2_tpu.pkg import idgen
        from dragonfly2_tpu.rpc.balancer import HashRing
        from tests.test_podlens import (
            _free_port,
            _spawn_cli,
            _start_e2e_origin,
        )

        sha = hashlib.sha256(E2E_CONTENT).hexdigest()

        async def wait_sock(path, timeout=90.0):
            deadline = asyncio.get_running_loop().time() + timeout
            while asyncio.get_running_loop().time() < deadline:
                if os.path.exists(path):
                    return True
                await asyncio.sleep(0.1)
            return False

        async def run():
            import tests.test_podlens as podlens_e2e

            # Reuse the podlens origin helper against OUR content.
            orig_content = podlens_e2e.E2E_CONTENT
            podlens_e2e.E2E_CONTENT = E2E_CONTENT
            runner, origin_port = await _start_e2e_origin()
            url = f"http://127.0.0.1:{origin_port}/pod.bin"
            ports = {"a": _free_port(), "b": _free_port()}
            mports = {"a": _free_port(), "b": _free_port()}
            addrs = [f"127.0.0.1:{ports['a']}", f"127.0.0.1:{ports['b']}"]
            task_id = idgen.task_id_v1(url, digest=f"sha256:{sha}")
            owner_addr = HashRing(addrs).pick(task_id)
            owner_key = "a" if owner_addr == addrs[0] else "b"
            survivor_key = "b" if owner_key == "a" else "a"
            sched_procs = {}
            procs = []
            homes = {}
            dmports = {}
            try:
                for key in ("a", "b"):
                    p = _spawn_cli(
                        ["scheduler", "--host", "127.0.0.1",
                         "--port", str(ports[key]),
                         "--metrics-port", str(mports[key])],
                        str(tmp_path / f"sched-{key}.log"))
                    sched_procs[key] = p
                    procs.append(p)

                # Pod daemons carry a seeded piece-body stall schedule so
                # the broadcast has a kill WINDOW (without it a 48 MiB
                # loopback pod finishes in well under a second).
                stall_env = {"DF_CHAOS": _json.dumps({"seed": 5, "rules": [
                    {"site": "piece.body", "kind": "stall", "rate": 0.45,
                     "stall_s": 0.8, "max_fires": 10}]})}
                names = ["pod-seed"] + [f"pod-{i}" for i in range(4)]
                for i, name in enumerate(names):
                    home = str(tmp_path / name)
                    homes[name] = home
                    dmports[name] = _free_port()
                    args = ["daemon", "--work-home", home,
                            "--hostname", name,
                            "--scheduler", addrs[0],
                            "--scheduler", addrs[1],
                            "--metrics-port", str(dmports[name])]
                    env = {}
                    if name == "pod-seed":
                        args += ["--seed-peer", "--tpu-slice", "slice-seed"]
                    else:
                        args += ["--tpu-slice", "slice-0",
                                 "--tpu-worker-index", str(i - 1)]
                        env = stall_env
                    p = _spawn_cli(args, str(tmp_path / f"{name}.log"), env)
                    procs.append(p)
                for name, home in homes.items():
                    ok = await wait_sock(f"{home}/run/dfdaemon.sock")
                    assert ok, open(tmp_path / f"{name}.log").read()[-2000:]

                def dfget(name, out):
                    return _spawn_cli(
                        ["dfget", url, "-O", out,
                         "--work-home", homes[name], "--no-daemon",
                         "--digest", f"sha256:{sha}", "--pod-broadcast"],
                        out + ".log")

                pod_names = names[1:]
                outs = {n: str(tmp_path / f"out-{n}.bin")
                        for n in pod_names}
                pulls = {n: dfget(n, outs[n]) for n in pod_names}

                async def scrape(port, path="/metrics"):
                    async with aiohttp.ClientSession() as sess:
                        async with sess.get(
                                f"http://127.0.0.1:{port}{path}",
                                timeout=aiohttp.ClientTimeout(
                                    total=5)) as r:
                            return await r.text()

                def piece_bytes(text: str) -> int:
                    return sum(metrics_mod.parse_labeled_samples(
                        text, "dragonfly_tpu_peer_piece_bytes_total",
                        "locality").values())

                # Kill gate: >=50% of the pod's bytes landed — and the
                # broadcast still in flight.
                target = 2 * len(E2E_CONTENT)
                deadline = asyncio.get_running_loop().time() + 180
                while True:
                    assert asyncio.get_running_loop().time() < deadline, \
                        "kill gate never opened"
                    total = 0
                    for n in pod_names:
                        try:
                            total += piece_bytes(await scrape(dmports[n]))
                        except Exception:
                            pass
                    if total >= target:
                        break
                    await asyncio.sleep(0.05)
                assert any(p.poll() is None for p in pulls.values()), \
                    "broadcast finished before the kill gate opened"
                sched_procs[owner_key].send_signal(signal.SIGKILL)
                sched_procs[owner_key].wait(timeout=10)

                # Every host completes byte-identical via the failover
                # member.
                for n in pod_names:
                    rc = await asyncio.to_thread(pulls[n].wait, 240)
                    assert rc == 0, (n,
                                     open(outs[n] + ".log").read()[-3000:])
                    got = hashlib.sha256(
                        open(outs[n], "rb").read()).hexdigest()
                    assert got == sha, n

                for n in pod_names:
                    text = await scrape(dmports[n])
                    # Zero re-downloads of landed pieces: per-locality
                    # byte accounting sums to EXACTLY one content copy.
                    assert piece_bytes(text) == len(E2E_CONTENT), (
                        n, piece_bytes(text), len(E2E_CONTENT))
                    # No pod host fell back to origin: the failover
                    # member adopted the task (back-source only rides an
                    # exhausted RECONNECT_BUDGET, which a live survivor
                    # never exhausts).
                    for line in text.splitlines():
                        if line.startswith(
                                "dragonfly_tpu_peer_back_source_total"):
                            assert float(line.split()[-1]) == 0.0, (n, line)
                # The recovery machinery actually fired somewhere.
                reconnects = 0
                failovers = 0
                for n in pod_names:
                    text = await scrape(dmports[n])
                    rc = metrics_mod.parse_labeled_samples(
                        text, "dragonfly_tpu_peer_announce_reconnects_total",
                        "result")
                    reconnects += rc.get("ok", 0) + rc.get("rehomed", 0)
                    fo = metrics_mod.parse_labeled_samples(
                        text, "dragonfly_tpu_peer_scheduler_failover_total",
                        "result")
                    failovers += fo.get("failover", 0) + fo.get("owner", 0)
                assert reconnects >= 1, "no announce recovery fired"
                assert failovers >= 1
                # The survivor rebuilt peers from resume registrations.
                stext = await scrape(mports[survivor_key])
                rebuilt = metrics_mod.parse_labeled_samples(
                    stext, "dragonfly_tpu_scheduler_state_rebuilt_peers_total",
                    "source")
                assert rebuilt.get("reregister", 0) >= 1, rebuilt
            finally:
                podlens_e2e.E2E_CONTENT = orig_content
                for p in procs:
                    if p.poll() is None:
                        p.send_signal(signal.SIGTERM)
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                await runner.cleanup()

        run_async(run(), timeout=420)


# --------------------------------------------------------------------- #
# Wire schema
# --------------------------------------------------------------------- #

class TestWire:
    def test_register_resume_schema(self):
        from dragonfly2_tpu.proto import wire

        wire.validate_stream_msg("Scheduler.AnnouncePeer", {
            "type": "register", "resume": {
                "piece_nums": [0, 1, 5], "content_length": 8,
                "piece_size": 4, "total_piece_count": 2,
                "prefix_digest": "sha256:ab", "pod_broadcast": True,
                "stripe": {"slice_size": 4, "slice_rank": 1}}})
        wire.validate_stream_msg("Scheduler.AnnouncePeer",
                                 {"type": "register"})
        with pytest.raises(wire.SchemaError, match="resume"):
            wire.validate_stream_msg("Scheduler.AnnouncePeer", {
                "type": "register", "resume": "nope"})
        with pytest.raises(wire.SchemaError, match="piece_nums"):
            wire.validate_stream_msg("Scheduler.AnnouncePeer", {
                "type": "register",
                "resume": {"piece_nums": ["a"]}})
