"""Dynconfig + announcer wiring tests: generic puller semantics, scheduler
registration with the manager, seed-peer pre-registration, daemon scheduler
resolution via manager. Mirrors reference internal/dynconfig tests and the
announcer wiring in scheduler/scheduler.go."""

from __future__ import annotations

import asyncio

import pytest

from dragonfly2_tpu.manager.config import ManagerConfig
from dragonfly2_tpu.manager.server import ManagerServer
from dragonfly2_tpu.pkg.dynconfig import Dynconfig
from dragonfly2_tpu.scheduler.config import SchedulerConfig, SchedulerServerConfig
from dragonfly2_tpu.scheduler.server import SchedulerServer


# -- generic puller ---------------------------------------------------------

def test_dynconfig_observer_and_cache(tmp_path, run_async):
    run_async(_dynconfig_observer_and_cache(tmp_path))


async def _dynconfig_observer_and_cache(tmp_path):
    calls = {"n": 0}
    fail = {"on": False}

    async def fetch():
        if fail["on"]:
            raise RuntimeError("manager down")
        calls["n"] += 1
        return {"v": calls["n"]}

    seen = []
    dc = Dynconfig("t", fetch, cache_dir=str(tmp_path))
    dc.register(seen.append)
    assert (await dc.get()) == {"v": 1}
    assert seen == [{"v": 1}]
    await dc.refresh()
    assert seen == [{"v": 1}, {"v": 2}]

    # Failure keeps last data; a fresh instance falls back to the disk cache.
    fail["on"] = True
    assert await dc.refresh()
    assert (await dc.get()) == {"v": 2}
    dc2 = Dynconfig("t", fetch, cache_dir=str(tmp_path))
    assert await dc2.refresh()           # fetch fails -> disk cache
    assert (await dc2.get()) == {"v": 2}


def test_dynconfig_unchanged_data_no_notify(run_async):
    async def fetch():
        return {"same": True}

    async def body():
        seen = []
        dc = Dynconfig("u", fetch)
        dc.register(seen.append)
        await dc.refresh()
        await dc.refresh()
        assert len(seen) == 1

    run_async(body())


# -- scheduler <-> manager --------------------------------------------------

def test_scheduler_registers_and_pulls_seed_peers(run_async):
    run_async(_scheduler_registers_and_pulls_seed_peers())


async def _scheduler_registers_and_pulls_seed_peers():
    manager = ManagerServer(ManagerConfig())
    await manager.start()
    # A seed peer registered only in the manager (it has not announced to the
    # scheduler yet) must still be visible as a seed host after dynconfig.
    manager.service.update_seed_peer({
        "hostname": "seed-a", "ip": "127.0.0.1", "port": 60991,
        "download_port": 60992})

    cfg = SchedulerConfig(server=SchedulerServerConfig(port=0),
                          manager_addr=f"127.0.0.1:{manager.grpc_port()}")
    sched = SchedulerServer(cfg)
    try:
        await sched.start()
        assert sched.announcer.registered["state"] == "active"
        # Seed pre-registered into the host manager via the dynconfig observer.
        seeds = [h for h in sched.service.hosts.all() if h.is_seed()]
        assert len(seeds) == 1 and seeds[0].ip == "127.0.0.1"
        assert seeds[0].port == 60991 and seeds[0].upload_port == 60992
        # And the manager now lists the scheduler as active for daemons.
        listed = manager.service.list_schedulers({"hostname": "w", "ip": "10.0.0.2"})
        assert any(s["port"] == sched.port() for s in listed)
    finally:
        await sched.stop()
        await manager.stop()


# -- daemon <-> manager -----------------------------------------------------

def test_daemon_resolves_schedulers_from_manager(tmp_path, run_async):
    run_async(_daemon_resolves(tmp_path))


async def _daemon_resolves(tmp_path):
    from dragonfly2_tpu.daemon.config import DaemonConfig
    from dragonfly2_tpu.daemon.daemon import Daemon

    manager = ManagerServer(ManagerConfig())
    await manager.start()
    cfg = SchedulerConfig(server=SchedulerServerConfig(port=0),
                          manager_addr=f"127.0.0.1:{manager.grpc_port()}")
    sched = SchedulerServer(cfg)
    await sched.start()

    dcfg = DaemonConfig()
    dcfg.work_home = str(tmp_path / "dfhome")
    dcfg.__post_init__()
    dcfg.host.ip = "127.0.0.1"
    dcfg.manager_addr = f"127.0.0.1:{manager.grpc_port()}"
    daemon = Daemon(dcfg)
    try:
        await daemon.start()
        # No static scheduler addrs; the manager supplied the active one.
        assert daemon.scheduler_client is not None
        assert f"127.0.0.1:{sched.port()}" in daemon.scheduler_client._ring.members()
        # The daemon announced itself to that scheduler.
        await asyncio.sleep(0.1)
        assert any(not h.is_seed() for h in sched.service.hosts.all())
    finally:
        await daemon.stop()
        await sched.stop()
        await manager.stop()


def test_unary_failover_is_idempotent_gated(run_async):
    """State-bearing unary calls must NOT fail over to a ring member that
    lacks the task's state (its authoritative-looking answer would replace
    a retryable connection error); idempotent methods may (advisor r3)."""
    from dragonfly2_tpu.daemon.schedulerclient import SchedulerClient
    from dragonfly2_tpu.pkg.errors import Code, DfError

    cli = SchedulerClient(["10.0.0.1:1", "10.0.0.2:1"])
    owner = cli._ring.pick_n("t1", 2)
    calls = []

    class _Stub:
        def __init__(self, addr):
            self.addr = addr

        async def call(self, method, body, timeout=None):
            calls.append(self.addr)
            if self.addr == owner[0]:
                raise DfError(Code.ClientConnectionError, "down")
            return {"ok": True, "from": self.addr}

    cli._client_for_addr = lambda addr: _Stub(addr)

    # Default (state-bearing): owner down -> retryable error, no failover.
    with pytest.raises(DfError) as ei:
        run_async(cli.unary("t1", "Scheduler.M", {}))
    assert ei.value.code == Code.ClientConnectionError
    assert calls == [owner[0]]

    # Idempotent: fails over clockwise to the next member.
    calls.clear()
    out = run_async(cli.unary("t1", "Scheduler.M", {}, idempotent=True))
    assert out["from"] == owner[1]
    assert calls == [owner[0], owner[1]]
