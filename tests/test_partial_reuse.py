"""Partial/ranged task reuse + prefetch.

Reference: client/daemon/peer/peertask_reuse.go:234 (ranged reuse off
completed AND partial stores via storage FindPartialCompletedTask :564)
and peertask_manager.go:288 (prefetch: a ranged miss starts a background
whole-task download). Round 1 shipped the storage half with no caller.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import random

from aiohttp import web

from dragonfly2_tpu.daemon.peer.task_manager import (
    FileTaskRequest,
    StreamTaskRequest,
)
from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.proto.common import UrlMeta

from tests.test_stream_proxy import make_task_manager

CONTENT = bytes(random.Random(23).randbytes(10 * 1024 * 1024))


async def start_origin():
    stats = {"gets": 0, "bytes": 0}

    async def blob(request: web.Request) -> web.Response:
        stats["gets"] += 1
        rng = request.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(CONTENT))
            body = CONTENT[r.start:r.start + r.length]
            stats["bytes"] += len(body)
            return web.Response(
                status=206, body=body,
                headers={"Content-Range":
                         f"bytes {r.start}-{r.start + r.length - 1}/{len(CONTENT)}",
                         "Accept-Ranges": "bytes"})
        stats["bytes"] += len(CONTENT)
        return web.Response(body=CONTENT, headers={"Accept-Ranges": "bytes"})

    app = web.Application()
    app.router.add_get("/blob", blob)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1], stats


async def _file_get(tm, url, out, range_header=""):
    # Mirror rpcserver.py: meta.range (task identity) + parsed req.range
    # (ranged back-source driver).
    req = FileTaskRequest(url=url, output=out,
                          meta=UrlMeta(range=range_header),
                          range=Range.parse_http(range_header))
    last = None
    async for p in tm.start_file_task(req):
        last = p
    assert last is not None and last.state == "done", last
    return last


def test_ranged_file_reuses_completed_parent(run_async, tmp_path):
    """Download whole file, then a ranged request: byte-exact slice, zero
    origin traffic, flagged from_reuse."""
    async def run():
        runner, port, stats = await start_origin()
        tm = make_task_manager(tmp_path)
        url = f"http://127.0.0.1:{port}/blob"
        try:
            await _file_get(tm, url, str(tmp_path / "full.bin"))
            before = stats["gets"]
            p = await _file_get(tm, url, str(tmp_path / "slice.bin"),
                                range_header="bytes=100000-299999")
            assert p.from_reuse
            assert stats["gets"] == before
            assert (tmp_path / "slice.bin").read_bytes() == CONTENT[100000:300000]
        finally:
            tm.storage.close()
            await runner.cleanup()

    run_async(run())


def test_overlapping_ranges_second_hits_partial_parent(run_async, tmp_path):
    """With prefetch ON: first ranged get misses (downloads its delta +
    starts the background whole task); once the prefetch finishes, a second
    overlapping range is served locally with no new origin range GET."""
    async def run():
        runner, port, stats = await start_origin()
        tm = make_task_manager(tmp_path)
        tm.prefetch = True
        url = f"http://127.0.0.1:{port}/blob"
        try:
            p1 = await _file_get(tm, url, str(tmp_path / "r1.bin"),
                                 range_header="bytes=0-99999")
            assert not p1.from_reuse
            assert (tmp_path / "r1.bin").read_bytes() == CONTENT[:100000]

            # The prefetch task is running in the background; wait for it.
            parent_id = FileTaskRequest(
                url=url, output="", meta=UrlMeta()).task_id()
            for _ in range(200):
                store = tm.storage.find_completed_task(parent_id)
                if store is not None:
                    break
                await asyncio.sleep(0.05)
            assert tm.storage.find_completed_task(parent_id) is not None, \
                "prefetch never completed"

            before = stats["gets"]
            p2 = await _file_get(tm, url, str(tmp_path / "r2.bin"),
                                 range_header="bytes=50000-199999")
            assert p2.from_reuse
            assert stats["gets"] == before
            assert (tmp_path / "r2.bin").read_bytes() == CONTENT[50000:200000]
        finally:
            tm.storage.close()
            await runner.cleanup()

    run_async(run())


def test_ranged_stream_served_from_partial_store(run_async, tmp_path):
    """A ranged stream request against a task whose covering pieces are on
    disk (but task incomplete) is served off the store, not re-downloaded."""
    async def run():
        runner, port, stats = await start_origin()
        tm = make_task_manager(tmp_path)
        url = f"http://127.0.0.1:{port}/blob"
        try:
            # Build a partial store by hand: whole-content task id with
            # only the first 3 pieces written.
            req = StreamTaskRequest(url=url)
            task_id = req.task_id()
            from dragonfly2_tpu.storage.manager import TaskStoreMetadata

            store = tm.storage.register_task(TaskStoreMetadata(
                task_id=task_id, peer_id="p", url=url))
            piece_size = 1 << 20
            store.update_task(content_length=len(CONTENT),
                              piece_size=piece_size,
                              total_piece_count=10)
            for n in range(3):
                store.write_piece(
                    n, CONTENT[n * piece_size:(n + 1) * piece_size])

            before = stats["gets"]
            attrs, body = await tm.start_stream_task(StreamTaskRequest(
                url=url, range=Range(100, 2 * piece_size)))
            got = b"".join([bytes(c) async for c in body])
            assert got == CONTENT[100:100 + 2 * piece_size]
            assert attrs["from_reuse"]
            assert stats["gets"] == before  # nothing fetched

            # A range crossing missing pieces falls through to download.
            attrs2, body2 = await tm.start_stream_task(StreamTaskRequest(
                url=url, range=Range(2 * piece_size, 2 * piece_size)))
            got2 = b"".join([bytes(c) async for c in body2])
            assert got2 == CONTENT[2 * piece_size:4 * piece_size]
            assert not attrs2["from_reuse"]
            assert stats["gets"] > before
        finally:
            tm.storage.close()
            await runner.cleanup()

    run_async(run())
