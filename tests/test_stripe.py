"""Striped slice broadcast: planner, dispatcher wanted-set, scheduler
handouts/reshuffles, synchronizer keep-alive, and the 2-slice e2e.

The tentpole invariants:
  - the stripe plan is a pure function of (slice membership, identity):
    same inputs on every host -> disjoint, exactly-covering stripes;
  - a striped dispatcher never DCN-assigns a non-stripe piece (wanted-set
    semantics), and reshuffles release cleanly when a slice peer dies;
  - the scheduler hands stripes out on registration and pushes reshuffles
    on membership change, with the lone-host unstriped fallback;
  - an idle sync stream is NOT a dead parent (keep-alive satellite).
"""

from __future__ import annotations

import asyncio
import hashlib
import random

import pytest

from dragonfly2_tpu.daemon.peer.piece_dispatcher import PieceDispatcher
from dragonfly2_tpu.scheduler.config import SchedulerConfig
from dragonfly2_tpu.scheduler.scheduling import stripe as stripe_mod
from dragonfly2_tpu.scheduler.service import SchedulerService

N_PIECES = 10
PIECE_SIZE = 1 << 20


# --------------------------------------------------------------------- #
# Plan determinism
# --------------------------------------------------------------------- #

class TestStripePlan:
    def test_deterministic_and_order_insensitive(self):
        members = [(1, "hb", "pb"), (0, "ha", "pa"), (2, "hc", "pc")]
        plans = [stripe_mod.plan_stripe(list(perm), "pb")
                 for perm in (members, members[::-1],
                              [members[2], members[0], members[1]])]
        assert plans[0] == plans[1] == plans[2]
        assert plans[0]["slice_size"] == 3
        assert plans[0]["members"] == ["pa", "pb", "pc"]
        assert plans[0]["slice_rank"] == 1

    def test_disjoint_exact_cover(self):
        # Every piece is owned by exactly one member's stripe.
        members = [(i % 4, f"h{i}", f"p{i}") for i in range(7)]
        plans = {m[2]: stripe_mod.plan_stripe(members, m[2])
                 for m in members}
        sizes = {p["slice_size"] for p in plans.values()}
        assert sizes == {7}
        ranks = sorted(p["slice_rank"] for p in plans.values())
        assert ranks == list(range(7))
        for piece in range(199):
            owners = [pid for pid, p in plans.items()
                      if stripe_mod.in_stripe(piece, p["slice_size"],
                                              p["slice_rank"])]
            assert len(owners) == 1, (piece, owners)

    def test_stripe_piece_counts_sum(self):
        for total in (0, 1, 5, 16, 17):
            counts = [stripe_mod.stripe_piece_count(total, 5, r)
                      for r in range(5)]
            assert sum(counts) == total
            assert max(counts) - min(counts) <= 1

    def test_lone_host_and_unknown_peer(self):
        assert stripe_mod.plan_stripe([(0, "h", "p")], "p") is None
        assert stripe_mod.plan_stripe(
            [(0, "h", "p"), (1, "i", "q")], "zz") is None

    def test_duplicate_peer_id_collapses(self):
        plan = stripe_mod.plan_stripe(
            [(0, "h", "p"), (5, "other", "p"), (1, "i", "q")], "q")
        assert plan["slice_size"] == 2


# --------------------------------------------------------------------- #
# Dispatcher wanted-set semantics
# --------------------------------------------------------------------- #

def _dispatcher(total=8) -> PieceDispatcher:
    d = PieceDispatcher()
    d.total_piece_count = total
    d.piece_size = PIECE_SIZE
    d.content_length = total * PIECE_SIZE
    return d


class TestDispatcherStripe:
    def test_non_stripe_pieces_never_dcn_assigned(self, monkeypatch):
        monkeypatch.setattr(random, "random", lambda: 1.0)  # no explore
        d = _dispatcher(8)
        cross = d.upsert_parent("cross", "10.0.0.1", 80, tpu_slice="other")
        cross.pieces.update(range(8))
        d.set_stripe(4, 1)
        got = []
        while (a := d.try_get()) is not None:
            assert a.parent is cross
            got.append(a.piece_num)
        assert got == [1, 5]          # rank 1 of 4: pieces 1 and 5 only
        assert not d.has_assignable()

        # A mate advertising non-stripe pieces makes them assignable —
        # intra only.
        mate = d.upsert_parent("mate", "10.0.0.2", 81, same_slice=True,
                               tpu_slice="mine")
        d.on_parent_pieces("mate", [0, 2, 3])
        got2 = []
        while (a := d.try_get()) is not None:
            assert a.parent is mate
            got2.append(a.piece_num)
        assert got2 == [0, 2, 3]

    def test_stripe_pieces_prefer_intra_holder(self, monkeypatch):
        monkeypatch.setattr(random, "random", lambda: 1.0)
        d = _dispatcher(4)
        cross = d.upsert_parent("cross", "10.0.0.1", 80, tpu_slice="other")
        cross.pieces.update(range(4))
        mate = d.upsert_parent("mate", "10.0.0.2", 81, same_slice=True)
        mate.pieces.add(0)
        d.set_stripe(2, 0)
        a = d.try_get()
        # Piece 0 is in OUR stripe but a mate already has it: don't
        # re-cross the DCN for it.
        assert a.piece_num == 0 and a.parent is mate
        b = d.try_get()
        assert b.piece_num == 2 and b.parent is cross

    def test_reshuffle_releases_cleanly(self, monkeypatch):
        """A slice peer dies -> S shrinks -> pieces the dead mate owned
        become DCN-assignable under the new plan; pieces still owned by
        live mates stay off the DCN."""
        monkeypatch.setattr(random, "random", lambda: 1.0)
        d = _dispatcher(8)
        cross = d.upsert_parent("cross", "10.0.0.1", 80, tpu_slice="other")
        cross.pieces.update(range(8))
        d.set_stripe(4, 0)
        while d.try_get() is not None:
            pass                      # drain our stripe: 0, 4
        assert not d.has_assignable()
        d.set_stripe(2, 0)            # two mates died: reshuffle to S=2
        got = []
        while (a := d.try_get()) is not None:
            got.append(a.piece_num)
        assert got == [2, 6]          # newly ours under S=2 (evens)
        assert not d.has_assignable()  # odds belong to the survivor mate
        d.clear_stripe()              # lone-host fallback: everything DCN
        got2 = []
        while (a := d.try_get()) is not None:
            got2.append(a.piece_num)
        assert got2 == [1, 3, 5, 7]

    def test_extend_run_stops_at_stripe_boundary(self, monkeypatch):
        monkeypatch.setattr(
            "dragonfly2_tpu.storage.local_store._native", lambda: object())
        monkeypatch.setattr(random, "random", lambda: 1.0)
        d = _dispatcher(8)
        cross = d.upsert_parent("cross", "10.0.0.1", 80, tpu_slice="other")
        cross.pieces.update(range(8))
        mate = d.upsert_parent("mate", "10.0.0.2", 81, same_slice=True)
        mate.pieces.update(range(8))
        d.set_stripe(2, 0)
        a = d.try_get()
        assert a.piece_num == 0
        if a.parent is cross:
            # A cross span must not spill into the mate's stripe.
            run = d.extend_run(a, 8)
            assert [r.piece_num for r in run] == [0]
        else:
            # Intra spans may cover both stripes.
            run = d.extend_run(a, 8)
            assert len(run) > 1
        for r in run[1:]:
            d.release_assignment(r)

    def test_near_tie_breaks_on_inflight(self, monkeypatch):
        monkeypatch.setattr(random, "random", lambda: 1.0)
        d = _dispatcher(8)
        a = d.upsert_parent("a", "10.0.0.1", 80)
        b = d.upsert_parent("b", "10.0.0.2", 81)
        a.pieces.update(range(8))
        b.pieces.update(range(8))
        used = []
        for _ in range(6):
            asg = d.try_get()
            used.append(asg.parent.peer_id)
        # Equal cost EWMAs: load spreads instead of herding onto one.
        assert used.count("a") == 3 and used.count("b") == 3, used

    def test_clear_tie_still_prefers_fast_parent(self, monkeypatch):
        monkeypatch.setattr(random, "random", lambda: 1.0)
        d = _dispatcher(8)
        fast = d.upsert_parent("fast", "10.0.0.1", 80)
        slow = d.upsert_parent("slow", "10.0.0.2", 81)
        fast.pieces.update(range(8))
        slow.pieces.update(range(8))
        fast.cost_ewma_ms = 10.0
        slow.cost_ewma_ms = 200.0     # far outside the near-tie band
        for _ in range(4):
            assert d.try_get().parent is fast


# --------------------------------------------------------------------- #
# Scheduler: handout, membership-change push, death reshuffle
# --------------------------------------------------------------------- #

class FakeStream:
    def __init__(self, open_body):
        self.open_body = open_body
        self.to_sched: asyncio.Queue = asyncio.Queue()
        self.to_peer: asyncio.Queue = asyncio.Queue()

    async def send(self, body):
        await self.to_peer.put(body)

    async def recv(self, timeout=None):
        return await self.to_sched.get()


async def _serve(svc, stream):
    try:
        await svc.announce_peer(stream, None)
    except Exception:
        pass


def _body(peer_id, host_id, *, slice_name="", worker=-1, broadcast=False,
          port=8000, upload_port=9000):
    b = {
        "host": {"id": host_id, "hostname": host_id, "ip": "10.0.0.1",
                 "port": port, "upload_port": upload_port,
                 "tpu_slice": slice_name, "tpu_worker_index": worker},
        "peer_id": peer_id,
        "task_id": "stripe-task",
        "url": "http://origin/ckpt",
    }
    if broadcast:
        b["pod_broadcast"] = True
    return b


async def _finish_source_peer(svc) -> FakeStream:
    """A plain sourcing peer that completes, so broadcast registrants get
    real candidate parents."""
    stream = FakeStream(_body("peer-src", "host-src"))
    asyncio.ensure_future(_serve(svc, stream))
    await stream.to_sched.put({"type": "register"})
    msg = await asyncio.wait_for(stream.to_peer.get(), 10)
    assert msg["type"] == "need_back_source", msg
    await stream.to_sched.put({
        "type": "download_started", "content_length": N_PIECES * PIECE_SIZE,
        "piece_size": PIECE_SIZE, "total_piece_count": N_PIECES})
    for n in range(N_PIECES):
        await stream.to_sched.put({
            "type": "piece_finished",
            "piece": {"piece_num": n, "range_start": n * PIECE_SIZE,
                      "range_size": PIECE_SIZE, "digest": "",
                      "download_cost_ms": 2, "dst_peer_id": ""}})
    await stream.to_sched.put({
        "type": "download_finished", "content_length": N_PIECES * PIECE_SIZE,
        "piece_size": PIECE_SIZE, "total_piece_count": N_PIECES})
    return stream


class TestSchedulerStripe:
    def _svc(self):
        cfg = SchedulerConfig()
        cfg.scheduling.retry_interval = 0.02
        cfg.scheduling.no_source_patience = 0.5
        cfg.seed_peer_enabled = False
        return SchedulerService(cfg)

    def test_handout_reshuffle_and_lone_fallback(self, run_async):
        async def body():
            svc = self._svc()
            await _finish_source_peer(svc)

            a = FakeStream(_body("peer-a", "host-a", slice_name="slice-0",
                                 worker=0, broadcast=True, port=8001,
                                 upload_port=9001))
            serve_a = asyncio.ensure_future(_serve(svc, a))
            await a.to_sched.put({"type": "register"})
            msg_a = await asyncio.wait_for(a.to_peer.get(), 10)
            assert msg_a["type"] == "normal_task"
            assert "stripe" not in msg_a     # lone host: unstriped

            b = FakeStream(_body("peer-b", "host-b", slice_name="slice-0",
                                 worker=1, broadcast=True, port=8002,
                                 upload_port=9002))
            serve_b = asyncio.ensure_future(_serve(svc, b))
            await b.to_sched.put({"type": "register"})
            msg_b = await asyncio.wait_for(b.to_peer.get(), 10)
            assert msg_b["type"] == "normal_task"
            stripe_b = msg_b["stripe"]
            assert stripe_b["slice_size"] == 2
            assert stripe_b["slice_rank"] == 1     # worker 1 sorts second
            assert stripe_b["members"] == ["peer-a", "peer-b"]
            assert [m["id"] for m in stripe_b["mates"]] == ["peer-a"]

            # Membership-change push: peer-a gets the reshuffled plan.
            push_a = await asyncio.wait_for(a.to_peer.get(), 10)
            assert push_a["type"] == "normal_task"
            stripe_a = push_a["stripe"]
            assert stripe_a["slice_size"] == 2 and stripe_a["slice_rank"] == 0
            assert [m["id"] for m in stripe_a["mates"]] == ["peer-b"]
            # Disjoint exact cover across the two plans.
            for piece in range(50):
                owners = sum(stripe_mod.in_stripe(piece, 2, p["slice_rank"])
                             for p in (stripe_a, stripe_b))
                assert owners == 1

            # Death reshuffle: b's stream drops -> a falls back unstriped.
            await b.to_sched.put(None)
            await asyncio.wait_for(serve_b, 10)
            push_a2 = await asyncio.wait_for(a.to_peer.get(), 10)
            assert push_a2["type"] == "normal_task"
            assert "stripe" not in push_a2   # lone survivor: no stripe
            await a.to_sched.put(None)
            await asyncio.wait_for(serve_a, 10)

        run_async(body(), timeout=30)

    def test_plain_peers_never_striped(self, run_async):
        async def body():
            svc = self._svc()
            await _finish_source_peer(svc)
            streams = []
            for i in range(3):
                s = FakeStream(_body(f"peer-{i}", f"host-{i}",
                                     slice_name="slice-0", worker=i,
                                     port=8100 + i, upload_port=9100 + i))
                streams.append(s)
                asyncio.ensure_future(_serve(svc, s))
                await s.to_sched.put({"type": "register"})
                msg = await asyncio.wait_for(s.to_peer.get(), 10)
                assert msg["type"] == "normal_task"
                assert "stripe" not in msg   # no pod_broadcast, no auto
            for s in streams:
                await s.to_sched.put(None)

        run_async(body(), timeout=30)

    def test_auto_stripe_threshold(self, run_async):
        async def body():
            svc = self._svc()
            svc.config.scheduling.stripe_min_slice_peers = 2
            await _finish_source_peer(svc)
            s1 = FakeStream(_body("peer-1", "host-1", slice_name="slice-0",
                                  worker=0, port=8201, upload_port=9201))
            asyncio.ensure_future(_serve(svc, s1))
            await s1.to_sched.put({"type": "register"})
            m1 = await asyncio.wait_for(s1.to_peer.get(), 10)
            assert "stripe" not in m1
            s2 = FakeStream(_body("peer-2", "host-2", slice_name="slice-0",
                                  worker=1, port=8202, upload_port=9202))
            asyncio.ensure_future(_serve(svc, s2))
            await s2.to_sched.put({"type": "register"})
            m2 = await asyncio.wait_for(s2.to_peer.get(), 10)
            # Auto mode: plain peers stripe once the slice holds >= the
            # configured threshold.
            assert m2["stripe"]["slice_size"] == 2
            await s1.to_sched.put(None)
            await s2.to_sched.put(None)

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# Synchronizer keep-alive (satellite)
# --------------------------------------------------------------------- #

class TestSynchronizerKeepalive:
    def test_idle_stream_is_not_a_dead_parent(self, run_async):
        """A parent that announced everything and went quiet must stay an
        active parent; the child sends {interested: true} keep-alives."""
        from dragonfly2_tpu.daemon.peer.synchronizer import (
            PieceTaskSynchronizer,
        )
        from dragonfly2_tpu.pkg.types import NetAddr
        from dragonfly2_tpu.rpc import Server

        async def body():
            received = []
            hold = asyncio.Event()

            async def handler(stream, ctx):
                await stream.send({"pieces": [0, 1], "total_piece_count": 4,
                                   "content_length": 4 * PIECE_SIZE,
                                   "piece_size": PIECE_SIZE, "done": False,
                                   "digests": {}})
                while True:
                    msg = await stream.recv()
                    if msg is None:
                        return
                    received.append(msg)
                    if len(received) >= 2:
                        hold.set()

            server = Server("test.parent")
            server.register_stream("Peer.SyncPieceTasks", handler)
            await server.serve(NetAddr.tcp("127.0.0.1", 0))
            port = server.port()
            try:
                dispatcher = PieceDispatcher()
                sync = PieceTaskSynchronizer("t-keepalive", "child-peer",
                                             dispatcher)
                sync.KEEPALIVE_INTERVAL = 0.1
                dispatcher.upsert_parent("parent-1", "127.0.0.1", 9000)
                sync._tasks["parent-1"] = asyncio.ensure_future(
                    sync._sync_one("parent-1", "127.0.0.1", port))
                # Well past several keep-alive slices (old code: one idle
                # 60 s recv timeout dropped the parent).
                await asyncio.wait_for(hold.wait(), 10)
                p = dispatcher.parents["parent-1"]
                assert not p.blocked
                assert p.pieces == {0, 1}
                assert all(m.get("interested") for m in received)
                await sync.close()
            finally:
                await server.close()

        run_async(body(), timeout=30)

    def test_blocked_parent_stops_keepalives(self, run_async):
        from dragonfly2_tpu.daemon.peer.synchronizer import (
            PieceTaskSynchronizer,
        )
        from dragonfly2_tpu.pkg.types import NetAddr
        from dragonfly2_tpu.rpc import Server

        async def body():
            async def handler(stream, ctx):
                await stream.send({"pieces": [0], "total_piece_count": 2,
                                   "content_length": 2 * PIECE_SIZE,
                                   "piece_size": PIECE_SIZE, "done": False,
                                   "digests": {}})
                while await stream.recv() is not None:
                    pass

            server = Server("test.parent2")
            server.register_stream("Peer.SyncPieceTasks", handler)
            await server.serve(NetAddr.tcp("127.0.0.1", 0))
            try:
                dispatcher = PieceDispatcher()
                sync = PieceTaskSynchronizer("t-blocked", "child-peer",
                                             dispatcher)
                sync.KEEPALIVE_INTERVAL = 0.05
                p = dispatcher.upsert_parent("parent-1", "127.0.0.1", 9000)
                task = asyncio.ensure_future(
                    sync._sync_one("parent-1", "127.0.0.1", server.port()))
                sync._tasks["parent-1"] = task
                await asyncio.sleep(0.1)
                p.blocked = True        # dispatcher gave up on this parent
                await asyncio.wait_for(task, 10)  # stream exits on its own
                await sync.close()
            finally:
                await server.close()

        run_async(body(), timeout=30)


# --------------------------------------------------------------------- #
# Striped 2-slice x 4-host e2e (real in-process daemons)
# --------------------------------------------------------------------- #

@pytest.mark.slow
class TestStripedFanoutE2E:
    def test_two_slices_dcn_bytes_and_content(self, run_async, tmp_path):
        """Cold fan-out to 2 slices x 4 hosts with pod_broadcast: every
        host's bytes sha-verify, and each host's cross-slice (DCN) bytes
        land near file/S instead of the full file."""
        from tests.test_p2p_e2e import (
            daemon_config,
            start_origin,
            start_scheduler,
        )
        from dragonfly2_tpu.client import dfget as dfget_lib
        from dragonfly2_tpu.daemon.daemon import Daemon
        from dragonfly2_tpu.proto.common import UrlMeta

        content = bytes(random.Random(42).randbytes(24 * 1024 * 1024))
        sha = "sha256:" + hashlib.sha256(content).hexdigest()

        async def body():
            origin, oport, stats = await start_origin()
            # start_origin serves the fixed test blob; patch the route to
            # our content by overriding the handler state is overkill —
            # serve our own origin instead.
            await origin.cleanup()
            from aiohttp import web

            from dragonfly2_tpu.pkg.piece import Range

            async def blob(request):
                rng = request.headers.get("Range")
                if rng:
                    r = Range.parse_http(rng, len(content))
                    data = content[r.start:r.start + r.length]
                    return web.Response(status=206, body=data, headers={
                        "Content-Range":
                            f"bytes {r.start}-{r.start + r.length - 1}"
                            f"/{len(content)}",
                        "Accept-Ranges": "bytes"})
                return web.Response(body=content,
                                    headers={"Accept-Ranges": "bytes"})

            app = web.Application()
            app.router.add_get("/blob", blob)
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            oport = site._server.sockets[0].getsockname()[1]

            sched = await start_scheduler()
            url = f"http://127.0.0.1:{oport}/blob"
            daemons = []
            try:
                seed_cfg = daemon_config(tmp_path, "seed", sched.port(),
                                         seed=True)
                seed_cfg.host.tpu_slice = "slice-seed"
                seed = Daemon(seed_cfg)
                await seed.start()
                daemons.append(seed)
                peers = []
                for i in range(8):
                    cfg = daemon_config(tmp_path, f"peer{i}", sched.port())
                    cfg.host.tpu_slice = f"slice-{i // 4}"
                    cfg.host.tpu_worker_index = i % 4
                    # One assignment in flight per worker pass: stripe
                    # pushes land before a racing first registrant can
                    # reserve the whole piece space.
                    cfg.download.parent_concurrency = 2
                    d = Daemon(cfg)
                    await d.start()
                    daemons.append(d)
                    peers.append(d)

                async def pull(i: int):
                    return await dfget_lib.download(dfget_lib.DfgetConfig(
                        url=url, output=str(tmp_path / f"out{i}.bin"),
                        daemon_sock=peers[i].config.unix_sock,
                        meta=UrlMeta(digest=sha),
                        pod_broadcast=True,
                        allow_source_fallback=False, timeout=180.0))

                results = await asyncio.gather(*[pull(i) for i in range(8)])
                task_id = results[0]["task_id"]
                for i, r in enumerate(results):
                    assert r["state"] == "done", r
                    data = (tmp_path / f"out{i}.bin").read_bytes()
                    assert hashlib.sha256(data).hexdigest() == sha[7:], i

                piece_size = 4 << 20
                file_mb = len(content)
                crosses = []
                for i, d in enumerate(peers):
                    loc = d.task_manager.locality_bytes.get(task_id, {})
                    crosses.append(loc.get("cross", 0))
                    assert loc.get("unlabeled", 0) == 0, (i, loc)
                # Every host's DCN bill stays well under the full file:
                # file/S plus slack for pieces reserved before the stripe
                # push landed (registration race, span reservations).
                bound = file_mb / 4 + 3 * piece_size
                for i, c in enumerate(crosses):
                    assert c <= bound, (i, c, bound, crosses)
                # The slice actually exchanged pieces internally.
                total_intra = sum(
                    d.task_manager.locality_bytes[task_id].get("intra", 0)
                    for d in peers)
                assert total_intra > 0
                # Aggregate DCN stays near one copy per slice, far from
                # the unstriped 8x file.
                assert sum(crosses) <= 2 * file_mb + 8 * 3 * piece_size
            finally:
                for d in daemons:
                    await d.stop()
                await sched.stop()
                await runner.cleanup()

        run_async(body(), timeout=300)


# --------------------------------------------------------------------- #
# Sim bench wiring (fast: small deterministic run + its own checks)
# --------------------------------------------------------------------- #

class TestStripeSim:
    def test_paired_sim_bounds(self):
        import importlib

        bench = importlib.import_module("benchmarks.stripe_sim_bench")
        result = bench.run_paired(n_slices=2, hosts_per_slice=4,
                                  n_pieces=32, piece_size=1 << 20)
        bench.check(result)
        s = result["striped"]
        # Exact stripe accounting: every host DCN-pulls file/S.
        assert s["max_host_dcn_mb"] <= s["content_mb"] / 4 + s["piece_mb"]
        assert result["speedup"] >= 1.5

    def test_sim_deterministic(self):
        import importlib

        bench = importlib.import_module("benchmarks.stripe_sim_bench")
        a = bench.run_sim(n_slices=2, hosts_per_slice=2, n_pieces=16,
                          piece_size=1 << 20, striped=True)
        b = bench.run_sim(n_slices=2, hosts_per_slice=2, n_pieces=16,
                          piece_size=1 << 20, striped=True)
        assert a == b
