"""Deploy artifacts: the compose-free launcher boots the full topology.

Reference: deploy/docker-compose/docker-compose.yaml:51-93 (manager +
scheduler + seed + peers). The Dockerfile/compose files are validated by
shape here (can't run docker in CI); deploy/local_up.py is exercised for
real: full boot + a dfget through the fabric.
"""

from __future__ import annotations

import os
import subprocess
import sys

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_compose_topology_shape():
    doc = yaml.safe_load(open(os.path.join(REPO, "deploy/docker-compose.yaml")))
    services = doc["services"]
    assert set(services) == {"manager", "scheduler", "seed-peer", "peer1", "peer2"}
    assert services["scheduler"]["command"][0] == "scheduler"
    assert "--seed-peer" in services["seed-peer"]["command"]
    # Every service runs the one image with a role command.
    assert all(s["image"] == "dragonfly2-tpu" for s in services.values())


def test_local_up_smoke(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "deploy/local_up.py"),
         "--smoke", "--peers", "1", "--base-dir", str(tmp_path / "fabric")],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "smoke: dfget through the fabric OK" in proc.stdout
