"""Native HTTP engine (native/src/dfhttp.cc) and its data-plane seams.

The reference moves piece payloads over plain HTTP with fully native byte
handling (Go piece_downloader.go / piece_manager.go); our equivalent is the
C++ engine where bodies flow socket→crc32c→pwrite without entering Python.
These tests drive the ctypes surface directly against a live aiohttp origin,
then the two integration seams: PieceDownloader.download_piece_to_store
(parent pulls) and PieceManager._native_fetch_span (origin ingest).
"""

import asyncio
import os

import pytest
from aiohttp import web

from dragonfly2_tpu.pkg.piece import Range
from dragonfly2_tpu.storage.local_store import LocalTaskStore, TaskStoreMetadata, _native

nb = _native()
pytestmark = pytest.mark.skipif(nb is None, reason="native library unavailable")

T = asyncio.to_thread  # engine calls block; keep the test's loop free


async def _serve(routes) -> tuple[web.AppRunner, int]:
    app = web.Application()
    for path, handler in routes.items():
        app.router.add_get(path, handler)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, site._server.sockets[0].getsockname()[1]


def _ranged(content: bytes):
    async def handler(req: web.Request) -> web.Response:
        rng = req.headers.get("Range")
        if rng:
            r = Range.parse_http(rng, len(content))
            body = content[r.start:r.start + r.length]
            return web.Response(status=206, body=body, headers={
                "Accept-Ranges": "bytes",
                "Content-Range":
                    f"bytes {r.start}-{r.start + r.length - 1}/{len(content)}"})
        return web.Response(body=content, headers={"Accept-Ranges": "bytes"})
    return handler


def _head(port: int, path: str = "/blob", rng: str = "") -> bytes:
    lines = [f"GET {path} HTTP/1.1", f"Host: 127.0.0.1:{port}"]
    if rng:
        lines.append(f"Range: {rng}")
    lines += ["Accept-Encoding: identity", "Connection: keep-alive"]
    return ("\r\n".join(lines) + "\r\n\r\n").encode()


class TestEngine:
    def test_fetch_stream_reuse_and_status(self, run_async, tmp_path):
        async def body():
            content = os.urandom((5 << 20) + 777)
            runner, port = await _serve({
                "/blob": _ranged(content),
                "/gone": lambda r: web.Response(status=404, text="nope"),
            })
            fd = os.open(tmp_path / "out", os.O_RDWR | os.O_CREAT)
            try:
                h = await T(nb.http_connect, "127.0.0.1", port, 5000)
                # whole-body fetch lands bytes + crc in one call
                status, n, crc, keep = await T(
                    nb.http_fetch_to_file, h, _head(port), fd, 0, len(content))
                assert (status, n) == (200, len(content)) and keep
                assert os.pread(fd, len(content), 0) == content
                assert crc == nb.crc32c(content)
                # ranged fetch reuses the same connection
                status, n, crc, _ = await T(
                    nb.http_fetch_to_file, h,
                    _head(port, rng="bytes=1000-2023"), fd, 0, 1024)
                assert (status, n) == (206, 1024)
                assert crc == nb.crc32c(content[1000:2024])
                # streaming: head once, then piece-sized reads
                status, clen, _ = await T(nb.http_start, h, _head(port))
                assert (status, clen) == (200, len(content))
                off, piece = 0, 1 << 20
                while off < clen:
                    take = min(piece, clen - off)
                    c = await T(nb.http_read_to_file, h, fd, off, take)
                    assert c == nb.crc32c(content[off:off + take])
                    off += take
                assert nb.http_reusable(h)
                # non-2xx drains the small body and keeps the connection
                status, n, _, _ = await T(
                    nb.http_fetch_to_file, h, _head(port, "/gone"), fd, 0, -1)
                assert (status, n) == (404, 0) and nb.http_reusable(h)
                nb.http_close(h)
            finally:
                os.close(fd)
                await runner.cleanup()

        run_async(body())

    def test_length_mismatch_and_chunked_rejected(self, run_async, tmp_path):
        async def body():
            content = os.urandom(1 << 20)

            async def chunked(req: web.Request) -> web.StreamResponse:
                resp = web.StreamResponse()  # no content-length → chunked
                await resp.prepare(req)
                await resp.write(content)
                return resp

            runner, port = await _serve({"/blob": _ranged(content),
                                         "/chunked": chunked})
            fd = os.open(tmp_path / "out", os.O_RDWR | os.O_CREAT)
            try:
                h = await T(nb.http_connect, "127.0.0.1", port, 5000)
                with pytest.raises(nb.NativeHttpError) as ei:
                    await T(nb.http_fetch_to_file, h, _head(port), fd, 0,
                            len(content) + 1)
                assert ei.value.code == nb.HTTP_E_LENMISMATCH
                nb.http_close(h)

                h = await T(nb.http_connect, "127.0.0.1", port, 5000)
                with pytest.raises(nb.NativeHttpError) as ei:
                    await T(nb.http_fetch_to_file, h, _head(port, "/chunked"),
                            fd, 0, -1)
                assert ei.value.code == nb.HTTP_E_UNSUPPORTED
                nb.http_close(h)
            finally:
                os.close(fd)
                await runner.cleanup()

        run_async(body())

    def test_stale_keepalive_detected(self, run_async, tmp_path):
        async def body():
            content = os.urandom(4096)
            runner, port = await _serve({"/blob": _ranged(content)})
            fd = os.open(tmp_path / "out", os.O_RDWR | os.O_CREAT)
            try:
                h = await T(nb.http_connect, "127.0.0.1", port, 5000)
                status, n, _, keep = await T(
                    nb.http_fetch_to_file, h, _head(port), fd, 0, len(content))
                assert status == 200 and keep and nb.http_reusable(h)
                # Server goes away: FIN arrives; the MSG_PEEK probe must
                # reject the handle instead of letting a request fail.
                await runner.cleanup()
                await asyncio.sleep(0.1)
                assert not nb.http_reusable(h)
                nb.http_close(h)
            finally:
                os.close(fd)

        run_async(body())


def _store(tmp_path, name: str, content_len: int, piece_size: int) -> LocalTaskStore:
    return LocalTaskStore.create(
        str(tmp_path / name),
        TaskStoreMetadata(task_id="t" * 16, peer_id=name,
                          content_length=content_len, piece_size=piece_size,
                          total_piece_count=-(-content_len // piece_size)))


class TestDownloadToStore:
    def test_parent_pull_lands_and_verifies(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_downloader import PieceDownloader

        async def body():
            ps = 1 << 20
            content = os.urandom(3 * ps + 123)
            src = _store(tmp_path, "src", len(content), ps)
            recs = [src.write_piece(n, content[n * ps:(n + 1) * ps])
                    for n in range(4)]

            async def piece(req: web.Request) -> web.Response:
                n = int(req.query["pieceNum"])
                return web.Response(body=src.read_piece(n))

            runner, port = await _serve(
                {"/download/{p}/{t}": piece})
            dst = _store(tmp_path, "dst", len(content), ps)
            dl = PieceDownloader()
            try:
                for n in range(4):
                    rec = await dl.download_piece_to_store(
                        "127.0.0.1", port, "t" * 16, n, dst,
                        expected_size=recs[n].size,
                        expected_digest=recs[n].digest)
                    assert rec is not None and rec.digest == recs[n].digest
                got = b"".join(dst.read_piece(n) for n in range(4))
                assert got == content
            finally:
                await dl.close()
                await runner.cleanup()

        run_async(body())

    def test_corrupt_parent_body_not_recorded(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_downloader import PieceDownloader
        from dragonfly2_tpu.pkg.errors import Code, DfError

        async def body():
            ps = 1 << 20
            content = os.urandom(ps)
            src = _store(tmp_path, "src", ps, ps)
            rec = src.write_piece(0, content)

            async def evil(req: web.Request) -> web.Response:
                return web.Response(body=os.urandom(ps))  # right size, bad bytes

            runner, port = await _serve({"/download/{p}/{t}": evil})
            dst = _store(tmp_path, "dst", ps, ps)
            dl = PieceDownloader()
            try:
                with pytest.raises(DfError) as ei:
                    await dl.download_piece_to_store(
                        "127.0.0.1", port, "t" * 16, 0, dst,
                        expected_size=ps, expected_digest=rec.digest)
                assert ei.value.code == Code.ClientPieceDownloadFail
                assert not dst.has_piece(0)  # bad bytes stay invisible
            finally:
                await dl.close()
                await runner.cleanup()

        run_async(body())

    def test_non_crc_digest_falls_back(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_downloader import PieceDownloader

        async def body():
            ps = 1 << 20
            dst = _store(tmp_path, "dst", ps, ps)
            dl = PieceDownloader()
            rec = await dl.download_piece_to_store(
                "127.0.0.1", 1, "t" * 16, 0, dst,
                expected_size=ps,
                expected_digest="sha256:" + "0" * 64)
            assert rec is None  # ineligible → caller takes the aiohttp path
            await dl.close()

        run_async(body())


class TestNativeSpan:
    def test_origin_span_records_pieces_in_order(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_manager import PieceManager
        from dragonfly2_tpu.pkg.ratelimit import Limiter
        from dragonfly2_tpu.source.clients.http import HTTPSourceClient
        from dragonfly2_tpu.source.client import Request as SourceRequest

        async def body():
            ps = 1 << 20
            content = os.urandom(2 * ps + 5)
            runner, port = await _serve({"/blob": _ranged(content)})
            store = _store(tmp_path, "dst", len(content), ps)
            pm = PieceManager()
            seen: list[int] = []

            async def on_piece(s, rec):
                seen.append(rec.num)

            try:
                ok = await pm._native_fetch_span(
                    store, HTTPSourceClient(),
                    SourceRequest(f"http://127.0.0.1:{port}/blob", {}),
                    0, 3, len(content), on_piece, Limiter(), ranged=False)
                assert ok and seen == [0, 1, 2]
                assert store.is_complete()
                got = b"".join(store.read_piece(n) for n in range(3))
                assert got == content
            finally:
                await runner.cleanup()

        run_async(body())

    def test_https_plan_ineligible(self):
        from dragonfly2_tpu.source.clients.http import HTTPSourceClient
        from dragonfly2_tpu.source.client import Request as SourceRequest

        c = HTTPSourceClient()
        assert c.native_fetch_plan(
            SourceRequest("https://secure.example/x", {})) is None
        # non-latin-1 header values must fall back, not raise
        assert c.native_fetch_plan(
            SourceRequest("http://h/x", {"X-Meta": "café…"})) is None
        # userinfo must not leak into Host
        plan = c.native_fetch_plan(
            SourceRequest("http://user:pw@origin:8080/f", {}))
        assert plan is not None
        host, port, head = plan
        assert b"Host: origin:8080\r\n" in head and b"user:pw" not in head


class TestP2PSpan:
    """PieceDownloader.download_span_to_store: one ranged GET coalescing a
    contiguous run of pieces, per-piece results streaming through the
    callback as they land (round-5 receive-path coalescing)."""

    @staticmethod
    def _assignments(recs, parent_port):
        from dragonfly2_tpu.daemon.peer.piece_dispatcher import (
            ParentInfo, PieceAssignment)

        parent = ParentInfo("p_src", "127.0.0.1", parent_port)
        return [PieceAssignment(r.num, parent, r.size, digest=r.digest)
                for r in recs]

    def test_span_streams_piece_results(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_downloader import PieceDownloader

        async def body():
            ps = 1 << 20
            content = os.urandom(3 * ps + 123)
            src = _store(tmp_path, "src", len(content), ps)
            recs = [src.write_piece(n, content[n * ps:(n + 1) * ps])
                    for n in range(4)]

            async def ranged(req: web.Request) -> web.Response:
                r = Range.parse_http(req.headers["Range"], len(content))
                return web.Response(status=206,
                                    body=content[r.start:r.start + r.length],
                                    headers={"Content-Range":
                                             f"bytes {r.start}-"
                                             f"{r.start + r.length - 1}"
                                             f"/{len(content)}"})

            runner, port = await _serve({"/download/{p}/{t}": ranged})
            dst = _store(tmp_path, "dst", len(content), ps)
            dl = PieceDownloader()
            seen: list[int] = []
            try:
                async def on_result(a, rec, err):
                    assert err is None and rec is not None
                    assert dst.has_piece(a.piece_num)  # already committed
                    seen.append(a.piece_num)

                handled = await dl.download_span_to_store(
                    "127.0.0.1", port, "t" * 16,
                    self._assignments(recs, port), dst, on_result=on_result)
                assert handled and seen == [0, 1, 2, 3]
                got = b"".join(dst.read_piece(n) for n in range(4))
                assert got == content
            finally:
                await dl.close()
                await runner.cleanup()

        run_async(body())

    def test_mid_span_corruption_fails_only_that_piece(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_downloader import PieceDownloader
        from dragonfly2_tpu.pkg.errors import Code

        async def body():
            ps = 1 << 20
            content = os.urandom(4 * ps)
            src = _store(tmp_path, "src", len(content), ps)
            recs = [src.write_piece(n, content[n * ps:(n + 1) * ps])
                    for n in range(4)]

            async def corrupting(req: web.Request) -> web.Response:
                r = Range.parse_http(req.headers["Range"], len(content))
                body_bytes = bytearray(content[r.start:r.start + r.length])
                # Flip a byte inside piece 2's window.
                body_bytes[2 * ps - r.start + 7] ^= 0xFF
                return web.Response(status=206, body=bytes(body_bytes),
                                    headers={"Content-Range":
                                             f"bytes {r.start}-"
                                             f"{r.start + r.length - 1}"
                                             f"/{len(content)}"})

            runner, port = await _serve({"/download/{p}/{t}": corrupting})
            dst = _store(tmp_path, "dst", len(content), ps)
            dl = PieceDownloader()
            outcomes: dict[int, object] = {}
            try:
                async def on_result(a, rec, err):
                    outcomes[a.piece_num] = err.code if err else "ok"

                handled = await dl.download_span_to_store(
                    "127.0.0.1", port, "t" * 16,
                    self._assignments(recs, port), dst, on_result=on_result)
                assert handled
                assert outcomes == {0: "ok", 1: "ok",
                                    2: Code.ClientPieceDownloadFail, 3: "ok"}
                assert not dst.has_piece(2)   # bad bytes stay invisible
                assert dst.has_piece(3)       # stream continued past the bad one
            finally:
                await dl.close()
                await runner.cleanup()

        run_async(body())

    def test_uncovered_span_fails_all_as_not_found(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_downloader import PieceDownloader
        from dragonfly2_tpu.pkg.errors import Code

        async def body():
            ps = 1 << 20
            src = _store(tmp_path, "src", 4 * ps, ps)
            recs = [src.write_piece(n, os.urandom(ps)) for n in range(4)]

            async def gone(req: web.Request) -> web.Response:
                return web.Response(status=416, text="range not covered")

            runner, port = await _serve({"/download/{p}/{t}": gone})
            dst = _store(tmp_path, "dst", 4 * ps, ps)
            dl = PieceDownloader()
            codes: list[object] = []
            try:
                async def on_result(a, rec, err):
                    codes.append(err.code)

                handled = await dl.download_span_to_store(
                    "127.0.0.1", port, "t" * 16,
                    self._assignments(recs, port), dst, on_result=on_result)
                assert handled
                assert codes == [Code.ClientPieceNotFound] * 4
            finally:
                await dl.close()
                await runner.cleanup()

        run_async(body())

    def test_malformed_crc_digest_is_coded_not_leaked(self, run_async, tmp_path):
        """A parent-advertised digest like 'crc32c:dead' (right prefix,
        bad encoding) must yield the per-piece coded error / span
        fallback — never leak InvalidDigestError through the worker,
        which would strand the run's reservations."""
        from dragonfly2_tpu.daemon.peer.piece_downloader import PieceDownloader
        from dragonfly2_tpu.daemon.peer.piece_dispatcher import (
            ParentInfo, PieceAssignment)
        from dragonfly2_tpu.pkg.errors import Code, DfError

        async def body():
            ps = 1 << 20
            dst = _store(tmp_path, "dst", 4 * ps, ps)
            parent = ParentInfo("p_src", "127.0.0.1", 1)
            dl = PieceDownloader()

            async def never(a, rec, err):
                raise AssertionError("malformed span must not call back")

            run = [PieceAssignment(n, parent, ps, digest="crc32c:dead")
                   for n in range(2)]
            assert not await dl.download_span_to_store(
                "127.0.0.1", 1, "t" * 16, run, dst, on_result=never)
            with pytest.raises(DfError) as ei:
                await dl.download_piece_to_store(
                    "127.0.0.1", 1, "t" * 16, 0, dst,
                    expected_size=ps, expected_digest="crc32c:dead")
            assert ei.value.code == Code.ClientPieceDownloadFail
            await dl.close()

        run_async(body())

    def test_span_ineligibility_falls_back(self, run_async, tmp_path):
        from dragonfly2_tpu.daemon.peer.piece_downloader import PieceDownloader
        from dragonfly2_tpu.daemon.peer.piece_dispatcher import (
            ParentInfo, PieceAssignment)

        async def body():
            ps = 1 << 20
            dst = _store(tmp_path, "dst", 4 * ps, ps)
            parent = ParentInfo("p_src", "127.0.0.1", 1)
            dl = PieceDownloader()

            async def never(a, rec, err):
                raise AssertionError("ineligible span must not call back")

            # Non-crc32c digest.
            run = [PieceAssignment(n, parent, ps,
                                   digest="sha256:" + "0" * 64)
                   for n in range(2)]
            assert not await dl.download_span_to_store(
                "127.0.0.1", 1, "t" * 16, run, dst, on_result=never)
            # Non-contiguous pieces.
            run = [PieceAssignment(0, parent, ps), PieceAssignment(2, parent, ps)]
            assert not await dl.download_span_to_store(
                "127.0.0.1", 1, "t" * 16, run, dst, on_result=never)
            # Unknown expected size.
            run = [PieceAssignment(0, parent, -1), PieceAssignment(1, parent, ps)]
            assert not await dl.download_span_to_store(
                "127.0.0.1", 1, "t" * 16, run, dst, on_result=never)
            await dl.close()

        run_async(body())


class TestSpanDispatch:
    """PieceDispatcher.extend_run / release_assignment."""

    def _dispatcher_with_parent(self, n_pieces=10, advertised=None):
        from dragonfly2_tpu.daemon.peer.piece_dispatcher import PieceDispatcher

        d = PieceDispatcher()
        d.piece_size = 1 << 20
        d.content_length = n_pieces << 20
        d.total_piece_count = n_pieces
        p = d.upsert_parent("par", "127.0.0.1", 9)
        d.on_parent_pieces("par", list(advertised
                                       if advertised is not None
                                       else range(n_pieces)))
        return d, p

    def test_extend_run_reserves_contiguous_pieces(self):
        d, p = self._dispatcher_with_parent()
        a = d.try_get()
        assert a is not None and a.piece_num == 0
        run = d.extend_run(a, 4)
        assert [x.piece_num for x in run] == [0, 1, 2, 3]
        # Extended pieces are reserved: the next worker starts at 4.
        b = d.try_get()
        assert b.piece_num == 4

    def test_extend_run_stops_at_unadvertised(self):
        d, p = self._dispatcher_with_parent(advertised=[0, 1, 5, 6])
        a = d.try_get()
        run = d.extend_run(a, 8)
        assert [x.piece_num for x in run] == [0, 1]

    def test_extend_run_stops_at_non_crc_digest(self):
        d, p = self._dispatcher_with_parent()
        d.piece_digests[2] = "sha256:" + "0" * 64
        a = d.try_get()
        run = d.extend_run(a, 8)
        assert [x.piece_num for x in run] == [0, 1]

    def test_release_assignment_requeues_without_penalty(self):
        d, p = self._dispatcher_with_parent()
        a = d.try_get()
        run = d.extend_run(a, 4)
        before = p.cost_ewma_ms
        for extra in run[1:]:
            d.release_assignment(extra)
        assert p.cost_ewma_ms == before and p.failures == 0
        # Released pieces are assignable again, in order.
        assert d.try_get().piece_num == 1


class TestMalformedResponses:
    def test_garbage_heads_fail_cleanly(self, run_async, tmp_path):
        """Random/adversarial response bytes must produce a coded error —
        never a hang past the socket timeout, a crash, or a bogus
        success."""
        import random

        cases = [
            b"",                                      # immediate close
            b"\x00" * 64,                             # binary junk
            b"HTTP/1.1\r\n\r\n",                      # no status code
            b"HTTP/1.1 9999 X\r\n\r\n",               # out-of-range status
            b"HTTP/1.1 200 OK\r\nContent-Length: banana\r\n\r\n",
            b"HTTP/1.1 200 OK\r\nContent-Length: -5\r\n\r\n",
            b"HTTP/1.1 200 OK\r\nContent-Length: 99999999999999999999\r\n\r\n",
            b"HTTP/1.1 200 OK\r\n" + b"X: y\r\n" * 20000,  # head > 64KiB
            random.Random(0).randbytes(512),
        ]

        async def body():
            def handle_factory(payload):
                async def handle(reader, writer):
                    try:
                        await reader.read(4096)  # consume the request
                        writer.write(payload)
                        await writer.drain()
                    finally:
                        writer.close()
                return handle

            fd = os.open(tmp_path / "out", os.O_RDWR | os.O_CREAT)
            try:
                for payload in cases:
                    server = await asyncio.start_server(
                        handle_factory(payload), "127.0.0.1", 0)
                    port = server.sockets[0].getsockname()[1]
                    h = await T(nb.http_connect, "127.0.0.1", port, 3000)
                    with pytest.raises(nb.NativeHttpError):
                        await T(nb.http_fetch_to_file, h,
                                _head(port), fd, 0, 1024)
                    nb.http_close(h)
                    server.close()
                    await server.wait_closed()
            finally:
                os.close(fd)

        run_async(body(), timeout=90)
